"""Pipelined out-of-core exchange primitives (docs/shuffle.md
"Pipelined exchange").

PR 8's spill shuffle is a strict phase barrier: both sides fully spill
to disk buckets, *then* bucket pairs join one at a time — disk I/O,
host decode, H2D and the compiled kernel never overlap, and tiny
buckets pay a full disk round-trip even when they would fit comfortably
in host memory. This module supplies the three pieces that turn the
exchange into a pipeline, mirroring the staged-redistribution framing
of arXiv:2112.01075 and the partitioned-exchange patterns of
arXiv:2209.06146:

- :class:`SpillWriter` — **write-behind spill**: ONE background thread
  owns every bucket's arrow IPC writer and consumes a bounded queue of
  (bucket, batch) jobs, so the partitioner's decode+hash of chunk n+1
  overlaps the disk write of chunk n. Publishes stay atomic
  temp-write+rename and the ``shuffle.spill`` fault site still fires
  between each bucket's write-close and its publish — on the writer
  thread. Errors raised on the writer thread are carried across the
  boundary (the :mod:`fugue_tpu.jax.pipeline` ``_Failure`` discipline)
  and re-raised in the submitting thread WITH the original traceback; a
  failed writer never leaves the partitioner blocked on a full queue,
  and an abandoned spill never leaves tmp files behind.
- :class:`MemBucketLedger` — the byte ledger behind the
  **memory-resident bucket tier**: buckets whose accumulated arrow
  bytes fit ``fugue.tpu.shuffle.mem_bucket_bytes`` are kept as host
  arrow buffers and never touch disk. Admission is strict (never over
  the cap); under pressure the partitioner demotes its LARGEST
  memory-resident bucket to the write-behind writer, so the ledger
  bound holds for the whole exchange (both sides share one ledger).
- :class:`SpillPipeline` — the per-exchange bundle handed down from
  the join/repartition layer into :func:`spill_partition`; ``None``
  (or the ``fugue.tpu.shuffle.pipeline.enabled=false`` kill-switch)
  leaves the PR 8 serial path byte-identical.

Bucket-pair prefetch (the third leg) lives in ``shuffle/join.py`` — it
reuses the PR 2 :func:`fugue_tpu.jax.pipeline.maybe_prefetch` machinery
directly rather than duplicating it here.
"""

import os
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

import pyarrow as pa

from ..resilience import SITE_SHUFFLE_SPILL
from ..workflow._checkpoint import _atomic_publish, _best_effort_remove

__all__ = ["MemBucketLedger", "SpillWriter", "SpillPipeline"]


class MemBucketLedger:
    """Thread-safe byte ledger bounding the host bytes held by
    memory-resident buckets across one exchange (both sides).

    ``admit`` is all-or-nothing — the tier NEVER runs over its cap; the
    caller demotes buckets (releasing their bytes) to make room or sends
    the batch to disk. ``cap_bytes <= 0`` disables the tier (every admit
    refuses), which is also the kill-switch representation.
    """

    def __init__(self, cap_bytes: int):
        self._lock = threading.Lock()
        self.cap_bytes = max(0, int(cap_bytes))
        self._used = 0
        self._peak = 0
        self._demotions = 0

    def admit(self, nbytes: int) -> bool:
        with self._lock:
            if self._used + nbytes > self.cap_bytes:
                return False
            self._used += int(nbytes)
            if self._used > self._peak:
                self._peak = self._used
            return True

    def release(self, nbytes: int) -> None:
        with self._lock:
            self._used = max(0, self._used - int(nbytes))

    def note_demotion(self) -> None:
        with self._lock:
            self._demotions += 1

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    @property
    def peak_bytes(self) -> int:
        with self._lock:
            return self._peak

    @property
    def demotions(self) -> int:
        with self._lock:
            return self._demotions


class _WriterFailure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


_FLUSH = object()


class SpillWriter:
    """Write-behind bucket writer for one spilled side.

    One daemon thread owns all of the side's ``<side>_<i>.arrow.tmp``
    IPC writers (single owner — no per-file locking) and drains a
    bounded job queue; :meth:`submit` blocks only when ``depth`` batches
    are already in flight, which is the memory bound the partitioner
    accounts for. :meth:`finalize` flushes the queue, closes every
    writer and publishes each bucket atomically ON THE WRITER THREAD,
    firing the ``shuffle.spill`` fault site between close and publish —
    an injected (or real) publish failure tears ONLY that bucket,
    exactly like the serial path, and the reader repairs it lazily.

    A failure while WRITING (a real I/O error, a poisoned batch) is
    carried across the thread boundary and re-raised — original
    traceback preserved — from the next ``submit``/``finalize`` call,
    after the thread has removed every tmp file it created.
    """

    def __init__(
        self,
        spill_dir: str,
        side: str,
        pa_schema: pa.Schema,
        depth: int,
        injector: Any = None,
        stats: Any = None,
    ):
        self._spill_dir = spill_dir
        self._side = side
        self._schema = pa_schema
        self._injector = injector
        self._stats = stats
        self._lock = threading.Lock()
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, int(depth)))
        self._aborting = threading.Event()
        self._done = threading.Event()
        self._failure: Optional[_WriterFailure] = None
        self._published: Dict[int, int] = {}  # bucket -> published bytes
        self._faults = 0
        self._batches = 0
        self._thread = threading.Thread(
            target=self._run, name=f"fugue-tpu-spill-writer-{side}", daemon=True
        )
        self._thread.start()

    # -- writer thread -------------------------------------------------------
    def _tmp(self, i: int) -> str:
        return os.path.join(self._spill_dir, f"{self._side}_{i:05d}.arrow.tmp")

    def _final(self, i: int) -> str:
        return os.path.join(self._spill_dir, f"{self._side}_{i:05d}.arrow")

    def _run(self) -> None:
        writers: Dict[int, Any] = {}
        sinks: Dict[int, Any] = {}
        try:
            while True:
                job = self._q.get()
                if job is _FLUSH:
                    break
                i, tbl = job
                w = writers.get(i)
                if w is None:
                    sink = pa.OSFile(self._tmp(i), "wb")
                    sinks[i] = sink
                    w = pa.ipc.new_stream(sink, self._schema)
                    writers[i] = w
                w.write_table(tbl)
                with self._lock:
                    self._batches += 1
            # close + publish each bucket; the fault site fires between
            # the write-close and the publish, on THIS thread — the
            # write-behind form of the serial publish loop. An aborting
            # caller (the partitioner's failure path) gets tmp cleanup
            # instead of publishes — it is about to remove the dir.
            for i in writers:
                writers[i].close()
                sinks[i].close()
                if self._aborting.is_set():
                    _best_effort_remove(self._tmp(i))
                    continue
                try:
                    if self._injector is not None:
                        self._injector.fire(SITE_SHUFFLE_SPILL)
                    _atomic_publish(self._tmp(i), self._final(i))
                    nbytes = os.path.getsize(self._final(i))
                    with self._lock:
                        self._published[i] = nbytes
                except Exception:
                    _best_effort_remove(self._tmp(i))
                    with self._lock:
                        self._faults += 1
        except BaseException as ex:  # noqa: BLE001 — carried to the caller
            with self._lock:
                self._failure = _WriterFailure(ex)
            # no orphans: every tmp this thread created is removed
            for i, w in writers.items():
                try:
                    w.close()
                except Exception:
                    pass
                try:
                    sinks[i].close()
                except Exception:
                    pass
                _best_effort_remove(self._tmp(i))
            # drain so a blocked submit() can observe the failure
            while True:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
        finally:
            self._done.set()

    # -- submitting side -----------------------------------------------------
    def _raise_if_failed(self) -> None:
        with self._lock:
            failure = self._failure
        if failure is not None:
            # the ORIGINAL exception object keeps its writer-thread
            # frames — the propagation contract of the PR 2 prefetcher
            raise failure.exc

    def submit(self, bucket: int, tbl: pa.Table) -> None:
        """Queue one bucket batch; blocks when ``depth`` batches are in
        flight. Re-raises a writer-thread failure instead of queueing
        into a dead writer."""
        while True:
            self._raise_if_failed()
            if self._done.is_set():
                self._raise_if_failed()
                raise RuntimeError("spill writer already finalized")
            try:
                self._q.put((bucket, tbl), timeout=0.05)
                return
            except queue.Full:
                continue

    def finalize(self) -> Tuple[Dict[int, int], int, int]:
        """Flush, close and publish everything; returns
        ``(bytes-per-published-bucket, publish_faults, batches)``.
        Re-raises any writer-thread failure with its original traceback."""
        while True:
            self._raise_if_failed()
            try:
                self._q.put(_FLUSH, timeout=0.05)
                break
            except queue.Full:
                continue
        self._done.wait()
        self._thread.join(timeout=10.0)
        self._raise_if_failed()
        with self._lock:
            return dict(self._published), self._faults, self._batches

    def abort(self) -> None:
        """Best-effort teardown on the partitioner's failure path: stop
        the thread (publishing nothing) and remove every tmp file.
        Never raises."""
        self._aborting.set()
        try:
            self._q.put_nowait(_FLUSH)
        except queue.Full:
            # drain one slot so the flush sentinel fits; the writer is
            # alive (it would have drained the queue on failure)
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            try:
                self._q.put_nowait(_FLUSH)
            except queue.Full:
                pass
        self._done.wait(timeout=10.0)
        for name in list(os.listdir(self._spill_dir) if os.path.isdir(self._spill_dir) else ()):
            if name.startswith(f"{self._side}_") and name.endswith(".tmp"):
                _best_effort_remove(os.path.join(self._spill_dir, name))


class SpillPipeline:
    """Per-exchange pipeline context handed into ``spill_partition``:
    the shared mem-bucket ledger plus the write-behind queue depth. One
    instance covers every side of one join/repartition, so the mem-tier
    ledger bound holds across sides."""

    def __init__(self, ledger: MemBucketLedger, writebehind_depth: int, stats: Any = None):
        self.ledger = ledger
        self.writebehind_depth = max(1, int(writebehind_depth))
        self.stats = stats

    def writer(
        self, spill_dir: str, side: str, pa_schema: pa.Schema, injector: Any
    ) -> SpillWriter:
        return SpillWriter(
            spill_dir,
            side,
            pa_schema,
            self.writebehind_depth,
            injector=injector,
            stats=self.stats,
        )
