"""Bucket-at-a-time spill-shuffle join and repartition.

Both sides stream through :mod:`fugue_tpu.shuffle.partitioner` into P
on-disk buckets keyed by the SAME normalized key hash, then buckets join
one pair at a time: load bucket i of both sides, run the existing device
join kernels (``ops/join.py``) on it, pull the result back to host, free
the device arrays, move on. Peak device bytes = one bucket pair + the
join's intermediates — independent of input size, so joins where BOTH
sides exceed device memory by 10×+ complete under a bounded
``peak_device_bytes`` (the round-5 STATUS gap / ROADMAP item 3; the
staged-exchange design of arXiv:2112.01075 and the partitioned-exchange
patterns of arXiv:2209.06146).

Correctness: rows are hash-partitioned on the join key, so every key
lives in exactly ONE bucket pair and ``⋃ᵢ join(Lᵢ, Rᵢ) = join(L, R)``
(up to row order) for every hash-partitionable join type —
inner/left_outer/semi/anti directly, right_outer by mirroring,
full_outer by the engine's left_outer ∪ NULL-extended-anti composition,
all PER BUCKET. NULL keys hash to a fixed bucket and keep SQL semantics
inside it (they never match; outer joins keep them). Cross joins cannot
hash-partition and refuse.

Every bucket table is padded to one per-side capacity (the max bucket
row count) before ingest, with the frame's tail-validity marking the pad
rows invalid — so ALL bucket joins share ONE compiled kernel instead of
recompiling per bucket shape.

The output is a one-pass stream of per-bucket result chunks; the spill
directory is removed when the stream is exhausted, errors, or is
abandoned (GeneratorExit) — and on any failure during partitioning.
"""

import os
import time
from typing import Any, Callable, Iterator, List, Optional

import numpy as np
import pyarrow as pa

from ..dataframe import (
    ArrowDataFrame,
    DataFrame,
    LocalDataFrameIterableDataFrame,
)
from ..resilience import FaultInjector
from ..schema import Schema
from .partitioner import (
    SpilledSide,
    bucket_ids,
    canonical_key_kinds,
    new_spill_dir,
    remove_spill_dir,
    spill_partition,
)
from .pipeline import MemBucketLedger, SpillPipeline
from .strategy import (
    bucket_count,
    device_budget_bytes,
    estimate_frame_bytes,
    mem_bucket_cap_bytes,
    pair_prefetch_depth,
    pipeline_enabled,
    spill_dir_root,
    writebehind_depth,
)

__all__ = ["shuffle_spill_join", "spill_repartition"]


def _chunk_rows(engine: Any) -> int:
    from ..constants import FUGUE_TPU_CONF_STREAM_CHUNK_ROWS
    from ..jax.streaming import DEFAULT_CHUNK_ROWS

    return int(engine.conf.get(FUGUE_TPU_CONF_STREAM_CHUNK_ROWS, DEFAULT_CHUNK_ROWS))


def _arrow_chunk_factory(
    engine: Any, df: DataFrame
) -> Callable[[], Iterator[pa.Table]]:
    """A (re-)iterable arrow-chunk view of any frame. For one-pass
    streams the factory is single-shot by nature — the caller records
    that by passing ``replay=None`` to the partitioner."""
    rows = _chunk_rows(engine)

    def gen() -> Iterator[pa.Table]:
        from ..jax.streaming import _closing, _iter_local_frames
        from ..jax.pipeline import engine_prefetcher

        chunks = engine_prefetcher(
            engine,
            (f.as_arrow() for f in _iter_local_frames(df, rows)),
            "shuffle",
        )
        yield from _closing(chunks)

    return gen


def _track_spill_dir(engine: Any, d: str, add: bool) -> None:
    dirs = getattr(engine, "_active_spill_dirs", None)
    if dirs is not None:
        (dirs.add if add else dirs.discard)(d)


def _spill_side(
    engine: Any,
    df: DataFrame,
    side: str,
    keys: List[str],
    kinds: List[str],
    n_buckets: int,
    spill_dir: str,
    injector: FaultInjector,
    parent_span: Optional[str],
    pipeline: Optional[SpillPipeline] = None,
) -> SpilledSide:
    from ..jax.streaming import is_stream_frame
    from ..obs import get_tracer

    stats = getattr(engine, "_shuffle_stats", None)
    factory = _arrow_chunk_factory(engine, df)
    replay = None if is_stream_frame(df) else factory
    pa_schema = Schema(df.schema).pa_schema
    with get_tracer().span(
        "shuffle.partition", cat="shuffle", parent=parent_span, side=side
    ) as sp:
        spilled = spill_partition(
            factory(),
            pa_schema,
            keys,
            kinds,
            n_buckets,
            spill_dir,
            side,
            injector=injector,
            stats=stats,
            replay=replay,
            pipeline=pipeline,
        )
        sp.set(
            rows=spilled.rows,
            buckets=sum(1 for r in spilled.bucket_rows if r > 0),
            bytes=spilled.bytes_spilled,
        )
        if pipeline is not None:
            sp.set(mem_buckets=len(spilled.mem_tables))
    return spilled


def _ingest_padded(engine: Any, tbl: pa.Table, cap: int) -> Any:
    """Device-ingest a bucket table padded to the join-wide capacity so
    every bucket shares one compiled kernel. Pad rows repeat row 0 (any
    valid-for-the-dtypes content works) and sit past ``row_count`` — the
    frame's tail-validity marks them invalid everywhere downstream."""
    from ..jax.dataframe import JaxDataFrame

    n = tbl.num_rows
    padded = tbl
    if n < cap:
        filler = tbl.take(pa.array(np.zeros(cap - n, dtype=np.int64)))
        padded = pa.concat_tables([tbl, filler]).combine_chunks()
    jdf = engine.to_df(ArrowDataFrame(padded))
    _ = jdf.device_cols  # force ingestion NOW (peak accounting is per bucket)
    if n == padded.num_rows:
        return jdf
    return JaxDataFrame(
        mesh=engine._mesh,
        _internal=dict(
            device_cols=dict(jdf.device_cols),
            host_tbl=jdf.host_table,
            row_count=n,
            valid_mask=None,
            nan_cols=jdf._nan_cols,
            encodings=dict(jdf.encodings),
            null_masks=dict(jdf.null_masks),
            schema=jdf.schema,
        ),
    )


def _to_out_table(res: Any, out_schema: Schema) -> pa.Table:
    """Normalize one bucket's join result to the stream's output schema
    (device and host bucket paths must emit interchangeable chunks)."""
    tbl = res.as_arrow() if isinstance(res, DataFrame) else res
    if list(tbl.schema.names) != list(out_schema.names):
        tbl = tbl.select(list(out_schema.names))
    if tbl.schema != out_schema.pa_schema:
        tbl = tbl.cast(out_schema.pa_schema)
    return tbl


def _host_bucket_join(
    engine: Any,
    lt: Optional[pa.Table],
    rt: Optional[pa.Table],
    l_schema: pa.Schema,
    r_schema: pa.Schema,
    jt: str,
    on: Any,
) -> Any:
    """The per-bucket catch-all: dtypes the device kernels refuse, and
    buckets where one side is empty (outer-join NULL extension with exact
    dtype semantics). The host engine is the oracle — per-bucket results
    stay bit-compatible with a whole-frame host join."""
    host = engine._host_engine
    ldf = ArrowDataFrame(lt if lt is not None else l_schema.empty_table())
    rdf = ArrowDataFrame(rt if rt is not None else r_schema.empty_table())
    return host.join(host.to_df(ldf), host.to_df(rdf), how=jt, on=on)


def _device_bucket_join(
    engine: Any,
    jl: Any,
    jr: Any,
    jt: str,
    on: Any,
    out_schema: Schema,
) -> Optional[Any]:
    """One bucket pair through the existing device kernels; None → the
    caller reruns the bucket on the host engine."""
    if jt in ("inner", "left_outer", "left_semi", "left_anti"):
        kernel_how = {
            "inner": "inner",
            "left_outer": "left_outer",
            "left_semi": "semi",
            "left_anti": "anti",
        }[jt]
        return engine._join_device(jl, jr, kernel_how, on)
    if jt == "right_outer":
        res = engine._join_device(jr, jl, "left_outer", on)
        if res is not None and list(res.schema.names) != list(out_schema.names):
            res = res[list(out_schema.names)]
        return res
    if jt == "full_outer":
        return engine._full_outer_device(jl, jr, on)
    return None


def shuffle_spill_join(
    engine: Any,
    df1: DataFrame,
    df2: DataFrame,
    how: str,
    on: Any = None,
    tune: Any = None,
) -> Optional[DataFrame]:
    """Spill-partition both sides and join bucket-at-a-time. Returns a
    one-pass stream of result chunks, or None when the join can't
    hash-partition (cross join, unhashable key types, keyless) — the
    caller falls back to the legacy ladder.

    ``tune`` is the adaptive-execution handle (docs/tuning.md): it
    supplies the CALIBRATED bucket count for this plan's join when prior
    runs observed it (replacing the static ``budget/32`` sizing guess)
    and receives this run's measured side bytes/rows and bucket-pair
    device peak as the next generation's evidence. None (tuning disabled,
    direct engine calls) resolves exactly as before."""
    from ..dataframe.utils import get_join_schemas, parse_join_type
    from ..jax.streaming import _device_peak_bytes
    from ..obs import get_tracer

    jt = parse_join_type(how)
    if jt == "cross":
        return None
    try:
        key_schema, out_schema = get_join_schemas(df1, df2, how=jt, on=on)
    except Exception:
        return None
    keys = list(key_schema.names)
    if len(keys) == 0:
        return None
    kinds = canonical_key_kinds(df1.schema, df2.schema, keys)
    if kinds is None:
        return None
    conf = engine.conf
    t_start = time.perf_counter()
    est1, est2 = estimate_frame_bytes(df1), estimate_frame_bytes(df2)
    est = max(est1 or 0, est2 or 0) or None
    n_buckets = (
        tune.bucket_count(conf, est) if tune is not None else bucket_count(conf, est)
    )
    # pipelined exchange (docs/shuffle.md "Pipelined exchange"): one mem
    # ledger + write-behind context shared by both sides; the tuner may
    # substitute a learned pair-prefetch depth / mem-tier budget for this
    # plan. The kill-switch leaves pipeline=None — the PR 8 phase-barrier
    # path, byte-identical.
    pipe_on = pipeline_enabled(conf)
    pair_depth = pair_prefetch_depth(conf)
    mem_cap = mem_bucket_cap_bytes(conf)
    if tune is not None and pipe_on:
        pair_depth, mem_cap = tune.pipeline_params(conf, pair_depth, mem_cap)
    stats = getattr(engine, "_shuffle_stats", None)
    pipeline = (
        SpillPipeline(MemBucketLedger(mem_cap), writebehind_depth(conf), stats)
        if pipe_on
        else None
    )
    root = spill_dir_root(conf)
    os.makedirs(root, exist_ok=True)
    spill_dir = new_spill_dir(root)
    _track_spill_dir(engine, spill_dir, True)
    injector = FaultInjector.from_conf(conf)
    tracer = get_tracer()
    parent = tracer.current_span_id()
    try:
        left = _spill_side(
            engine, df1, "left", keys, kinds, n_buckets, spill_dir, injector,
            parent, pipeline,
        )
        right = _spill_side(
            engine, df2, "right", keys, kinds, n_buckets, spill_dir, injector,
            parent, pipeline,
        )
    except BaseException:
        _track_spill_dir(engine, spill_dir, False)
        remove_spill_dir(spill_dir)
        if stats is not None:
            stats.inc("spill_dirs_cleaned")
        raise
    if stats is not None:
        stats.inc("joins_spill")
    if tune is not None:
        # the ACTUAL side sizes (the partitioner measured every row) — the
        # observed cardinalities the next run's strategy decision consumes
        tune.observe_sides(
            left.bytes_spilled, right.bytes_spilled, left.rows, right.rows
        )
    l_schema = Schema(df1.schema).pa_schema
    r_schema = Schema(df2.schema).pa_schema
    cap_l = max(left.max_bucket_rows, 1)
    cap_r = max(right.max_bucket_rows, 1)

    def gen() -> Iterator[Any]:
        run = {"chunks": 0, "rows": 0, "peak_device_bytes": 0, "buckets": n_buckets}
        try:
            for i in range(n_buckets):
                with tracer.span(
                    "shuffle.bucket", cat="shuffle", parent=parent, bucket=i
                ) as sp:
                    lt = left.read_bucket(i, stats)
                    rt = right.read_bucket(i, stats)
                    if lt is None and rt is None:
                        continue
                    res: Optional[Any] = None
                    if lt is not None and rt is not None:
                        jl = _ingest_padded(engine, lt, cap_l)
                        jr = _ingest_padded(engine, rt, cap_r)
                        res = _device_bucket_join(engine, jl, jr, jt, on, out_schema)
                        if res is None:
                            jl = jr = None
                            res = _host_bucket_join(
                                engine, lt, rt, l_schema, r_schema, jt, on
                            )
                    elif jt in ("inner", "left_semi"):
                        continue  # one side empty ⇒ no matches, no output
                    else:
                        res = _host_bucket_join(
                            engine, lt, rt, l_schema, r_schema, jt, on
                        )
                    out = _to_out_table(res, out_schema)
                    # peak while the bucket pair + result are still live —
                    # the honest high-water mark for this bucket
                    run["peak_device_bytes"] = max(
                        run["peak_device_bytes"], _device_peak_bytes()
                    )
                    res = jl = jr = None  # free device refs before the next bucket
                    if stats is not None:
                        stats.inc("bucket_joins")
                        stats.inc("bucket_rows_out", out.num_rows)
                        stats.peak(run["peak_device_bytes"])
                    sp.set(
                        rows_left=0 if lt is None else lt.num_rows,
                        rows_right=0 if rt is None else rt.num_rows,
                        rows_out=out.num_rows,
                    )
                run["chunks"] += 1
                run["rows"] += out.num_rows
                if out.num_rows > 0:
                    yield ArrowDataFrame(out)
        finally:
            _track_spill_dir(engine, spill_dir, False)
            remove_spill_dir(spill_dir)
            if stats is not None:
                stats.inc("spill_dirs_cleaned")
            if tune is not None:
                tune.observe_run(
                    run["peak_device_bytes"], time.perf_counter() - t_start
                )
            from ..jax import streaming as _streaming

            _streaming.last_run_stats = dict(run, verb="shuffle_join")

    def gen_pipelined() -> Iterator[Any]:
        """The overlapped consumer: bucket pairs flow through a
        depth-bounded producer (the PR 2 prefetcher machinery) that
        reads+decodes+pads+device-ingests pair group i+1 while the join
        kernel runs group i. Adjacent device-eligible pairs coalesce
        into budget-bounded GROUPS — hash partitioning guarantees keys
        never cross buckets, so ``join(concat Lᵢ, concat Rᵢ) =
        ⋃ᵢ join(Lᵢ, Rᵢ)`` and one kernel launch covers many tiny
        buckets. Group size is capped so ``(depth+1)`` in-flight groups
        stay under half the device budget; the measured peak (sampled on
        BOTH threads, so in-flight prefetched pairs count) proves it."""
        from ..jax.pipeline import maybe_prefetch
        from ..jax.streaming import _device_peak_bytes

        budget = device_budget_bytes(conf)
        inflight = max(1, pair_depth + 1)
        bpr_l = left.bytes_spilled / max(left.rows, 1)
        bpr_r = right.bytes_spilled / max(right.rows, 1)
        pair_bytes = cap_l * bpr_l + cap_r * bpr_r
        # group sizing is MEASURED, not guessed: the first group is one
        # pair (the serial working set, known to fit), and every group's
        # sampled live-array peak re-derives the target — budget over
        # 2.5x the RUNNING-MAX per-pair peak per in-flight group, growth
        # bounded to 2x per step so a skewed bucket can't overshoot.
        # ``g_max`` is a static guard from the raw ingest estimate:
        # dup-heavy joins whose expansion output dwarfs their ingest
        # stay near 1 pair per launch (exactly the serial shape),
        # because their measured pair peak says so.
        g_max = max(1, min(64, int(budget / max(1.0, 2.0 * inflight * pair_bytes))))
        run = {
            "chunks": 0,
            "rows": 0,
            "peak_device_bytes": 0,
            "buckets": n_buckets,
            "pairs_per_group": 1,
        }
        state = {"pair_peak": 0, "g": 1}

        def _retarget() -> None:
            pp = state["pair_peak"]
            if pp <= 0:
                return
            g = int(budget / max(1.0, 2.5 * inflight * pp))
            state["g"] = max(1, min(g_max, g, state["g"] * 2))
            run["pairs_per_group"] = max(run["pairs_per_group"], state["g"])

        def build(batch: List[Any]) -> Any:
            lcat = (
                batch[0][1]
                if len(batch) == 1
                else pa.concat_tables([b[1] for b in batch])
            )
            rcat = (
                batch[0][2]
                if len(batch) == 1
                else pa.concat_tables([b[2] for b in batch])
            )
            jl = _ingest_padded(engine, lcat, cap_l * len(batch))
            jr = _ingest_padded(engine, rcat, cap_r * len(batch))
            # sampled on the PRODUCER thread, right after ingest: an
            # in-flight prefetched group is device-resident from this
            # moment and must count toward the budget proof
            peak = _device_peak_bytes()
            run["peak_device_bytes"] = max(run["peak_device_bytes"], peak)
            if stats is not None:
                stats.peak(peak)
            return ("dev", [b[0] for b in batch], jl, jr, lcat, rcat)

        def produce() -> Iterator[Any]:
            batch: List[Any] = []
            for i in range(n_buckets):
                lt = left.read_bucket(i, stats)
                rt = right.read_bucket(i, stats)
                if lt is None and rt is None:
                    continue
                if lt is not None and rt is not None:
                    batch.append((i, lt, rt))
                    if len(batch) >= state["g"]:
                        yield build(batch)
                        batch = []
                elif jt in ("inner", "left_semi"):
                    continue  # one side empty ⇒ no matches, no output
                else:
                    if batch:  # flush first: outputs stay in bucket order
                        yield build(batch)
                        batch = []
                    yield ("host", i, lt, rt)
            if batch:
                yield build(batch)

        it = maybe_prefetch(
            produce(),
            pair_depth,
            stats=getattr(engine, "pipeline_stats", None),
            verb="shuffle.pairs",
            stream=tune.sid if tune is not None else "",
            observer=tune.observe_pair_stream if tune is not None else None,
        )
        if stats is not None:
            stats.inc("pipelined_joins")
        try:
            for item in it:
                if item[0] == "host":
                    _, i, lt, rt = item
                    with tracer.span(
                        "shuffle.bucket", cat="shuffle", parent=parent, bucket=i
                    ) as sp:
                        res = _host_bucket_join(
                            engine, lt, rt, l_schema, r_schema, jt, on
                        )
                        out = _to_out_table(res, out_schema)
                        if stats is not None:
                            stats.inc("bucket_joins")
                            stats.inc("bucket_rows_out", out.num_rows)
                        sp.set(
                            rows_left=0 if lt is None else lt.num_rows,
                            rows_right=0 if rt is None else rt.num_rows,
                            rows_out=out.num_rows,
                        )
                else:
                    _, bids, jl, jr, lcat, rcat = item
                    item = None  # drop the tuple's device refs: only the
                    # locals below keep the group alive, and they are
                    # cleared before the next dequeue
                    with tracer.span(
                        "shuffle.bucket",
                        cat="shuffle",
                        parent=parent,
                        bucket=bids[0],
                        pairs=len(bids),
                    ) as sp:
                        res = _device_bucket_join(
                            engine, jl, jr, jt, on, out_schema
                        )
                        if res is None:
                            # the kernels refuse the whole group (exotic
                            # dtypes, slot overflow): the host engine is
                            # the per-bucket oracle and a group is a
                            # union of disjoint-key buckets, so one host
                            # join of the concatenations is exact
                            jl = jr = None
                            res = _host_bucket_join(
                                engine, lcat, rcat, l_schema, r_schema, jt, on
                            )
                        out = _to_out_table(res, out_schema)
                        peak = _device_peak_bytes()
                        run["peak_device_bytes"] = max(
                            run["peak_device_bytes"], peak
                        )
                        state["pair_peak"] = max(
                            state["pair_peak"], -(-peak // len(bids))
                        )
                        _retarget()
                        rows_l, rows_r = lcat.num_rows, rcat.num_rows
                        res = jl = jr = lcat = rcat = None  # free eagerly
                        if stats is not None:
                            stats.inc("bucket_joins", len(bids))
                            stats.inc("group_joins")
                            stats.inc("bucket_rows_out", out.num_rows)
                            stats.peak(peak)
                        sp.set(
                            rows_left=rows_l,
                            rows_right=rows_r,
                            rows_out=out.num_rows,
                        )
                run["chunks"] += 1
                run["rows"] += out.num_rows
                if out.num_rows > 0:
                    yield ArrowDataFrame(out)
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
            left.release_mem()
            right.release_mem()
            _track_spill_dir(engine, spill_dir, False)
            remove_spill_dir(spill_dir)
            if stats is not None:
                stats.inc("spill_dirs_cleaned")
            if tune is not None:
                # the tuner calibrates BUCKET COUNT from a per-pair peak;
                # normalize the grouped measurement so its target holds
                tune.observe_run(
                    state["pair_peak"] or run["peak_device_bytes"],
                    time.perf_counter() - t_start,
                )
                tune.observe_pipeline(
                    {
                        "pairs_per_group": run["pairs_per_group"],
                        "mem_bytes_used": pipeline.ledger.peak_bytes,
                        "mem_cap_bytes": pipeline.ledger.cap_bytes,
                        "mem_demotions": pipeline.ledger.demotions,
                    }
                )
            from ..jax import streaming as _streaming

            _streaming.last_run_stats = dict(run, verb="shuffle_join")

    chosen = gen_pipelined() if pipeline is not None else gen()
    return LocalDataFrameIterableDataFrame(chosen, schema=out_schema)


def spill_repartition(
    engine: Any, df: DataFrame, by: List[str], num: int = 0
) -> Optional[DataFrame]:
    """Hash-repartition through the spill partitioner: the result is a
    one-pass stream where every key lives in exactly ONE chunk (bucket) —
    the out-of-core physical layout behind arbitrarily large
    ``PartitionSpec`` maps. None → key types the partitioner can't hash."""
    from ..obs import get_tracer

    kinds = canonical_key_kinds(df.schema, df.schema, by)
    if kinds is None or len(by) == 0:
        return None
    conf = engine.conf
    n_buckets = int(num) if num and num > 0 else bucket_count(
        conf, estimate_frame_bytes(df)
    )
    stats = getattr(engine, "_shuffle_stats", None)
    pipeline = (
        SpillPipeline(
            MemBucketLedger(mem_bucket_cap_bytes(conf)),
            writebehind_depth(conf),
            stats,
        )
        if pipeline_enabled(conf)
        else None
    )
    root = spill_dir_root(conf)
    os.makedirs(root, exist_ok=True)
    spill_dir = new_spill_dir(root)
    _track_spill_dir(engine, spill_dir, True)
    injector = FaultInjector.from_conf(conf)
    parent = get_tracer().current_span_id()
    try:
        side = _spill_side(
            engine, df, "part", by, kinds, n_buckets, spill_dir, injector,
            parent, pipeline,
        )
    except BaseException:
        _track_spill_dir(engine, spill_dir, False)
        remove_spill_dir(spill_dir)
        if stats is not None:
            stats.inc("spill_dirs_cleaned")
        raise
    if stats is not None:
        stats.inc("repartitions_spill")
    schema = Schema(df.schema)

    def gen() -> Iterator[Any]:
        try:
            for i in range(n_buckets):
                tbl = side.read_bucket(i, stats)
                if tbl is not None and tbl.num_rows > 0:
                    yield ArrowDataFrame(tbl)
        finally:
            _track_spill_dir(engine, spill_dir, False)
            remove_spill_dir(spill_dir)
            if stats is not None:
                stats.inc("spill_dirs_cleaned")

    def gen_pipelined() -> Iterator[Any]:
        # the pipelined form keeps ONE chunk per bucket (every key lives
        # in exactly one chunk — the spill-repartition contract) but
        # reads+decodes bucket i+1 in the background while the consumer
        # maps bucket i; mem-resident buckets skip disk entirely
        from ..jax.pipeline import maybe_prefetch

        def produce() -> Iterator[Any]:
            for i in range(n_buckets):
                tbl = side.read_bucket(i, stats)
                if tbl is not None and tbl.num_rows > 0:
                    yield ArrowDataFrame(tbl)

        it = maybe_prefetch(
            produce(),
            pair_prefetch_depth(conf),
            stats=getattr(engine, "pipeline_stats", None),
            verb="shuffle.read",
        )
        try:
            yield from it
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
            side.release_mem()
            _track_spill_dir(engine, spill_dir, False)
            remove_spill_dir(spill_dir)
            if stats is not None:
                stats.inc("spill_dirs_cleaned")

    chosen = gen_pipelined() if pipeline is not None else gen()
    return LocalDataFrameIterableDataFrame(chosen, schema=schema)
