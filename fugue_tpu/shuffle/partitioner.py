"""Streaming hash partitioner: route rows by key hash into P on-disk
bucket spill files, one pass, bounded host memory, zero device bytes.

Any input — bounded frame, parquet load, one-pass stream — is consumed
chunk-by-chunk (the PR 2 ``engine_prefetcher`` overlaps decode with the
spill writes). Each chunk's key columns are normalized to a canonical
dtype shared by BOTH join sides (so ``int64 5`` and ``float64 5.0``
co-bucket exactly like they match by value in the join kernels), hashed
with ``pd.util.hash_pandas_object`` (deterministic across processes),
and the chunk is split with arrow ``take`` — schema preserved bit-for-bit
— onto per-bucket arrow IPC stream writers.

Publish discipline: every bucket writes to ``<name>.tmp`` and is
atomically renamed on completion (the cache store's
``_atomic_publish``), so a bucket file either doesn't exist or is
complete. A missing, truncated, or corrupt bucket is detected at read
time (full IPC decode + row-count check against the partitioner's own
ledger) and recovered by repartitioning ONLY that bucket from the
source — possible whenever the source is replayable (anything but a
one-pass stream). The ``shuffle.spill`` FaultInjector site fires between
each bucket's write and its publish.
"""

import os
import shutil
import uuid as _uuid
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from ..exceptions import FugueTPUError
from ..resilience import SITE_SHUFFLE_SPILL, FaultInjector
from ..workflow._checkpoint import _atomic_publish, _best_effort_remove

__all__ = [
    "canonical_key_kinds",
    "bucket_ids",
    "SpilledSide",
    "spill_partition",
    "new_spill_dir",
    "remove_spill_dir",
    "spill_dir_bytes",
]


# ---------------------------------------------------------------------------
# key normalization + hashing
# ---------------------------------------------------------------------------

def _kind_of(tp: pa.DataType) -> Optional[str]:
    if pa.types.is_dictionary(tp):
        tp = tp.value_type
    if pa.types.is_floating(tp):
        return "f"
    if pa.types.is_integer(tp) or pa.types.is_boolean(tp):
        return "i"
    if pa.types.is_string(tp) or pa.types.is_large_string(tp):
        return "s"
    if pa.types.is_timestamp(tp) or pa.types.is_date(tp):
        return "t"
    return None


def canonical_key_kinds(
    schema1: Any, schema2: Any, keys: List[str]
) -> Optional[List[str]]:
    """Per key column, the canonical hash dtype BOTH sides normalize to
    before hashing — equal-by-value keys must co-bucket even across
    dtypes (int64 ⋈ float64 matches by value in the join kernels). None
    = a key type the partitioner can't hash (decimal, binary, nested):
    the caller refuses and the legacy ladder handles the join."""
    kinds: List[str] = []
    for k in keys:
        k1, k2 = _kind_of(schema1[k].type), _kind_of(schema2[k].type)
        if k1 is None or k2 is None:
            return None
        if k1 == k2:
            kinds.append("f" if k1 == "f" else k1)
        elif {k1, k2} <= {"i", "f"}:
            kinds.append("f")  # value-equality across int/float via float64
        else:
            return None  # string vs numeric etc. — no value equality
    return kinds


def _normalize_key(col: pa.ChunkedArray, kind: str) -> pd.Series:
    """One key column → canonical pandas Series with NULLs filled to a
    fixed value (NULL keys never match, they only need a deterministic
    bucket)."""
    s = col.to_pandas()
    if kind == "f":
        s = pd.to_numeric(s, errors="coerce").astype(np.float64)
        # + 0.0 canonicalizes -0.0 → +0.0 (IEEE): the hash sees float bit
        # patterns, but the join kernels match 0.0 == -0.0 by value, so
        # both must land in the same bucket
        return s.fillna(0.0) + 0.0
    if kind == "i":
        # nullable ints arrive as Int64/object; uint64 wraps into int64
        # deterministically on both sides (bucketing needs consistency,
        # not order)
        s = s.fillna(0)
        return s.astype(np.int64, errors="ignore").astype(np.int64)
    if kind == "t":
        s = pd.to_datetime(s)
        try:
            # tz-aware → the UTC instant, so equal instants co-bucket even
            # when the two sides carry different timezones; tz-naive
            # raises TypeError and keeps its wall-clock int64 view
            s = s.dt.tz_convert("UTC").dt.tz_localize(None)
        except (AttributeError, TypeError):
            pass
        v = s.astype("int64", errors="ignore")
        if v.dtype != np.int64:  # NaT-bearing — view through float64
            return pd.to_numeric(v, errors="coerce").fillna(0.0).astype(np.float64)
        return v
    # strings
    return s.astype("object").where(~s.isna(), "").astype(str)


def bucket_ids(
    tbl: pa.Table, keys: List[str], kinds: List[str], n_buckets: int
) -> np.ndarray:
    """Per-row bucket id for one chunk (uint64 hash of the normalized key
    frame, mod P). Deterministic across processes and chunk boundaries."""
    norm = pd.DataFrame(
        {k: _normalize_key(tbl.column(k), kind) for k, kind in zip(keys, kinds)}
    )
    h = pd.util.hash_pandas_object(norm, index=False).to_numpy()
    return (h % np.uint64(n_buckets)).astype(np.int64)


# ---------------------------------------------------------------------------
# spill directories
# ---------------------------------------------------------------------------

def new_spill_dir(root: str) -> str:
    d = os.path.join(root, f"shuffle-{os.getpid()}-{_uuid.uuid4().hex[:12]}")
    os.makedirs(d, exist_ok=True)
    return d


def remove_spill_dir(path: str) -> None:
    try:
        shutil.rmtree(path)
    except OSError:
        pass


def spill_dir_bytes(paths: Any) -> int:
    """Live on-disk bytes across a set of spill dirs (the sampler probe).

    ``paths`` may be the engine's live spill-dir set, mutated by
    join/repartition threads while the sampler iterates — snapshot it,
    retrying once if a concurrent add/discard races the copy.

    ``*.tmp`` files are EXCLUDED from the walk: a bucket mid-publish
    briefly has both its tmp and (on republish after recovery) its
    published file visible, and with write-behind spill the tmp files
    stay open for the whole partition pass — counting them double-counts
    the bucket and made the probe report phantom bytes during the
    temp-write+rename window."""
    dirs: Tuple[str, ...] = ()
    for _ in range(2):
        try:
            dirs = tuple(paths)
            break
        except RuntimeError:
            continue
    total = 0
    for d in dirs:
        try:
            for name in os.listdir(d):
                if name.endswith(".tmp"):
                    continue
                try:
                    total += os.path.getsize(os.path.join(d, name))
                except OSError:
                    pass
        except OSError:
            pass
    return total


# ---------------------------------------------------------------------------
# the spilled representation of one join side
# ---------------------------------------------------------------------------

class SpilledSide:
    """P published bucket files plus the ledger needed to read them back
    safely (expected per-bucket row counts) and to recover a damaged one
    (the replay factory, when the source can be re-iterated).

    With the pipelined exchange, some buckets live in the
    **memory-resident tier** instead of on disk: ``mem_tables`` maps
    bucket id → accumulated arrow slices whose bytes fit the exchange's
    ``MemBucketLedger``. ``read_bucket`` serves them without any disk or
    IPC round-trip, combining the slices into ONE contiguous table the
    first time and caching that decoded form (keyed by bucket id,
    budget-accounted — see :meth:`_retain_combined`) so later reads of
    the same bucket never re-concat or re-decode the per-chunk slices.
    Torn/absent-file detection and recovery are unchanged for everything
    else (a demoted bucket is indistinguishable from a serial one)."""

    def __init__(
        self,
        spill_dir: str,
        side: str,
        pa_schema: pa.Schema,
        keys: List[str],
        kinds: List[str],
        n_buckets: int,
        bucket_rows: List[int],
        bytes_spilled: int,
        replay: Optional[Callable[[], Iterator[pa.Table]]],
        mem_tables: Optional[Dict[int, List[pa.Table]]] = None,
        ledger: Any = None,
        mem_bytes: int = 0,
        mem_bucket_bytes: Optional[Dict[int, int]] = None,
    ):
        self.spill_dir = spill_dir
        self.side = side
        self.pa_schema = pa_schema
        self.keys = keys
        self.kinds = kinds
        self.n_buckets = n_buckets
        self.bucket_rows = bucket_rows
        self.bytes_spilled = bytes_spilled
        self.replay = replay
        self.mem_tables = mem_tables or {}
        self.mem_bytes = mem_bytes
        self.mem_bucket_bytes = mem_bucket_bytes or {}
        self._ledger = ledger
        self._combined: set = set()

    def path(self, i: int) -> str:
        return os.path.join(self.spill_dir, f"{self.side}_{i:05d}.arrow")

    def release_mem(self) -> None:
        """Return this side's memory-resident bytes to the exchange
        ledger (the consuming stream's ``finally``). Idempotent."""
        if self._ledger is not None and self.mem_bytes > 0:
            self._ledger.release(self.mem_bytes)
            self.mem_bytes = 0
        self.mem_tables = {}
        self.mem_bucket_bytes = {}
        self._combined = set()

    @property
    def rows(self) -> int:
        return sum(self.bucket_rows)

    @property
    def max_bucket_rows(self) -> int:
        return max(self.bucket_rows) if self.bucket_rows else 0

    def read_bucket(self, i: int, stats: Any = None) -> Optional[pa.Table]:
        """Bucket ``i`` fully decoded (torn files can't parse), validated
        against the ledger row count; a missing/corrupt bucket is deleted
        and repartitioned from the source — only that bucket. A
        memory-resident bucket is served straight from its accumulated
        arrow slices, no disk and no IPC decode."""
        expected = self.bucket_rows[i]
        if expected == 0:
            return None
        parts = self.mem_tables.get(i)
        if parts is not None:
            if i in self._combined:
                # decoded-form cache hit: this bucket was already combined
                # into one contiguous table by an earlier read — serve it
                # straight, no re-concat and no per-slice re-decode for
                # the consumer's ingest
                if stats is not None:
                    stats.inc("mem_bucket_hits")
                    stats.inc("mem_bucket_ingest_hits")
                return parts[0]
            tbl = parts[0] if len(parts) == 1 else pa.concat_tables(parts)
            if tbl.num_rows == expected:
                if stats is not None:
                    stats.inc("mem_bucket_hits")
                return self._retain_combined(i, tbl)
            # a mem bucket that disagrees with its own ledger can only be
            # a bug — but recovery is cheap and already exists: fall
            # through to the disk/replay path below
            self.mem_tables.pop(i, None)
        path = self.path(i)
        tbl: Optional[pa.Table] = None
        if os.path.exists(path):
            try:
                with pa.ipc.open_stream(path) as reader:
                    tbl = reader.read_all()
                if tbl.num_rows != expected:
                    tbl = None
            except Exception:
                tbl = None
        if tbl is None:
            _best_effort_remove(path)
            tbl = self._recover_bucket(i)
            if stats is not None:
                stats.inc("bucket_recoveries")
        return tbl

    def _retain_combined(self, i: int, tbl: pa.Table) -> pa.Table:
        """Replace bucket ``i``'s accumulated per-chunk slices with ONE
        contiguous combined table and cache it for later reads. Budget-
        accounted: the combined copy's byte delta vs the slices is
        admitted to (or released from) the exchange ledger, so the cache
        can never exceed the mem-tier budget — a refused admit serves the
        chunked concat view uncached (correctness never depends on the
        cache)."""
        combined = tbl.combine_chunks()
        new_nb = int(combined.nbytes)
        old_nb = int(self.mem_bucket_bytes.get(i, new_nb))
        delta = new_nb - old_nb
        if self._ledger is not None:
            if delta > 0 and not self._ledger.admit(delta):
                return tbl
            if delta < 0:
                self._ledger.release(-delta)
        self.mem_tables[i] = [combined]
        self.mem_bucket_bytes[i] = new_nb
        self.mem_bytes += delta
        self._combined.add(i)
        return combined

    def _recover_bucket(self, i: int) -> pa.Table:
        if self.replay is None:
            raise FugueTPUError(
                f"shuffle spill bucket {self.side}_{i} is torn or missing and "
                "the source is a one-pass stream (not replayable); re-run the "
                "join or materialize the input first"
            )
        parts: List[pa.Table] = []
        for tbl in self.replay():
            if tbl.schema != self.pa_schema:
                tbl = tbl.cast(self.pa_schema)
            ids = bucket_ids(tbl, self.keys, self.kinds, self.n_buckets)
            (sel,) = np.nonzero(ids == i)
            if len(sel) > 0:
                parts.append(tbl.take(pa.array(sel, type=pa.int64())))
        got = (
            pa.concat_tables(parts)
            if parts
            else self.pa_schema.empty_table()
        )
        if got.num_rows != self.bucket_rows[i]:
            raise FugueTPUError(
                f"shuffle bucket {self.side}_{i} recovery produced "
                f"{got.num_rows} rows, ledger expects {self.bucket_rows[i]} "
                "(source changed between spill and recovery)"
            )
        # re-publish so later readers (and retries) see a complete file
        tmp = self.path(i) + ".tmp"
        with pa.OSFile(tmp, "wb") as sink:
            with pa.ipc.new_stream(sink, self.pa_schema) as writer:
                writer.write_table(got)
        _atomic_publish(tmp, self.path(i))
        return got


# ---------------------------------------------------------------------------
# the one-pass spill
# ---------------------------------------------------------------------------

def _chunk_bucket_parts(
    tbl: pa.Table, keys: List[str], kinds: List[str], n_buckets: int
) -> Iterator[Tuple[int, pa.Table]]:
    """One chunk split into its non-empty (bucket_id, slice) parts —
    the ONE split implementation shared by the serial and pipelined
    spill paths (stable argsort, schema preserved bit-for-bit)."""
    ids = bucket_ids(tbl, keys, kinds, n_buckets)
    order = np.argsort(ids, kind="stable")
    sorted_ids = ids[order]
    bounds = np.searchsorted(sorted_ids, np.arange(n_buckets + 1), side="left")
    for i in range(n_buckets):
        lo, hi = bounds[i], bounds[i + 1]
        if lo == hi:
            continue
        yield i, tbl.take(pa.array(order[lo:hi], type=pa.int64()))


def spill_partition(
    chunks: Iterator[pa.Table],
    pa_schema: pa.Schema,
    keys: List[str],
    kinds: List[str],
    n_buckets: int,
    spill_dir: str,
    side: str,
    injector: Optional[FaultInjector] = None,
    stats: Any = None,
    replay: Optional[Callable[[], Iterator[pa.Table]]] = None,
    pipeline: Any = None,
) -> SpilledSide:
    """Consume ``chunks`` once, routing rows into ``n_buckets`` spill
    files under ``spill_dir``. Buckets a fault rule tears stay
    unpublished — the reader repairs them lazily via ``read_bucket``.

    ``pipeline`` (a :class:`~fugue_tpu.shuffle.pipeline.SpillPipeline`)
    switches to the overlapped form: batches go to a write-behind
    background writer and small buckets stay in the memory-resident
    tier. ``None`` is the strict PR 8 serial path, byte-identical."""
    if pipeline is not None:
        return _spill_partition_pipelined(
            chunks,
            pa_schema,
            keys,
            kinds,
            n_buckets,
            spill_dir,
            side,
            injector,
            stats,
            replay,
            pipeline,
        )
    writers: Dict[int, Any] = {}
    sinks: Dict[int, Any] = {}
    bucket_rows = [0] * n_buckets
    n_chunks = 0

    def _writer(i: int) -> Any:
        w = writers.get(i)
        if w is None:
            sink = pa.OSFile(
                os.path.join(spill_dir, f"{side}_{i:05d}.arrow.tmp"), "wb"
            )
            sinks[i] = sink
            w = pa.ipc.new_stream(sink, pa_schema)
            writers[i] = w
        return w

    try:
        for tbl in chunks:
            if tbl.num_rows == 0:
                continue
            n_chunks += 1
            if tbl.schema != pa_schema:
                tbl = tbl.cast(pa_schema)
            for i, part in _chunk_bucket_parts(tbl, keys, kinds, n_buckets):
                _writer(i).write_table(part)
                bucket_rows[i] += part.num_rows
    finally:
        for w in writers.values():
            try:
                w.close()
            except Exception:
                pass
        for s in sinks.values():
            try:
                s.close()
            except Exception:
                pass

    bytes_spilled = 0
    for i in writers:
        tmp = os.path.join(spill_dir, f"{side}_{i:05d}.arrow.tmp")
        final = os.path.join(spill_dir, f"{side}_{i:05d}.arrow")
        try:
            if injector is not None:
                injector.fire(SITE_SHUFFLE_SPILL)
            _atomic_publish(tmp, final)
            bytes_spilled += os.path.getsize(final)
        except Exception:
            # an injected (or real) publish failure tears ONLY this
            # bucket; the reader recovers it from the replayable source
            _best_effort_remove(tmp)
            if stats is not None:
                stats.inc("spill_faults")
    if stats is not None:
        stats.inc("partitions")
        stats.inc("chunks", n_chunks)
        stats.inc("rows_spilled", sum(bucket_rows))
        stats.inc("bytes_spilled", bytes_spilled)
        stats.inc("buckets", len(writers))
    return SpilledSide(
        spill_dir,
        side,
        pa_schema,
        keys,
        kinds,
        n_buckets,
        bucket_rows,
        bytes_spilled,
        replay,
    )


def _spill_partition_pipelined(
    chunks: Iterator[pa.Table],
    pa_schema: pa.Schema,
    keys: List[str],
    kinds: List[str],
    n_buckets: int,
    spill_dir: str,
    side: str,
    injector: Optional[FaultInjector],
    stats: Any,
    replay: Optional[Callable[[], Iterator[pa.Table]]],
    pipeline: Any,
) -> SpilledSide:
    """The overlapped spill (docs/shuffle.md "Pipelined exchange"):
    decode/hash of chunk n+1 overlaps the disk write of chunk n through
    the bounded write-behind writer, and buckets whose accumulated arrow
    bytes fit the exchange's mem ledger never touch disk at all.

    Demotion is largest-first: when a batch can't be admitted, the
    biggest memory-resident bucket moves (in accumulation order, so the
    on-disk row order matches a serial spill of the same bucket) to the
    write-behind writer until the batch fits or the tier is empty. The
    ``shuffle.spill`` fault site fires per bucket either on the writer
    thread (disk buckets, between write-close and publish) or at mem
    retention — an injected fault DROPS the mem bucket, the tier's form
    of a torn publish, and ``read_bucket`` recovers it from the source.
    """
    ledger = pipeline.ledger
    writer: Any = None
    mem: Dict[int, List[pa.Table]] = {}
    mem_bytes: Dict[int, int] = {}
    disk_bound: set = set()
    bucket_rows = [0] * n_buckets
    n_chunks = 0

    def _writer() -> Any:
        nonlocal writer
        if writer is None:
            writer = pipeline.writer(spill_dir, side, pa_schema, injector)
        return writer

    def _demote_one() -> bool:
        if not mem_bytes:
            return False
        j = max(mem_bytes, key=lambda k: mem_bytes[k])
        for p in mem.pop(j):
            _writer().submit(j, p)
        ledger.release(mem_bytes.pop(j))
        disk_bound.add(j)
        ledger.note_demotion()
        if stats is not None:
            stats.inc("mem_demotions")
        return True

    try:
        for tbl in chunks:
            if tbl.num_rows == 0:
                continue
            n_chunks += 1
            if tbl.schema != pa_schema:
                tbl = tbl.cast(pa_schema)
            for i, part in _chunk_bucket_parts(tbl, keys, kinds, n_buckets):
                bucket_rows[i] += part.num_rows
                admitted = False
                nb = int(part.nbytes)
                if i not in disk_bound:
                    while True:
                        if ledger.admit(nb):
                            admitted = True
                            break
                        if not _demote_one():
                            break
                if admitted and i in disk_bound:
                    # the demotion loop evicted THIS bucket while making
                    # room — a bucket is mem- or disk-resident, never both
                    ledger.release(nb)
                    admitted = False
                if admitted:
                    mem.setdefault(i, []).append(part)
                    mem_bytes[i] = mem_bytes.get(i, 0) + nb
                else:
                    _writer().submit(i, part)
                    disk_bound.add(i)
    except BaseException:
        if writer is not None:
            writer.abort()
        ledger.release(sum(mem_bytes.values()))
        raise

    published: Dict[int, int] = {}
    batches = 0
    try:
        if writer is not None:
            published, wfaults, batches = writer.finalize()
            if stats is not None and wfaults:
                stats.inc("spill_faults", wfaults)
    except BaseException:
        ledger.release(sum(mem_bytes.values()))
        raise
    # mem-tier retention: the fault site fires per resident bucket, in
    # bucket order; a fault drops the bucket (release + lazy recovery)
    mem_total = 0
    for i in sorted(mem):
        try:
            if injector is not None:
                injector.fire(SITE_SHUFFLE_SPILL)
            mem_total += mem_bytes[i]
        except Exception:
            ledger.release(mem_bytes[i])
            del mem[i]
            del mem_bytes[i]
            if stats is not None:
                stats.inc("spill_faults")
    bytes_spilled = sum(published.values()) + mem_total
    if stats is not None:
        stats.inc("partitions")
        stats.inc("chunks", n_chunks)
        stats.inc("rows_spilled", sum(bucket_rows))
        stats.inc("bytes_spilled", bytes_spilled)
        stats.inc("buckets", sum(1 for r in bucket_rows if r > 0))
        stats.inc("mem_buckets", len(mem))
        stats.inc("mem_bucket_bytes", mem_total)
        stats.inc("writebehind_batches", batches)
    return SpilledSide(
        spill_dir,
        side,
        pa_schema,
        keys,
        kinds,
        n_buckets,
        bucket_rows,
        bytes_spilled,
        replay,
        mem_tables=mem,
        ledger=ledger,
        mem_bytes=mem_total,
        mem_bucket_bytes=mem_bytes,
    )
