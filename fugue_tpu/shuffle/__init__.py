"""Out-of-core hash shuffle (docs/shuffle.md): spill-partitioned
repartition and joins past device memory.

- :mod:`.partitioner` — streaming hash partitioner: any input, chunk by
  chunk, into P atomically-published arrow IPC bucket files; torn-bucket
  detection + single-bucket recovery.
- :mod:`.join` — bucket-at-a-time spill joins over the existing device
  kernels, and spill-based hash repartition.
- :mod:`.pipeline` — the pipelined-exchange primitives (ISSUE 15):
  write-behind spill writer, the memory-resident bucket tier's byte
  ledger, and the per-exchange pipeline context; kill-switch
  ``fugue.tpu.shuffle.pipeline.enabled=false`` restores the strict
  phase-barrier path bit-identically.
- :mod:`.exchange` — the device-resident staged exchange (ISSUE 17):
  rows past the per-device budget but within aggregate mesh memory move
  with a one-hop-at-a-time ``ppermute`` schedule whose per-stage payload
  stays under the budget (arXiv:2112.01075) — zero host round trips;
  kill-switch ``fugue.tpu.shuffle.device_exchange.enabled``.
- :mod:`.strategy` — the ONE broadcast/copartition/device_exchange/
  shuffle_spill decision rule, shared by plan time
  (``workflow.explain()``) and run time (``engine.join``).
- :mod:`.stats` — ``engine.stats()["shuffle"]`` counters.
"""

from .partitioner import (
    SpilledSide,
    bucket_ids,
    canonical_key_kinds,
    new_spill_dir,
    remove_spill_dir,
    spill_dir_bytes,
    spill_partition,
)
from .exchange import staged_copartition_by_keys, staged_exchange_rows
from .join import shuffle_spill_join, spill_repartition
from .pipeline import MemBucketLedger, SpillPipeline, SpillWriter
from .stats import ShuffleStats
from .strategy import (
    JoinDecision,
    broadcast_max_rows,
    bucket_count,
    choose_join_strategy,
    device_budget_bytes,
    device_budget_info,
    device_exchange_enabled,
    estimate_frame_bytes,
    estimate_frame_rows,
    exchange_stage_bytes,
    mem_bucket_cap_bytes,
    pair_prefetch_depth,
    pipeline_enabled,
    shuffle_enabled,
    spill_dir_root,
    target_bucket_bytes,
    writebehind_depth,
)

__all__ = [
    "SpilledSide",
    "bucket_ids",
    "canonical_key_kinds",
    "new_spill_dir",
    "remove_spill_dir",
    "spill_dir_bytes",
    "spill_partition",
    "shuffle_spill_join",
    "spill_repartition",
    "ShuffleStats",
    "JoinDecision",
    "broadcast_max_rows",
    "bucket_count",
    "choose_join_strategy",
    "device_budget_bytes",
    "device_budget_info",
    "device_exchange_enabled",
    "estimate_frame_bytes",
    "estimate_frame_rows",
    "exchange_stage_bytes",
    "staged_copartition_by_keys",
    "staged_exchange_rows",
    "shuffle_enabled",
    "spill_dir_root",
    "target_bucket_bytes",
    "MemBucketLedger",
    "SpillPipeline",
    "SpillWriter",
    "mem_bucket_cap_bytes",
    "pair_prefetch_depth",
    "pipeline_enabled",
    "writebehind_depth",
]
