"""Join-strategy selection: ONE decision function shared by plan time
and run time.

The ladder (docs/shuffle.md):

- ``broadcast`` — right side replicated to every device; cheapest when it
  fits (``fugue.tpu.join.broadcast_max_rows`` rows AND under the device
  budget).
- ``copartition`` — both sides device-resident at once, co-partitioned by
  key hash with the in-device all-to-all, probed shard-locally.
- ``device_exchange`` — sides exceed the per-device budget but fit
  AGGREGATE mesh memory (budget × shards): rows stay device-resident and
  move with the staged one-hop-at-a-time schedule
  (``fugue_tpu/shuffle/exchange.py``, arXiv:2112.01075) whose per-stage
  collective payload is capped by the same device budget — zero host
  round trips. Kill-switched by
  ``fugue.tpu.shuffle.device_exchange.enabled``.
- ``shuffle_spill`` — neither bound holds: both sides stream through the
  on-disk hash partitioner (``fugue_tpu/shuffle/partitioner.py``) and
  matching buckets join one pair at a time under the device budget.

The plan optimizer calls :func:`choose_join_strategy` with schema+file
size estimates and records the choice in ``PlanReport`` /
``workflow.explain()``; ``engine.join`` calls it again with live frame
sizes — the runtime decision is authoritative, the plan note is the
explainable prediction, and both can never disagree about the RULE
because there is only one implementation.
"""

from typing import Any, NamedTuple, Optional

from typing import Tuple

from ..constants import (
    FUGUE_TPU_CONF_JOIN_BROADCAST_MAX_ROWS,
    FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES,
    FUGUE_TPU_CONF_SHUFFLE_BUCKETS,
    FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET,
    FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED,
    FUGUE_TPU_CONF_SHUFFLE_DIR,
    FUGUE_TPU_CONF_SHUFFLE_ENABLED,
    FUGUE_TPU_CONF_SHUFFLE_EXCHANGE_STAGE_BYTES,
    FUGUE_TPU_CONF_SHUFFLE_MEM_BUCKET_BYTES,
    FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED,
    FUGUE_TPU_CONF_SHUFFLE_PREFETCH_DEPTH,
    FUGUE_TPU_CONF_SHUFFLE_WRITEBEHIND_DEPTH,
)

__all__ = [
    "JoinDecision",
    "broadcast_max_rows",
    "shuffle_enabled",
    "spill_dir_root",
    "device_budget_bytes",
    "device_budget_info",
    "device_exchange_enabled",
    "exchange_stage_bytes",
    "default_mesh_shards",
    "target_bucket_bytes",
    "bucket_count",
    "estimate_frame_bytes",
    "estimate_frame_rows",
    "choose_join_strategy",
    "pipeline_enabled",
    "mem_bucket_cap_bytes",
    "pair_prefetch_depth",
    "writebehind_depth",
]

DEFAULT_BUCKET_BYTES = 1 << 26  # 64 MiB on disk per bucket
MAX_BUCKETS = 4096
DEFAULT_MEM_BUCKET_CAP = 1 << 28  # mem-tier auto ledger ceiling: 256 MiB
DEFAULT_WRITEBEHIND_DEPTH = 8


class JoinDecision(NamedTuple):
    strategy: str  # broadcast | copartition | device_exchange | shuffle_spill
    reason: str


def _conf_get(conf: Any, key: str, default: Any) -> Any:
    if conf is None:
        return default
    try:
        return conf.get(key, default)
    except Exception:
        return default


def broadcast_max_rows(conf: Any) -> int:
    """Conf-driven broadcast threshold (default: the historical
    ``ops/join.py MAX_BROADCAST_ROWS`` constant)."""
    from ..ops.join import MAX_BROADCAST_ROWS

    return int(_conf_get(conf, FUGUE_TPU_CONF_JOIN_BROADCAST_MAX_ROWS, MAX_BROADCAST_ROWS))


def shuffle_enabled(conf: Any) -> bool:
    return bool(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_ENABLED, True))


def spill_dir_root(conf: Any) -> str:
    import os
    import tempfile

    d = str(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_DIR, "") or "")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "fugue_tpu_shuffle")
    return d


def _auto_device_budget() -> Tuple[int, str]:
    """Best-effort device byte budget when none is configured, plus the
    source that won: the backend's reported memory limit
    (``device_memory_stats`` — TPU/GPU ``bytes_limit``) is preferred,
    else half of host MemTotal (CPU "devices" are host RAM), else a
    conservative constant."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit), "device_memory_stats"
    except Exception:
        pass
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024 // 2, "host_meminfo"
    except Exception:
        pass
    return 1 << 34, "fallback"  # 16 GiB — conservative fallback


def device_budget_info(conf: Any) -> Tuple[int, str]:
    """(budget bytes, source) — source is ``conf`` when explicitly set,
    else whichever auto-detection rung won (``device_memory_stats`` /
    ``host_meminfo`` / ``fallback``). Recorded in
    ``engine.stats()["shuffle"]`` so a mis-detected budget is observable."""
    b = int(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_DEVICE_BUDGET, 0) or 0)
    if b > 0:
        return b, "conf"
    return _auto_device_budget()


def device_budget_bytes(conf: Any) -> int:
    return device_budget_info(conf)[0]


def device_exchange_enabled(conf: Any) -> bool:
    """``fugue.tpu.shuffle.device_exchange.enabled`` — the staged-
    exchange rung's kill-switch. False restores the three-rung ladder:
    joins in the exchange band spill, bit-identically to pre-exchange."""
    return bool(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_DEVICE_EXCHANGE_ENABLED, True))


def exchange_stage_bytes(conf: Any) -> int:
    """Per-stage collective payload cap for the staged exchange, per
    device. Explicit conf wins; else 1/8 of the device budget — small
    enough that a stage buffer never threatens the budget, large enough
    that the schedule's per-stage fixed cost (collective sync + the
    append pass) amortizes: measured on an 8-shard mesh, 1/32 cost ~60%
    more wall than 1/8 purely in stage count. Floored so tiny budgets
    keep a workable stage."""
    t = int(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_EXCHANGE_STAGE_BYTES, 0) or 0)
    if t > 0:
        return t
    return max(1 << 16, device_budget_bytes(conf) // 8)


def default_mesh_shards() -> int:
    """Plan-time shard-count estimate (the default mesh spans every
    device). The runtime decision uses the engine's REAL mesh; this keeps
    the ``workflow.explain()`` prediction honest on multi-device hosts."""
    try:
        import jax

        return max(1, len(jax.devices()))
    except Exception:
        return 1


def target_bucket_bytes(conf: Any) -> int:
    t = int(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_BUCKET_BYTES, 0) or 0)
    if t > 0:
        return t
    # a bucket PAIR plus join intermediates (pow2-padded hash tables,
    # expansion output for duplicate keys) must fit the budget TOGETHER —
    # measured ~8-14x one bucket's bytes for dup-heavy joins, so default
    # to 1/32 of the budget, floored so tiny budgets stay practical
    return max(1 << 16, min(DEFAULT_BUCKET_BYTES, device_budget_bytes(conf) // 32))


def pipeline_enabled(conf: Any) -> bool:
    """``fugue.tpu.shuffle.pipeline.enabled`` — the pipelined-exchange
    kill-switch (docs/shuffle.md "Pipelined exchange"). False restores
    the strict phase-barrier spill path bit-identically."""
    return bool(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_PIPELINE_ENABLED, True))


def mem_bucket_cap_bytes(conf: Any) -> int:
    """Host-byte ledger cap for the memory-resident bucket tier. 0/unset
    = auto (1/16 of host MemTotal, at most 256MiB — the tier is a cache,
    not a license to buffer a whole exchange); negative disables."""
    raw = int(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_MEM_BUCKET_BYTES, 0) or 0)
    if raw < 0:
        return 0
    if raw > 0:
        return raw
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return min(DEFAULT_MEM_BUCKET_CAP, int(line.split()[1]) * 1024 // 16)
    except Exception:
        pass
    return DEFAULT_MEM_BUCKET_CAP


def pair_prefetch_depth(conf: Any) -> int:
    """Bucket-pair prefetch depth for the pipelined spill join. Unset →
    the stream prefetcher's auto default (0 on single-core cpu-mesh
    hosts, where a producer thread only steals consumer time)."""
    raw = _conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_PREFETCH_DEPTH, None)
    if raw is None:
        from ..jax.pipeline import default_prefetch_depth

        return default_prefetch_depth()
    return int(raw)


def writebehind_depth(conf: Any) -> int:
    """Bounded write-behind queue depth (bucket batches in flight to the
    background spill writer before the partitioner blocks)."""
    d = int(
        _conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_WRITEBEHIND_DEPTH, 0)
        or DEFAULT_WRITEBEHIND_DEPTH
    )
    return max(1, d)


def bucket_count(conf: Any, est_bytes: Optional[int]) -> int:
    """P for one shuffle: explicit conf wins; else size/target, bounded;
    16 when the size is unknowable (one-pass streams)."""
    p = int(_conf_get(conf, FUGUE_TPU_CONF_SHUFFLE_BUCKETS, 0) or 0)
    if p > 0:
        return min(p, MAX_BUCKETS)
    if not est_bytes or est_bytes <= 0:
        return 16
    return max(1, min(MAX_BUCKETS, -(-est_bytes // target_bucket_bytes(conf))))


def estimate_frame_bytes(df: Any) -> Optional[int]:
    """Cheap host-side byte estimate of a frame; None = unknowable
    without consuming it (one-pass streams). Never materializes."""
    try:
        nb = getattr(df, "device_nbytes", None)
        if nb is not None:
            total = int(nb)
            has_pending = getattr(df, "_has_pending", None)
            if has_pending is None or not has_pending():
                # host-resident residual columns — but ONLY once the frame
                # is already ingested: the host_table property of a pending
                # frame forces ingestion (the very device residency this
                # estimate exists to avoid)
                try:
                    host_tbl = getattr(df, "_host_tbl", None)
                    if host_tbl is not None:
                        total += int(host_tbl.nbytes)
                except Exception:
                    pass
            return total
    except Exception:
        pass
    for attr in ("native",):
        native = getattr(df, attr, None)
        if native is None:
            continue
        try:
            import pandas as pd
            import pyarrow as pa

            if isinstance(native, pa.Table):
                return int(native.nbytes)
            if isinstance(native, pd.DataFrame):
                return int(native.memory_usage(index=False, deep=False).sum())
        except Exception:
            pass
    return None


def estimate_frame_rows(df: Any) -> Optional[int]:
    try:
        if getattr(df, "is_bounded", False):
            return int(df.count())
    except Exception:
        pass
    return None


def choose_join_strategy(
    conf: Any,
    est_left_bytes: Optional[int],
    est_right_bytes: Optional[int],
    est_right_rows: Optional[int],
    streaming: bool = False,
    n_shards: int = 1,
) -> JoinDecision:
    """The one strategy rule. Unknown estimates choose conservatively:
    an unknown BOUNDED side is assumed to fit (runtime re-checks with the
    real size); a one-pass stream (``streaming=True``) with no eligible
    streaming plan can only spill — materializing it is the unbounded-
    memory hazard this subsystem removes.

    ``n_shards`` opens the ``device_exchange`` rung between copartition
    and spill: sides past the per-device budget but within AGGREGATE mesh
    memory (budget × shards) stay device-resident and move with the
    staged exchange. ``n_shards=1`` (the default) keeps the historical
    three-rung ladder — on a single device the aggregate IS the budget."""
    budget, budget_src = device_budget_info(conf)
    bmax = broadcast_max_rows(conf)
    if not shuffle_enabled(conf):
        if est_right_rows is not None and est_right_rows <= bmax:
            return JoinDecision("broadcast", f"right ~{est_right_rows} rows <= {bmax}")
        return JoinDecision("copartition", "shuffle disabled (fugue.tpu.shuffle.enabled=false)")
    if streaming:
        return JoinDecision(
            "shuffle_spill", "one-pass stream with no eligible streaming join plan"
        )
    r_fits_bc = (
        est_right_rows is not None
        and est_right_rows <= bmax
        and (est_right_bytes is None or est_right_bytes <= budget)
    )
    if r_fits_bc:
        return JoinDecision(
            "broadcast", f"right ~{est_right_rows} rows <= broadcast_max_rows {bmax}"
        )
    both = (est_left_bytes or 0) + (est_right_bytes or 0)
    if (est_left_bytes is None and est_right_bytes is None) or both <= budget:
        return JoinDecision(
            "copartition", f"both sides ~{both}B fit device budget {budget}B"
        )
    aggregate = budget * max(1, int(n_shards))
    if (
        device_exchange_enabled(conf)
        and int(n_shards) > 1
        and both <= aggregate
    ):
        return JoinDecision(
            "device_exchange",
            f"sides ~{both}B exceed per-device budget {budget}B "
            f"({budget_src}) but fit aggregate mesh memory {aggregate}B "
            f"across {n_shards} shards",
        )
    return JoinDecision(
        "shuffle_spill",
        f"sides ~{both}B exceed device budget {budget}B ({budget_src})",
    )
