"""Shuffle counters — an ``engine.metrics`` source (``engine.stats()["shuffle"]``).

Follows the system-wide reset contract (``JitCache.reset``): counters
zero, nothing structural is dropped. ``peak_device_bytes`` is a
high-water gauge (max over bucket joins since the last reset) — the
proof artifact that bucket-at-a-time execution really bounds the device
working set.
"""

import threading
from typing import Dict

__all__ = ["ShuffleStats"]

_COUNTERS = (
    "partitions",  # sides spilled to buckets
    "chunks",  # input chunks consumed by the partitioner
    "rows_spilled",
    "bytes_spilled",  # bucket payload bytes routed (disk-encoded + mem-resident)
    "buckets",  # buckets materialized (disk files + mem-resident)
    "bucket_joins",  # bucket pairs joined
    "bucket_rows_out",
    "bucket_recoveries",  # torn/corrupt/missing buckets repartitioned
    "spill_faults",  # injected shuffle.spill faults absorbed
    "spill_dirs_cleaned",
    "joins_spill",  # joins executed with the spill-shuffle strategy
    "repartitions_spill",
    # --- pipelined exchange (docs/shuffle.md "Pipelined exchange") ---
    "pipelined_joins",  # spill joins that ran the overlapped pipeline
    "group_joins",  # coalesced pair-group kernel launches
    "mem_buckets",  # buckets retained in the memory-resident tier
    "mem_bucket_bytes",  # arrow bytes those buckets held (never hit disk)
    "mem_bucket_hits",  # bucket reads served from the mem tier
    "mem_demotions",  # mem buckets demoted to disk under ledger pressure
    "writebehind_batches",  # batches routed through the background writer
)


class ShuffleStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + int(n)

    def peak(self, nbytes: int) -> None:
        with self._lock:
            if nbytes > self._peak:
                self._peak = int(nbytes)

    def get(self, name: str) -> int:
        with self._lock:
            if name == "peak_device_bytes":
                return self._peak
            return self._c.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            out = {k: self._c.get(k, 0) for k in _COUNTERS}
            out["peak_device_bytes"] = self._peak
            return out

    def reset(self) -> None:
        with self._lock:
            self._c: Dict[str, int] = {}
            self._peak = 0
