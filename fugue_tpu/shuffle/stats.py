"""Shuffle counters — an ``engine.metrics`` source (``engine.stats()["shuffle"]``).

Follows the system-wide reset contract (``JitCache.reset``): counters
zero, nothing structural is dropped. ``peak_device_bytes`` is a
high-water gauge (max over bucket joins since the last reset) — the
proof artifact that bucket-at-a-time execution really bounds the device
working set.
"""

import threading
from typing import Dict

__all__ = ["ShuffleStats"]

_COUNTERS = (
    "partitions",  # sides spilled to buckets
    "chunks",  # input chunks consumed by the partitioner
    "rows_spilled",
    "bytes_spilled",  # bucket payload bytes routed (disk-encoded + mem-resident)
    "buckets",  # buckets materialized (disk files + mem-resident)
    "bucket_joins",  # bucket pairs joined
    "bucket_rows_out",
    "bucket_recoveries",  # torn/corrupt/missing buckets repartitioned
    "spill_faults",  # injected shuffle.spill faults absorbed
    "spill_dirs_cleaned",
    "joins_spill",  # joins executed with the spill-shuffle strategy
    "repartitions_spill",
    # --- pipelined exchange (docs/shuffle.md "Pipelined exchange") ---
    "pipelined_joins",  # spill joins that ran the overlapped pipeline
    "group_joins",  # coalesced pair-group kernel launches
    "mem_buckets",  # buckets retained in the memory-resident tier
    "mem_bucket_bytes",  # arrow bytes those buckets held (never hit disk)
    "mem_bucket_hits",  # bucket reads served from the mem tier
    "mem_demotions",  # mem buckets demoted to disk under ledger pressure
    "writebehind_batches",  # batches routed through the background writer
    # --- device-resident exchange (docs/shuffle.md "Device exchange") ---
    "device_exchange_joins",  # joins executed with the device_exchange strategy
    "device_exchange_fallbacks",  # exchange-band joins forced back to spill
    "device_exchange_stages",  # staged-schedule collective launches (hops × rounds)
    "device_exchange_rows",  # rows moved through the staged exchange
    "device_exchange_bytes",  # payload bytes moved (rows × row width)
    "mem_bucket_ingest_hits",  # pair reads served from the decoded-form cache
)


class ShuffleStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + int(n)

    def peak(self, nbytes: int) -> None:
        with self._lock:
            if nbytes > self._peak:
                self._peak = int(nbytes)

    def peak_exchange(self, nbytes: int) -> None:
        """High-water per-stage collective payload of the staged device
        exchange — the proof artifact that the one-hop-at-a-time schedule
        really bounds peak per-device exchange bytes."""
        with self._lock:
            if nbytes > self._peak_exchange:
                self._peak_exchange = int(nbytes)

    def set_budget(self, nbytes: int, source: str) -> None:
        """Record the resolved device budget and which detection source
        won (``conf`` / ``device_memory_stats`` / ``host_meminfo`` /
        ``fallback``). Survives ``reset()`` — it is configuration, not a
        counter."""
        with self._lock:
            self._budget_bytes = int(nbytes)
            self._budget_source = str(source)

    def get(self, name: str) -> int:
        with self._lock:
            if name == "peak_device_bytes":
                return self._peak
            if name == "device_exchange_peak_stage_bytes":
                return self._peak_exchange
            return self._c.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            out = {k: self._c.get(k, 0) for k in _COUNTERS}
            out["peak_device_bytes"] = self._peak
            out["device_exchange_peak_stage_bytes"] = self._peak_exchange
            out["device_budget_bytes"] = self._budget_bytes
            # string leaf: /metrics flattening skips non-numerics, so the
            # source shows in engine.stats() without breaking exposition
            out["device_budget_source"] = self._budget_source  # type: ignore[assignment]
            return out

    def reset(self) -> None:
        with self._lock:
            self._c: Dict[str, int] = {}
            self._peak = 0
            self._peak_exchange = 0
            self._budget_bytes = getattr(self, "_budget_bytes", 0)
            self._budget_source = getattr(self, "_budget_source", "unset")
