from . import viz  # registers viz:* outputters
