"""``viz:*`` namespaced outputters — plot dataframes from workflows.

Parity with the reference (`fugue_contrib/viz/__init__.py:12-14`): strings
like ``"viz:bar"`` parse as outputters that call pandas ``.plot``. Gated on
matplotlib availability (not present in every environment).
"""

from typing import Any

from ..dataframe import DataFrames
from ..extensions.outputter.convert import parse_outputter
from ..extensions.outputter.outputter import Outputter
from ..plugins import namespace_candidate

_PLOT_KINDS = {
    "line", "bar", "barh", "hist", "box", "kde", "density", "area",
    "pie", "scatter", "hexbin",
}


class _VizOutputter(Outputter):
    def __init__(self, kind: str):
        self._kind = kind

    def process(self, dfs: DataFrames) -> None:
        try:
            import matplotlib  # noqa: F401
        except ImportError as e:
            raise NotImplementedError(
                "viz:* outputters require matplotlib"
            ) from e
        for df in dfs.values():
            df.as_pandas().plot(kind=self._kind, **dict(self.params))


@parse_outputter.candidate(namespace_candidate("viz", lambda x: x in _PLOT_KINDS))
def _parse_viz(obj: str) -> Outputter:
    return _VizOutputter(obj.split(":", 1)[1])
