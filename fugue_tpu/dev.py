"""Everything needed to develop and extend fugue_tpu, in one import.

The extension-developer facade (reference ``fugue/dev.py``): backend
authors get the engine contract, the annotated-param machinery, the raw
SQL/partition collections, RPC, and the workflow internals without
hunting through submodules. The user-facing surface lives in
``fugue_tpu.api``; the plugin hooks in ``fugue_tpu.plugins``.
"""

# flake8: noqa

from .bag.bag import BagDisplay
from .collections.partition import PartitionCursor, PartitionSpec
from .collections.sql import StructuredRawSQL, TempTableName, transpile_sql
from .collections.yielded import PhysicalYielded, Yielded
from .dataframe.function_wrapper import (
    AnnotatedParam,
    DataFrameFunctionWrapper,
    DataFrameParam,
    LocalDataFrameParam,
    fugue_annotated_param,
)
from .dataset import DatasetDisplay
from .execution import ExecutionEngineParam
from .execution.execution_engine import (
    EngineFacet,
    ExecutionEngine,
    MapEngine,
    SQLEngine,
)
from .execution.factory import (
    is_pandas_or,
    make_execution_engine,
    make_sql_engine,
    register_default_execution_engine,
    register_default_sql_engine,
    register_execution_engine,
    register_sql_engine,
)
from .execution.native_execution_engine import (
    NativeExecutionEngine,
    PandasMapEngine,
)
from .rpc import (
    EmptyRPCHandler,
    RPCClient,
    RPCFunc,
    RPCHandler,
    RPCServer,
    make_rpc_server,
    to_rpc_handler,
)
from .serve import (
    EngineServer,
    ServeHttpClient,
    ServeRejected,
    Submission,
    SubmissionCanceled,
)
from .sql.dialect import DialectProfile, register_dialect
from .warehouse.profile import WarehouseProfile
from .workflow._workflow_context import FugueWorkflowContext
from .workflow.module import module
from .workflow.workflow import (
    FugueWorkflow,
    WorkflowDataFrame,
    WorkflowDataFrames,
)
