from .env import NotebookSetup, setup
