from .env import NotebookSetup, _load_ipython_extension, setup


def _jupyter_nbextension_paths():  # pragma: no cover - jupyter hook
    """Classic-notebook extension registration (reference
    ``fugue_notebook/__init__.py``)."""
    return [
        dict(
            section="notebook",
            src="nbextension",
            dest="fugue_tpu",
            require="fugue_tpu/main",
        )
    ]


def load_ipython_extension(ip):  # pragma: no cover - ipython hook
    _load_ipython_extension(ip)
