// fugue_tpu nbextension: register %%fsql cells as SQL-highlighted
// (parity with the reference's fugue_notebook/nbextension/main.js)
define(["codemirror/lib/codemirror", "base/js/namespace"], function (
  CodeMirror,
  Jupyter
) {
  "use strict";
  function load() {
    CodeMirror.defineMode("fsql", function (config) {
      return CodeMirror.getMode(config, "text/x-sql");
    });
    CodeMirror.modeInfo.push({
      name: "Fugue SQL",
      mime: "text/x-fsql",
      mode: "fsql",
    });
    var magic = /^%%fsql/;
    function hl(cell) {
      if (cell.get_text !== undefined && magic.test(cell.get_text())) {
        cell.code_mirror.setOption("mode", "fsql");
      }
    }
    Jupyter.notebook.get_cells().forEach(hl);
    Jupyter.notebook.events.on("create.Cell", function (_, d) {
      hl(d.cell);
    });
  }
  return { load_ipython_extension: load };
});
