"""Notebook integration: the ``%%fsql`` cell magic.

Parity with the reference (`fugue_notebook/env.py:53-66`): running a
``%%fsql [engine]`` cell compiles+runs FugueSQL and injects yielded
dataframes into the notebook namespace. Gated on IPython availability.
"""

from typing import Any, Optional


def _setup_magic() -> bool:
    try:
        from IPython import get_ipython
        from IPython.core.magic import Magics, cell_magic, magics_class
    except ImportError:
        return False
    ip = get_ipython()
    if ip is None:
        return False

    from ..sql.fsql import FugueSQLCompiler, fill_sql_template
    from ..sql import FugueSQLWorkflow

    @magics_class
    class _FugueSQLMagics(Magics):
        @cell_magic("fsql")
        def fsql(self, line: str, cell: str) -> None:
            engine = line.strip() or None
            ns = self.shell.user_ns
            dag = FugueSQLWorkflow()
            code = fill_sql_template(cell, dict(ns))
            compiler = FugueSQLCompiler(dag, {}, dict(ns), dict(ns))
            compiler.compile(code)
            result = dag.run(engine)
            for name, yielded in result.yields.items():
                ns[name] = yielded

    ip.register_magics(_FugueSQLMagics)
    return True


class NotebookSetup:
    """Call ``setup()`` in a notebook to enable ``%%fsql``."""

    def setup(self) -> bool:
        return _setup_magic()


def setup(**kwargs: Any) -> bool:
    return NotebookSetup().setup()
