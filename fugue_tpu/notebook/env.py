"""Notebook integration: the ``%%fsql`` cell magic + HTML display chain.

Parity with the reference (`fugue_notebook/env.py:53-130`): running a
``%%fsql [engine]`` cell compiles+runs FugueSQL and injects yielded
dataframes into the notebook namespace; inside IPython, ``df.show()`` and
the rich-repr hook render DataFrames as HTML tables with the schema
footer. Gated on IPython availability.
"""

import html as _html
from typing import Any, List, Optional


def _setup_magic() -> bool:
    try:
        from IPython import get_ipython
        from IPython.core.magic import Magics, cell_magic, magics_class
    except ImportError:
        return False
    ip = get_ipython()
    if ip is None:
        return False

    from ..sql.fsql import FugueSQLCompiler, fill_sql_template
    from ..sql import FugueSQLWorkflow

    @magics_class
    class _FugueSQLMagics(Magics):
        @cell_magic("fsql")
        def fsql(self, line: str, cell: str) -> None:
            engine = line.strip() or None
            ns = self.shell.user_ns
            dag = FugueSQLWorkflow()
            code = fill_sql_template(cell, dict(ns))
            compiler = FugueSQLCompiler(dag, {}, dict(ns), dict(ns))
            compiler.compile(code)
            result = dag.run(engine)
            for name, yielded in result.yields.items():
                ns[name] = yielded

    ip.register_magics(_FugueSQLMagics)
    return True


def _setup_display() -> bool:
    """Register the Jupyter HTML renderer on the display plugin chain
    (reference ``fugue_notebook/env.py:91-126``)."""
    try:
        from IPython import get_ipython
        from IPython.display import HTML, display
    except ImportError:
        return False
    if get_ipython() is None:
        return False

    from ..dataframe import DataFrame
    from ..dataframe.dataframe import DataFrameDisplay
    from ..dataset.dataset import Dataset, get_dataset_display

    class JupyterDataFrameDisplay(DataFrameDisplay):
        def show(
            self, n: int = 10, with_count: bool = False, title: Optional[str] = None
        ) -> None:
            components: List[Any] = []
            if title is not None:
                components.append(HTML(f"<h3>{_html.escape(title)}</h3>"))
            components.append(HTML(self._df_html(n)))
            if with_count:
                components.append(
                    HTML(f"<strong>total count: {self.df.count()}</strong>")
                )
            display(*components)

        def repr_html(self) -> str:
            return self._df_html(10)

        def _df_html(self, n: int) -> str:
            pdf = self.df.head(n).as_pandas()
            body = pdf._repr_html_()
            schema = type(self.df).__name__ + ": " + str(self.df.schema)
            return body + '\n<font size="-1">' + _html.escape(schema) + "</font>"

    @get_dataset_display.candidate(
        lambda ds: get_ipython() is not None and isinstance(ds, DataFrame),
        priority=3.0,
    )
    def _jupyter_display(ds: Dataset) -> DataFrameDisplay:
        return JupyterDataFrameDisplay(ds)

    return True


_HIGHLIGHT_JS = r"""
require(["codemirror/lib/codemirror"], function (CodeMirror) {
  CodeMirror.defineMode("fsql", function (config) {
    return CodeMirror.getMode(config, "text/x-sql");
  });
  CodeMirror.modeInfo.push({name: "Fugue SQL", mime: "text/x-fsql", mode: "fsql"});
  var magic = /^%%fsql/;
  function hl(cell) {
    if (cell.get_text !== undefined && magic.test(cell.get_text())) {
      cell.code_mirror.setOption("mode", "fsql");
    }
  }
  if (window.Jupyter !== undefined) {
    Jupyter.notebook.get_cells().forEach(hl);
    Jupyter.notebook.events.on("create.Cell", function (_, d) { hl(d.cell); });
  }
});
"""


def _load_ipython_extension(ip: Any) -> None:
    """``%load_ext fugue_tpu.notebook`` entrypoint-compatible hook."""
    _setup_magic()
    _setup_display()


class NotebookSetup:
    """Call ``setup()`` in a notebook to enable ``%%fsql`` + HTML display."""

    def setup(self) -> bool:
        ok = _setup_magic()
        _setup_display()
        return ok

    def register_execution_engines(self) -> None:  # reference-parity hook
        pass

    @property
    def highlight_js(self) -> str:
        """The codemirror highlight snippet the nbextension injects
        (reference ``fugue_notebook/nbextension/main.js``)."""
        return _HIGHLIGHT_JS


def setup(run_js: bool = False, **kwargs: Any) -> bool:
    res = NotebookSetup().setup()
    if res and run_js:
        try:
            from IPython.display import Javascript, display

            display(Javascript(_HIGHLIGHT_JS))
        except ImportError:  # pragma: no cover
            pass
    return res
