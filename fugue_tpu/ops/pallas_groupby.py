"""Binned (dense groupby) reductions via one-hot MXU matmuls — the
TPU-native alternative to XLA scatter-add.

Why: scatter on TPU serializes through the VPU's scalar update path,
while a histogram expressed as ``one_hot(keys) @ values`` rides the MXU
systolic array (the reference's analog of this choice is delegating
grouping to DuckDB's vectorized C++ engine,
``/root/reference/fugue_duckdb/execution_engine.py:137``; here the
hardware-matched primitive IS the design). Two implementations with one
contract:

- :func:`bin_sum_count_xla` — chunked ``lax.scan`` over rows, one-hot
  compare + matmul per chunk; pure jnp, runs on every backend, and XLA
  fuses the compare into the matmul operand feed.
- :func:`bin_sum_count_pallas` — a Pallas TPU kernel: grid over row
  chunks, one-hot partial products accumulated into a VMEM-resident
  ``(buckets,)`` table across sequential grid steps (no HBM one-hot is
  ever materialized). ``interpret=True`` makes it testable on CPU.

Both compute per-bucket SUM and COUNT of float32 values in one pass.
float32 only: the MXU has no 64-bit path — f64 aggregation keeps the
scatter/XLA-emulation route (see ``ops/segment.py``), a deliberate
precision/speed split the engine picks per column dtype.

Exactness bound: the COUNT table also accumulates in float32 through the
matmul, so counts are exact only up to 2**24 rows per bucket — above
that, float32 cannot represent every integer and increments are lost.
The engine's dense-groupby path is NOT exposed to this: it keeps COUNT
in an int64 scatter (``segment.py``) and only routes the f32 SUM through
these kernels. Direct callers needing bigger per-bucket counts should
split their input or use the engine path.
"""

from typing import Any, Tuple

import jax

CHUNK = 1024  # rows per grid step; multiple of the f32 sublane tile (8)


def _pad_inputs(keys: Any, values: Any, valid: Any, buckets: int):
    import jax.numpy as jnp

    n = keys.shape[0]
    padded = ((n + CHUNK - 1) // CHUNK) * CHUNK
    pad = padded - n
    if pad > 0:
        keys = jnp.pad(keys, (0, pad))
        values = jnp.pad(values, (0, pad))
        valid = jnp.pad(valid, (0, pad))  # False
    # invalid rows contribute 0 via the mask; clamp keys so the one-hot
    # compare never sees out-of-range ids
    keys = jnp.clip(keys, 0, buckets - 1).astype(jnp.int32)
    return keys, values, valid, padded // CHUNK


def bin_sum_count_xla(
    keys: Any, values: Any, valid: Any, buckets: int
) -> Tuple[Any, Any]:
    """Per-bucket (sum, count) of ``values`` grouped by ``keys`` via
    chunked one-hot matmuls. ``buckets`` must be a multiple of 128 on
    real TPUs for MXU alignment (any value works functionally)."""
    import jax
    import jax.numpy as jnp

    keys, values, valid, n_chunks = _pad_inputs(keys, values, valid, buckets)
    kc = keys.reshape(n_chunks, CHUNK)
    vc = values.astype(jnp.float32).reshape(n_chunks, CHUNK)
    mc = valid.astype(jnp.float32).reshape(n_chunks, CHUNK)
    iota = jnp.arange(buckets, dtype=jnp.int32)

    # vmap-over-chunks (not a scan): a scan carry would need replicated→
    # varying casts under shard_map, and XLA fuses the chunk matmuls +
    # final reduction into the same loop anyway
    def chunk(k: Any, v: Any, m: Any) -> Tuple[Any, Any]:
        onehot = (k[:, None] == iota[None, :]).astype(jnp.float32) * m[:, None]
        s = jnp.dot(v[None, :], onehot, preferred_element_type=jnp.float32)[0]
        c = jnp.dot(m[None, :], onehot, preferred_element_type=jnp.float32)[0]
        return s, c

    ps, pc = jax.vmap(chunk)(kc, vc, mc)
    return ps.sum(axis=0), pc.sum(axis=0).astype(jnp.int32)


def _bin_kernel(keys_ref, vals_ref, mask_ref, sums_ref, cnts_ref):
    """One grid step: CHUNK rows → partial one-hot products accumulated
    into the full (1, buckets) output block (same block every step, so
    the accumulator lives in VMEM across the sequential TPU grid)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        sums_ref[:, :] = jnp.zeros_like(sums_ref)
        cnts_ref[:, :] = jnp.zeros_like(cnts_ref)

    buckets = sums_ref.shape[1]
    k = keys_ref[0, :]  # (CHUNK,) int32
    v = vals_ref[0, :]  # (CHUNK,) f32
    m = mask_ref[0, :]  # (CHUNK,) f32
    # 2D iota (1D iota does not lower on TPU)
    iota = jax.lax.broadcasted_iota(jnp.int32, (CHUNK, buckets), 1)
    onehot = (k[:, None] == iota).astype(jnp.float32) * m[:, None]
    sums_ref[:, :] += jnp.dot(
        v[None, :], onehot, preferred_element_type=jnp.float32
    )
    cnts_ref[:, :] += jnp.dot(
        m[None, :], onehot, preferred_element_type=jnp.float32
    )


def bin_sum_idx(idx: Any, values: Any, buckets: int, backend: str) -> Any:
    """Per-bucket SUM of pre-masked float32 ``values`` routed by bucket id
    ``idx`` (invalid rows carry 0 and any in-range id) — the drop-in
    alternative to ``zeros(buckets).at[idx].add(values)`` used by the
    dense groupby kernel (``segment.py``). ``backend``: "onehot" (chunked
    jnp) or "pallas" (the sum-only TPU kernel — pallas outputs can't be
    dead-code-eliminated, so the count table is not computed here)."""
    import jax.numpy as jnp

    ones = jnp.ones(idx.shape[0], dtype=jnp.float32)
    if backend == "pallas":
        return bin_sum_pallas(idx, values, ones, buckets)
    sums, _ = bin_sum_count_xla(idx, values, ones, buckets)
    return sums


def _sum_kernel(keys_ref, vals_ref, mask_ref, sums_ref):
    """Sum-only grid step (no count table — half the MXU work when the
    caller doesn't need counts)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        sums_ref[:, :] = jnp.zeros_like(sums_ref)

    buckets = sums_ref.shape[1]
    k = keys_ref[0, :]
    v = vals_ref[0, :]
    m = mask_ref[0, :]
    iota = jax.lax.broadcasted_iota(jnp.int32, (CHUNK, buckets), 1)
    onehot = (k[:, None] == iota).astype(jnp.float32) * m[:, None]
    sums_ref[:, :] += jnp.dot(
        v[None, :], onehot, preferred_element_type=jnp.float32
    )


def _pallas_binned(kernel, n_out: int, keys, values, valid, buckets, interpret):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    # the accumulator's last dim must tile to the TPU's 128-lane registers
    # — a BlockSpec over e.g. (1, 2) buckets fails or misbehaves on real
    # hardware, so round the bucket table up and slice the result back
    lanes = ((buckets + 127) // 128) * 128
    keys, values, valid, n_chunks = _pad_inputs(keys, values, valid, buckets)
    kc = keys.reshape(n_chunks, CHUNK)
    vc = values.astype(jnp.float32).reshape(n_chunks, CHUNK)
    mc = valid.astype(jnp.float32).reshape(n_chunks, CHUNK)

    row_spec = pl.BlockSpec((1, CHUNK), lambda i: (i, 0))
    acc_spec = pl.BlockSpec((1, lanes), lambda i: (0, 0))
    out = pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[row_spec, row_spec, row_spec],
        out_specs=[acc_spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct((1, lanes), jnp.float32)] * n_out,
        interpret=interpret,
    )(kc, vc, mc)
    return [o[:, :buckets] for o in out]


def bin_sum_pallas(
    keys: Any, values: Any, valid: Any, buckets: int, interpret: bool = False
) -> Any:
    """Per-bucket SUM only (the dense-kernel hot path)."""
    (sums,) = _pallas_binned(_sum_kernel, 1, keys, values, valid, buckets, interpret)
    return sums[0]


def bin_sum_count_pallas(
    keys: Any, values: Any, valid: Any, buckets: int, interpret: bool = False
) -> Tuple[Any, Any]:
    """Pallas TPU version of :func:`bin_sum_count_xla` — identical
    contract; ``interpret=True`` runs the kernel in the Pallas
    interpreter (CPU-testable)."""
    import jax.numpy as jnp

    sums, cnts = _pallas_binned(
        _bin_kernel, 2, keys, values, valid, buckets, interpret
    )
    return sums[0], cnts[0].astype(jnp.int32)
