"""Device broadcast join (fact × dimension).

The reference delegates joins to backend SQL/shuffles (SURVEY §2.9); the
first device join here is the common warehouse shape: a large row-sharded
fact frame INNER-joined to a small dimension frame on a unique int key.

Design (no data-dependent shapes anywhere):

- the dimension side is replicated to every device and sorted by key once;
- each shard binary-searches its fact keys against the sorted dim keys
  (``searchsorted`` → O(n log m) on the VPU);
- dim value columns gather by the found index; misses stay as garbage rows
  but the frame's validity mask is ANDed with the match mask — the same
  zero-copy mechanism device filters use, so an inner join never needs
  compaction or null representation.

Uniqueness of the dim key is verified on device (adjacent-equal check after
the sort); non-unique or oversized dims fall back to the host join.
"""

from typing import Any, Dict

_JOIN_CACHE: Dict[Any, Any] = {}

# dimension sides larger than this stay on the host join path
MAX_BROADCAST_ROWS = 1 << 21


def _get_compiled_dim_prep(mesh: Any):
    """Sort the replicated dim key + report uniqueness (cached per mesh)."""
    import jax
    import jax.numpy as jnp

    key = ("dimprep", mesh)
    if key not in _JOIN_CACHE:

        def prep(dim_key: Any, dim_valid: Any):
            # push invalid rows to the end so they never match
            big = jnp.where(dim_valid, dim_key, jnp.iinfo(dim_key.dtype).max)
            order = jnp.argsort(big)
            k_sorted = big[order]
            n_valid = dim_valid.sum()
            dup = jnp.any(
                (k_sorted[1:] == k_sorted[:-1])
                & (jnp.arange(1, k_sorted.shape[0]) < n_valid)
            )
            return k_sorted, order, n_valid, dup

        _JOIN_CACHE[key] = jax.jit(prep)
    return _JOIN_CACHE[key]


def _get_compiled_probe(mesh: Any, n_values: int):
    """Probe fact keys against the sorted dim and gather value columns."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    key = ("probe", mesh, n_values)
    if key not in _JOIN_CACHE:

        def probe(fact_key: Any, fact_valid: Any, k_sorted: Any, order: Any,
                  n_valid: Any, *dim_values: Any):
            def shard_fn(fk: Any, fv: Any, ks: Any, od: Any, nv: Any, *dvs: Any):
                idx = jnp.searchsorted(ks, fk)
                idx_c = jnp.clip(idx, 0, ks.shape[0] - 1)
                match = (ks[idx_c] == fk) & (idx < nv) & fv
                src = od[idx_c]
                gathered = tuple(dv[src] for dv in dvs)
                return (match,) + gathered

            n_out = 1 + len(dim_values)
            return jax.shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P(ROW_AXIS), P(), P(), P())
                + tuple(P() for _ in dim_values),
                out_specs=tuple(P(ROW_AXIS) for _ in range(n_out)),
            )(fact_key, fact_valid, k_sorted, order, n_valid, *dim_values)

        _JOIN_CACHE[key] = jax.jit(probe)
    return _JOIN_CACHE[key]


def device_broadcast_inner_join(
    mesh: Any,
    fact_cols: Dict[str, Any],
    fact_valid: Any,
    key_name: str,
    dim_cols: Dict[str, Any],
    dim_valid: Any,
) -> Any:
    """Returns (new_device_cols, new_valid_mask) or None on fallback.

    ``dim_cols`` must include the key column; all dim columns must be
    replicated (caller replicates). Fallback (None) when the dim key is not
    unique.
    """
    import jax

    dim_key = dim_cols[key_name]
    if dim_key.shape[0] > MAX_BROADCAST_ROWS:
        return None
    k_sorted, order, n_valid, dup = _get_compiled_dim_prep(mesh)(dim_key, dim_valid)
    if bool(jax.device_get(dup)):
        return None  # non-unique dim keys → host join (may multiply rows)
    value_names = [n for n in dim_cols if n != key_name]
    probe = _get_compiled_probe(mesh, len(value_names))
    outs = probe(
        fact_cols[key_name],
        fact_valid,
        k_sorted,
        order,
        n_valid,
        *[dim_cols[n] for n in value_names],
    )
    match = outs[0]
    new_cols = dict(fact_cols)
    for name, arr in zip(value_names, outs[1:]):
        new_cols[name] = arr
    return new_cols, match
