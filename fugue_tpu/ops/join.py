"""Device joins: broadcast and shuffle hash joins over the mesh.

The reference delegates joins to backend SQL engines / task shuffles
(SURVEY §2.9, ``fugue_duckdb/execution_engine.py:233+``); here they are
static-shape XLA kernels (SURVEY §7 "mask, don't branch"):

- keys (one or many, int/float/bool) are mixed into a u64 row hash; the
  right side is sorted by hash, the left probes with ``searchsorted``
  (O(n log m) on the VPU) and verifies REAL key equality on the gathered
  row, so hash collisions can only cause a fallback (duplicate hashes on
  the right are detected at prep), never a wrong match;
- join types map onto the frame validity mask: ``inner``/``semi`` AND the
  match in, ``anti`` ANDs its negation, ``left_outer`` keeps all left rows
  and NaN-fills gathered values (device NULL) — so no join ever compacts
  or materializes variable-shape output;
- strategies: **broadcast** replicates a small right side to every device;
  **shuffle** co-partitions both sides by key hash with the all-to-all
  exchange (``ops/shuffle.py``) and probes shard-locally — the large×large
  path. Both require unique join keys on the right (verified on device);
  many-to-many joins fall back to the host engine.

NULL keys never match (SQL semantics): NaN float keys are excluded from
both sides' match sets on device.
"""

from typing import Any, Dict, List, Optional, Tuple

from ..parallel.mesh import ROW_AXIS, num_row_shards
from . import collectives
from .shuffle import _hash_cols
from .._utils.jax_compat import shard_map

_JOIN_CACHE: Dict[Any, Any] = {}

# right sides larger than this use the shuffle strategy
MAX_BROADCAST_ROWS = 1 << 20
# per-shard output-slot budget for the 1:N expansion join
MAX_EXPAND_ROWS = 1 << 22


def _key_hash_and_valid(jnp: Any, key_cols: List[Any], valid: Any):
    """(u64 hash, validity excluding NaN keys) for a set of key columns."""
    kv = valid
    for c in key_cols:
        if jnp.issubdtype(c.dtype, jnp.floating):
            kv = kv & ~jnp.isnan(c)
    return _hash_cols(jnp, key_cols), kv


def _probe_body(
    jnp: Any,
    how: str,
    fk_cols: Tuple[Any, ...],
    f_valid: Any,
    rk_sorted_hash: Any,
    r_order: Any,
    r_nvalid: Any,
    rk_cols: Tuple[Any, ...],
    r_values: Tuple[Any, ...],
    fills: Tuple[Any, ...] = (),
):
    """Shared probe: fact hashes against the hash-sorted right side.

    ``fills`` (static, one per value array) are the left_outer miss values:
    NaN for floats, −1 for dictionary codes, True for null masks, 0 for
    plain ints whose misses get a generated null mask from the returned
    match flags.
    """
    fh, fkv = _key_hash_and_valid(jnp, list(fk_cols), f_valid)
    idx = jnp.searchsorted(rk_sorted_hash, fh)
    idx_c = jnp.clip(idx, 0, rk_sorted_hash.shape[0] - 1)
    cand = (rk_sorted_hash[idx_c] == fh) & (idx < r_nvalid) & fkv
    src = r_order[idx_c]
    # verify true key equality on the candidate row (collision safety)
    eq = cand
    for fk, rk in zip(fk_cols, rk_cols):
        eq = eq & (rk[src] == fk)
    if how == "inner":
        new_valid = f_valid & eq
        gathered = tuple(rv[src] for rv in r_values)
    elif how == "left_outer":
        new_valid = f_valid
        gathered = tuple(
            jnp.where(eq, rv[src], jnp.asarray(fill, dtype=rv.dtype))
            for rv, fill in zip(r_values, fills)
        ) + (eq,)  # match flags: the engine derives generated null masks
    elif how == "semi":
        new_valid = f_valid & eq
        gathered = ()
    elif how == "anti":
        new_valid = f_valid & ~eq
        gathered = ()
    else:  # pragma: no cover
        raise NotImplementedError(how)
    return (new_valid,) + gathered


def _get_compiled_right_prep(mesh: Any, n_keys: int, dtypes: Any, local: bool):
    """Hash + sort the right side; report duplicate hashes among valid rows.

    ``local=True`` preps each shard's block independently (shuffle join);
    ``local=False`` preps a replicated array (broadcast join).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    key = ("rprep", mesh, n_keys, dtypes, local)
    if key not in _JOIN_CACHE:

        def prep(valid: Any, *key_cols: Any):
            h, kv = _key_hash_and_valid(jnp, list(key_cols), valid)
            n = h.shape[0]
            inv = jnp.logical_not(kv)
            iota = lax.iota(jnp.int32, n)
            s_inv, s_h, order = lax.sort((inv, h, iota), num_keys=2)
            nv = kv.sum(dtype=jnp.int64)
            dup = jnp.any(
                (s_h[1:] == s_h[:-1])
                & jnp.logical_not(s_inv[1:])
                & jnp.logical_not(s_inv[:-1])
            )
            # invalid rows sit at the tail but keep arbitrary hashes — pin
            # them to the max so the array stays globally sorted for
            # searchsorted (the idx < nv guard keeps them unmatchable)
            s_h = jnp.where(s_inv, jnp.uint64(0xFFFFFFFFFFFFFFFF), s_h)
            return s_h, order, nv[None], dup[None]

        if local:
            spec = P(ROW_AXIS)
            _JOIN_CACHE[key] = jax.jit(
                shard_map(
                    prep,
                    mesh=mesh,
                    in_specs=tuple(spec for _ in range(1 + n_keys)),
                    out_specs=(spec, spec, spec, spec),
                )
            )
        else:
            _JOIN_CACHE[key] = jax.jit(prep)
    return _JOIN_CACHE[key]


def _get_compiled_probe(
    mesh: Any,
    how: str,
    n_keys: int,
    n_values: int,
    dtypes: Any,
    local: bool,
    fills: Tuple[Any, ...] = (),
):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    key = ("probe", mesh, how, n_keys, n_values, dtypes, local, fills)
    if key not in _JOIN_CACHE:

        def probe(*args: Any):
            (f_valid, s_h, order, nv) = args[:4]
            fk = args[4 : 4 + n_keys]
            rk = args[4 + n_keys : 4 + 2 * n_keys]
            rv = args[4 + 2 * n_keys :]

            def shard_fn(fv_, sh_, od_, nv_, *rest: Any):
                fk_ = rest[:n_keys]
                rk_ = rest[n_keys : 2 * n_keys]
                rv_ = rest[2 * n_keys :]
                return _probe_body(
                    jnp, how, fk_, fv_, sh_, od_, nv_[0], rk_, rv_, fills
                )

            row = P(ROW_AXIS)
            right = row if local else P()
            n_out = 1 + (
                (n_values + 1) if how == "left_outer" else (n_values if how == "inner" else 0)
            )
            return shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(row, right, right, right)
                + tuple(row for _ in range(n_keys))
                + tuple(right for _ in range(n_keys + n_values)),
                out_specs=tuple(row for _ in range(n_out)),
            )(f_valid, s_h, order, nv, *fk, *rk, *rv)

        _JOIN_CACHE[key] = jax.jit(probe)
    return _JOIN_CACHE[key]


def copartition_by_keys(
    mesh: Any,
    left_cols: Dict[str, Any],
    left_valid: Any,
    left_key_names: List[str],
    right_keys: List[Any],
    right_values: List[Tuple[str, Any, Any]],
    right_valid: Any,
) -> Tuple[Dict[str, Any], Any, List[Any], List[Tuple[str, Any, Any]], Any]:
    """Co-partition both join sides by key hash (ONE all-to-all per side);
    shared by the unique-probe and expansion joins so a dup-key fallback
    never repeats the exchange."""
    from .shuffle import compute_dest, exchange_rows

    n_keys = len(left_key_names)
    l_dest = compute_dest(
        mesh, "hash", [left_cols[k] for k in left_key_names], left_valid
    )
    r_dest = compute_dest(mesh, "hash", list(right_keys), right_valid)
    left_cols, left_valid, _ = exchange_rows(
        mesh, dict(left_cols), left_valid, l_dest
    )
    r_payload = {f"__k{i}__": a for i, a in enumerate(right_keys)}
    r_payload.update({f"__v__{n}": a for n, a, _ in right_values})
    r_payload, right_valid, _ = exchange_rows(
        mesh, r_payload, right_valid, r_dest
    )
    right_keys = [r_payload[f"__k{i}__"] for i in range(n_keys)]
    right_values = [
        (n, r_payload[f"__v__{n}"], f) for n, _, f in right_values
    ]
    return left_cols, left_valid, right_keys, right_values, right_valid


def device_hash_join(
    mesh: Any,
    how: str,
    left_cols: Dict[str, Any],
    left_valid: Any,
    left_key_names: List[str],
    right_keys: List[Any],
    right_valid: Any,
    right_values: List[Tuple[str, Any, Any]],
    strategy: str = "broadcast",
) -> Optional[Tuple[Dict[str, Any], Any, Optional[Any]]]:
    """Join the left payload against prepared right-side arrays.

    - ``left_cols`` is the FULL left payload (columns, null masks, prepared
      probe keys — any row-aligned arrays); ``left_key_names`` picks the
      probe keys out of it;
    - ``right_keys`` are the prepared right key arrays (dictionary codes
      remapped, masked keys as NaN float views — the caller aligns
      representations across frames);
    - ``right_values`` entries are ``(out_name, array, miss_fill)`` — the
      fill is the left_outer NULL for that array's representation (NaN /
      −1 code / True mask / 0 plain).

    Returns ``(new_cols, new_valid, match)`` where ``match`` (left_outer
    only) flags rows that found a partner — the caller derives generated
    null masks for plain columns from it. None → host fallback (non-unique
    right keys / hash collision).

    ``strategy="broadcast"`` expects the right arrays replicated;
    ``"shuffle"`` expects both sides row-sharded and co-partitions them by
    key hash with the all-to-all exchange first.
    """
    import jax
    import numpy as np

    if strategy == "shuffle":
        left_cols, left_valid, right_keys, right_values, right_valid = (
            copartition_by_keys(
                mesh, left_cols, left_valid, left_key_names,
                right_keys, right_values, right_valid,
            )
        )
        strategy = "local"
    shuffle = strategy == "local"
    n_keys = len(left_key_names)
    kdt = tuple(str(a.dtype) for a in right_keys)
    prep = _get_compiled_right_prep(mesh, n_keys, kdt, local=shuffle)
    s_h, order, nv, dup = prep(right_valid, *right_keys)
    if bool(np.asarray(jax.device_get(dup)).any()):
        return None  # duplicate keys (or hash collision) → host join
    vdt = tuple(str(a.dtype) for _, a, _ in right_values)
    fills = (
        tuple(f for _, _, f in right_values) if how == "left_outer" else ()
    )
    probe = _get_compiled_probe(
        mesh,
        how,
        n_keys,
        len(right_values),
        (kdt, vdt),
        local=shuffle,
        fills=fills,
    )
    outs = probe(
        left_valid,
        s_h,
        order,
        nv,
        *[left_cols[k] for k in left_key_names],
        *right_keys,
        *[a for _, a, _ in right_values],
    )
    new_valid = outs[0]
    match = None
    new_cols = dict(left_cols)
    if how == "inner":
        for (name, _, _), arr in zip(right_values, outs[1:]):
            new_cols[name] = arr
    elif how == "left_outer":
        for (name, _, _), arr in zip(right_values, outs[1:-1]):
            new_cols[name] = arr
        match = outs[-1]
    return new_cols, new_valid, match


def _get_compiled_expand_count(mesh: Any, n_keys: int, dtypes: Any, local: bool, miss_slot: bool):
    """Phase A of the 1:N expansion: per-left-row candidate counts (hash-run
    length in the sorted right side), exclusive offsets, and the replicated
    per-shard max slot total (→ static output capacity)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    key = ("xcount", mesh, n_keys, dtypes, local, miss_slot)
    if key not in _JOIN_CACHE:

        def count(f_valid: Any, s_h: Any, nv: Any, *fk: Any):
            fh, fkv = _key_hash_and_valid(jnp, list(fk), f_valid)
            lo = jnp.searchsorted(s_h, fh, side="left")
            hi = jnp.searchsorted(s_h, fh, side="right")
            hi = jnp.minimum(hi, nv[0])
            lo = jnp.minimum(lo, hi)
            cand = jnp.where(f_valid & fkv, hi - lo, 0).astype(jnp.int64)
            slots = cand + (f_valid.astype(jnp.int64) if miss_slot else 0)
            off = jnp.cumsum(slots) - slots  # exclusive
            total = jnp.where(
                slots.shape[0] > 0, off[-1] + slots[-1], jnp.int64(0)
            )
            return cand, lo.astype(jnp.int64), off, collectives.pmax(total, ROW_AXIS)[None]

        row = P(ROW_AXIS)
        right = row if local else P()
        _JOIN_CACHE[key] = jax.jit(
            shard_map(
                count,
                mesh=mesh,
                in_specs=(row, right, right) + tuple(row for _ in range(n_keys)),
                out_specs=(row, row, row, P()),
            )
        )
    return _JOIN_CACHE[key]


def _get_compiled_expand(
    mesh: Any,
    how: str,
    cap: int,
    n_keys: int,
    n_left: int,
    n_values: int,
    dtypes: Any,
    local: bool,
    fills: Tuple[Any, ...],
):
    """Phase B: materialize one output row per (left row, candidate) pair
    into a static ``cap``-per-shard buffer; collisions and misses become
    masked slots, never wrong rows."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    key = ("xpand", mesh, how, cap, n_keys, n_left, n_values, dtypes, local, fills)
    if key not in _JOIN_CACHE:

        def expand(*args: Any):
            cand, lo, off, f_valid, order = args[:5]
            fk = args[5 : 5 + n_keys]
            lp = args[5 + n_keys : 5 + n_keys + n_left]
            rk = args[5 + n_keys + n_left : 5 + 2 * n_keys + n_left]
            rv = args[5 + 2 * n_keys + n_left :]
            n = f_valid.shape[0]
            nr = order.shape[0]
            io = lax.iota(jnp.int64, cap)
            row = jnp.clip(
                jnp.searchsorted(off, io, side="right") - 1, 0, n - 1
            )
            within = io - off[row]
            is_cand = within < cand[row]
            src = order[jnp.clip(lo[row] + within, 0, nr - 1)]
            eq = is_cand & f_valid[row]
            for k_, r_ in zip(fk, rk):
                eq = eq & (r_[src] == k_[row])
            matched = (
                jnp.zeros(n, dtype=jnp.int32)
                .at[row]
                .max(eq.astype(jnp.int32), mode="drop")
            ) > 0
            if how in ("semi", "anti"):
                mres = matched if how == "semi" else jnp.logical_not(matched)
                return (f_valid & mres,)
            total = off[-1] + cand[-1] + (
                f_valid[-1].astype(jnp.int64) if how == "left_outer" else 0
            )
            in_range = io < total
            if how == "left_outer":
                miss = (
                    (within == cand[row])
                    & f_valid[row]
                    & jnp.logical_not(matched[row])
                )
                valid_out = in_range & (eq | miss)
            else:
                valid_out = in_range & eq
            louts = tuple(a[row] for a in lp)
            if how == "left_outer":
                routs = tuple(
                    jnp.where(eq, a[src], jnp.asarray(f, dtype=a.dtype))
                    for a, (f,) in zip(rv, fills_z)
                )
            else:
                routs = tuple(a[src] for a in rv)
            return (valid_out,) + louts + routs + ((eq,) if how == "left_outer" else ())

        fills_z = [(f,) for f in fills] if len(fills) else [(0,)] * n_values
        row_spec = P(ROW_AXIS)
        right = row_spec if local else P()
        n_out = (
            1
            if how in ("semi", "anti")
            else 1 + n_left + n_values + (1 if how == "left_outer" else 0)
        )
        _JOIN_CACHE[key] = jax.jit(
            shard_map(
                expand,
                mesh=mesh,
                in_specs=(row_spec, row_spec, row_spec, row_spec, right)
                + tuple(row_spec for _ in range(n_keys + n_left))
                + tuple(right for _ in range(n_keys + n_values)),
                out_specs=tuple(row_spec for _ in range(n_out)),
            )
        )
    return _JOIN_CACHE[key]


def device_expand_join(
    mesh: Any,
    how: str,
    left_cols: Dict[str, Any],
    left_valid: Any,
    left_key_names: List[str],
    right_keys: List[Any],
    right_valid: Any,
    right_values: List[Tuple[str, Any, Any]],
    strategy: str = "broadcast",
) -> Optional[Tuple[Dict[str, Any], Any, Optional[Any]]]:
    """1:N / N:M device join — duplicate right keys allowed.

    Same contract as :func:`device_hash_join` but the output is an
    EXPANDED frame: one row per (left row, matching right row), built in a
    statically-capacity-negotiated buffer (the only host sync is the tiny
    replicated slot-total). For ``semi``/``anti`` the left frame keeps its
    shape and only the validity mask changes.

    The reference handles 1:N joins on every backend via its SQL engines
    (``fugue_test/execution_suite.py:379-544``); this is the device-native
    equivalent.
    """
    import jax
    import numpy as np

    if strategy == "shuffle":
        left_cols, left_valid, right_keys, right_values, right_valid = (
            copartition_by_keys(
                mesh, left_cols, left_valid, left_key_names,
                right_keys, right_values, right_valid,
            )
        )
        strategy = "local"
    shuffle = strategy == "local"
    n_keys = len(left_key_names)
    kdt = tuple(str(a.dtype) for a in right_keys)
    prep = _get_compiled_right_prep(mesh, n_keys, kdt, local=shuffle)
    s_h, order, nv, _dup = prep(right_valid, *right_keys)
    fk_arrs = [left_cols[k] for k in left_key_names]
    counter = _get_compiled_expand_count(
        mesh, n_keys, kdt, local=shuffle, miss_slot=(how == "left_outer")
    )
    cand, lo, off, max_total = counter(left_valid, s_h, nv, *fk_arrs)
    mt = int(np.asarray(jax.device_get(max_total))[0])
    if mt > MAX_EXPAND_ROWS:
        return None  # output would blow past the per-shard budget → host
    cap = 1 << (max(1, mt) - 1).bit_length()  # pow2 ≥ mt, ≥ 1
    left_payload_names = [k for k in left_cols if k not in left_key_names]
    vdt = tuple(str(a.dtype) for _, a, _ in right_values)
    ldt = tuple(str(left_cols[k].dtype) for k in left_payload_names)
    fills = (
        tuple(f for _, _, f in right_values) if how == "left_outer" else ()
    )
    expander = _get_compiled_expand(
        mesh,
        how,
        cap,
        n_keys,
        len(left_payload_names),
        len(right_values),
        (kdt, ldt, vdt),
        local=shuffle,
        fills=fills,
    )
    outs = expander(
        cand,
        lo,
        off,
        left_valid,
        order,
        *fk_arrs,
        *[left_cols[k] for k in left_payload_names],
        *right_keys,
        *[a for _, a, _ in right_values],
    )
    if how in ("semi", "anti"):
        return dict(left_cols), outs[0], None
    new_valid = outs[0]
    new_cols: Dict[str, Any] = {}
    lo_i = 1
    for k, arr in zip(left_payload_names, outs[lo_i : lo_i + len(left_payload_names)]):
        new_cols[k] = arr
    vi = lo_i + len(left_payload_names)
    for (name, _, _), arr in zip(right_values, outs[vi : vi + len(right_values)]):
        new_cols[name] = arr
    match = outs[-1] if how == "left_outer" else None
    return new_cols, new_valid, match


def device_broadcast_inner_join(
    mesh: Any,
    fact_cols: Dict[str, Any],
    fact_valid: Any,
    key_name: str,
    dim_cols: Dict[str, Any],
    dim_valid: Any,
) -> Any:
    """Back-compat single-key INNER wrapper over :func:`device_hash_join`."""
    import math

    values = [
        (n, a, math.nan) for n, a in dim_cols.items() if n != key_name
    ]
    res = device_hash_join(
        mesh,
        "inner",
        fact_cols,
        fact_valid,
        [key_name],
        [dim_cols[key_name]],
        dim_valid,
        values,
    )
    if res is None:
        return None
    new_cols, new_valid, _ = res
    return new_cols, new_valid
