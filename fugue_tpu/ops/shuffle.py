"""Device shuffle: all-to-all row exchange over the mesh rows axis.

The TPU-native replacement for the reference's per-backend repartition
algorithms (``fugue_spark/_utils/partition.py:15-117`` hash/rand/even and
``fugue_dask/_utils.py:44-123``): instead of a task-graph shuffle, rows move
between shards with ONE ``lax.all_to_all`` collective inside ``shard_map``
— the layout XLA maps onto ICI links.

Protocol (static shapes throughout, SURVEY §7 "mask, don't branch"):

1. every row gets a destination shard (hash of keys / even rank / random);
2. a tiny per-(shard, dest) count matrix comes to host to negotiate a
   static block ``capacity`` (pow2-rounded so compiled variants are reused);
3. the exchange kernel sorts rows by destination, scatters them into a
   ``(shards, capacity)`` send buffer, ``all_to_all``s the buffers, and
   returns the received rows + validity mask.

Skew safety: when a hot destination pushes the block capacity past
``SINGLE_ROUND_MAX_CAPACITY``, the exchange escalates to MULTIPLE bounded
rounds (each moving ≤ that many rows per destination) that compact-append
into output buffers sized by the true max received total — collective
buffers and outputs stay O(data), never O(shards × hot-key count).
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..parallel.mesh import ROW_AXIS, num_row_shards
from . import collectives
from .._utils.jax_compat import shard_map

_COMPILE_CACHE: Dict[Any, Any] = {}

# splitmix64 multipliers — the standard 64-bit finalizer mix
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)


def _hash_cols(jnp: Any, cols: List[Any]) -> Any:
    """Combine columns into a well-mixed uint64 row hash (device-side)."""
    h = jnp.zeros(cols[0].shape, dtype=jnp.uint64)
    for c in cols:
        if jnp.issubdtype(c.dtype, jnp.floating):
            # bitcast so equal keys hash equally; normalize -0.0 to +0.0
            c = jnp.where(c == 0, jnp.zeros_like(c), c)
            x = jax_bitcast_u64(jnp, c)
        elif c.dtype == jnp.bool_:
            x = c.astype(jnp.uint64)
        else:
            x = c.astype(jnp.uint64)
        x = (x ^ (x >> 30)) * _MIX1
        x = (x ^ (x >> 27)) * _MIX2
        x = x ^ (x >> 31)
        h = h * np.uint64(31) + x
    return h


def jax_bitcast_u64(jnp: Any, c: Any) -> Any:
    import jax.lax as lax

    if c.dtype == jnp.float64:
        return lax.bitcast_convert_type(c, jnp.uint64)
    return lax.bitcast_convert_type(c.astype(jnp.float64), jnp.uint64)


def _get_compiled_dest_hash(mesh: Any, n_keys: int, dtypes: Tuple[Any, ...]):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("dest_hash", mesh, n_keys, dtypes)
    if cache_key not in _COMPILE_CACHE:

        def kernel(*cols: Any):
            h = _hash_cols(jnp, list(cols))
            return (h % np.uint64(shards)).astype(jnp.int32)

        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=tuple(P(ROW_AXIS) for _ in range(n_keys)),
                out_specs=P(ROW_AXIS),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_dest_even(mesh: Any):
    """dest = global rank of the valid row, spread evenly over shards
    (invalid rows keep their shard — they're masked anyway)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("dest_even", mesh)
    if cache_key not in _COMPILE_CACHE:

        def kernel(valid: Any):
            local = jnp.cumsum(valid.astype(jnp.int64)) - 1  # local rank
            counts = collectives.all_gather(valid.sum(dtype=jnp.int64), ROW_AXIS)
            me = jax.lax.axis_index(ROW_AXIS)
            offset = jnp.where(
                jax.lax.iota(jnp.int64, shards) < me, counts, 0
            ).sum()
            total = counts.sum()
            rank = local + offset
            # ceil-sized blocks: shard i gets ranks [i*block, (i+1)*block)
            block = jnp.maximum((total + shards - 1) // shards, 1)
            return jnp.clip(rank // block, 0, shards - 1).astype(jnp.int32)

        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel, mesh=mesh, in_specs=(P(ROW_AXIS),), out_specs=P(ROW_AXIS)
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_dest_rand(mesh: Any):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("dest_rand", mesh)
    if cache_key not in _COMPILE_CACHE:

        def kernel(template: Any, seed: Any):
            me = jax.lax.axis_index(ROW_AXIS)
            key = jax.random.fold_in(jax.random.PRNGKey(seed[0]), me)
            return jax.random.randint(
                key, template.shape, 0, shards, dtype=jnp.int32
            )

        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P()),
                out_specs=P(ROW_AXIS),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_dest_single(mesh: Any):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    cache_key = ("dest_single", mesh)
    if cache_key not in _COMPILE_CACHE:
        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                lambda template: jnp.zeros(template.shape, jnp.int32),
                mesh=mesh,
                in_specs=(P(ROW_AXIS),),
                out_specs=P(ROW_AXIS),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_counts(mesh: Any):
    """Destination-histogram summary → (max_count, total) as REPLICATED
    scalars: replication keeps the host read addressable from every process
    on multi-host meshes (a sharded matrix would not be)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("shuffle_counts", mesh)
    if cache_key not in _COMPILE_CACHE:

        def kernel(dest: Any, valid: Any):
            h = (
                jnp.zeros(shards, dtype=jnp.int32)
                .at[dest]
                .add(valid.astype(jnp.int32))
            )
            received = collectives.psum(h, ROW_AXIS)  # per-dest totals, replicated
            return (
                collectives.pmax(h.max(), ROW_AXIS)[None],
                collectives.psum(h.sum(), ROW_AXIS)[None],
                received.max()[None],
            )

        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P(ROW_AXIS)),
                out_specs=(P(), P(), P()),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_exchange(
    mesh: Any, dtypes: Tuple[Any, ...], capacity: int
):
    """The all-to-all exchange for ``len(dtypes)`` row-aligned arrays.

    Per shard: sort rows by destination, scatter each destination's rows
    into its block of a ``(shards, capacity)`` send buffer, exchange
    blocks with ``lax.all_to_all``, return flattened received arrays and
    the received-validity mask. Output local length = shards × capacity.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("exchange", mesh, dtypes, capacity)
    if cache_key not in _COMPILE_CACHE:

        def kernel(dest: Any, valid: Any, *arrs: Any):
            n = dest.shape[0]
            big_dest = jnp.where(valid, dest, shards)  # invalid rows last
            iota = lax.iota(jnp.int32, n)
            sd, perm = lax.sort((big_dest, iota), num_keys=1)
            # position of each sorted row within its destination block
            starts_tbl = jnp.zeros(shards + 1, dtype=jnp.int32).at[sd].add(1)
            starts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(starts_tbl[:shards])]
            )
            pos = iota - starts[jnp.clip(sd, 0, shards - 1)]
            ok = (sd < shards) & (pos < capacity)
            flat = jnp.where(
                ok, jnp.clip(sd, 0, shards - 1) * capacity + pos, shards * capacity
            )
            send_valid = (
                jnp.zeros(shards * capacity, dtype=bool)
                .at[flat]
                .set(True, mode="drop")
            )
            recv_valid = collectives.all_to_all(
                send_valid.reshape(shards, capacity),
                ROW_AXIS,
                split_axis=0,
                concat_axis=0,
            ).reshape(-1)
            outs = [recv_valid]
            for a in arrs:
                sa = a[perm]
                send = (
                    jnp.zeros(shards * capacity, dtype=a.dtype)
                    .at[flat]
                    .set(sa, mode="drop")
                )
                outs.append(
                    collectives.all_to_all(
                        send.reshape(shards, capacity),
                        ROW_AXIS,
                        split_axis=0,
                        concat_axis=0,
                    ).reshape(-1)
                )
            return tuple(outs)

        n_in = 2 + len(dtypes)
        n_out = 1 + len(dtypes)
        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=tuple(P(ROW_AXIS) for _ in range(n_in)),
                out_specs=tuple(P(ROW_AXIS) for _ in range(n_out)),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_rank(mesh: Any):
    """Per-row rank among rows of the SAME destination on this shard —
    computed once, reused by every round of the multi-round exchange."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("shuffle_rank", mesh)
    if cache_key not in _COMPILE_CACHE:

        def kernel(dest: Any, valid: Any):
            n = dest.shape[0]
            big_dest = jnp.where(valid, dest, shards)
            iota = lax.iota(jnp.int32, n)
            sd, perm = lax.sort((big_dest, iota), num_keys=1)
            starts_tbl = jnp.zeros(shards + 1, dtype=jnp.int32).at[sd].add(1)
            starts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(starts_tbl[:shards])]
            )
            pos = iota - starts[jnp.clip(sd, 0, shards - 1)]
            return jnp.zeros(n, dtype=jnp.int32).at[perm].set(pos)

        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P(ROW_AXIS)),
                out_specs=P(ROW_AXIS),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_round(
    mesh: Any, dtypes: Tuple[Any, ...], cap: int, out_cap: int
):
    """ONE bounded round of the multi-round exchange: send rows whose
    within-destination rank falls in this round's window (≤ ``cap`` rows
    per destination), then compact-append the received rows into the
    accumulating output buffers. Peak collective buffer = shards × cap,
    independent of skew."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    shards = num_row_shards(mesh)
    cache_key = ("xround", mesh, dtypes, cap, out_cap)
    if cache_key not in _COMPILE_CACHE:

        def kernel(dest: Any, valid: Any, rank: Any, out_len: Any, r: Any, *rest: Any):
            arrs = rest[: len(dtypes)]
            bufs = rest[len(dtypes) :]
            lo = r[0] * cap
            sel = valid & (rank >= lo) & (rank < lo + cap)
            flat = jnp.where(
                sel, dest * cap + (rank - lo), shards * cap
            )
            send_valid = (
                jnp.zeros(shards * cap, dtype=bool)
                .at[flat]
                .set(True, mode="drop")
            )
            recv_valid = collectives.all_to_all(
                send_valid.reshape(shards, cap),
                ROW_AXIS,
                split_axis=0,
                concat_axis=0,
            ).reshape(-1)
            cum = jnp.cumsum(recv_valid.astype(jnp.int32))
            pos = out_len[0] + cum - 1
            idx = jnp.where(recv_valid, pos, out_cap)
            new_bufs = []
            for a, buf in zip(arrs, bufs):
                send = (
                    jnp.zeros(shards * cap, dtype=a.dtype)
                    .at[flat]
                    .set(a, mode="drop")
                )
                recv = collectives.all_to_all(
                    send.reshape(shards, cap),
                    ROW_AXIS,
                    split_axis=0,
                    concat_axis=0,
                ).reshape(-1)
                new_bufs.append(buf.at[idx].set(recv, mode="drop"))
            new_len = out_len[0] + cum[-1]
            return (new_len[None],) + tuple(new_bufs)

        row = P(ROW_AXIS)
        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(row, row, row, row, P())
                + tuple(row for _ in range(2 * len(dtypes))),
                out_specs=tuple(row for _ in range(1 + len(dtypes))),
            )
        )
    return _COMPILE_CACHE[cache_key]


def compute_dest(
    mesh: Any,
    algo: str,
    key_cols: List[Any],
    valid: Any,
    seed: Optional[int] = None,
) -> Any:
    """Destination shard per row for the given algorithm."""
    import numpy as np_

    if algo == "hash":
        dtypes = tuple(str(c.dtype) for c in key_cols)
        return _get_compiled_dest_hash(mesh, len(key_cols), dtypes)(*key_cols)
    if algo == "even":
        return _get_compiled_dest_even(mesh)(valid)
    if algo == "single":
        # every row to shard 0 — the one-partition layout behind global
        # (no PARTITION BY) window evaluation
        return _get_compiled_dest_single(mesh)(valid)
    if algo == "rand":
        if seed is None:
            seed = int(np_.random.default_rng().integers(0, 2**31 - 1))
        template = valid
        return _get_compiled_dest_rand(mesh)(
            template, np_.asarray([seed], dtype=np_.uint32)
        )
    raise ValueError(f"unknown shuffle algo {algo!r}")


# single-round block capacity ceiling: a (shard, dest) pair needing more
# rows than this escalates to the bounded multi-round exchange, whose peak
# collective buffer stays shards × this regardless of key skew
SINGLE_ROUND_MAX_CAPACITY = 1 << 17


def _get_compiled_lenmask(mesh: Any, out_cap: int):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    cache_key = ("lenmask", mesh, out_cap)
    if cache_key not in _COMPILE_CACHE:

        def kernel(out_len: Any):
            return lax.iota(jnp.int32, out_cap) < out_len[0]

        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=(P(ROW_AXIS),),
                out_specs=P(ROW_AXIS),
            )
        )
    return _COMPILE_CACHE[cache_key]


def exchange_rows(
    mesh: Any,
    arrays: Dict[str, Any],
    valid: Any,
    dest: Any,
    round_capacity: Optional[int] = None,
) -> Tuple[Dict[str, Any], Any, int]:
    """Move rows to their destination shards.

    Returns (new_arrays, new_valid_mask, received_row_count).

    Small/balanced exchanges run in ONE all-to-all with block capacity =
    the max per-(shard, dest) count (output local length shards ×
    capacity). Skewed exchanges — a hot destination pushing the block past
    ``round_capacity`` — run MULTIPLE bounded rounds: each round moves at
    most ``round_capacity`` rows per destination and compact-appends into
    output buffers sized by the TRUE max received total, so neither the
    collective buffers nor the output inflate with skew.
    """
    import jax
    import numpy as np_

    mx, total, mr = jax.device_get(_get_compiled_counts(mesh)(dest, valid))
    cap = max(1, int(mx[0]))
    capacity = 1 << (cap - 1).bit_length()  # pow2 → reuse compiled variants
    limit = (
        round_capacity if round_capacity is not None else SINGLE_ROUND_MAX_CAPACITY
    )
    dtypes = tuple(str(a.dtype) for a in arrays.values())
    if capacity <= limit:
        compiled = _get_compiled_exchange(mesh, dtypes, capacity)
        outs = compiled(dest, valid, *arrays.values())
        new_valid = outs[0]
        new_arrays = {k: v for k, v in zip(arrays.keys(), outs[1:])}
        return new_arrays, new_valid, int(total[0])
    # ---- multi-round path -------------------------------------------------
    from ..parallel.mesh import row_sharding

    shards = num_row_shards(mesh)
    round_cap = 1 << (max(1, limit) - 1).bit_length()
    rounds = -(-cap // round_cap)  # ceil
    out_cap = 1 << (max(1, int(mr[0])) - 1).bit_length()
    sharding = row_sharding(mesh)
    rank = _get_compiled_rank(mesh)(dest, valid)
    out_len = jax.device_put(
        np_.zeros(shards, dtype=np_.int32), sharding
    )
    bufs = [
        jax.device_put(
            np_.zeros(shards * out_cap, dtype=a.dtype), sharding
        )
        for a in arrays.values()
    ]
    step = _get_compiled_round(mesh, dtypes, round_cap, out_cap)
    for r in range(rounds):
        outs = step(
            dest,
            valid,
            rank,
            out_len,
            np_.asarray([r], dtype=np_.int32),
            *arrays.values(),
            *bufs,
        )
        out_len = outs[0]
        bufs = list(outs[1:])
    new_valid = _get_compiled_lenmask(mesh, out_cap)(out_len)
    new_arrays = {k: v for k, v in zip(arrays.keys(), bufs)}
    return new_arrays, new_valid, int(total[0])
