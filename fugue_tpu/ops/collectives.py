"""Axis-size-aware collective wrappers.

Every cross-shard collective in the device kernels goes through these
instead of raw ``lax`` so that:

1. On a 1-device mesh (the single-chip TPU tunnel) every reduce equals
   ``psum`` (sum over one element is also the min, the max, and the
   identity) — and Sum all-reduce is the ONLY collective the axon TPU
   platform's AOT compiler lowers (observed live: ``lax.pmin`` fails to
   compile with "Supported lowering only of Sum all reduce"). Rewriting
   to ``psum`` at size 1 both compiles on the real chip and keeps
   ``shard_map``'s replication typing intact (plain identity would leave
   the value "varying" and trip the out_specs VMA check). The axis size
   is static inside ``shard_map`` (``lax.axis_size``), so the branch
   disappears at trace time.
2. On a multi-device mesh whose platform still only lowers Sum
   all-reduces, setting ``FUGUE_TPU_SUM_ONLY_COLLECTIVES=1`` rewrites
   min/max/gather/all-to-all in terms of ``psum`` over one-hot buffers
   (n× the bandwidth — correct everywhere, tested on the CPU mesh).
   Default off; the CPU mesh and standard TPU runtimes lower the native
   collectives fine.

The reference delegates all of this to its backends' transports (Spark
shuffle / Dask comm / Ray object store — SURVEY §5.8); here the XLA
collectives ARE the transport, so platform quirks surface in-tree.
"""

import os
from typing import Any

from .._utils.jax_compat import axis_size, lax_ppermute

__all__ = ["psum", "pmin", "pmax", "all_gather", "all_to_all", "ppermute"]


def _sum_only() -> bool:
    return os.environ.get("FUGUE_TPU_SUM_ONLY_COLLECTIVES", "") == "1"


def _gather_via_psum(x: Any, axis: str) -> Any:
    """``all_gather`` built from the one collective every platform lowers:
    each shard psums a one-hot-indexed buffer holding its own block."""
    import jax.numpy as jnp
    from jax import lax

    n = axis_size(axis)
    i = lax.axis_index(axis)
    buf = jnp.zeros((n,) + x.shape, x.dtype).at[i].set(x)
    # psum upcasts bool to int32 — restore the caller's dtype (the buffers
    # are one-hot, so the cast is lossless)
    return lax.psum(buf, axis).astype(x.dtype)


def psum(x: Any, axis: str) -> Any:
    from jax import lax

    return lax.psum(x, axis)


def pmin(x: Any, axis: str) -> Any:
    from jax import lax

    if axis_size(axis) == 1:
        return lax.psum(x, axis).astype(x.dtype)
    if _sum_only():
        return _gather_via_psum(x, axis).min(axis=0)
    return lax.pmin(x, axis)


def pmax(x: Any, axis: str) -> Any:
    from jax import lax

    if axis_size(axis) == 1:
        return lax.psum(x, axis).astype(x.dtype)
    if _sum_only():
        return _gather_via_psum(x, axis).max(axis=0)
    return lax.pmax(x, axis)


def all_gather(x: Any, axis: str, *, tiled: bool = False) -> Any:
    import jax.numpy as jnp
    from jax import lax

    if axis_size(axis) == 1:
        g = lax.psum(x, axis).astype(x.dtype)
        return g if tiled else g[None]
    if _sum_only():
        g = _gather_via_psum(x, axis)
        return jnp.concatenate(list(g), axis=0) if tiled else g
    return lax.all_gather(x, axis, tiled=tiled)


def ppermute(x: Any, axis: str, shift: int) -> Any:
    """Ring shift: shard i's block lands on shard ``(i + shift) % n`` —
    ONE point-to-point hop per shard, the staged exchange's primitive.
    Peak in-flight payload is a single block (vs ``all_to_all``'s n
    blocks), which is what lets the staged schedule bound per-stage
    bytes. ``shift % n == 0`` is the local hop: no comm at all."""
    from jax import lax

    n = axis_size(axis)
    if n == 1 or shift % n == 0:
        # identity hop — keep shard_map's replication typing intact the
        # same way the size-1 reduces do (psum of the zero delta would be
        # wasteful; the value's VMA is already "varying" here, so a plain
        # pass-through is sound: out_specs stay row-sharded)
        return x
    if _sum_only():
        # my source shard under the ring shift is (i - shift) mod n
        src = (lax.axis_index(axis) - shift) % n
        return _gather_via_psum(x, axis)[src]
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax_ppermute(x, axis, perm)


def all_to_all(x: Any, axis: str, split_axis: int, concat_axis: int) -> Any:
    """Shard i's ``x[j]`` block lands on shard j (split/concat over the
    leading axis — the only shape the shuffle kernels use)."""
    from jax import lax

    assert split_axis == 0 and concat_axis == 0
    if axis_size(axis) == 1:
        return x
    if _sum_only():
        # g[src, dest, ...] replicated via psum; my receive row is g[:, i]
        return _gather_via_psum(x, axis)[:, lax.axis_index(axis)]
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis)
