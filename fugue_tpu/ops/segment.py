"""Device-side segment (groupby) aggregation kernels.

The TPU-native replacement for the reference's backend-SQL groupby
(SURVEY §7.8): a two-phase aggregate —

1. **Device phase (the O(rows) work)**: inside ``shard_map`` each shard
   lexicographically sorts its rows by the key columns (``lax.sort`` with
   ``num_keys``), derives segment ids, reduces values with
   ``jax.ops.segment_*`` and packs group representatives to the front.
   Everything is static-shape; the data-dependent group count is carried as
   a per-shard scalar (SURVEY §7 hard parts: "mask, don't branch").
2. **Host phase (the O(groups) work)**: only the first ``max_groups`` rows
   per shard cross the wire (bounded transfer); partials merge by
   re-aggregation on host.

Compiled executables are cached per (mesh, key-count, agg signature) — jit
re-tracing happens only on dtype/shape changes.

Supported aggregations: sum, count, min, max (avg = sum+count at merge).
"""

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

_COMPILE_CACHE: Dict[Any, Any] = {}


def _shard_kernel(num_keys: int, agg_specs: Sequence[Tuple[str, str]]):
    """Per-shard kernel: (keys..., values..., valid) →
    (nseg(1,), packed_keys...(n,), aggs...(n,)).

    ``aggs[i][j]`` is the reduction of segment j; ``packed_keys[i][j]`` its
    key — both valid for j < nseg.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_aggs = len(agg_specs)

    def kernel(*args: Any):
        keys = args[:num_keys]
        values = args[num_keys : num_keys + n_aggs]
        valid = args[num_keys + n_aggs]
        n = keys[0].shape[0]
        # sort invalid (padding) rows to the end, then lexicographic by keys;
        # sort a row-index payload instead of f64 values (narrow comparator)
        iota = lax.iota(jnp.int32, n)
        sorted_ops = lax.sort(
            (jnp.logical_not(valid),) + tuple(keys) + (iota,),
            num_keys=1 + num_keys,
        )
        s_keys = sorted_ops[1 : 1 + num_keys]
        perm = sorted_ops[-1]
        s_valid = valid[perm]
        s_values = [v[perm] for v in values]
        change = jnp.zeros(n, dtype=bool).at[0].set(True)
        for k in s_keys:
            change = change | jnp.concatenate(
                [jnp.ones(1, dtype=bool), k[1:] != k[:-1]]
            )
        change = change & s_valid
        nseg = change.sum(dtype=jnp.int32)
        seg_id = jnp.cumsum(change.astype(jnp.int32)) - 1
        seg_id = jnp.where(s_valid, seg_id, n - 1)
        outs = []
        for (_, agg), v in zip(agg_specs, s_values):
            if agg == "sum":
                vv = jnp.where(s_valid, v, jnp.zeros_like(v))
                outs.append(jax.ops.segment_sum(vv, seg_id, num_segments=n))
            elif agg == "count":
                outs.append(
                    jax.ops.segment_sum(
                        s_valid.astype(jnp.int64), seg_id, num_segments=n
                    )
                )
            elif agg == "min":
                big = jnp.where(s_valid, v, jnp.full_like(v, _max_of(jnp, v.dtype)))
                outs.append(jax.ops.segment_min(big, seg_id, num_segments=n))
            elif agg == "max":
                small = jnp.where(s_valid, v, jnp.full_like(v, _min_of(jnp, v.dtype)))
                outs.append(jax.ops.segment_max(small, seg_id, num_segments=n))
            else:  # pragma: no cover
                raise NotImplementedError(agg)
        # pack each segment's representative key to the front: stable argsort
        # on ~change puts segment-start rows first, in order
        starts = jnp.argsort(jnp.logical_not(change), stable=True)
        packed_keys = tuple(k[starts] for k in s_keys)
        return (nseg[None],) + packed_keys + tuple(outs)

    return kernel


def _max_of(jnp: Any, dt: Any) -> Any:
    return jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).max


def _min_of(jnp: Any, dt: Any) -> Any:
    return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).min


def _get_compiled_kernel(mesh: Any, num_keys: int, agg_sig: Tuple[Tuple[str, str], ...]):
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("kernel", mesh, num_keys, agg_sig)
    if cache_key not in _COMPILE_CACHE:
        kernel = _shard_kernel(num_keys, agg_sig)
        n_in = num_keys + len(agg_sig) + 1
        n_out = 1 + num_keys + len(agg_sig)
        spec = P(ROW_AXIS)
        _COMPILE_CACHE[cache_key] = jax.jit(
            jax.shard_map(
                kernel,
                mesh=mesh,
                in_specs=tuple(spec for _ in range(n_in)),
                out_specs=tuple(spec for _ in range(n_out)),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_slicer(mesh: Any, n_arrays: int, k: int):
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("slice", mesh, n_arrays, k)
    if cache_key not in _COMPILE_CACHE:
        spec = P(ROW_AXIS)

        def take_k(*arrs: Any):
            return tuple(a[:k] for a in arrs)

        _COMPILE_CACHE[cache_key] = jax.jit(
            jax.shard_map(
                take_k,
                mesh=mesh,
                in_specs=tuple(spec for _ in range(n_arrays)),
                out_specs=tuple(spec for _ in range(n_arrays)),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_mask(mesh: Any):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("mask", mesh)
    if cache_key not in _COMPILE_CACHE:

        def mask(template: Any, row_count: Any):
            def shard_fn(t: Any, rc: Any):
                n_local = t.shape[0]
                base = jax.lax.axis_index(ROW_AXIS).astype(jnp.int64) * n_local
                return base + jax.lax.iota(jnp.int64, n_local) < rc

            return jax.shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P()),
                out_specs=P(ROW_AXIS),
            )(template, row_count)

        _COMPILE_CACHE[cache_key] = jax.jit(mask)
    return _COMPILE_CACHE[cache_key]


# max bucket table size for the dense (sort-free) groupby path
_DENSE_MAX_RANGE = 1 << 18


def _get_compiled_minmax(mesh: Any):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("minmax", mesh)
    if cache_key not in _COMPILE_CACHE:

        def mm(k: Any, valid: Any):
            def shard_fn(k_: Any, v_: Any):
                big = jnp.where(v_, k_, jnp.iinfo(k_.dtype).max)
                small = jnp.where(v_, k_, jnp.iinfo(k_.dtype).min)
                return (
                    jax.lax.pmin(big.min(), ROW_AXIS)[None],
                    jax.lax.pmax(small.max(), ROW_AXIS)[None],
                )

            return jax.shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P(ROW_AXIS)),
                out_specs=(P(), P()),
            )(k, valid)

        _COMPILE_CACHE[cache_key] = jax.jit(mm)
    return _COMPILE_CACHE[cache_key]


def _get_compiled_dense(mesh: Any, buckets: int, agg_sig: Tuple[Tuple[str, str], ...]):
    """Sort-free per-shard groupby: scatter-add into a dense bucket table.

    Applies when the key range fits ``buckets`` — the common case — and
    avoids ``lax.sort`` entirely (sorts are the slow path on TPU; scatter
    reductions vectorize on the VPU).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("dense", mesh, buckets, agg_sig)
    if cache_key not in _COMPILE_CACHE:

        def kernel(k: Any, kmin: Any, *rest: Any):
            values = rest[:-1]
            valid = rest[-1]
            idx = jnp.where(valid, (k - kmin).astype(jnp.int32), buckets - 1)
            outs = []
            present = jnp.zeros(buckets, dtype=jnp.int64).at[idx].add(
                valid.astype(jnp.int64)
            )
            for (_, agg), v in zip(agg_sig, values):
                if agg == "sum":
                    vv = jnp.where(valid, v, jnp.zeros_like(v))
                    outs.append(jnp.zeros(buckets, dtype=v.dtype).at[idx].add(vv))
                elif agg == "count":
                    outs.append(present)
                elif agg == "min":
                    big = jnp.where(valid, v, jnp.full_like(v, _max_of(jnp, v.dtype)))
                    outs.append(
                        jnp.full(buckets, _max_of(jnp, v.dtype), dtype=v.dtype)
                        .at[idx]
                        .min(big)
                    )
                elif agg == "max":
                    small = jnp.where(valid, v, jnp.full_like(v, _min_of(jnp, v.dtype)))
                    outs.append(
                        jnp.full(buckets, _min_of(jnp, v.dtype), dtype=v.dtype)
                        .at[idx]
                        .max(small)
                    )
                else:  # pragma: no cover
                    raise NotImplementedError(agg)
            return (present,) + tuple(outs)

        n_out = 1 + len(agg_sig)
        mapped = jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(ROW_AXIS), P()) + tuple(P(ROW_AXIS) for _ in range(len(agg_sig) + 1)),
            out_specs=tuple(P(ROW_AXIS) for _ in range(n_out)),
        )
        _COMPILE_CACHE[cache_key] = jax.jit(mapped)
    return _COMPILE_CACHE[cache_key]


def _dense_groupby_partials(
    mesh: Any,
    key_name: str,
    key_arr: Any,
    agg_cols: List[Tuple[str, str, Any]],
    valid: Any,
    kmin: int,
    buckets: int,
) -> "Any":
    import jax
    import numpy as np_
    import pandas as pd

    from ..parallel.mesh import ROW_AXIS

    agg_sig = tuple((name, agg) for name, agg, _ in agg_cols)
    compiled = _get_compiled_dense(mesh, buckets, agg_sig)
    outs = compiled(
        key_arr, np_.int64(kmin), *[arr for _, _, arr in agg_cols], valid
    )
    shards = mesh.shape[ROW_AXIS]
    host = [np_.asarray(jax.device_get(o)).reshape(shards, buckets) for o in outs]
    present = host[0]
    # the overflow bucket (buckets-1) may mix padding rows; presence counts
    # only valid rows, so zero-presence buckets drop out naturally
    srow, idx = np_.nonzero(present > 0)
    data: Dict[str, Any] = {key_name: idx.astype(np_.int64) + kmin}
    for (name, _), arr in zip(agg_sig, host[1:]):
        data[name] = arr[srow, idx]
    return pd.DataFrame(data)


def device_groupby_partials(
    mesh: Any,
    key_cols: Dict[str, Any],
    agg_cols: List[Tuple[str, str, Any]],
    valid_mask: Any,
) -> "Any":
    """Run the device phase; return a host pandas frame of per-shard-group
    partials. Strategy: single int key with a small range → dense scatter-add
    (no sort); otherwise lexicographic sort + segment reduction. Only
    ``O(shards * groups)`` rows are transferred either way.
    """
    import jax
    import numpy as np_
    import pandas as pd

    from ..parallel.mesh import ROW_AXIS

    key_names = list(key_cols.keys())
    valid0 = valid_mask
    if len(key_names) == 1:
        import jax.numpy as jnp

        karr = key_cols[key_names[0]]
        if jnp.issubdtype(karr.dtype, jnp.integer):
            kmin_a, kmax_a = _get_compiled_minmax(mesh)(karr, valid0)
            kmin = int(np_.asarray(jax.device_get(kmin_a))[0])
            kmax = int(np_.asarray(jax.device_get(kmax_a))[0])
            rng = kmax - kmin + 1
            if 0 < rng <= _DENSE_MAX_RANGE:
                # pow2 bucket count bounds the number of compiled variants;
                # the top bucket is reserved for padding rows
                buckets = 1 << (rng + 1 - 1).bit_length()
                return _dense_groupby_partials(
                    mesh, key_names[0], karr, agg_cols, valid0, kmin, buckets
                )
    agg_sig = tuple((name, agg) for name, agg, _ in agg_cols)
    compiled = _get_compiled_kernel(mesh, len(key_names), agg_sig)
    valid = valid0
    in_args = (
        tuple(key_cols.values()) + tuple(arr for _, _, arr in agg_cols) + (valid,)
    )
    outs = compiled(*in_args)
    nsegs = np_.asarray(jax.device_get(outs[0]))  # (shards,) tiny transfer
    shards = mesh.shape[ROW_AXIS]
    k_max = int(nsegs.max()) if len(nsegs) > 0 else 0
    if k_max == 0:
        return pd.DataFrame({n: [] for n in key_names + [n for n, _ in agg_sig]})
    # round up to limit distinct compiled slicers
    k = 1 << (k_max - 1).bit_length()
    local_n = outs[1].shape[0] // shards
    k = min(k, local_n)
    sliced = _get_compiled_slicer(mesh, len(outs) - 1, k)(*outs[1:])
    host = [np_.asarray(jax.device_get(a)).reshape(shards, k) for a in sliced]
    # keep only the first nsegs[s] rows of each shard block
    take = np_.arange(k)[None, :] < nsegs[:, None]
    srow, idx = np_.nonzero(take)
    data = {}
    for name, arr in zip(key_names, host[: len(key_names)]):
        data[name] = arr[srow, idx]
    for (name, _), arr in zip(agg_sig, host[len(key_names) :]):
        data[name] = arr[srow, idx]
    return pd.DataFrame(data)


def merge_partials(
    partials: "Any", key_names: List[str], agg_specs: List[Tuple[str, str]]
) -> "Any":
    """Host phase: combine per-shard partials into final aggregates."""
    agg_map = {}
    for name, agg in agg_specs:
        if agg in ("sum", "count"):
            agg_map[name] = "sum"
        elif agg in ("min", "max"):
            agg_map[name] = agg
        else:  # pragma: no cover
            raise NotImplementedError(agg)
    return (
        partials.groupby(key_names, dropna=False, sort=False)
        .agg(agg_map)
        .reset_index()
    )
