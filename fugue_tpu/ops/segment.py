"""Device-side segment (groupby) aggregation kernels.

The TPU-native replacement for the reference's backend-SQL groupby
(SURVEY §7.8): a two-phase aggregate —

1. **Device phase (the O(rows) work)**: inside ``shard_map`` each shard
   lexicographically sorts its rows by the key columns (``lax.sort`` with
   ``num_keys``), derives segment ids, reduces values with
   ``jax.ops.segment_*`` and packs group representatives to the front.
   Everything is static-shape; the data-dependent group count is carried as
   a per-shard scalar (SURVEY §7 hard parts: "mask, don't branch").
2. **Host phase (the O(groups) work)**: only the first ``max_groups`` rows
   per shard cross the wire (bounded transfer); partials merge by
   re-aggregation on host.

Compiled executables are cached per (mesh, key-count, agg signature) — jit
re-tracing happens only on dtype/shape changes.

Supported aggregations: sum, count, min, max (avg = sum+count at merge).
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import collectives

_COMPILE_CACHE: Dict[Any, Any] = {}


def _norm_specs(
    agg_specs: Sequence[Tuple[Any, ...]]
) -> Tuple[Tuple[Tuple[str, str, int, bool], ...], int]:
    """Normalize agg specs to (name, agg, value_idx, nullable).

    Short forms: ``(name, agg)`` → one distinct value column per spec,
    nullable; ``(name, agg, vidx)`` → nullable. ``nullable`` means the
    (float) column may contain NaN — NaN-as-NULL handling is skipped for
    columns the caller proved null-free (the common pandas-ingestion case).
    Returns (normalized_specs, num_value_columns).
    """
    norm: List[Tuple[str, str, int, bool]] = []
    for i, spec in enumerate(agg_specs):
        if len(spec) == 2:
            norm.append((spec[0], spec[1], i, True))
        elif len(spec) == 3:
            norm.append((spec[0], spec[1], spec[2], True))
        else:
            norm.append(tuple(spec))  # type: ignore[arg-type]
    num_vals = max(s[2] for s in norm) + 1 if len(norm) > 0 else 0
    return tuple(norm), num_vals


def _agg_outputs(
    jnp: Any,
    specs: Sequence[Tuple[str, str, int, bool]],
    values: Sequence[Any],
    valid: Any,
    sum_of: Any,
    min_of: Any,
    max_of: Any,
    count_all: Any = None,
    merge_ops: Optional[Dict[str, Any]] = None,
) -> List[Any]:
    """Per-group aggregate arrays with NaN-as-NULL semantics — the single
    implementation shared by the sort+segment and dense-bucket kernels.

    ``sum_of``/``min_of``/``max_of`` inject the reduction primitive: they map
    a masked full-length row array to a per-group array. ``count_all`` is an
    optional precomputed per-group count of valid rows (the dense path's
    presence table), reused for NaN-free columns — when ``merge_ops`` is
    given it must already be cross-shard merged.

    ``merge_ops`` (optional ``{"sum", "min", "max"}`` → collective) merges
    the per-shard tables across shards ON DEVICE (psum/pmin/pmax) before
    the NULL-ify step, so the host receives one table instead of
    shards × buckets — the order matters: NULL-ify must see the GLOBAL
    non-null count, not the per-shard one.

    NaN in a nullable float column IS null: excluded from every aggregate
    (matching the oracle's dropna-first semantics) so results don't depend
    on shard layout; all-null groups come out NaN (NULL). ev/nn/agg results
    are memoized per value column — avg decomposes to sum+count of one
    column, and XLA does not reliably CSE scatter/segment reductions.
    """

    def _null_of(vidx: int) -> bool:
        nullable = any(s[2] == vidx and s[3] for s in specs)
        return nullable and jnp.issubdtype(values[vidx].dtype, jnp.floating)

    ev_cache: Dict[int, Any] = {}
    nn_cache: Dict[int, Any] = {}
    agg_cache: Dict[Tuple[str, int], Any] = {}

    def _merge(kind: str, table: Any) -> Any:
        return merge_ops[kind](table) if merge_ops is not None else table

    def _ev(vidx: int) -> Any:
        if vidx not in ev_cache:
            v = values[vidx]
            ev_cache[vidx] = (valid & ~jnp.isnan(v)) if _null_of(vidx) else valid
        return ev_cache[vidx]

    def _nn(vidx: int) -> Any:
        key = vidx if _null_of(vidx) else -1  # NaN-free columns share one count
        if key not in nn_cache:
            if key == -1 and count_all is not None:
                nn_cache[key] = count_all  # pre-merged by the caller
            else:
                nn_cache[key] = _merge(
                    "sum", sum_of(_ev(vidx).astype(jnp.int64))
                )
        return nn_cache[key]

    def _one(agg: str, vidx: int) -> Any:
        ckey = (agg, vidx)
        if ckey in agg_cache:
            return agg_cache[ckey]
        v = values[vidx]
        ev = _ev(vidx)
        may_null = _null_of(vidx)
        if agg == "sum":
            part = _merge("sum", sum_of(jnp.where(ev, v, jnp.zeros_like(v))))
            if may_null:
                part = jnp.where(_nn(vidx) > 0, part, jnp.nan)  # all-null → NULL
        elif agg == "count":
            part = _nn(vidx)
        elif agg == "min":
            part = _merge(
                "min",
                min_of(jnp.where(ev, v, jnp.full_like(v, _max_of(jnp, v.dtype)))),
            )
            if may_null:
                part = jnp.where(_nn(vidx) > 0, part, jnp.nan)
        elif agg == "max":
            part = _merge(
                "max",
                max_of(jnp.where(ev, v, jnp.full_like(v, _min_of(jnp, v.dtype)))),
            )
            if may_null:
                part = jnp.where(_nn(vidx) > 0, part, jnp.nan)
        else:  # pragma: no cover
            raise NotImplementedError(agg)
        agg_cache[ckey] = part
        return part

    return [_one(agg, vidx) for _, agg, vidx, _ in specs]


def _shard_kernel(num_keys: int, agg_specs: Sequence[Tuple[Any, ...]]):
    """Per-shard kernel: (keys..., values[num_vals], valid) →
    (nseg(1,), packed_keys...(n,), aggs...(n,)).

    ``aggs[i][j]`` is the reduction of segment j; ``packed_keys[i][j]`` its
    key — both valid for j < nseg. Value columns are deduplicated by index
    (see ``_norm_specs``) so identical reductions are computed once — XLA
    does not CSE scatter/segment ops reliably.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    specs, num_vals = _norm_specs(agg_specs)

    def kernel(*args: Any):
        keys = args[:num_keys]
        values = args[num_keys : num_keys + num_vals]
        valid = args[num_keys + num_vals]
        n = keys[0].shape[0]
        # sort invalid (padding) rows to the end, then lexicographic by keys;
        # sort a row-index payload instead of f64 values (narrow comparator)
        iota = lax.iota(jnp.int32, n)
        sorted_ops = lax.sort(
            (jnp.logical_not(valid),) + tuple(keys) + (iota,),
            num_keys=1 + num_keys,
        )
        s_keys = sorted_ops[1 : 1 + num_keys]
        perm = sorted_ops[-1]
        s_valid = valid[perm]
        s_values = [v[perm] for v in values]
        change = jnp.zeros(n, dtype=bool).at[0].set(True)
        for k in s_keys:
            change = change | jnp.concatenate(
                [jnp.ones(1, dtype=bool), k[1:] != k[:-1]]
            )
        change = change & s_valid
        nseg = change.sum(dtype=jnp.int32)
        seg_id = jnp.cumsum(change.astype(jnp.int32)) - 1
        seg_id = jnp.where(s_valid, seg_id, n - 1)
        outs = _agg_outputs(
            jnp,
            specs,
            s_values,
            s_valid,
            sum_of=lambda a: jax.ops.segment_sum(a, seg_id, num_segments=n),
            min_of=lambda a: jax.ops.segment_min(a, seg_id, num_segments=n),
            max_of=lambda a: jax.ops.segment_max(a, seg_id, num_segments=n),
        )
        # pack each segment's representative key to the front: stable argsort
        # on ~change puts segment-start rows first, in order
        starts = jnp.argsort(jnp.logical_not(change), stable=True)
        packed_keys = tuple(k[starts] for k in s_keys)
        return (nseg[None],) + packed_keys + tuple(outs)

    return kernel


def _max_of(jnp: Any, dt: Any) -> Any:
    return jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).max


def _min_of(jnp: Any, dt: Any) -> Any:
    return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).min


def _get_compiled_kernel(mesh: Any, num_keys: int, agg_sig: Tuple[Tuple[Any, ...], ...]):
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    agg_sig, num_vals = _norm_specs(agg_sig)
    cache_key = ("kernel", mesh, num_keys, agg_sig)
    if cache_key not in _COMPILE_CACHE:
        kernel = _shard_kernel(num_keys, agg_sig)
        n_in = num_keys + num_vals + 1
        n_out = 1 + num_keys + len(agg_sig)
        spec = P(ROW_AXIS)
        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                kernel,
                mesh=mesh,
                in_specs=tuple(spec for _ in range(n_in)),
                out_specs=tuple(spec for _ in range(n_out)),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_slicer(mesh: Any, n_arrays: int, k: int):
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("slice", mesh, n_arrays, k)
    if cache_key not in _COMPILE_CACHE:
        spec = P(ROW_AXIS)

        def take_k(*arrs: Any):
            return tuple(a[:k] for a in arrs)

        _COMPILE_CACHE[cache_key] = jax.jit(
            shard_map(
                take_k,
                mesh=mesh,
                in_specs=tuple(spec for _ in range(n_arrays)),
                out_specs=tuple(spec for _ in range(n_arrays)),
            )
        )
    return _COMPILE_CACHE[cache_key]


def _get_compiled_mask(mesh: Any):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("mask", mesh)
    if cache_key not in _COMPILE_CACHE:

        def mask(template: Any, row_count: Any):
            def shard_fn(t: Any, rc: Any):
                n_local = t.shape[0]
                base = jax.lax.axis_index(ROW_AXIS).astype(jnp.int64) * n_local
                return base + jax.lax.iota(jnp.int64, n_local) < rc

            return shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P()),
                out_specs=P(ROW_AXIS),
            )(template, row_count)

        _COMPILE_CACHE[cache_key] = jax.jit(mask)
    return _COMPILE_CACHE[cache_key]


# max bucket table size for the dense (sort-free) groupby path
_DENSE_MAX_RANGE = 1 << 18

# float32 SUM engine inside the dense kernel: "scatter" (XLA scatter-add),
# "onehot" (chunked one-hot MXU matmul, jnp), or "pallas" (the Pallas TPU
# kernel in ops/pallas_groupby.py). Resolution order: env FUGUE_TPU_DENSE_SUM
# → per-platform tuned default written by the bench A/B (``_tuned.json``
# next to this file, keyed by jax.default_backend()) → "scatter".
import json as _json
import os as _os
from .._utils.jax_compat import shard_map

_DENSE_SUM_BACKENDS = ("scatter", "onehot", "pallas")
_TUNED_PATH = _os.path.join(_os.path.dirname(__file__), "_tuned.json")


def _read_backend_env() -> str:
    raw = _os.environ.get("FUGUE_TPU_DENSE_SUM", "").strip().lower()
    if not raw:
        return ""
    if raw not in _DENSE_SUM_BACKENDS:
        raise ValueError(
            f"FUGUE_TPU_DENSE_SUM={raw!r} is not one of {_DENSE_SUM_BACKENDS}"
        )
    return raw


def _read_tuned_default() -> str:
    """Per-platform default chosen by the bench A/B (bench.py --capture
    writes the winner per platform). Falls back to scatter — the safe
    choice on platforms never benchmarked."""
    try:
        with open(_TUNED_PATH) as f:
            tuned = _json.load(f).get("dense_sum", {})
    except Exception:
        return "scatter"
    import jax

    name = tuned.get(jax.default_backend(), "scatter")
    return name if name in _DENSE_SUM_BACKENDS else "scatter"


class _BackendBox:
    """Lazy one-slot holder: index 0 resolves env → tuned file → scatter on
    first read (after jax backend selection settles), then sticks."""

    def __init__(self) -> None:
        self._name: str = _read_backend_env()

    def __getitem__(self, i: int) -> str:
        if not self._name:
            self._name = _read_tuned_default()
        return self._name

    def __setitem__(self, i: int, name: str) -> None:
        self._name = name


_DENSE_SUM_BACKEND = _BackendBox()


def set_dense_sum_backend(name: str) -> None:
    if name not in _DENSE_SUM_BACKENDS:
        raise ValueError(f"unknown dense sum backend {name!r}")
    _DENSE_SUM_BACKEND[0] = name
    _COMPILE_CACHE.clear()  # compiled programs bake the backend in


def _get_compiled_minmax(mesh: Any):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    cache_key = ("minmax", mesh)
    if cache_key not in _COMPILE_CACHE:

        def mm(k: Any, valid: Any):
            def shard_fn(k_: Any, v_: Any):
                big = jnp.where(v_, k_, jnp.iinfo(k_.dtype).max)
                small = jnp.where(v_, k_, jnp.iinfo(k_.dtype).min)
                return (
                    collectives.pmin(big.min(), ROW_AXIS)[None],
                    collectives.pmax(small.max(), ROW_AXIS)[None],
                )

            return shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(ROW_AXIS), P(ROW_AXIS)),
                out_specs=(P(), P()),
            )(k, valid)

        _COMPILE_CACHE[cache_key] = jax.jit(mm)
    return _COMPILE_CACHE[cache_key]


def _get_compiled_dense(mesh: Any, buckets: int, agg_sig: Tuple[Tuple[str, str], ...]):
    """Sort-free per-shard groupby: scatter-add into a dense bucket table,
    merged ACROSS shards on device (psum/pmin/pmax over the rows axis).

    Applies when the key range fits ``buckets`` — the common case — and
    avoids ``lax.sort`` entirely (sorts are the slow path on TPU; scatter
    reductions vectorize on the VPU). The cross-shard merge rides ICI and
    leaves ONE replicated table, so the host transfer is O(buckets), not
    O(shards × buckets).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS

    agg_sig, num_vals = _norm_specs(agg_sig)
    cache_key = ("dense", mesh, buckets, agg_sig, _DENSE_SUM_BACKEND[0])
    if cache_key not in _COMPILE_CACHE:

        def kernel(k: Any, kmin: Any, *rest: Any):
            values = rest[:num_vals]
            valid = rest[num_vals]
            idx = jnp.where(valid, (k - kmin).astype(jnp.int32), buckets - 1)
            present = collectives.psum(
                jnp.zeros(buckets, dtype=jnp.int64).at[idx].add(
                    valid.astype(jnp.int64)
                ),
                ROW_AXIS,
            )
            def sum_of(a: Any) -> Any:
                if (
                    _DENSE_SUM_BACKEND[0] != "scatter"
                    and a.dtype == jnp.float32
                ):
                    # one-hot MXU matmul path (ops/pallas_groupby.py):
                    # scatter on TPU serializes; histograms ride the MXU.
                    # float32 only — the MXU has no 64-bit path, so f64
                    # exactness keeps the scatter/XLA-emulation route
                    from .pallas_groupby import bin_sum_idx

                    return bin_sum_idx(idx, a, buckets, _DENSE_SUM_BACKEND[0])
                return jnp.zeros(buckets, dtype=a.dtype).at[idx].add(a)

            outs = _agg_outputs(
                jnp,
                agg_sig,
                values,
                valid,
                sum_of=sum_of,
                min_of=lambda a: (
                    jnp.full(buckets, _max_of(jnp, a.dtype), dtype=a.dtype)
                    .at[idx]
                    .min(a)
                ),
                max_of=lambda a: (
                    jnp.full(buckets, _min_of(jnp, a.dtype), dtype=a.dtype)
                    .at[idx]
                    .max(a)
                ),
                count_all=present,
                merge_ops={
                    "sum": lambda t: collectives.psum(t, ROW_AXIS),
                    "min": lambda t: collectives.pmin(t, ROW_AXIS),
                    "max": lambda t: collectives.pmax(t, ROW_AXIS),
                },
            )
            return (present,) + tuple(outs)

        n_out = 1 + len(agg_sig)
        mapped = shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(ROW_AXIS), P()) + tuple(P(ROW_AXIS) for _ in range(num_vals + 1)),
            out_specs=tuple(P() for _ in range(n_out)),
        )
        _COMPILE_CACHE[cache_key] = jax.jit(mapped)
    return _COMPILE_CACHE[cache_key]


def _dedupe_cols(
    agg_cols: Sequence[Tuple[Any, ...]]
) -> Tuple[Tuple[Tuple[str, str, int, bool], ...], List[Any]]:
    """Dedupe value arrays by identity → (specs with column indexes, arrays).

    ``agg_cols`` entries are ``(name, agg, arr)`` or ``(name, agg, arr,
    nullable)``; the same array referenced by several aggs (avg → sum+count)
    is passed to the kernel once.
    """
    uniq: Dict[int, int] = {}
    arrays: List[Any] = []
    specs: List[Tuple[str, str, int, bool]] = []
    for entry in agg_cols:
        name, agg, arr = entry[0], entry[1], entry[2]
        nullable = bool(entry[3]) if len(entry) > 3 else True
        if id(arr) not in uniq:
            uniq[id(arr)] = len(arrays)
            arrays.append(arr)
        specs.append((name, agg, uniq[id(arr)], nullable))
    return tuple(specs), arrays


def dense_buckets(rng: int) -> int:
    """Bucket count for a dense plan over a key range of ``rng`` distinct
    slots: the next power of two STRICTLY greater than ``rng``, so the
    top bucket is free for padding/invalid rows (real keys occupy
    ``[0, rng)`` and never reach it); pow2 bounds compiled variants."""
    return 1 << rng.bit_length()


def dense_kernel_parts(
    mesh: Any, agg_cols: List[Tuple[Any, ...]], buckets: int
) -> "Tuple[Any, List[Any], Tuple[Tuple[str, str, int, bool], ...]]":
    """The callable + deduped value arrays + signature of the dense-bucket
    kernel — exposed so callers can compose the kernel with further device
    work inside ONE jitted program (per-program dispatch has real latency
    on a remote-chip tunnel)."""
    agg_sig, arrays = _dedupe_cols(agg_cols)
    return _get_compiled_dense(mesh, buckets, agg_sig), arrays, agg_sig


def device_dense_groupby(
    mesh: Any,
    key_arr: Any,
    agg_cols: List[Tuple[Any, ...]],
    valid: Any,
    kmin: int,
    buckets: int,
) -> "Tuple[Any, List[Tuple[str, Any]]]":
    """Dense-bucket groupby that STAYS on device.

    Returns ``(present, [(name, array), ...])`` — per-bucket presence
    counts and aggregate tables, cross-shard merged and replicated, with
    NaN marking NULL (all-NULL groups). No host transfer happens here;
    callers either fetch (``_dense_groupby_partials``) or finish the
    result on device (the engine's device-resident aggregate)."""
    import numpy as np_

    compiled, arrays, agg_sig = dense_kernel_parts(mesh, agg_cols, buckets)
    outs = compiled(key_arr, np_.int64(kmin), *arrays, valid)
    return outs[0], [(spec[0], arr) for spec, arr in zip(agg_sig, outs[1:])]


def _dense_groupby_partials(
    mesh: Any,
    key_name: str,
    key_arr: Any,
    agg_cols: List[Tuple[Any, ...]],
    valid: Any,
    kmin: int,
    buckets: int,
) -> "Any":
    import jax
    import numpy as np_
    import pandas as pd

    present_a, named = device_dense_groupby(
        mesh, key_arr, agg_cols, valid, kmin, buckets
    )
    outs = [present_a] + [a for _, a in named]
    agg_sig = [(n,) for n, _ in named]
    # outputs are cross-shard merged + replicated: ONE table comes to host.
    # Start every copy before reading any — on a remote-chip tunnel the
    # roundtrips overlap instead of serializing.
    for o in outs:
        o.copy_to_host_async()
    host = [np_.asarray(jax.device_get(o)) for o in outs]
    present = host[0]
    # the overflow bucket (buckets-1) may mix padding rows; presence counts
    # only valid rows, so zero-presence buckets drop out naturally
    (idx,) = np_.nonzero(present > 0)
    data: Dict[str, Any] = {key_name: idx.astype(np_.int64) + kmin}
    for spec, arr in zip(agg_sig, host[1:]):
        data[spec[0]] = arr[idx]
    return pd.DataFrame(data)


class PartialsTooLarge(Exception):
    """The per-shard group count is too high for the O(shards × groups)
    host transfer — callers should fall back to a host-side plan."""


def device_groupby_partials(
    mesh: Any,
    key_cols: Dict[str, Any],
    agg_cols: List[Tuple[Any, ...]],
    valid_mask: Any,
    max_partial_rows: Optional[int] = None,
    range_hint: Optional[Tuple[int, int]] = None,
) -> "Any":
    """Run the device phase; return a host pandas frame of per-shard-group
    partials. Strategy: single int key with a small range → dense scatter-add
    (no sort); otherwise lexicographic sort + segment reduction. Only
    ``O(shards * groups)`` rows are transferred either way.

    ``agg_cols`` entries are ``(name, agg, arr)`` or ``(name, agg, arr,
    nullable)`` — ``nullable=False`` marks a float column proved NaN-free,
    which skips the NaN-as-NULL masking work in the kernels.
    ``range_hint`` is the caller's cached (min, max) of the single key
    column (``JaxDataFrame.key_range``) — it skips the device probe AND its
    device→host roundtrip.
    """
    import jax
    import numpy as np_
    import pandas as pd

    from ..parallel.mesh import ROW_AXIS

    key_names = list(key_cols.keys())
    valid0 = valid_mask
    if len(key_names) == 1:
        import jax.numpy as jnp

        karr = key_cols[key_names[0]]
        if jnp.issubdtype(karr.dtype, jnp.integer):
            if range_hint is not None:
                kmin, kmax = range_hint
            else:
                kmin_a, kmax_a = _get_compiled_minmax(mesh)(karr, valid0)
                kmin_a.copy_to_host_async()
                kmax_a.copy_to_host_async()
                kmin = int(np_.asarray(jax.device_get(kmin_a))[0])
                kmax = int(np_.asarray(jax.device_get(kmax_a))[0])
            rng = kmax - kmin + 1
            if 0 < rng <= _DENSE_MAX_RANGE:
                buckets = dense_buckets(rng)
                return _dense_groupby_partials(
                    mesh, key_names[0], karr, agg_cols, valid0, kmin, buckets
                )
    agg_sig, arrays = _dedupe_cols(agg_cols)
    compiled = _get_compiled_kernel(mesh, len(key_names), agg_sig)
    valid = valid0
    in_args = tuple(key_cols.values()) + tuple(arrays) + (valid,)
    outs = compiled(*in_args)
    nsegs = np_.asarray(jax.device_get(outs[0]))  # (shards,) tiny transfer
    shards = mesh.shape[ROW_AXIS]
    if max_partial_rows is not None and int(nsegs.sum()) > max_partial_rows:
        # cardinality guard: shipping this many partial rows would beat the
        # purpose of the bounded-transfer design
        raise PartialsTooLarge(
            f"{int(nsegs.sum())} partial rows > limit {max_partial_rows}"
        )
    k_max = int(nsegs.max()) if len(nsegs) > 0 else 0
    if k_max == 0:
        return pd.DataFrame(
            {n: [] for n in key_names + [s[0] for s in agg_sig]}
        )
    # round up to limit distinct compiled slicers
    k = 1 << (k_max - 1).bit_length()
    local_n = outs[1].shape[0] // shards
    k = min(k, local_n)
    sliced = _get_compiled_slicer(mesh, len(outs) - 1, k)(*outs[1:])
    for a in sliced:
        a.copy_to_host_async()
    host = [np_.asarray(jax.device_get(a)).reshape(shards, k) for a in sliced]
    # keep only the first nsegs[s] rows of each shard block
    take = np_.arange(k)[None, :] < nsegs[:, None]
    srow, idx = np_.nonzero(take)
    data = {}
    for name, arr in zip(key_names, host[: len(key_names)]):
        data[name] = arr[srow, idx]
    for spec, arr in zip(agg_sig, host[len(key_names) :]):
        data[spec[0]] = arr[srow, idx]
    return pd.DataFrame(data)


def merge_partials(
    partials: "Any", key_names: List[str], agg_specs: List[Tuple[str, str]]
) -> "Any":
    """Host phase: combine per-shard partials into final aggregates.

    NaN partials mean "this shard's group slice was all-NULL" — min/max use
    pandas' skipna merge, and sum uses ``min_count=1`` so a group that is
    all-NULL across every shard stays NULL instead of becoming 0.
    """

    sum_cols: List[str] = []
    agg_map: Dict[str, Any] = {}
    for name, agg in agg_specs:
        if agg == "sum":
            sum_cols.append(name)
        elif agg == "count":
            agg_map[name] = "sum"
        elif agg in ("min", "max"):
            agg_map[name] = agg
        else:  # pragma: no cover
            raise NotImplementedError(agg)
    grouped = partials.groupby(key_names, dropna=False, sort=False)
    pieces = []
    if len(sum_cols) > 0:
        # vectorized (no per-group python) NULL-preserving sum
        pieces.append(grouped[sum_cols].sum(min_count=1))
    if len(agg_map) > 0:
        pieces.append(grouped.agg(agg_map))
    merged = pieces[0] if len(pieces) == 1 else pieces[0].join(pieces[1])
    # restore the caller's column order
    return merged[[n for n, _ in agg_specs]].reset_index()
