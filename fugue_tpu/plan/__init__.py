"""Logical plan optimizer for the workflow DAG (docs/plan.md).

Runs at ``workflow.run()`` time over the task graph, before execution:

- **column pruning** — projections pushed into ``to_df``/load/stream
  producers so unread columns are never decoded or H2D-transferred;
- **filter pushdown** — filters hoisted through row-local verbs and
  inner-join sides so invalid rows are masked at the producer;
- **verb fusion** — adjacent select/filter/assign chains collapsed into
  one jitted per-chunk step;
- **segment lowering** — a fused chain flowing into a dense aggregate /
  take / distinct / broadcast-join probe collapsed into ONE
  ``shard_map``-partitioned SPMD program over the mesh (per-segment
  fallback to the per-verb path on any refusal).

Disable with ``fugue.tpu.plan.optimize=false`` (or per pass:
``.prune`` / ``.pushdown`` / ``.fuse`` / ``.lower_segments``). Every
rewrite is result-identical to the unoptimized path.

A separate post-optimization pass (``distribute.py``) partitions the
task DAG into board jobs for the fault-tolerant dist tier when
``fugue.tpu.dist.board`` is set — see docs/distributed.md.
"""

from .distribute import (
    DistributePlan,
    describe_distribution,
    execute_fragment,
    plan_distribution,
)
from .fused import FusedVerbs, apply_steps_engine, compose_steps
from .lowering import (
    LoweredSegment,
    apply_terminal_engine,
    lower_segments,
    segment_fingerprint,
)
from .optimizer import PlanReport, PlanStats, explain_tasks, optimize_tasks

__all__ = [
    "DistributePlan",
    "FusedVerbs",
    "LoweredSegment",
    "PlanReport",
    "PlanStats",
    "apply_steps_engine",
    "apply_terminal_engine",
    "compose_steps",
    "describe_distribution",
    "execute_fragment",
    "explain_tasks",
    "plan_distribution",
    "lower_segments",
    "optimize_tasks",
    "segment_fingerprint",
]
