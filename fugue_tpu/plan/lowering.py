"""Segment lowering: one SPMD program per device-resident plan segment.

PR 4's fusion pass collapses row-local verb chains into one task, but the
chain still executes as its own step with host orchestration between it
and the verb that consumes it — on the streaming hot path every chunk
crosses the host/device boundary once per verb. This pass (DrJAX-style,
arXiv:2403.07128) goes one level up: after prune/pushdown/fuse it
identifies **maximal device-resident segments** — a fused (or still
unfused) row-local chain flowing into a dense aggregate, a take, a
distinct, or a broadcast-join probe — and collapses each into ONE
:class:`LoweredSegment` task.

Execution is engine-mediated via ``engine.lowered_segment``:

- the default (every engine) interprets the segment per-verb —
  ``fused_apply`` then the terminal verb with the engine's own methods —
  which is exactly what the unlowered task pair would have run
  (bit-identical by construction). This is also the **refusal fallback**:
  any lowering ineligibility on the jax engine degrades per segment to
  this path;
- the jax engine compiles eligible segments into a single
  ``shard_map``-partitioned jitted XLA program over the mesh (via the
  ``_utils/jax_compat.py`` shim): the chain's Kleene-AND predicate and
  projections evaluate on device and feed straight into the dense-bucket
  aggregate kernel, whose cross-shard combine is an in-program collective
  (``psum``/``pmin``/``pmax`` — ``ops/segment.py``). Streaming inputs
  fold chunk-by-chunk into donated device accumulators: a chunk goes H2D
  once and never returns to host between verbs.

Everything is gated by ``fugue.tpu.plan.lower_segments`` (default ON).
A lowered segment executes under ONE ``plan.segment`` span (replacing the
per-verb ``engine.<verb>`` spans) and compiles to ONE engine jit-cache
entry labeled ``segment:<fingerprint>``.

Join segments past the broadcast probe bound route through
``engine.join``, where the strategy ladder (annotated on the plan by
``annotate_join_strategies``, docs/shuffle.md) picks copartition,
device_exchange (the staged on-device exchange — chain steps still fuse
into one program via ``fused_apply`` and the exchanged shards feed the
join kernel with zero host round trips), or the spill shuffle.
"""

from typing import Any, Dict, List, Optional, Set, Tuple

from .._utils.hash import to_uuid
from ..exceptions import FugueWorkflowError
from ..extensions.processor.processor import Processor
from .fused import describe_step
from .ir import (
    FUSABLE_KINDS,
    K_AGGREGATE,
    K_DISTINCT,
    K_FUSED,
    K_JOIN,
    K_SEGMENT,
    K_TAKE,
    LNode,
    consumers_map,
)

__all__ = [
    "LoweredSegment",
    "apply_terminal_engine",
    "describe_terminal",
    "lower_segments",
    "segment_fingerprint",
]


class LoweredSegment(Processor):
    """Execute a device-resident plan segment (row-local chain + terminal
    verb) as one engine step — ideally one compiled SPMD program."""

    def process(self, dfs: Any) -> Any:
        from .._utils.assertion import assert_or_throw

        steps = self.params.get_or_throw("steps", list)
        terminal = tuple(self.params.get_or_throw("terminal", object))
        expected = 2 if terminal[0] == "join" else 1
        assert_or_throw(
            len(dfs) == expected,
            FugueWorkflowError(
                f"lowered {terminal[0]} segment takes {expected} input(s)"
            ),
        )
        return self.execution_engine.lowered_segment(
            [dfs[i] for i in range(len(dfs))],
            steps,
            terminal,
            self.partition_spec,
            fingerprint=self.params.get("fingerprint", ""),
        )


def segment_fingerprint(steps: List[Tuple], terminal: Tuple) -> str:
    """Stable short id of a segment's program shape — labels its jit-cache
    entry, its ``plan.segment`` span and the explain() rendering."""
    return to_uuid(list(steps), list(terminal))[:8]


def describe_terminal(terminal: Tuple) -> str:
    kind = terminal[0]
    if kind == "aggregate":
        return "aggregate[" + ",".join(
            c.infer_alias().output_name for c in terminal[1]
        ) + "]"
    if kind == "take":
        return f"take[{terminal[1]}]"
    if kind == "join":
        return f"join[{terminal[1]}:{','.join(terminal[2])}]"
    return kind


def apply_terminal_engine(
    engine: Any,
    dfs: List[Any],
    steps: List[Tuple],
    terminal: Tuple,
    partition_spec: Any,
) -> Any:
    """Per-verb interpretation of a segment: the chain via
    ``engine.fused_apply`` then the terminal with the engine's own verb —
    exactly what the unlowered task pair executes (the default engine
    implementation AND the jax engine's per-segment refusal fallback)."""
    kind = terminal[0]
    probe = terminal[3] if kind == "join" else 0
    df = engine.fused_apply(dfs[probe], list(steps)) if steps else dfs[probe]
    if kind == "aggregate":
        return engine.aggregate(df, partition_spec, list(terminal[1]))
    if kind == "take":
        return engine.take(
            df,
            n=terminal[1],
            presort=terminal[2],
            na_position=terminal[3],
            partition_spec=partition_spec,
        )
    if kind == "distinct":
        return engine.distinct(df)
    if kind == "join":
        other = dfs[1 - probe]
        d1, d2 = (df, other) if probe == 0 else (other, df)
        return engine.join(d1, d2, how=terminal[1], on=list(terminal[2]))
    raise FugueWorkflowError(f"unknown segment terminal {kind}")


# ---------------------------------------------------------------------------
# the pass: chain + terminal -> K_SEGMENT
# ---------------------------------------------------------------------------


def _chain_steps(n: LNode) -> List[Tuple]:
    from .passes import _node_steps

    if n.kind == K_FUSED:
        return list(n.steps or [])
    return _node_steps(n)


def _chain_verbs(n: LNode) -> int:
    # how many ORIGINAL verbs this chain node stands for (a fused node
    # already absorbed a whole chain)
    return max(len(n.steps or []), 1) if n.kind == K_FUSED else 1


def _chainable(n: LNode) -> bool:
    from .ir import task_pinned
    from .passes import _fusable

    if n.pinned or len(n.inputs) != 1:
        return False
    if n.kind == K_FUSED:
        # a fused chain whose tail carried yield/broadcast keeps those
        # handlers on ITS task — absorbing it would lose them
        return n.tail_origin is None or not task_pinned(n.tail_origin)
    return _fusable(n)


def _collect_chain(
    tail: LNode, consumer: LNode, cons: Dict[int, List[LNode]]
) -> List[LNode]:
    """Walk producer-ward from ``tail`` (the terminal's input) collecting
    the single-consumer row-local chain, returned head→tail. Empty when
    ``tail`` is not chainable into ``consumer``."""
    if not _chainable(tail) or cons[id(tail)] != [consumer]:
        return []
    chain = [tail]
    while True:
        p = chain[0].inputs[0]
        if not _chainable(p) or cons[id(p)] != [chain[0]]:
            break
        chain.insert(0, p)
    return chain


def _terminal_spec(term: LNode) -> Optional[Tuple]:
    t = term.task
    assert t is not None
    if term.kind == K_AGGREGATE:
        return ("aggregate", tuple(t.params.get("columns", [])))
    if term.kind == K_TAKE:
        return (
            "take",
            t.params.get_or_none("n", int),
            t.params.get("presort", ""),
            t.params.get("na_position", "last"),
        )
    if term.kind == K_DISTINCT:
        return ("distinct",)
    return None  # join spec is built by the caller (needs the probe side)


def lower_segments(nodes: List[LNode], report: Any) -> None:
    """Collapse each (row-local chain → terminal verb) pair into one
    K_SEGMENT node. The terminal may carry yield/broadcast (transferred
    onto the segment task, like fusion's tail rules) but not a
    checkpoint; chain nodes must be fully unpinned — their intermediate
    results are absorbed into the segment."""
    for term in list(nodes):
        if term.kind not in (K_AGGREGATE, K_TAKE, K_DISTINCT, K_JOIN):
            continue
        if term.task is None or not term.task.checkpoint.is_null:
            continue
        cons = consumers_map(nodes)
        chain: List[LNode] = []
        side = 0
        for i, inp in enumerate(term.inputs):
            chain = _collect_chain(inp, term, cons)
            if chain:
                side = i
                break
        if not chain:
            continue
        if term.kind == K_JOIN:
            if len(term.inputs) != 2 or term.inputs[0] is term.inputs[1]:
                continue
            terminal: Optional[Tuple] = (
                "join",
                term.task.params.get_or_throw("how", str),
                tuple(term.task.params.get("on", [])),
                side,
            )
        else:
            if len(term.inputs) != 1:
                continue
            terminal = _terminal_spec(term)
        if terminal is None:
            continue
        steps: List[Tuple] = []
        for c in chain:
            steps.extend(_chain_steps(c))
        fp = segment_fingerprint(steps, terminal)
        seg = LNode(None, K_SEGMENT)
        seg.steps = steps
        seg.terminal = terminal
        seg.tail_origin = term.task
        # the segment's output IS the terminal's output; chain results are
        # absorbed (their handles raise the descriptive optimized-away
        # error, like fused interiors)
        seg.result_of = list(term.result_of)
        new_inputs = list(term.inputs)
        new_inputs[side] = chain[0].inputs[0]
        seg.inputs = new_inputs
        desc = (
            f"lowered segment {fp}: "
            + " | ".join(describe_step(s) for s in steps)
            + " -> "
            + describe_terminal(terminal)
        )
        seg.annotations.append(desc)
        if hasattr(report, "segments"):
            report.segments.append(desc)
        for c in cons[id(term)]:
            c.inputs = [seg if i is term else i for i in c.inputs]
        nodes[nodes.index(term)] = seg
        for c in chain:
            nodes.remove(c)
        report.segments_lowered += 1
        report.verbs_absorbed += sum(_chain_verbs(c) for c in chain) + 1
