"""Logical IR over the workflow task DAG.

The optimizer (``fugue_tpu/plan/optimizer.py``) never executes anything —
it inspects the ``FugueTask`` graph built by ``FugueWorkflow``, classifies
every task into a small set of logical kinds (HiFrames-style dataframe
plan nodes: create/project/filter/select/join/aggregate/...), and exposes
the two analyses the passes need:

- forward **schema inference**: the output column NAMES of each node,
  where derivable (creates over concrete data, projections, joins, ...);
  ``None`` means unknown;
- backward **column demand**: which input columns each node actually
  reads given what its consumers demand. ``ALL`` (``None``) is the
  conservative top — UDF transformers, distinct, raw SQL and any
  unrecognized extension demand everything (the "can't infer column
  usage" no-op guard).

Nodes are lightweight wrappers (``LNode``); passes mutate the wrapper
graph (rewire inputs, override params, collapse chains) and the emitter
in ``passes.py`` turns the result back into tasks, cloning only what
changed.
"""

from typing import Any, Dict, List, Optional, Set, Tuple

from ..column.expressions import (
    ColumnExpr,
    _FuncExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _WindowExpr,
)
from ..column.sql import SelectColumns
from ..schema import Schema
from ..workflow._tasks import CreateTask, FugueTask, OutputTask

# the conservative top of the column-demand lattice: "all columns"
ALL = None

# logical node kinds
K_CREATE = "create"  # CreateData over concrete data
K_LOAD = "load"  # Load from storage
K_CREATE_OPAQUE = "create?"  # any other creator
K_PROJECT = "project"  # SelectColumns (name list)
K_DROP = "drop"
K_RENAME = "rename"
K_FILTER = "filter"
K_SELECT = "select"  # column-IR select
K_ASSIGN = "assign"
K_AGGREGATE = "aggregate"
K_DISTINCT = "distinct"
K_DROPNA = "dropna"
K_FILLNA = "fillna"
K_SAMPLE = "sample"
K_TAKE = "take"
K_JOIN = "join"
K_SETOP = "setop"
K_TRANSFORM = "transform"  # UDF transformer: column usage unknowable
K_OUTPUT = "output"  # sink
K_OPAQUE = "opaque"  # anything else: zip, SQL, save_and_use, ...
K_FUSED = "fused"  # synthesized by the fusion pass
K_SEGMENT = "segment"  # synthesized by the segment-lowering pass

# kinds whose row-local semantics allow fusion into one per-chunk step
FUSABLE_KINDS = {K_PROJECT, K_DROP, K_RENAME, K_FILTER, K_SELECT, K_ASSIGN}

# kinds a device-resident segment may terminate in (lowering.py): the verb
# that consumes the fused row-local chain inside ONE compiled program
SEGMENT_TERMINAL_KINDS = {K_AGGREGATE, K_TAKE, K_DISTINCT, K_JOIN}

# kinds whose output rows each depend on exactly ONE input row — the
# precondition for partition-level delta recompute (fugue_tpu/cache/delta):
# f(old ++ new) == f(old) ++ f(new). dropna/fillna are row-local but not
# fusable (they have no per-chunk step form); distinct/take/sample are NOT
# (row identity / position spans partitions).
DELTA_ROW_LOCAL_KINDS = FUSABLE_KINDS | {K_DROPNA, K_FILLNA, K_FUSED}


def node_delta_row_local(n: "LNode") -> bool:
    """Whether this node provably computes each output row from one input
    row (delta recompute may split its input at any partition boundary).
    Mirrors the fusion pass's K_SELECT guard: an aggregating / distinct /
    HAVING select reads the whole frame. A UDF transformer qualifies when
    the static analyzer (``fugue_tpu/analysis``) proves it row-local,
    pure and deterministic — every analysis failure is False."""
    if n.kind == K_TRANSFORM:
        if n.task is None:
            return False
        a = n.info.get("analysis")
        if a is not None:
            return bool(a.row_local and a.deterministic)
        from ..analysis import transform_row_local

        return transform_row_local(n.task)
    if n.kind not in DELTA_ROW_LOCAL_KINDS:
        return False
    if n.kind == K_SELECT:
        sc = n.info["columns"]
        if sc.has_agg or sc.is_distinct or n.info.get("having") is not None:
            return False
    return True


class LNode:
    """One logical node. ``task`` is the originating FugueTask (None for
    synthesized nodes); ``info`` holds the parsed params the passes read;
    overrides make the emitter clone instead of reuse."""

    __slots__ = (
        "task",
        "kind",
        "info",
        "inputs",
        "pinned",
        "param_override",
        "extension_override",
        "steps",
        "terminal",
        "tail_origin",
        "result_of",
        "annotations",
    )

    def __init__(self, task: Optional[FugueTask], kind: str, info: Optional[dict] = None):
        self.task = task
        self.kind = kind
        self.info = info or {}
        self.inputs: List["LNode"] = []
        self.pinned = False if task is None else task_pinned(task)
        self.param_override: Optional[dict] = None
        self.extension_override: Any = None
        self.steps: Optional[List[Tuple]] = None  # K_FUSED / K_SEGMENT
        self.terminal: Optional[Tuple] = None  # K_SEGMENT only
        self.tail_origin: Optional[FugueTask] = None  # K_FUSED / K_SEGMENT
        # the ORIGINAL tasks whose result this node's output is provably
        # identical to. Rewrites that reposition a node (filter pushdown)
        # or collapse a chain (fusion) transfer this set to the node that
        # now computes that value; a node left representing nothing means
        # the original task's intermediate result is no longer computed
        # anywhere (get_result raises a descriptive error for it).
        self.result_of: List[FugueTask] = [] if task is None else [task]
        self.annotations: List[str] = []

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"LNode({self.kind})"


def task_pinned(task: FugueTask) -> bool:
    """Whether the task's result is externally observable beyond the DAG
    edges: checkpoints (storage identity is uuid-keyed), yields and
    broadcasts. Pinned nodes demand all their columns and are never
    removed or rewritten."""
    return (
        not task.checkpoint.is_null
        or task.yield_dataframe_handler is not None
        or task.broadcast_flag
    )


def expr_columns(
    expr: ColumnExpr, ignore_count_star: bool = False
) -> Optional[Set[str]]:
    """Column names referenced by an expression tree; ``ALL`` (None) when
    a wildcard or an unrecognized node makes the set unknowable.
    ``ignore_count_star`` treats ``COUNT(*)``/``COUNT(lit)`` as reading no
    columns (it only needs row existence)."""
    out: Set[str] = set()

    def walk(e: ColumnExpr) -> bool:
        if isinstance(e, _NamedColumnExpr):
            if e.wildcard:
                return False
            out.add(e.name)
            return True
        if isinstance(e, _LitColumnExpr):
            return True
        if isinstance(e, _WindowExpr):
            out.update(e.partition_by)
            for ob in e.order_by:
                try:
                    out.add(ob[0])
                except Exception:
                    return False
            return all(walk(a) for a in e.args)
        if (
            ignore_count_star
            and isinstance(e, _FuncExpr)
            and e.is_agg
            and e.func.upper() == "COUNT"
            and len(e.args) == 1
            and (
                isinstance(e.args[0], _LitColumnExpr)
                or (
                    isinstance(e.args[0], _NamedColumnExpr)
                    and e.args[0].wildcard
                )
            )
        ):
            return True
        return all(walk(c) for c in e.children)

    return out if walk(expr) else ALL


def _exprs_columns(
    exprs: List[ColumnExpr], ignore_count_star: bool = False
) -> Optional[Set[str]]:
    out: Set[str] = set()
    for e in exprs:
        cols = expr_columns(e, ignore_count_star=ignore_count_star)
        if cols is ALL:
            return ALL
        out.update(cols)
    return out


def _union(a: Optional[Set[str]], b: Optional[Set[str]]) -> Optional[Set[str]]:
    if a is ALL or b is ALL:
        return ALL
    return a | b


# ---------------------------------------------------------------------------
# task -> LNode classification
# ---------------------------------------------------------------------------


def classify(task: FugueTask) -> LNode:
    from ..extensions._builtins import creators as bc
    from ..extensions._builtins import processors as bp

    ext = task.extension
    if isinstance(task, OutputTask):
        return LNode(task, K_OUTPUT)
    # synthesized optimizer tasks (a post-optimization list may be
    # re-classified by the cache fingerprint/delta layer): recover their
    # logical kind from the carried params instead of falling to opaque
    from .fused import FusedVerbs
    from .lowering import LoweredSegment

    if isinstance(ext, FusedVerbs):
        return LNode(
            task, K_FUSED, {"steps": list(task.params.get("steps", []))}
        )
    if isinstance(ext, LoweredSegment):
        return LNode(
            task,
            K_SEGMENT,
            {
                "steps": list(task.params.get("steps", [])),
                "terminal": tuple(task.params.get_or_throw("terminal", object)),
            },
        )
    if isinstance(task, CreateTask):
        if isinstance(ext, bc.CreateData):
            data = task.params.get_or_none("data", object)
            info: Dict[str, Any] = {"data": data}
            schema_str = task.params.get_or_none("schema", object)
            if schema_str is not None:
                info["schema"] = schema_str
            info["is_stream"] = _is_stream_data(data)
            return LNode(task, K_CREATE, info)
        if isinstance(ext, bc.Load):
            return LNode(
                task,
                K_LOAD,
                {
                    "columns": task.params.get_or_none("columns", object),
                    "path": task.params.get_or_none("path", object),
                    "fmt": task.params.get("fmt", ""),
                },
            )
        return LNode(task, K_CREATE_OPAQUE)
    if isinstance(ext, bp.SelectColumns):
        cols = task.params.get("columns", [])
        if all(isinstance(c, str) for c in cols):
            return LNode(task, K_PROJECT, {"columns": list(cols)})
        return LNode(task, K_OPAQUE)
    if isinstance(ext, bp.DropColumns):
        return LNode(
            task,
            K_DROP,
            {
                "columns": list(task.params.get("columns", [])),
                "if_exists": task.params.get("if_exists", False),
            },
        )
    if isinstance(ext, bp.Rename):
        return LNode(task, K_RENAME, {"columns": dict(task.params.get("columns", {}))})
    if isinstance(ext, bp.Filter):
        return LNode(
            task, K_FILTER, {"condition": task.params.get_or_throw("condition", object)}
        )
    if isinstance(ext, bp.Select):
        return LNode(
            task,
            K_SELECT,
            {
                "columns": task.params.get_or_throw("columns", SelectColumns),
                "where": task.params.get_or_none("where", object),
                "having": task.params.get_or_none("having", object),
            },
        )
    if isinstance(ext, bp.Assign):
        return LNode(task, K_ASSIGN, {"columns": list(task.params.get("columns", []))})
    if isinstance(ext, bp.Aggregate):
        return LNode(
            task,
            K_AGGREGATE,
            {
                "columns": list(task.params.get("columns", [])),
                "keys": list(task.partition_spec.partition_by),
            },
        )
    if isinstance(ext, bp.Distinct):
        return LNode(task, K_DISTINCT)
    if isinstance(ext, bp.Dropna):
        return LNode(task, K_DROPNA, {"subset": task.params.get_or_none("subset", list)})
    if isinstance(ext, bp.Fillna):
        value = task.params.get_or_none("value", object)
        return LNode(
            task,
            K_FILLNA,
            {
                "subset": task.params.get_or_none("subset", list),
                "value_keys": list(value.keys()) if isinstance(value, dict) else [],
            },
        )
    if isinstance(ext, bp.Sample):
        return LNode(task, K_SAMPLE)
    if isinstance(ext, bp.Take):
        presort = task.params.get("presort", "") or ""
        presort_cols = [
            p.strip().split(" ")[0] for p in presort.split(",") if p.strip() != ""
        ]
        return LNode(
            task,
            K_TAKE,
            {
                "presort_cols": presort_cols,
                "keys": list(task.partition_spec.partition_by),
            },
        )
    if isinstance(ext, bp.RunJoin):
        return LNode(
            task,
            K_JOIN,
            {
                "how": task.params.get_or_throw("how", str).lower().replace("_", ""),
                "on": list(task.params.get("on", [])),
            },
        )
    if isinstance(ext, bp.RunSetOperation):
        return LNode(
            task,
            K_SETOP,
            {
                "how": task.params.get_or_throw("how", str),
                "distinct": task.params.get("distinct", True),
            },
        )
    if isinstance(ext, bp.RunTransformer):
        return LNode(task, K_TRANSFORM)
    return LNode(task, K_OPAQUE)


def _is_stream_data(data: Any) -> bool:
    from ..dataframe import DataFrame

    return isinstance(data, DataFrame) and data.is_local and not data.is_bounded


def build_graph(tasks: List[FugueTask]) -> List[LNode]:
    """Classify every task and wire LNode inputs (tasks appear in
    construction = topological order)."""
    by_id: Dict[int, LNode] = {}
    nodes: List[LNode] = []
    for t in tasks:
        n = classify(t)
        n.inputs = [by_id[id(d)] for d in t.inputs if id(d) in by_id]
        # a task referencing an input OUTSIDE the given list would break
        # rewiring invariants — treat the whole node as opaque+pinned
        if len(n.inputs) != len(t.inputs):
            n.kind = K_OPAQUE
            n.pinned = True
        by_id[id(t)] = n
        nodes.append(n)
    return nodes


def consumers_map(nodes: List[LNode]) -> Dict[int, List[LNode]]:
    out: Dict[int, List[LNode]] = {id(n): [] for n in nodes}
    for n in nodes:
        for i in n.inputs:
            out[id(i)].append(n)
    return out


# ---------------------------------------------------------------------------
# forward schema (column names) inference
# ---------------------------------------------------------------------------


def infer_schemas(nodes: List[LNode]) -> Dict[int, Optional[List[str]]]:
    """Output column names per node, None = unknown. Purely static — no
    data access beyond reading column names off concrete create inputs."""
    schemas: Dict[int, Optional[List[str]]] = {}
    for n in nodes:
        schemas[id(n)] = _node_schema(n, [schemas[id(i)] for i in n.inputs])
    return schemas


def _node_schema(
    n: LNode, in_schemas: List[Optional[List[str]]]
) -> Optional[List[str]]:
    first = in_schemas[0] if len(in_schemas) > 0 else None
    if n.kind == K_CREATE:
        schema_str = n.info.get("schema")
        if schema_str is not None:
            try:
                return list(Schema(schema_str).names)
            except Exception:
                return None
        return _data_columns(n.info.get("data"))
    if n.kind == K_LOAD:
        cols = n.info.get("columns")
        if isinstance(cols, list) and all(isinstance(c, str) for c in cols):
            return list(cols)
        if isinstance(cols, str):
            try:
                return list(Schema(cols).names)
            except Exception:
                return None
        # no explicit columns: sniff the file metadata (memoized — the
        # pushdown loop re-runs inference many times)
        if "sniffed_schema" not in n.info:
            n.info["sniffed_schema"] = sniff_load_columns(
                n.info.get("path"), n.info.get("fmt") or ""
            )
        return n.info["sniffed_schema"]
    if n.kind == K_PROJECT:
        return list(n.info["columns"])
    if n.kind == K_DROP:
        if first is None:
            return None
        dropped = set(n.info["columns"])
        return [c for c in first if c not in dropped]
    if n.kind == K_RENAME:
        if first is None:
            return None
        m = n.info["columns"]
        return [m.get(c, c) for c in first]
    if n.kind in (K_FILTER, K_SAMPLE, K_TAKE, K_DISTINCT, K_DROPNA, K_FILLNA):
        return first
    if n.kind == K_ASSIGN:
        if first is None:
            return None
        new = [c.output_name for c in n.info["columns"]]
        return list(first) + [c for c in new if c not in first]
    if n.kind == K_SELECT:
        sc: SelectColumns = n.info["columns"]
        out: List[str] = []
        for c in sc.all_cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard:
                if first is None:
                    return None
                out.extend([x for x in first if x not in out])
            else:
                name = c.output_name
                if name == "":
                    return None
                out.append(name)
        return out
    if n.kind == K_AGGREGATE:
        out = list(n.info["keys"])
        for c in n.info["columns"]:
            name = c.infer_alias().output_name
            if name == "":
                return None
            out.append(name)
        return out
    if n.kind == K_JOIN:
        if len(in_schemas) != 2 or any(s is None for s in in_schemas):
            return None
        s1, s2 = in_schemas
        how = n.info["how"]
        if how in ("semi", "leftsemi", "anti", "leftanti"):
            return list(s1)
        return list(s1) + [c for c in s2 if c not in s1]
    if n.kind == K_SETOP:
        return first
    if n.kind in (K_FUSED, K_SEGMENT):
        return None  # no pass runs after fusion/lowering
    if n.kind == K_TRANSFORM:
        # the analyzer (fugue_tpu/analysis) knows the declared output
        # schema of analyzed plain-function UDFs
        a = n.info.get("analysis")
        if a is not None and a.schema_ok:
            declared = [x for x, _ in a.declared]
            if not a.star:
                return declared
            if first is not None:
                return list(first) + [c for c in declared if c not in first]
        return None
    return None  # opaque / output


def sniff_load_columns(path: Any, fmt: str) -> Optional[List[str]]:
    """Column names of a Load source, read from file METADATA only (no
    row data is decoded). Restricted to plain parquet files: directory
    datasets go through the sidecar/hive-restore path whose column order
    and types change once an explicit column list is passed, and globs
    may span files with differing schemas — both refuse with None."""
    import os

    if not isinstance(path, str):
        return None
    try:
        from .._utils.io import FileParser

        parser = FileParser(path, fmt or None)
        if (
            parser.file_format != "parquet"
            or parser.has_glob
            or os.path.isdir(path)
        ):
            return None
        import pyarrow.parquet as pq

        return list(pq.read_schema(path).names)
    except Exception:
        return None


def estimate_load_bytes(path: Any, dropped: List[str]) -> int:
    """Compressed bytes the pruned load will no longer read, from parquet
    column-chunk metadata (0 when unknown)."""
    try:
        import pyarrow.parquet as pq

        meta = pq.ParquetFile(path).metadata
        total = 0
        wanted = set(dropped)
        for rg in range(meta.num_row_groups):
            g = meta.row_group(rg)
            for ci in range(g.num_columns):
                c = g.column(ci)
                if c.path_in_schema.split(".")[0] in wanted:
                    total += int(c.total_compressed_size)
        return total
    except Exception:
        return 0


def _data_columns(data: Any) -> Optional[List[str]]:
    import pandas as pd
    import pyarrow as pa

    from ..dataframe import DataFrame

    if isinstance(data, DataFrame):
        try:
            return list(data.schema.names)
        except Exception:
            return None
    if isinstance(data, pd.DataFrame):
        return [str(c) for c in data.columns]
    if isinstance(data, pa.Table):
        return list(data.column_names)
    return None


# ---------------------------------------------------------------------------
# backward column demand
# ---------------------------------------------------------------------------


def input_requirements(
    n: LNode,
    required_out: Optional[Set[str]],
    in_schemas: List[Optional[List[str]]],
) -> List[Optional[Set[str]]]:
    """For each input of ``n``: the set of its columns ``n`` reads, given
    that consumers read ``required_out`` of ``n``'s output. ``ALL`` is the
    conservative answer everywhere something is not statically known."""
    d = required_out
    if n.kind in (K_CREATE, K_LOAD, K_CREATE_OPAQUE):
        return []
    if n.kind == K_PROJECT:
        return [set(n.info["columns"])]
    if n.kind == K_DROP:
        # the drop still validates/removes its columns, so they must exist
        return [_union(d, set(n.info["columns"]))]
    if n.kind == K_RENAME:
        if d is ALL:
            return [ALL]
        inv = {v: k for k, v in n.info["columns"].items()}
        return [{inv.get(c, c) for c in d}]
    if n.kind == K_FILTER:
        return [_union(d, expr_columns(n.info["condition"]))]
    if n.kind == K_SELECT:
        exprs = list(n.info["columns"].all_cols)
        if n.info.get("where") is not None:
            exprs.append(n.info["where"])
        if n.info.get("having") is not None:
            exprs.append(n.info["having"])
        return [_exprs_columns(exprs, ignore_count_star=True)]
    if n.kind == K_ASSIGN:
        new_names = {c.output_name for c in n.info["columns"]}
        refs = _exprs_columns(n.info["columns"])
        if d is ALL or refs is ALL:
            return [ALL]
        return [(d - new_names) | refs]
    if n.kind == K_AGGREGATE:
        refs = _exprs_columns(n.info["columns"], ignore_count_star=True)
        return [_union(set(n.info["keys"]), refs)]
    if n.kind == K_DISTINCT:
        return [ALL]  # row identity is ALL columns
    if n.kind == K_DROPNA:
        subset = n.info.get("subset")
        if subset:
            return [_union(d, set(subset))]
        return [ALL]  # the null predicate reads every column
    if n.kind == K_FILLNA:
        extra = set(n.info.get("subset") or []) | set(n.info.get("value_keys") or [])
        return [_union(d, extra)]
    if n.kind == K_SAMPLE:
        return [d]
    if n.kind == K_TAKE:
        return [_union(d, set(n.info["presort_cols"]) | set(n.info["keys"]))]
    if n.kind == K_JOIN and len(n.inputs) == 2:
        s1, s2 = in_schemas
        how = n.info["how"]
        on = n.info["on"]
        if not on:
            if s1 is None or s2 is None:
                return [ALL, ALL]
            on = [c for c in s1 if c in s2]
        keys = set(on)
        if how in ("semi", "leftsemi", "anti", "leftanti"):
            return [_union(d, keys), set(keys)]
        if d is ALL:
            return [ALL, ALL]
        left = _union(keys, set(d) & set(s1)) if s1 is not None else ALL
        right = _union(keys, set(d) & set(s2)) if s2 is not None else ALL
        return [left, right]
    if n.kind == K_SETOP:
        if n.info["distinct"]:
            return [ALL for _ in n.inputs]
        return [d for _ in n.inputs]
    if n.kind in (K_FUSED, K_SEGMENT):
        return [ALL for _ in n.inputs]
    if n.kind == K_TRANSFORM and len(n.inputs) == 1:
        # exact column facts from the static analyzer: the UDF reads R,
        # writes W, and its declared schema decides what passes through —
        # so pruning finally commutes through analyzed UDF transformers
        a = n.info.get("analysis")
        if a is not None and a.facts_ok and a.schema_ok and a.pure:
            req = set(a.reads) | set(a.required_extra)
            if a.star:
                if d is ALL:
                    return [ALL]
                # demanded passthrough outputs must exist on the input
                # (declared new names are produced by the UDF itself)
                return [req | (set(d) - a.new_names)]
            # explicit schema: enforcement selects every declared column
            # from the returned frame; unwritten ones come from the input
            return [req | ({x for x, _ in a.declared} - set(a.writes))]
        return [ALL]
    # transform (column usage unknowable), output sinks, opaque
    return [ALL for _ in n.inputs]


def compute_demand(
    nodes: List[LNode], schemas: Dict[int, Optional[List[str]]]
) -> Dict[int, Optional[Set[str]]]:
    """Backward walk: what each node's OUTPUT must contain. Pinned nodes
    and dangling results (no consumer) demand everything."""
    cons = consumers_map(nodes)
    demand: Dict[int, Optional[Set[str]]] = {}
    for n in reversed(nodes):
        if n.pinned or len(cons[id(n)]) == 0:
            demand[id(n)] = ALL
        elif id(n) not in demand:
            demand[id(n)] = set()
    for n in reversed(nodes):
        d = demand.get(id(n), ALL)
        reqs = input_requirements(n, d, [schemas[id(i)] for i in n.inputs])
        for i, r in zip(n.inputs, reqs):
            if demand.get(id(i), set()) is not ALL:
                demand[id(i)] = _union(demand.get(id(i), set()), r)
    return demand
