"""The three plan rewrites: column pruning, filter pushdown, verb fusion.

Each pass mutates the LNode graph (``fugue_tpu/plan/ir.py``) and records
what it did on a :class:`PlanReport` (``optimizer.py``). The emitter at
the bottom turns the rewritten graph back into ``FugueTask`` objects,
REUSING every untouched original task (an unoptimizable DAG round-trips
to the identical task list) and cloning only what changed. Original
tasks never mutate — their uuids, checkpoints and yield handlers are
undisturbed, and a result-alias map keeps
``WorkflowDataFrame.result`` working for every task that still executes.
"""

from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..column.expressions import ColumnExpr, _NamedColumnExpr, col as _col
from ..workflow._tasks import FugueTask, ProcessTask
from .fused import FusedVerbs, _inline, describe_step
from .ir import (
    ALL,
    FUSABLE_KINDS,
    K_ASSIGN,
    K_CREATE,
    K_DISTINCT,
    K_DROP,
    K_DROPNA,
    K_FILLNA,
    K_FILTER,
    K_JOIN,
    K_LOAD,
    K_PROJECT,
    K_RENAME,
    K_SELECT,
    K_FUSED,
    K_SEGMENT,
    K_TRANSFORM,
    LNode,
    compute_demand,
    consumers_map,
    estimate_load_bytes,
    expr_columns,
    infer_schemas,
)

# testing hook: called with the kept column list every time a pruned
# create materializes (bounded) or emits a chunk (stream)
PRUNE_OBSERVER: Optional[Callable[[List[str]], None]] = None


def _rename_refs(e: ColumnExpr, mapping: Dict[str, str]) -> Optional[ColumnExpr]:
    """Rewrite named references through ``mapping`` (identity default)."""
    state = {n: _col(mapping.get(n, n)) for n in (expr_columns(e) or set())}
    refs = expr_columns(e)
    if refs is ALL:
        return None
    return _inline(e, state)


# ---------------------------------------------------------------------------
# pass 1: filter pushdown
# ---------------------------------------------------------------------------


def pushdown_filters(nodes: List[LNode], report: Any) -> None:
    """Hoist each Filter toward its producer through row-local verbs and
    one side of inner/cross/semi/anti joins. Every hop is a provably
    result-identical commute; anything else refuses loudly into the
    report. Single-consumer edges only (otherwise the un-filtered branch
    would have to recompute)."""
    for _ in range(len(nodes) * len(nodes) + 1):
        cons = consumers_map(nodes)
        schemas = infer_schemas(nodes)
        moved = False
        for f in list(nodes):
            if f.kind != K_FILTER or f.pinned or len(f.inputs) != 1:
                continue
            p = f.inputs[0]
            if p.pinned or cons[id(p)] != [f]:
                continue
            if _push_once(f, p, nodes, cons, schemas, report):
                moved = True
                break
        if not moved:
            return


def _push_once(
    f: LNode,
    p: LNode,
    nodes: List[LNode],
    cons: Dict[int, List[LNode]],
    schemas: Dict[int, Optional[List[str]]],
    report: Any,
) -> bool:
    cond = f.info["condition"]
    refs = expr_columns(cond)
    if refs is ALL:
        return False

    def swap(new_cond: Optional[ColumnExpr] = None) -> None:
        # X -> P -> F -> C   becomes   X -> F -> P -> C
        if new_cond is not None:
            f.info["condition"] = new_cond
            f.param_override = {"condition": new_cond}
        f.inputs = list(p.inputs)
        p.inputs = [f]
        for c in cons[id(f)]:
            c.inputs = [p if i is f else i for i in c.inputs]
        # the pair's output now materializes at P (the new tail) and is
        # identical to what F produced before the commute; P's own
        # intermediate (and F's new, earlier one) are no longer computed
        p.result_of = f.result_of
        f.result_of = []
        # emission order follows dependencies, but keep the list sane
        fi, pi = nodes.index(f), nodes.index(p)
        if fi > pi:
            nodes[fi], nodes[pi] = nodes[pi], nodes[fi]
        f.annotations.append("pushed")
        report.filters_pushed += 1

    if p.kind in (K_PROJECT, K_DROP, K_DISTINCT, K_DROPNA, K_FILTER):
        swap()
        return True
    if p.kind == K_RENAME:
        inv = {v: k for k, v in p.info["columns"].items()}
        new_cond = _rename_refs(cond, inv)
        if new_cond is None:
            report.note(f"pushdown refused: condition not rewritable through rename")
            return False
        swap(new_cond)
        return True
    if p.kind == K_FILLNA:
        filled = set(p.info.get("subset") or []) | set(p.info.get("value_keys") or [])
        if filled and not (refs & filled):
            swap()
            return True
        report.note("pushdown refused: filter reads fillna-modified columns")
        return False
    if p.kind == K_ASSIGN:
        assigned = {c.output_name for c in p.info["columns"]}
        if not (refs & assigned):
            swap()
            return True
        report.note("pushdown refused: filter reads assigned columns (fusion handles)")
        return False
    if p.kind == K_SELECT:
        sc = p.info["columns"]
        if sc.has_agg or sc.is_distinct or p.info.get("having") is not None:
            report.note("pushdown refused: select aggregates/distincts")
            return False
        # only through pass-through named outputs (computed outputs are
        # the fusion pass's job); wildcard-carried names map to themselves
        mapping: Dict[str, str] = {}
        computed: Set[str] = set()
        has_wildcard = False
        for c in sc.all_cols:
            if isinstance(c, _NamedColumnExpr) and c.wildcard:
                has_wildcard = True
            elif isinstance(c, _NamedColumnExpr) and c.as_type is None:
                mapping[c.output_name] = c.name
            else:
                computed.add(c.output_name)
        if any(
            r in computed or (r not in mapping and not has_wildcard) for r in refs
        ):
            report.note("pushdown refused: filter reads computed select columns")
            return False
        new_cond = _rename_refs(cond, mapping)
        if new_cond is None:
            return False
        swap(new_cond)
        return True
    if p.kind == K_JOIN and len(p.inputs) == 2:
        how = p.info["how"]
        s1, s2 = (schemas[id(i)] for i in p.inputs)
        side = None
        if how in ("semi", "leftsemi", "anti", "leftanti"):
            side = 0  # output schema IS the left side
        elif how in ("inner", "cross"):
            if s1 is not None and refs <= set(s1):
                side = 0
            elif s2 is not None and refs <= set(s2):
                side = 1
        else:
            report.note(f"pushdown refused: {how} join null-extends rows")
            return False
        if side is None:
            report.note("pushdown refused: join side schemas unknown or mixed refs")
            return False
        x = p.inputs[side]
        f.inputs = [x]
        new_inputs = list(p.inputs)
        new_inputs[side] = f
        p.inputs = new_inputs
        for c in cons[id(f)]:
            c.inputs = [p if i is f else i for i in c.inputs]
        # same transfer as swap(): the join output now equals the original
        # post-join filter result; the unfiltered join is gone
        p.result_of = f.result_of
        f.result_of = []
        fi, pi = nodes.index(f), nodes.index(p)
        if fi > pi:
            nodes[fi], nodes[pi] = nodes[pi], nodes[fi]
        f.annotations.append(f"pushed below {how} join ({'left' if side == 0 else 'right'})")
        report.filters_pushed += 1
        return True
    if p.kind == K_TRANSFORM:
        # a filter commutes below an analyzed UDF transformer when the
        # analyzer (fugue_tpu/analysis) proves the UDF row-local, pure and
        # deterministic (dropping rows first changes nothing row-wise),
        # under a '*' schema (names/dtypes of the filtered columns pass
        # through unchanged), and the filter reads no written column
        a = p.info.get("analysis")
        if (
            a is not None
            and a.row_local
            and a.deterministic
            and a.star
            and a.schema_ok
            and a.writes is not None
            and not (refs & (a.writes | a.new_names))
        ):
            swap()
            return True
        report.note(
            "pushdown refused: UDF transformer not provably row-local/"
            "pure or filter reads UDF-written columns"
        )
        return False
    if p.kind in (K_CREATE, K_LOAD):
        return False  # already at the producer
    report.note(f"pushdown stopped at {p.kind} (no commuting rule)")
    return False


# ---------------------------------------------------------------------------
# pass 2: column pruning
# ---------------------------------------------------------------------------


def prune_columns(nodes: List[LNode], report: Any) -> None:
    """Backward demand analysis, then push a projection into every
    create/load whose consumers read a strict subset of its columns —
    the pruned columns are never decoded or H2D-transferred (lazy-ingest
    frames drop them BEFORE device transfer; streams drop them per
    chunk inside the producer)."""
    schemas = infer_schemas(nodes)
    demand = compute_demand(nodes, schemas)
    for n in nodes:
        if n.kind not in (K_CREATE, K_LOAD) or n.pinned:
            continue
        schema = schemas[id(n)]
        d = demand.get(id(n), ALL)
        if schema is None or d is ALL:
            if n.kind in (K_CREATE, K_LOAD) and d is ALL:
                report.note(
                    f"pruning skipped at {n.kind}: a consumer demands all columns"
                )
            continue
        keep = [c for c in schema if c in d]
        if len(keep) == 0:
            keep = [schema[0]]  # preserve row count
        if len(keep) >= len(schema):
            continue
        dropped = [c for c in schema if c not in keep]
        if n.kind == K_LOAD:
            if n.info.get("columns") is not None:
                continue
            n.param_override = dict(n.task.params)
            n.param_override["columns"] = keep
            report.bytes_skipped += estimate_load_bytes(
                n.info.get("path"), dropped
            )
        else:
            n.extension_override = _PrunedCreator(n.task.extension, keep)
            report.bytes_skipped += _estimate_bytes(n.info.get("data"), dropped)
        n.annotations.append(f"pruned {len(dropped)} cols: {','.join(dropped)}")
        report.cols_pruned += len(dropped)


def _estimate_bytes(data: Any, dropped: List[str]) -> int:
    import pandas as pd
    import pyarrow as pa

    try:
        if isinstance(data, pa.Table):
            return int(sum(data.column(c).nbytes for c in dropped))
        if isinstance(data, pd.DataFrame):
            usage = data.memory_usage(index=False, deep=False)
            return int(sum(int(usage[c]) for c in dropped))
        from ..dataframe import DataFrame

        if isinstance(data, DataFrame) and data.is_bounded:
            # rough: rows x 8 bytes per dropped column
            return int(data.count() * 8 * len(dropped))
    except Exception:
        pass
    return 0


class _PrunedCreator:
    """Wraps a Creator so its result keeps only the demanded columns.

    Bounded frames select lazily (a lazy-ingest JaxDataFrame drops the
    columns from its pending arrow table, so they are never decoded or
    device_put); one-pass streams wrap the generator and select per
    chunk inside the producer."""

    def __init__(self, inner: Any, columns: List[str]):
        self._inner = inner
        self._columns = list(columns)

    @property
    def pruned_columns(self) -> List[str]:
        return self._columns

    def __uuid__(self) -> str:
        from .._utils.hash import to_uuid

        inner_uuid = getattr(
            self._inner, "__uuid__", lambda: to_uuid(type(self._inner).__name__)
        )()
        return to_uuid("_PrunedCreator", inner_uuid, self._columns)

    def create(self) -> Any:
        for a in (
            "_params",
            "_workflow_conf",
            "_execution_engine",
            "_partition_spec",
            "_rpc_server",
        ):
            if hasattr(self, a):
                setattr(self._inner, a, getattr(self, a))
        df = self._inner.create()
        return prune_frame(df, self._columns)


def prune_frame(df: Any, columns: List[str]) -> Any:
    """Project a created frame down to ``columns`` without materializing:
    streams select per chunk; bounded frames use the frame's (lazy where
    available) column selection."""
    keep = [c for c in df.schema.names if c in columns]
    if len(keep) == len(df.schema.names):
        return df
    if df.is_local and not df.is_bounded:
        from ..dataframe import LocalDataFrameIterableDataFrame

        schema = df.schema.extract(keep)
        if isinstance(df, LocalDataFrameIterableDataFrame):
            frames = df.native
        else:
            frames = iter([df])

        def gen() -> Any:
            for f in frames:
                out = f[keep]
                if PRUNE_OBSERVER is not None:
                    PRUNE_OBSERVER(list(out.schema.names))
                yield out

        return LocalDataFrameIterableDataFrame(gen(), schema=schema)
    out = df[keep]
    if PRUNE_OBSERVER is not None:
        PRUNE_OBSERVER(list(out.schema.names))
    return out


# ---------------------------------------------------------------------------
# pass 3: verb fusion
# ---------------------------------------------------------------------------


def fuse_verbs(nodes: List[LNode], report: Any) -> None:
    """Collapse maximal single-consumer chains of row-local verbs into
    one FusedVerbs task (length >= 2 anywhere; a single verb directly
    above a one-pass stream create also fuses so the step runs inside
    the chunk producer)."""
    cons = consumers_map(nodes)
    visited: Set[int] = set()
    for start in list(nodes):
        if id(start) in visited or not _fusable(start):
            continue
        # walk down to the head of the chain
        head = start
        while (
            len(head.inputs) == 1
            and _fusable(head.inputs[0])
            and cons[id(head.inputs[0])] == [head]
        ):
            head = head.inputs[0]
        # walk up collecting the chain
        chain = [head]
        while cons[id(chain[-1])] and len(cons[id(chain[-1])]) == 1:
            nxt = cons[id(chain[-1])][0]
            if not _fusable(nxt) or len(nxt.inputs) != 1:
                break
            chain.append(nxt)
        for c in chain:
            visited.add(id(c))
        # interior nodes must be fully unpinned; the tail may carry
        # yield/broadcast (transferred onto the fused task)
        if any(c.pinned for c in chain[:-1]):
            continue
        tail = chain[-1]
        # a synthesized node (e.g. a translated UDF's tail) carries its
        # origin task on tail_origin — same identity rules as a real task
        tail_task = tail.tail_origin if tail.tail_origin is not None else tail.task
        if tail_task is not None and not tail_task.checkpoint.is_null:
            continue
        stream_src = (
            len(head.inputs) == 1
            and head.inputs[0].kind == K_CREATE
            and head.inputs[0].info.get("is_stream", False)
        )
        if len(chain) < 2 and not stream_src:
            continue
        steps: List[Tuple] = []
        for c in chain:
            steps.extend(_node_steps(c))
        fused = LNode(None, K_FUSED)
        fused.steps = steps
        fused.tail_origin = tail_task
        # the fused task's output IS the chain tail's output; interior
        # results are fused away (their handles get a descriptive error)
        fused.result_of = list(tail.result_of)
        fused.inputs = list(head.inputs)
        fused.annotations.append(
            "fused " + " | ".join(describe_step(s) for s in steps)
        )
        for c in cons[id(tail)]:
            c.inputs = [fused if i is tail else i for i in c.inputs]
        pos = nodes.index(tail)
        nodes[pos] = fused
        for c in chain[:-1]:
            nodes.remove(c)
        report.verbs_fused += len(chain)
        cons = consumers_map(nodes)


def _fusable(n: LNode) -> bool:
    if n.kind not in FUSABLE_KINDS or len(n.inputs) != 1:
        return False
    if n.kind == K_SELECT:
        sc = n.info["columns"]
        if sc.has_agg or sc.is_distinct or n.info.get("having") is not None:
            return False
    return True


def _node_steps(n: LNode) -> List[Tuple]:
    if n.kind == K_PROJECT:
        return [("project", tuple(n.info["columns"]))]
    if n.kind == K_DROP:
        return [("drop", tuple(n.info["columns"]), bool(n.info["if_exists"]))]
    if n.kind == K_RENAME:
        return [("rename", dict(n.info["columns"]))]
    if n.kind == K_FILTER:
        return [("filter", n.info["condition"])]
    if n.kind == K_ASSIGN:
        return [("assign", tuple(n.info["columns"]))]
    if n.kind == K_SELECT:
        steps: List[Tuple] = []
        if n.info.get("where") is not None:
            steps.append(("filter", n.info["where"]))
        steps.append(("select", n.info["columns"]))
        return steps
    raise AssertionError(f"not fusable: {n.kind}")  # pragma: no cover


# ---------------------------------------------------------------------------
# emission: LNode graph -> task list (+ result aliases)
# ---------------------------------------------------------------------------


def emit(nodes: List[LNode]) -> Tuple[List[FugueTask], Dict[int, FugueTask]]:
    made: Dict[int, FugueTask] = {}
    aliases: Dict[int, FugueTask] = {}
    tasks: List[FugueTask] = []
    remaining = list(nodes)
    while remaining:
        progressed = False
        for n in list(remaining):
            if any(id(i) not in made for i in n.inputs):
                continue
            in_tasks = [made[id(i)] for i in n.inputs]
            t = _emit_node(n, in_tasks)
            made[id(n)] = t
            tasks.append(t)
            # aliases follow RESULT identity, not node identity: a
            # pushdown-repositioned filter's original handle resolves to
            # the new chain tail (whose output is provably the same
            # frame), never to the interior clone
            for orig in n.result_of:
                aliases[id(orig)] = t
            remaining.remove(n)
            progressed = True
        if not progressed:  # pragma: no cover - graph invariant
            raise AssertionError("optimized plan has a cycle")
    return tasks, aliases


def _emit_node(n: LNode, in_tasks: List[FugueTask]) -> FugueTask:
    if n.kind == K_SEGMENT:
        from .lowering import LoweredSegment, segment_fingerprint

        steps = list(n.steps or [])
        terminal = tuple(n.terminal or ())
        t = ProcessTask(
            LoweredSegment(),
            in_tasks,
            params=dict(
                steps=steps,
                terminal=terminal,
                fingerprint=segment_fingerprint(steps, terminal),
            ),
            partition_spec=(
                None if n.tail_origin is None else n.tail_origin.partition_spec
            ),
        )
        if n.tail_origin is not None:
            t.name = n.tail_origin.name
            t.broadcast_flag = n.tail_origin.broadcast_flag
            if n.tail_origin.yield_dataframe_handler is not None:
                t.set_yield_dataframe_handler(
                    n.tail_origin.yield_dataframe_handler
                )
            t.defined_at = n.tail_origin.defined_at
        return t
    if n.kind == K_FUSED:
        t = ProcessTask(
            FusedVerbs(),
            in_tasks,
            params=dict(steps=list(n.steps or [])),
            partition_spec=(
                None if n.tail_origin is None else n.tail_origin.partition_spec
            ),
        )
        if n.tail_origin is not None:
            t.name = n.tail_origin.name
            t.broadcast_flag = n.tail_origin.broadcast_flag
            if n.tail_origin.yield_dataframe_handler is not None:
                t.set_yield_dataframe_handler(
                    n.tail_origin.yield_dataframe_handler
                )
            t.defined_at = n.tail_origin.defined_at
        return t
    if n.task is None:
        # a synthesized plain verb (translated-UDF expansion,
        # fugue_tpu/analysis/expand.py): emit a real builtin-processor
        # task; the chain tail carries the origin transform's identity
        t = _emit_synth_plain(n, in_tasks)
        if t is not None:
            return t
    assert n.task is not None
    unchanged = (
        n.param_override is None
        and n.extension_override is None
        and len(in_tasks) == len(n.task.inputs)
        and all(a is b for a, b in zip(in_tasks, n.task.inputs))
    )
    if unchanged:
        return n.task
    return n.task.clone_with(
        extension=n.extension_override,
        params=n.param_override,
        input_tasks=in_tasks,
    )


def _emit_synth_plain(n: LNode, in_tasks: List[FugueTask]) -> Optional[FugueTask]:
    """Task for a synthesized plain-verb node (no originating task). The
    same extension/params a workflow-built verb would carry, so the task
    executes, fingerprints and classifies exactly like a hand-written one."""
    from ..extensions._builtins import processors as bp

    if n.kind == K_FILTER:
        ext: Any = bp.Filter()
        params: Dict[str, Any] = {"condition": n.info["condition"]}
    elif n.kind == K_ASSIGN:
        ext = bp.Assign()
        params = {"columns": list(n.info["columns"])}
    elif n.kind == K_SELECT:
        ext = bp.Select()
        params = {"columns": n.info["columns"]}
        if n.info.get("where") is not None:
            params["where"] = n.info["where"]
        if n.info.get("having") is not None:
            params["having"] = n.info["having"]
    elif n.kind == K_PROJECT:
        ext = bp.SelectColumns()
        params = {"columns": list(n.info["columns"])}
    elif n.kind == K_DROP:
        ext = bp.DropColumns()
        params = {
            "columns": list(n.info["columns"]),
            "if_exists": bool(n.info.get("if_exists", False)),
        }
    elif n.kind == K_RENAME:
        ext = bp.Rename()
        params = {"columns": dict(n.info["columns"])}
    else:
        return None
    t = ProcessTask(ext, in_tasks, params=params, partition_spec=None)
    if n.tail_origin is not None:
        t.name = n.tail_origin.name
        t.broadcast_flag = n.tail_origin.broadcast_flag
        if n.tail_origin.yield_dataframe_handler is not None:
            t.set_yield_dataframe_handler(n.tail_origin.yield_dataframe_handler)
        t.defined_at = n.tail_origin.defined_at
    return t
