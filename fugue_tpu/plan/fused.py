"""Verb fusion: one task for an adjacent select/filter/assign chain.

The fusion pass collapses maximal single-consumer chains of row-local
verbs (project/drop/rename/filter/select/assign) into ONE
:class:`FusedVerbs` task. Execution is engine-mediated via
``engine.fused_apply(df, steps)``:

- the default (every engine) applies the steps sequentially with the
  engine's own verbs — bit-identical to the unfused chain by
  construction;
- the jax engine compiles the whole chain into a single jitted per-chunk
  step when every step is expressible in the column IR (see
  ``JaxExecutionEngine.fused_apply``), eliminating the intermediate
  device buffers and per-verb chunk loops;
- stream-frame inputs apply the steps per chunk inside the chunk
  producer (``streaming_fused_steps``), so filtered-out rows are masked
  before H2D and the downstream jitted step, and the stream stays
  one-pass/out-of-core.

A step is a plain tuple (uuid-hashable through ``ParamDict``):

- ``("project", (names...))``
- ``("drop", (names...), if_exists)``
- ``("rename", {old: new})``
- ``("filter", ColumnExpr)``
- ``("assign", (ColumnExpr...))``
- ``("select", SelectColumns)``
"""

from typing import Any, Dict, List, Optional, Tuple

from ..column.expressions import (
    ColumnExpr,
    _BinaryOpExpr,
    _CaseWhenExpr,
    _FuncExpr,
    _InExpr,
    _LikeExpr,
    _LitColumnExpr,
    _NamedColumnExpr,
    _UnaryOpExpr,
    col as _col,
)
from ..column.sql import SelectColumns
from ..exceptions import FugueWorkflowError
from ..extensions.processor.processor import Processor

__all__ = [
    "FusedVerbs",
    "apply_steps_engine",
    "compose_steps",
    "describe_step",
]


class FusedVerbs(Processor):
    """Execute a fused chain of row-local verbs as one task."""

    def process(self, dfs: Any) -> Any:
        from .._utils.assertion import assert_or_throw

        assert_or_throw(
            len(dfs) == 1, FugueWorkflowError("fused verbs take one input")
        )
        steps = self.params.get_or_throw("steps", list)
        return self.execution_engine.fused_apply(dfs[0], steps)


def apply_steps_engine(engine: Any, df: Any, steps: List[Tuple]) -> Any:
    """Sequential fallback: interpret the steps with the engine's own
    verbs — exactly what the unfused task chain would have executed."""
    df = engine.to_df(df)
    for st in steps:
        kind = st[0]
        if kind == "project":
            df = df[list(st[1])]
        elif kind == "drop":
            names = list(st[1])
            if st[2]:  # if_exists
                names = [c for c in names if c in df.schema]
            df = df.drop(names)
        elif kind == "rename":
            df = df.rename(dict(st[1]))
        elif kind == "filter":
            df = engine.filter(df, st[1])
        elif kind == "assign":
            df = engine.assign(df, list(st[1]))
        elif kind == "select":
            df = engine.select(df, st[1])
        else:  # pragma: no cover - the fusion pass only emits the above
            raise FugueWorkflowError(f"unknown fused step {kind}")
    return df


def describe_step(st: Tuple) -> str:
    kind = st[0]
    if kind == "project":
        return f"project[{','.join(st[1])}]"
    if kind == "drop":
        return f"drop[{','.join(st[1])}]"
    if kind == "rename":
        return "rename[" + ",".join(f"{k}->{v}" for k, v in st[1].items()) + "]"
    if kind == "filter":
        return f"filter[{st[1]!r}]"
    if kind == "assign":
        return "assign[" + ",".join(c.output_name for c in st[1]) + "]"
    if kind == "select":
        return "select[" + ",".join(repr(c) for c in st[1].all_cols) + "]"
    return kind


# ---------------------------------------------------------------------------
# symbolic composition: chain -> (one predicate, one projection)
# ---------------------------------------------------------------------------


def _finish(out: ColumnExpr, e: ColumnExpr) -> ColumnExpr:
    """Restore e's cast/alias onto a rebuilt node."""
    if e.as_type is not None and out.as_type != e.as_type:
        out = out.cast(e.as_type)
    if e.as_name != "" and out.as_name != e.as_name:
        out = out.alias(e.as_name)
    return out


def _inline(e: ColumnExpr, state: Dict[str, ColumnExpr]) -> Optional[ColumnExpr]:
    """Rebuild ``e`` with every named reference replaced by its defining
    expression over the ORIGINAL input columns. None = not composable."""
    if isinstance(e, _NamedColumnExpr):
        if e.wildcard or e.name not in state:
            return None
        return _finish(state[e.name], e)
    if isinstance(e, _LitColumnExpr):
        return e
    if isinstance(e, _UnaryOpExpr):
        c = _inline(e.col, state)
        return None if c is None else _finish(_UnaryOpExpr(e.op, c), e)
    if isinstance(e, _BinaryOpExpr):
        l = _inline(e.left, state)
        r = _inline(e.right, state)
        if l is None or r is None:
            return None
        return _finish(_BinaryOpExpr(e.op, l, r), e)
    if isinstance(e, _FuncExpr) and not e.is_agg:
        args = [_inline(a, state) for a in e.args]
        if any(a is None for a in args):
            return None
        return _finish(
            _FuncExpr(e.func, *args, arg_distinct=e.is_distinct), e
        )
    if isinstance(e, _InExpr):
        c = _inline(e.col, state)
        return None if c is None else _finish(_InExpr(c, e.values, e.positive), e)
    if isinstance(e, _LikeExpr):
        c = _inline(e.col, state)
        return None if c is None else _finish(_LikeExpr(c, e.pattern, e.positive), e)
    if isinstance(e, _CaseWhenExpr):
        cases = []
        for cc, vv in e.cases:
            ic, iv = _inline(cc, state), _inline(vv, state)
            if ic is None or iv is None:
                return None
            cases.append((ic, iv))
        dd = _inline(e.default, state)
        return None if dd is None else _finish(_CaseWhenExpr(cases, dd), e)
    return None  # windows / aggregates / unknown nodes don't compose


def compose_steps(
    input_names: List[str], steps: List[Tuple]
) -> Optional[Tuple[Optional[ColumnExpr], List[ColumnExpr]]]:
    """Normalize a step chain into ``(predicate, output expressions)``
    over the ORIGINAL input columns — the single-jit form. The predicate
    is the Kleene-AND of every filter (a row survives the chain iff every
    filter is TRUE on it, which is exactly sequential filtering because
    all steps are row-local). Returns None when any step resists
    composition (the caller falls back to sequential execution)."""
    state: Dict[str, ColumnExpr] = {n: _col(n) for n in input_names}
    pred: Optional[ColumnExpr] = None
    for st in steps:
        kind = st[0]
        if kind == "project":
            names = list(st[1])
            if any(n not in state for n in names):
                return None
            state = {n: state[n] for n in names}
        elif kind == "drop":
            names = set(st[1])
            if not st[2] and any(n not in state for n in names):
                return None  # sequential path raises the proper error
            state = {k: v for k, v in state.items() if k not in names}
            if len(state) == 0:
                return None
        elif kind == "rename":
            m = dict(st[1])
            if any(k not in state for k in m):
                return None
            new_state = {m.get(k, k): v for k, v in state.items()}
            if len(new_state) != len(state):
                return None
            state = new_state
        elif kind == "filter":
            c = _inline(st[1], state)
            if c is None:
                return None
            pred = c if pred is None else (pred & c)
        elif kind == "assign":
            adds: List[Tuple[str, ColumnExpr]] = []
            for e in st[1]:
                name = e.output_name
                if name == "":
                    return None
                ie = _inline(e, state)
                if ie is None:
                    return None
                adds.append((name, ie))
            # all assign expressions evaluate against the PRE-assign frame
            # (engine.assign = one select with replacements)
            for name, ie in adds:
                state[name] = ie
        elif kind == "select":
            sc: SelectColumns = st[1]
            if sc.is_distinct or sc.has_agg:
                return None
            out: Dict[str, ColumnExpr] = {}
            for c in sc.all_cols:
                if isinstance(c, _NamedColumnExpr) and c.wildcard:
                    for k, v in state.items():
                        out.setdefault(k, v)
                    continue
                name = c.output_name
                if name == "":
                    return None
                ie = _inline(c, state)
                if ie is None:
                    return None
                out[name] = ie
            if len(out) == 0:
                return None
            state = out
        else:
            return None
    outputs = [
        (e if e.output_name == name else e.alias(name))
        for name, e in state.items()
    ]
    return pred, outputs
