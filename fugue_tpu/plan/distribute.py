"""Distributed workflow execution: partition the task DAG into board jobs.

The planner pass that routes ``workflow.run`` through the fault-tolerant
dist tier (``fugue_tpu/dist``). After optimization (and after the cache
planner cut), it scans the task DAG for *fragments* — subgraphs of the
canonical distributed shape::

    Load ──(row-local steps)──┐
                              ├── equi-JOIN / keyed AGGREGATE /
    Load ──(row-local steps)──┘    bucket-local SQL SELECT
                                        │
                              (row-local tail, ≤1 keyed aggregate)
                                        │
                                   result task

and hands each one to :meth:`DistSupervisor.run_workflow_job`: the Load
roots become leased map tasks whose bodies are the fused row-local step
chains (interpreted with the same engine verbs the local path uses), the
shuffle between map and reduce is the network-partitioned fragment
exchange, and each bucket's reduce publishes a content-addressed partial
to the shared store. The ENTIRE PR 14 recovery ladder — lease steal on
stale heartbeat, categorized TRANSIENT/WORKER_LOST re-dispatch,
orphaned-fragment invalidation, speculative straggler twins, supervisor
restart resume — applies to the workflow for free.

The refusal ladder (every rung readable in ``workflow.explain()``):
anything the planner cannot PROVE safe degrades that subgraph to local
execution with the reason recorded — non-parquet or partitioned sources,
non-row-local interior verbs (UDF transforms, distinct, take, ...),
pinned or multi-consumer interiors, cross joins, global aggregates,
tail aggregates whose keys don't cover the shuffle keys, SQL shapes that
are not bucket-local (DISTINCT, ORDER BY/LIMIT, set ops, subqueries,
grouping sets, group keys not covering the join keys), cache-served
subgraphs (a warm local cut always wins), and shuffle keys with no
canonical hashable dtype. ``fugue.tpu.dist.enabled=false`` (or an unset
``fugue.tpu.dist.board``) leaves the planner inert — the local path runs
bit-identically, by construction rather than by equivalence testing.

Correctness argument for bucket-local execution: rows are hash-bucketed
by the shuffle keys on BOTH sides, so every join match and every group
whose keys cover the shuffle keys is contained in one bucket — running
the reduce body per bucket and concatenating in bucket order is exact
(the same argument the hand-written ``plan_join_job`` jobs rely on).
Warm reruns delta-skip at two tiers: the local result cache cuts served
subgraphs before this planner sees them, and the board's
content-addressed task ids reuse done records for unchanged partitions
(``workflow_partitions_delta_skipped``).
"""

import functools
import os
from typing import Any, Dict, List, Optional, Set, Tuple

import pandas as pd

from ..workflow._tasks import FugueTask

__all__ = [
    "DistributePlan",
    "plan_distribution",
    "execute_fragment",
    "describe_distribution",
]

# load-source extensions the worker tier's read_source_paths can read
# with the SAME semantics as the engine loader (plain parquet files;
# csv/json engine loads carry header/dtype conf the workers don't mirror)
_DIST_SOURCE_EXTS = (".parquet", ".pq")


class _Refuse(Exception):
    """Planner-internal: this candidate fragment cannot distribute."""


class Fragment:
    """One distributable subgraph, resolved to a board-job recipe."""

    def __init__(
        self,
        label: str,
        result_task: FugueTask,
        covered_ids: Set[int],
        sides: List[Dict[str, Any]],
        keys: List[str],
        buckets: int,
        terminal: Tuple,
        tail_ops: List[Tuple],
        reduce_token: str,
    ):
        self.label = label
        self.result_task = result_task
        self.covered_ids = covered_ids
        self.interior_ids = covered_ids - {id(result_task)}
        self.sides = sides
        self.keys = keys
        self.buckets = buckets
        self.terminal = terminal
        self.tail_ops = tail_ops
        self.reduce_token = reduce_token

    def describe(self) -> List[str]:
        t = self.terminal
        if t[0] == "join":
            head = f"join how={t[1]} on={list(self.keys)}"
        elif t[0] == "aggregate":
            head = f"aggregate keys={list(self.keys)}"
        else:
            head = f"sql {t[2]} keys={list(self.keys)}"
        lines = [
            f"fragment -> {self.label}: {head} buckets={self.buckets} "
            f"covers {len(self.covered_ids)} task(s)"
        ]
        for s in self.sides:
            steps = " | ".join(_op_token(st) for st in s["steps"])
            lines.append(
                f"  map[{s['name']}]: {len(s['paths'])} file(s)"
                + (f" | {steps}" if steps else "")
            )
        for op in self.tail_ops:
            if op[0] == "steps":
                lines.append(
                    "  tail: " + " | ".join(_op_token(st) for st in op[1])
                )
            else:
                lines.append(f"  tail: aggregate keys={list(op[1])}")
        return lines


class DistributePlan:
    """The pass output: fragments to route, refusals to explain."""

    def __init__(self, board: str, enabled: bool):
        self.board = board
        self.enabled = enabled
        self.fragments: List[Fragment] = []
        self.refusals: List[Tuple[str, str]] = []
        self.results: Dict[int, Fragment] = {}
        self.interior_ids: Set[int] = set()

    @property
    def active(self) -> bool:
        return bool(self.board) and self.enabled


# ---------------------------------------------------------------------------
# worker-side bodies (module-level: cloudpickled by reference, the same
# package import on every worker — and shared VERBATIM by the serial
# kill-switch path, so bit-identity is by construction)
# ---------------------------------------------------------------------------

_WORKER_ENGINE: Any = None


def _worker_engine() -> Any:
    """Module-cached NativeExecutionEngine for step interpretation (cache
    and tuning off: map/reduce bodies must be pure functions of their
    input rows — the dist tier owns caching via content addresses)."""
    global _WORKER_ENGINE
    if _WORKER_ENGINE is None:
        from ..constants import (
            FUGUE_TPU_CONF_CACHE_ENABLED,
            FUGUE_TPU_CONF_TUNING_ENABLED,
        )
        from ..execution import NativeExecutionEngine

        _WORKER_ENGINE = NativeExecutionEngine(
            {
                FUGUE_TPU_CONF_CACHE_ENABLED: False,
                FUGUE_TPU_CONF_TUNING_ENABLED: False,
            }
        )
    return _WORKER_ENGINE


def _apply_ext_steps(engine: Any, df: Any, steps: List[Tuple]) -> Any:
    """Interpret the extended step grammar: the fused-verbs grammar via
    ``apply_steps_engine`` plus ``("dropna", how, thresh, subset)`` and
    ``("fillna", value, subset)`` via the matching engine verbs."""
    from .fused import apply_steps_engine

    plain: List[Tuple] = []
    for st in steps:
        if st[0] in ("dropna", "fillna"):
            if plain:
                df = apply_steps_engine(engine, df, plain)
                plain = []
            if st[0] == "dropna":
                df = engine.dropna(df, how=st[1], thresh=st[2], subset=st[3])
            else:
                df = engine.fillna(df, value=st[1], subset=st[2])
        else:
            plain.append(st)
    if plain:
        df = apply_steps_engine(engine, df, plain)
    return df


def _map_body(pdf: pd.DataFrame, *, steps: List[Tuple]) -> pd.DataFrame:
    """One map task's body: the side's row-local step chain."""
    if not steps:
        return pdf
    eng = _worker_engine()
    return _apply_ext_steps(eng, eng.to_df(pdf), steps).as_pandas()


def _reduce_body(
    *pdfs: pd.DataFrame, terminal: Tuple, tail_ops: List[Tuple]
) -> pd.DataFrame:
    """One bucket's reduce: the fragment terminal (join / keyed aggregate
    / whole SQL statement) followed by the tail ops — all via the same
    engine verbs the local path uses."""
    from ..collections.partition import PartitionSpec

    eng = _worker_engine()
    kind = terminal[0]
    if kind == "join":
        df = eng.join(
            eng.to_df(pdfs[0]),
            eng.to_df(pdfs[1]),
            how=terminal[1],
            on=list(terminal[2]),
        )
    elif kind == "aggregate":
        df = eng.aggregate(
            eng.to_df(pdfs[0]),
            PartitionSpec(by=list(terminal[1])),
            list(terminal[2]),
        )
    elif kind == "sql":
        from ..dataframe import DataFrames

        statement, names = terminal[1], terminal[2]
        dfs = DataFrames(
            {n: eng.to_df(p) for n, p in zip(names, pdfs)}
        )
        df = eng.sql_engine.select(dfs, statement)
    else:  # pragma: no cover - planner emits only the three kinds
        raise ValueError(f"unknown fragment terminal {kind!r}")
    for op in tail_ops:
        if op[0] == "steps":
            df = _apply_ext_steps(eng, df, op[1])
        else:
            df = eng.aggregate(df, PartitionSpec(by=list(op[1])), list(op[2]))
    return df.as_pandas()


# ---------------------------------------------------------------------------
# step extraction + tokens
# ---------------------------------------------------------------------------


def _steps_of(n: Any) -> Optional[List[Tuple]]:
    """A node's row-local step list in the extended grammar, or None when
    it has no step form (the refusal reason is the node's kind)."""
    from .ir import (
        K_ASSIGN,
        K_DROP,
        K_DROPNA,
        K_FILLNA,
        K_FILTER,
        K_FUSED,
        K_PROJECT,
        K_RENAME,
        K_SELECT,
    )

    t = n.task
    if n.kind == K_FUSED:
        return list(n.info.get("steps", []))
    if n.kind == K_PROJECT:
        return [("project", tuple(n.info["columns"]))]
    if n.kind == K_DROP:
        return [("drop", tuple(n.info["columns"]), bool(n.info["if_exists"]))]
    if n.kind == K_RENAME:
        return [("rename", dict(n.info["columns"]))]
    if n.kind == K_FILTER:
        return [("filter", n.info["condition"])]
    if n.kind == K_ASSIGN:
        return [("assign", tuple(n.info["columns"]))]
    if n.kind == K_SELECT:
        sc = n.info["columns"]
        if sc.has_agg or sc.is_distinct or n.info.get("having") is not None:
            return None
        steps: List[Tuple] = []
        if n.info.get("where") is not None:
            steps.append(("filter", n.info["where"]))
        steps.append(("select", sc))
        return steps
    if n.kind == K_DROPNA and t is not None:
        return [
            (
                "dropna",
                t.params.get("how", "any"),
                t.params.get_or_none("thresh", int),
                t.params.get_or_none("subset", list),
            )
        ]
    if n.kind == K_FILLNA and t is not None:
        return [
            (
                "fillna",
                t.params.get_or_none("value", object),
                t.params.get_or_none("subset", list),
            )
        ]
    return None


def _op_token(st: Tuple) -> str:
    """Deterministic description of one step — the content-address token
    fed into board task ids (NOT a pickle: cloudpickle blobs are not
    stable across processes, ``describe_step`` renderings are)."""
    from .fused import describe_step

    if st[0] == "dropna":
        return f"dropna[how={st[1]},thresh={st[2]},subset={st[3]}]"
    if st[0] == "fillna":
        return f"fillna[value={st[1]!r},subset={st[2]}]"
    return describe_step(st)


def _steps_token(steps: List[Tuple]) -> str:
    return " | ".join(_op_token(s) for s in steps)


def _terminal_token(terminal: Tuple, tail_ops: List[Tuple]) -> str:
    kind = terminal[0]
    if kind == "join":
        head = f"join[{terminal[1]},on={list(terminal[2])}]"
    elif kind == "aggregate":
        head = (
            f"aggregate[keys={list(terminal[1])},"
            f"cols={[repr(c) for c in terminal[2]]}]"
        )
    else:
        head = f"sql[{terminal[1].construct(dialect='spark')!r},names={terminal[2]}]"
    parts = [head]
    for op in tail_ops:
        if op[0] == "steps":
            parts.append(_steps_token(op[1]))
        else:
            parts.append(
                f"aggregate[keys={list(op[1])},"
                f"cols={[repr(c) for c in op[2]]}]"
            )
    return " ;; ".join(parts)


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


def plan_distribution(
    tasks: List[FugueTask],
    conf: Any,
    cache_plan: Any = None,
) -> DistributePlan:
    """Scan the (post-optimization) task list for distributable fragments.
    Never raises: every obstacle is a recorded refusal and the subgraph
    stays local. ``cache_plan`` (when present) blocks fragments whose
    tasks the local cache already serves — a warm local cut always wins."""
    from ..constants import (
        FUGUE_TPU_CONF_DIST_BOARD,
        FUGUE_TPU_CONF_DIST_BUCKETS,
        FUGUE_TPU_CONF_DIST_ENABLED,
    )

    board = str(conf.get(FUGUE_TPU_CONF_DIST_BOARD, "") or "")
    enabled = bool(conf.get(FUGUE_TPU_CONF_DIST_ENABLED, True))
    plan = DistributePlan(board, enabled)
    if not plan.active:
        return plan
    buckets = int(conf.get(FUGUE_TPU_CONF_DIST_BUCKETS, 8))
    from .ir import K_AGGREGATE, K_JOIN, K_SEGMENT, classify

    ln = {id(t): classify(t) for t in tasks}
    cons: Dict[int, int] = {}
    for t in tasks:
        for d in t.inputs:
            cons[id(d)] = cons.get(id(d), 0) + 1
    blocked: Set[int] = set()
    if cache_plan is not None:
        blocked |= set(cache_plan.hits) | set(cache_plan.delta_hits)
        blocked |= set(cache_plan.skipped) | set(cache_plan.checkpoint_hits)
    used: Set[int] = set()
    for i, t in enumerate(tasks):
        if id(t) in used:
            continue
        n = ln[id(t)]
        is_sql = _is_plain_sql(t)
        # a lowered segment is itself a shuffle-point candidate when its
        # terminal is a join or a KEYED aggregate (the lowering pass runs
        # before this one, so segments are what joins/aggregates with
        # row-local chains look like post-optimization)
        is_seg = False
        if n.kind == K_SEGMENT:
            term_spec = n.info.get("terminal") or (None,)
            is_seg = term_spec[0] == "join" or (
                term_spec[0] == "aggregate"
                and list(t.partition_spec.partition_by)
            )
        if not (
            n.kind == K_JOIN
            or (n.kind == K_AGGREGATE and n.info.get("keys"))
            or is_seg
            or is_sql
        ):
            continue
        label = f"t{i} {type(t.extension).__name__}" + (
            f" ({t.name})" if t.name else ""
        )
        try:
            frag = _build_fragment(
                t, label, ln, cons, blocked, used, buckets, is_sql, is_seg
            )
        except _Refuse as r:
            plan.refusals.append((label, str(r)))
            continue
        plan.fragments.append(frag)
        used |= frag.covered_ids
        plan.results[id(frag.result_task)] = frag
        plan.interior_ids |= frag.interior_ids
    return plan


def _is_plain_sql(t: FugueTask) -> bool:
    from ..extensions._builtins.processors import RunSQLSelect

    return isinstance(t.extension, RunSQLSelect)


def _check_interior(t: FugueTask, cons: Dict[int, int], blocked: Set[int],
                    used: Set[int], what: str) -> None:
    from .ir import task_pinned

    if id(t) in used:
        raise _Refuse(f"{what} is already claimed by another fragment")
    if id(t) in blocked:
        raise _Refuse(
            f"{what} is served by the local result cache (warm cut wins)"
        )
    if task_pinned(t):
        raise _Refuse(
            f"{what} is pinned (checkpoint/yield/broadcast must "
            "materialize locally)"
        )
    if cons.get(id(t), 0) != 1:
        raise _Refuse(
            f"{what} feeds {cons.get(id(t), 0)} consumers (its intermediate "
            "frame must materialize locally)"
        )


def _expand_load(t: FugueTask, n: Any) -> Tuple[List[str], List[Tuple]]:
    """A Load root → (worker-readable file list, projection step prefix);
    refuses anything ``read_source_paths`` cannot reproduce byte-for-byte
    semantically (non-parquet, load kwargs, schema coercion, partitioned
    directory datasets, sidecar schemas)."""
    from .._utils.io import FileParser

    path = n.info.get("path")
    if not isinstance(path, str):
        raise _Refuse("load path is not a plain string")
    if dict(t.params.get("params", {})):
        raise _Refuse("load carries reader kwargs workers don't mirror")
    try:
        parser = FileParser(path, n.info.get("fmt") or None)
        fmt = parser.file_format
        files = parser.find_files()
    except Exception as e:
        raise _Refuse(f"load source not resolvable at plan time ({e})")
    if fmt != "parquet":
        raise _Refuse(
            f"{fmt} sources don't distribute (engine reader semantics — "
            "header/dtype conf — are not mirrored by workers)"
        )
    if not files:
        raise _Refuse("load matched no files")
    for f in files:
        if os.path.isdir(f):
            raise _Refuse("partitioned (hive) dataset directories stay local")
        if os.path.splitext(f)[1].lower() not in _DIST_SOURCE_EXTS:
            raise _Refuse(f"unsupported source extension on {f!r}")
    if os.path.isdir(path) and os.path.exists(
        os.path.join(path, "_fugue_schema")
    ):
        raise _Refuse("dataset carries a _fugue_schema sidecar (stays local)")
    cols = n.info.get("columns")
    if cols is None:
        return files, []
    if isinstance(cols, list) and all(isinstance(c, str) for c in cols):
        return files, [("project", tuple(cols))]
    raise _Refuse("load with schema coercion (non name-list columns)")


def _side_chain(
    t: FugueTask,
    ln: Dict[int, Any],
    cons: Dict[int, int],
    blocked: Set[int],
    used: Set[int],
) -> Tuple[List[str], List[Tuple], Set[int]]:
    """Walk from a terminal input down to its Load root, converting every
    interior node to row-local steps. Returns (paths, steps, covered)."""
    from .ir import K_LOAD

    rev: List[Tuple[FugueTask, Any]] = []
    cur = t
    while True:
        n = ln[id(cur)]
        if n.kind == K_LOAD:
            _check_interior(cur, cons, blocked, used, f"load {_tlabel(cur)}")
            paths, prefix = _expand_load(cur, n)
            steps = list(prefix)
            covered = {id(cur)}
            for task, node in reversed(rev):
                steps.extend(_steps_of(node) or [])
                covered.add(id(task))
            return paths, steps, covered
        _check_interior(cur, cons, blocked, used, _tlabel(cur))
        if _steps_of(n) is None:
            raise _Refuse(
                f"{_tlabel(cur)} ({n.kind}) is not row-local-distributable"
            )
        if len(cur.inputs) != 1:
            raise _Refuse(f"{_tlabel(cur)} has {len(cur.inputs)} inputs")
        rev.append((cur, n))
        cur = cur.inputs[0]


def _tlabel(t: FugueTask) -> str:
    return t.name or type(t.extension).__name__


def _has_window_expr(e: Any) -> bool:
    from ..column.expressions import _WindowExpr

    if isinstance(e, _WindowExpr):
        return True
    return any(_has_window_expr(c) for c in getattr(e, "children", ()) or ())


def _sql_terminal(t: FugueTask) -> Tuple[Tuple, List[str], List[int]]:
    """Validate a RunSQLSelect statement as bucket-local and return
    ``(("sql", statement, scan_names), shuffle_keys, input_positions)``.
    Accepted shapes: two-table equi-join (optional row-local residual /
    WHERE / HAVING, group keys covering the join keys) and single-table
    keyed GROUP BY. Everything else refuses with the specific rung."""
    from ..column.expressions import _NamedColumnExpr
    from ..column.functions import is_agg
    from ..sql.parser import JoinNode, Scan, SelectNode, SQLParser

    if t.params.get_or_none("sql_engine", object) is not None:
        raise _Refuse("engine-specific SQL (CONNECT) stays local")
    statement = t.params.get_or_throw("statement", object)
    raw = statement.construct(dialect="spark")  # mirror LocalSQLEngine
    if raw.lower().count("select") > 1:
        raise _Refuse("nested SELECT (subquery/CTE/set op) is not bucket-local")
    try:
        node = SQLParser(raw).parse_full()
    except Exception as e:
        raise _Refuse(f"SQL not parseable at plan time ({e})")
    if not isinstance(node, SelectNode):
        raise _Refuse(
            f"{type(node).__name__} (ORDER BY/LIMIT/set op) is not "
            "bucket-local"
        )
    if node.distinct:
        raise _Refuse("SELECT DISTINCT is not bucket-local")
    if node.grouping_sets:
        raise _Refuse("GROUPING SETS/ROLLUP/CUBE are not bucket-local")
    for e in list(node.projections) + (
        [node.where] if node.where is not None else []
    ) + ([node.having] if node.having is not None else []):
        if _has_window_expr(e):
            raise _Refuse("window functions are not bucket-local")
    group_names: List[str] = []
    for g in node.group_by:
        if not isinstance(g, _NamedColumnExpr) or g.wildcard:
            raise _Refuse("non-column GROUP BY expressions stay local")
        group_names.append(g.name)
    child = node.child
    if isinstance(child, JoinNode):
        if not isinstance(child.left, Scan) or not isinstance(
            child.right, Scan
        ):
            raise _Refuse("only two-table FROM a JOIN b distributes")
        if child.how == "cross" or not child.on:
            raise _Refuse("cross/non-equi joins are not bucket-local")
        if child.condition is not None and child.how != "inner":
            raise _Refuse("residual ON predicates distribute for INNER only")
        keys = list(child.on)
        names = [child.left.name, child.right.name]
        if names[0] == names[1]:
            raise _Refuse("self-joins stay local")
        if group_names:
            if not set(group_names) >= set(keys):
                raise _Refuse(
                    f"GROUP BY {group_names} does not cover the join keys "
                    f"{keys} (groups would span buckets)"
                )
        elif any(is_agg(p) for p in node.projections):
            raise _Refuse("global (ungrouped) aggregates span buckets")
        return ("sql", statement, names), keys, _scan_positions(t, names)
    if isinstance(child, Scan):
        if not group_names:
            raise _Refuse(
                "single-table SELECT has no shuffle point (no GROUP BY keys)"
            )
        names = [child.name]
        return ("sql", statement, names), group_names, _scan_positions(
            t, names
        )
    raise _Refuse(
        f"FROM {type(child).__name__ if child else 'nothing'} is not "
        "distributable"
    )


def _scan_positions(t: FugueTask, names: List[str]) -> List[int]:
    in_names = list(t.input_names or [])
    pos = []
    for name in names:
        if name not in in_names:
            raise _Refuse(
                f"SQL table {name!r} is not a direct workflow input "
                f"(inputs: {in_names})"
            )
        pos.append(in_names.index(name))
    return pos


def _build_fragment(
    term: FugueTask,
    label: str,
    ln: Dict[int, Any],
    cons: Dict[int, int],
    blocked: Set[int],
    used: Set[int],
    buckets: int,
    is_sql: bool,
    is_seg: bool = False,
) -> Fragment:
    from .ir import K_AGGREGATE, task_pinned

    n = ln[id(term)]
    if id(term) in blocked:
        raise _Refuse("terminal is served by the local result cache")
    # a lowered segment's own row-local chain applies to ONE side (the
    # probe side for joins, the only side for aggregates) AFTER that
    # side's upstream steps
    seg_steps: List[Tuple] = []
    seg_side = 0
    # terminal shape → (terminal tuple, shuffle keys, side input tasks)
    if is_sql:
        terminal, keys, positions = _sql_terminal(term)
        side_tasks = [term.inputs[p] for p in positions]
    elif is_seg:
        spec = tuple(n.info["terminal"])
        seg_steps = list(n.info.get("steps", []))
        if spec[0] == "join":
            how_raw = spec[1]
            if how_raw.lower().replace("_", "") == "cross" or not spec[2]:
                raise _Refuse("cross/non-equi joins are not bucket-local")
            if len(term.inputs) != 2:
                raise _Refuse("segment join without two inputs")
            keys = list(spec[2])
            terminal = ("join", how_raw, keys)
            seg_side = int(spec[3])
            side_tasks = list(term.inputs)
        else:  # keyed aggregate segment
            keys = list(term.partition_spec.partition_by)
            terminal = ("aggregate", keys, list(spec[1]))
            if len(term.inputs) != 1:
                raise _Refuse("segment aggregate without a single input")
            side_tasks = [term.inputs[0]]
    elif n.kind == K_AGGREGATE:
        keys = list(n.info["keys"])
        terminal = ("aggregate", keys, list(n.info["columns"]))
        if len(term.inputs) != 1:
            raise _Refuse("aggregate with multiple inputs")
        side_tasks = [term.inputs[0]]
    else:  # join
        how_raw = term.params.get_or_throw("how", str)
        if n.info["how"] == "cross":
            raise _Refuse("cross joins are not bucket-local")
        if len(term.inputs) != 2:
            raise _Refuse(
                f"{len(term.inputs)}-way join chains stay local "
                "(only binary joins distribute)"
            )
        keys = list(n.info["on"])  # may be empty: inferred from probe below
        terminal = ("join", how_raw, keys)
        side_tasks = list(term.inputs)
    # side chains
    sides: List[Dict[str, Any]] = []
    covered: Set[int] = {id(term)}
    for name, st in zip(("left", "right"), side_tasks):
        paths, steps, side_cov = _side_chain(st, ln, cons, blocked, used)
        if side_cov & covered:
            raise _Refuse("sides share an input chain (self-join) — stays local")
        covered |= side_cov
        sides.append({"name": name, "paths": paths, "steps": steps})
    if seg_steps:
        sides[seg_side]["steps"] = list(sides[seg_side]["steps"]) + seg_steps
    for s in sides:
        s["token"] = _steps_token(s["steps"])
    # tail extension: row-local steps and at most one keyed aggregate
    # whose keys cover the shuffle keys (bucket-local ⇒ exact). A pinned
    # node may end the tail (it materializes as the fragment result);
    # interiors must stay unpinned and single-consumer.
    tail_ops: List[Tuple] = []
    pending: List[Tuple] = []
    seen_tail_agg = False

    def _extend(result: FugueTask) -> FugueTask:
        nonlocal seen_tail_agg
        while True:
            if task_pinned(result) or cons.get(id(result), 0) != 1:
                return result
            nxt = _single_consumer(result, ln)
            if nxt is None or id(nxt) in blocked or id(nxt) in used:
                return result
            m = ln[id(nxt)]
            st = _steps_of(m)
            if st is not None:
                pending.extend(st)
            elif (
                m.kind == K_AGGREGATE
                and m.info.get("keys")
                and not seen_tail_agg
                and set(m.info["keys"]) >= set(keys or [])
                and len(nxt.inputs) == 1
            ):
                if pending:
                    tail_ops.append(("steps", list(pending)))
                    pending.clear()
                tail_ops.append(
                    ("aggregate", list(m.info["keys"]), list(m.info["columns"]))
                )
                seen_tail_agg = True
            else:
                return result
            covered.add(id(nxt))
            result = nxt

    result = _extend(term)
    if pending:
        tail_ops.append(("steps", list(pending)))
    # probe: run the whole fragment over ≤16 head rows per side with the
    # SAME bodies the workers execute — any failure is a plan-time
    # refusal, never a distributed POISON surprise; also infers empty
    # join keys and proves the keys co-bucketable
    keys, buckets = _probe_fragment(sides, terminal, tail_ops, keys, buckets)
    return Fragment(
        label=label,
        result_task=result,
        covered_ids=covered,
        sides=sides,
        keys=keys,
        buckets=buckets,
        terminal=terminal,
        tail_ops=tail_ops,
        reduce_token=_terminal_token(terminal, tail_ops),
    )


def _single_consumer(t: FugueTask, ln: Dict[int, Any]) -> Optional[FugueTask]:
    for node in ln.values():
        task = node.task
        if task is not None and any(d is t for d in task.inputs):
            return task
    return None


def _probe_fragment(
    sides: List[Dict[str, Any]],
    terminal: Tuple,
    tail_ops: List[Tuple],
    keys: List[str],
    buckets: int,
) -> Tuple[List[str], int]:
    from ..dist.worker import read_source_paths
    from ..shuffle.partitioner import canonical_key_kinds

    import pyarrow as pa

    mapped: List[pd.DataFrame] = []
    for s in sides:
        try:
            pdf = read_source_paths(s["paths"][:1]).head(16)
            mapped.append(_map_body(pdf, steps=s["steps"]))
        except Exception as e:
            raise _Refuse(f"map[{s['name']}] probe failed: {e}")
    if terminal[0] == "join" and not keys:
        left_cols = list(mapped[0].columns)
        right_cols = set(mapped[1].columns)
        keys = [c for c in left_cols if c in right_cols]
        if not keys:
            raise _Refuse("join has no common columns to infer keys from")
        terminal_keys = terminal[2]
        terminal_keys.extend(keys)
    for s, pdf in zip(sides, mapped):
        missing = [k for k in keys if k not in pdf.columns]
        if missing:
            raise _Refuse(
                f"shuffle keys {missing} missing from map[{s['name']}] output"
            )
    schemas = [
        pa.Table.from_pandas(p.head(0), preserve_index=False).schema
        for p in mapped
    ]
    fields = [
        {nm: sc.field(nm) for nm in sc.names} for sc in schemas
    ]
    if canonical_key_kinds(fields[0], fields[-1], list(keys)) is None:
        raise _Refuse(
            f"shuffle keys {list(keys)} have no canonical hashable dtype "
            "(the exchange cannot co-bucket them)"
        )
    try:
        _reduce_body(*mapped, terminal=terminal, tail_ops=tail_ops)
    except Exception as e:
        raise _Refuse(f"reduce probe failed: {e}")
    return list(keys), buckets


# ---------------------------------------------------------------------------
# execution (called from the workflow context per result task)
# ---------------------------------------------------------------------------


def _supervisor_for(engine: Any, root: str, conf: Any) -> Any:
    """One cached DistSupervisor per engine+board: its DistStats registers
    as ``engine.stats()["dist"]`` once and accumulates across runs (the
    registry reset contract zeroes it like every other source)."""
    from ..dist.supervisor import DistSupervisor

    sup = getattr(engine, "_wf_dist_supervisor", None)
    if sup is None or os.path.abspath(str(sup.board.root)) != os.path.abspath(
        root
    ):
        sup = DistSupervisor(root, engine=engine, conf=dict(conf))
        engine._wf_dist_supervisor = sup
    return sup


def execute_fragment(frag: Fragment, engine: Any, conf: Any) -> pd.DataFrame:
    """Run one fragment through ``DistSupervisor.run_workflow_job``. The
    supervisor's kill-switch serial path never runs here — the planner is
    inert when ``fugue.tpu.dist.enabled=false`` — but stays wired so a
    conf flip between plan and run still degrades safely."""
    from ..constants import (
        FUGUE_TPU_CONF_DIST_BOARD,
        FUGUE_TPU_CONF_DIST_WORKFLOW_TIMEOUT_S,
    )

    root = str(conf.get(FUGUE_TPU_CONF_DIST_BOARD, ""))
    sup = _supervisor_for(engine, root, conf)
    timeout = float(conf.get(FUGUE_TPU_CONF_DIST_WORKFLOW_TIMEOUT_S, 0.0))
    left = frag.sides[0]
    right = frag.sides[1] if len(frag.sides) > 1 else None

    def side_fn(s: Optional[Dict[str, Any]]) -> Any:
        if s is None or not s["steps"]:
            return None
        return functools.partial(_map_body, steps=list(s["steps"]))

    return sup.run_workflow_job(
        list(left["paths"]),
        None if right is None else list(right["paths"]),
        list(frag.keys),
        functools.partial(
            _reduce_body, terminal=frag.terminal, tail_ops=list(frag.tail_ops)
        ),
        map_left=side_fn(left),
        map_right=side_fn(right),
        buckets=frag.buckets,
        tokens={
            "left": left["token"],
            **({"right": right["token"]} if right is not None else {}),
            "reduce": frag.reduce_token,
        },
        timeout=timeout if timeout > 0 else None,
    )


# ---------------------------------------------------------------------------
# explain rendering
# ---------------------------------------------------------------------------


def describe_distribution(tasks: List[FugueTask], conf: Any) -> List[str]:
    """The board plan for ``workflow.explain()``: every fragment with its
    map/reduce recipe, every refusal with its rung. Dry run — no board
    writes, no cache consultation (warm local cuts are shown by the cache
    section above; at run time they additionally block fragments)."""
    from ..constants import FUGUE_TPU_CONF_DIST_BOARD, FUGUE_TPU_CONF_DIST_ENABLED

    board = str(conf.get(FUGUE_TPU_CONF_DIST_BOARD, "") or "")
    if not board:
        return [
            "== distributed workflows: off (set fugue.tpu.dist.board to a "
            "shared dir to enable) =="
        ]
    if not bool(conf.get(FUGUE_TPU_CONF_DIST_ENABLED, True)):
        return [
            "== distributed workflows: disabled "
            "(fugue.tpu.dist.enabled=false) =="
        ]
    try:
        plan = plan_distribution(tasks, conf, cache_plan=None)
    except Exception as e:  # planning must never break explain
        return [f"== distributed workflows: planner error ({e}) =="]
    lines = [
        f"== distributed workflows (board={board}, "
        f"{len(plan.fragments)} fragment(s), {len(plan.refusals)} refused) =="
    ]
    for f in plan.fragments:
        lines.extend("  " + ln for ln in f.describe())
    for label, why in plan.refusals:
        lines.append(f"  not distributed {label}: {why}")
    if not plan.fragments and not plan.refusals:
        lines.append(
            "  no shuffle points (joins / keyed aggregates / bucket-local "
            "SQL) found — everything runs locally"
        )
    return lines
