"""Plan optimizer orchestration: conf gates, report, metrics, explain.

``optimize_tasks`` is the single entry point ``FugueWorkflow.run`` calls
before execution. Everything is gated by ``fugue.tpu.plan.optimize``
(default ON) with per-pass switches; the unoptimized path is always one
conf key away, and the parity suite (``tests/plan/test_optimizer.py``)
asserts both paths produce bit-identical results.
"""

import threading
from typing import Any, Dict, List, Optional, Set, Tuple

from ..constants import (
    FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS,
    FUGUE_TPU_CONF_PLAN_FUSE,
    FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS,
    FUGUE_TPU_CONF_PLAN_OPTIMIZE,
    FUGUE_TPU_CONF_PLAN_PRUNE,
    FUGUE_TPU_CONF_PLAN_PUSHDOWN,
    FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS,
)
from ..workflow._tasks import FugueTask
from .ir import (
    K_CREATE,
    K_DISTINCT,
    K_DROP,
    K_DROPNA,
    K_FILLNA,
    K_FILTER,
    K_JOIN,
    K_LOAD,
    K_PROJECT,
    K_RENAME,
    K_SAMPLE,
    K_SELECT,
    K_TAKE,
    LNode,
    build_graph,
)
from .lowering import lower_segments
from .passes import emit, fuse_verbs, prune_columns, pushdown_filters

__all__ = [
    "PlanReport",
    "PlanStats",
    "optimize_tasks",
    "explain_tasks",
    "annotate_delta_eligibility",
]


class PlanStats:
    """Engine-level optimizer counters (an ``engine.metrics`` source).

    Thread-safe since ISSUE 10: concurrent serving runs ``absorb``/
    ``inc`` from many sessions on one engine — bare ``+=`` was losing
    updates. Same narrow-lock pattern as ``CacheStats``/``ShuffleStats``."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.runs = 0
            self.cols_pruned = 0
            self.filters_pushed = 0
            self.verbs_fused = 0
            self.bytes_skipped = 0
            self.segments_lowered = 0
            self.verbs_absorbed = 0
            # execution-side counters (via ``inc`` from engine.lowered_segment):
            # a lowered segment ran as ONE compiled program / fell back to the
            # per-verb path — together they make the "one program per segment"
            # claim checkable from stats alone
            self.segments_executed = 0
            self.segments_fallback = 0

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)

    def absorb(self, report: "PlanReport") -> None:
        with self._lock:
            self.runs += 1
            self.cols_pruned += report.cols_pruned
            self.filters_pushed += report.filters_pushed
            self.verbs_fused += report.verbs_fused
            self.bytes_skipped += report.bytes_skipped
            self.segments_lowered += report.segments_lowered
            self.verbs_absorbed += report.verbs_absorbed

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return {
                "runs": self.runs,
                "cols_pruned": self.cols_pruned,
                "filters_pushed": self.filters_pushed,
                "verbs_fused": self.verbs_fused,
                "bytes_skipped": self.bytes_skipped,
                "segments_lowered": self.segments_lowered,
                "verbs_absorbed": self.verbs_absorbed,
                "segments_executed": self.segments_executed,
                "segments_fallback": self.segments_fallback,
            }


class PlanReport:
    """What one optimization run did — rendered by ``workflow.explain()``
    and attached (as attrs) to the ``plan.optimize`` span."""

    def __init__(self, enabled: bool):
        self.enabled = enabled
        self.cols_pruned = 0
        self.filters_pushed = 0
        self.verbs_fused = 0
        self.bytes_skipped = 0
        self.segments_lowered = 0
        self.verbs_absorbed = 0
        # UDF static analysis (fugue_tpu/analysis): per-run counters plus
        # the structured per-UDF diagnostics workflow.lint() folds in
        self.udfs_analyzed = 0
        self.udfs_translated = 0
        self.udfs_refused = 0
        self.udf_diags: List[Dict[str, Any]] = []
        # structured prediction facts for workflow.lint()
        self.join_strategies: List[Dict[str, Any]] = []
        self.segments: List[str] = []
        self.notes: List[str] = []
        self.before: List[str] = []
        self.after: List[str] = []

    def note(self, msg: str) -> None:
        if msg not in self.notes:
            self.notes.append(msg)

    def span_attrs(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "cols_pruned": self.cols_pruned,
            "filters_pushed": self.filters_pushed,
            "verbs_fused": self.verbs_fused,
            "bytes_skipped": self.bytes_skipped,
            "segments_lowered": self.segments_lowered,
            "verbs_absorbed": self.verbs_absorbed,
            "udfs_translated": self.udfs_translated,
        }

    @property
    def changed(self) -> bool:
        return (
            self.cols_pruned
            + self.filters_pushed
            + self.verbs_fused
            + self.segments_lowered
            + self.udfs_translated
        ) > 0

    def render(self) -> str:
        lines = ["== logical plan =="]
        lines.extend("  " + s for s in self.before)
        if not self.enabled:
            lines.append("== optimizer disabled (fugue.tpu.plan.optimize=false) ==")
            return "\n".join(lines)
        lines.append(
            "== optimized plan (cols_pruned=%d filters_pushed=%d "
            "verbs_fused=%d segments_lowered=%d verbs_absorbed=%d "
            "udfs_translated=%d/%d bytes_skipped~%d) =="
            % (
                self.cols_pruned,
                self.filters_pushed,
                self.verbs_fused,
                self.segments_lowered,
                self.verbs_absorbed,
                self.udfs_translated,
                self.udfs_analyzed,
                self.bytes_skipped,
            )
        )
        lines.extend("  " + s for s in self.after)
        if self.notes:
            lines.append("== notes ==")
            lines.extend("  " + s for s in self.notes)
        return "\n".join(lines)


def _render_nodes(nodes: List[LNode]) -> List[str]:
    idx = {id(n): i for i, n in enumerate(nodes)}
    out = []
    for i, n in enumerate(nodes):
        ins = ",".join(f"t{idx[id(x)]}" for x in n.inputs if id(x) in idx)
        label = n.kind
        if n.task is not None:
            label += f"<{type(n.task.extension).__name__}>"
        ann = (" -- " + "; ".join(n.annotations)) if n.annotations else ""
        pin = " [pinned]" if n.pinned else ""
        out.append(f"t{i}: {label}({ins}){pin}{ann}")
    return out


def _flag(conf: Any, key: str, default: bool = True) -> bool:
    try:
        return bool(conf.get(key, default))
    except Exception:
        return default


# kinds whose output is never larger than their (first) input — a size
# estimate can flow through them toward the nearest create/load source
_SIZE_PASSTHROUGH_KINDS = {
    K_PROJECT,
    K_DROP,
    K_RENAME,
    K_FILTER,
    K_SELECT,
    K_DISTINCT,
    K_DROPNA,
    K_FILLNA,
    K_SAMPLE,
    K_TAKE,
}


def _estimate_node_size(
    n: LNode, memo: Dict[int, Tuple[Optional[int], Optional[int], bool]]
) -> Tuple[Optional[int], Optional[int], bool]:
    """Static (bytes, rows, is_stream) upper-bound estimate for one plan
    node: concrete create data and parquet load metadata are the ground
    sources; row-shrinking verbs pass the estimate through; everything
    else is unknown (None) — the runtime decision re-checks live sizes."""
    if id(n) in memo:
        return memo[id(n)]
    est: Tuple[Optional[int], Optional[int], bool] = (None, None, False)
    if n.kind == K_CREATE:
        data = n.info.get("data")
        if n.info.get("is_stream"):
            est = (None, None, True)
        else:
            try:
                import pandas as pd
                import pyarrow as pa

                from ..dataframe import DataFrame
                from ..shuffle.strategy import (
                    estimate_frame_bytes,
                    estimate_frame_rows,
                )

                if isinstance(data, pa.Table):
                    est = (int(data.nbytes), int(data.num_rows), False)
                elif isinstance(data, pd.DataFrame):
                    est = (
                        int(data.memory_usage(index=False, deep=False).sum()),
                        int(len(data)),
                        False,
                    )
                elif isinstance(data, DataFrame):
                    est = (
                        estimate_frame_bytes(data),
                        estimate_frame_rows(data),
                        False,
                    )
                elif isinstance(data, list):
                    est = (None, len(data), False)
            except Exception:
                est = (None, None, False)
    elif n.kind == K_LOAD:
        path, fmt = n.info.get("path"), n.info.get("fmt") or ""
        try:
            from .._utils.io import FileParser

            if isinstance(path, str) and FileParser(
                path, fmt or None
            ).file_format == "parquet":
                import pyarrow.parquet as pq

                meta = pq.ParquetFile(path).metadata
                nbytes = sum(
                    meta.row_group(i).total_byte_size
                    for i in range(meta.num_row_groups)
                )
                est = (int(nbytes), int(meta.num_rows), False)
        except Exception:
            est = (None, None, False)
    elif n.kind in _SIZE_PASSTHROUGH_KINDS and len(n.inputs) >= 1:
        est = _estimate_node_size(n.inputs[0], memo)
    memo[id(n)] = est
    return est


def annotate_join_strategies(
    nodes: List[LNode], conf: Any, report: "PlanReport"
) -> None:
    """Annotate every join node with the strategy the engine's ladder
    (``fugue_tpu/shuffle/strategy.py`` — the SAME decision function) will
    pick for the plan-time size estimates, and note it in the report so
    ``workflow.explain()`` shows broadcast / copartition / device_exchange
    / shuffle_spill before anything runs. Annotation only — no rewrite,
    no task cloning; the runtime decision over live frame sizes stays
    authoritative (it uses the engine's REAL mesh shard count; plan time
    assumes the default every-device mesh)."""
    from ..shuffle.strategy import choose_join_strategy, default_mesh_shards

    n_shards = default_mesh_shards()

    memo: Dict[int, Tuple[Optional[int], Optional[int], bool]] = {}
    idx = {id(n): i for i, n in enumerate(nodes)}
    for n in nodes:
        if n.kind != K_JOIN or len(n.inputs) != 2:
            continue
        how = n.info.get("how", "")
        lb, _lr, ls = _estimate_node_size(n.inputs[0], memo)
        rb, rr, rs = _estimate_node_size(n.inputs[1], memo)
        if how == "cross":
            strategy, reason = "broadcast", "cross join (constant-key expansion)"
        elif ls or rs:
            strategy, reason = (
                "stream",
                "one-pass side: streaming join plan, spill shuffle if ineligible",
            )
        else:
            dec = choose_join_strategy(conf, lb, rb, rr, n_shards=n_shards)
            strategy, reason = dec.strategy, dec.reason
        n.annotations.append(f"strategy={strategy}")
        report.join_strategies.append(
            {"node": f"t{idx[id(n)]}", "how": how, "strategy": strategy,
             "reason": reason}
        )
        report.note(
            "join t%d (%s): strategy=%s -- %s"
            % (idx[id(n)], how, strategy, reason)
        )


def annotate_delta_eligibility(nodes: List[LNode], report: "PlanReport") -> None:
    """Mark every verb the partition-level delta cache
    (``fugue_tpu/cache/delta.py``) can serve incrementally: row-local
    verbs split at any partition boundary; sum/count/avg/min/max
    aggregates maintain a partial accumulator. Everything unmarked routes
    through the PR 5 all-or-nothing path — ``workflow.explain()``'s cache
    section shows the per-task refusal reason."""
    from .ir import node_delta_row_local

    marked = 0
    for n in nodes:
        try:
            if n.kind == K_LOAD:
                n.annotations.append("delta:source")
            elif node_delta_row_local(n):
                n.annotations.append("delta:row-local")
            elif n.kind in ("aggregate", "segment"):
                from ..cache.delta import _DeltaRefused, parse_agg_spec

                # a segment synthesized THIS pass keeps its terminal/task
                # on node attributes; a re-classified segment task carries
                # them in info/params
                origin = n.task if n.task is not None else n.tail_origin
                if n.kind == "segment":
                    terminal = n.info.get("terminal") or n.terminal or ("?",)
                    if terminal[0] != "aggregate":
                        continue
                    cols = list(terminal[1])
                else:
                    cols = list(
                        origin.params.get("columns", [])
                        if origin is not None
                        else []
                    )
                keys = (
                    list(origin.partition_spec.partition_by)
                    if origin is not None
                    else []
                )
                try:
                    parse_agg_spec(keys, cols)
                except _DeltaRefused:
                    continue
                n.annotations.append("delta:accumulator")
            else:
                continue
            marked += 1
        except Exception:  # annotation must never fail planning
            continue
    if marked:
        report.note(
            "%d verb(s) delta-eligible (partition-level incremental "
            "recompute, docs/cache.md)" % marked
        )


def optimize_tasks(
    tasks: List[FugueTask],
    conf: Any,
    stats: Optional[PlanStats] = None,
    analysis_stats: Any = None,
) -> Tuple[List[FugueTask], Dict[int, FugueTask], Set[int], PlanReport]:
    """Rewrite the task DAG. Returns (tasks to execute, result-alias map
    {id(original task): executed task}, ids of original tasks whose
    intermediate result is no longer computed anywhere (fused interiors,
    producers a filter commuted past), report). With the optimizer off
    the ORIGINAL list round-trips untouched."""
    enabled = _flag(conf, FUGUE_TPU_CONF_PLAN_OPTIMIZE, True)
    report = PlanReport(enabled)
    if not enabled or len(tasks) == 0:
        return tasks, {}, set(), report
    nodes = build_graph(tasks)
    report.before = _render_nodes(nodes)
    annotate_join_strategies(nodes, conf, report)
    if _flag(conf, FUGUE_TPU_CONF_PLAN_ANALYZE_UDFS, True):
        # UDF static analysis FIRST: translated UDFs become plain plan
        # nodes every later pass (pushdown/prune/fuse/lower) composes
        # with; analyzed-but-refused ones carry exact column facts
        from ..analysis import expand_udf_transforms

        diags = expand_udf_transforms(
            nodes,
            report,
            translate=_flag(conf, FUGUE_TPU_CONF_PLAN_TRANSLATE_UDFS, True),
        )
        if analysis_stats is not None and diags:
            analysis_stats.absorb(diags)
    if _flag(conf, FUGUE_TPU_CONF_PLAN_PUSHDOWN, True):
        pushdown_filters(nodes, report)
    if _flag(conf, FUGUE_TPU_CONF_PLAN_PRUNE, True):
        prune_columns(nodes, report)
    if _flag(conf, FUGUE_TPU_CONF_PLAN_FUSE, True):
        fuse_verbs(nodes, report)
    if _flag(conf, FUGUE_TPU_CONF_PLAN_LOWER_SEGMENTS, True):
        lower_segments(nodes, report)
    annotate_delta_eligibility(nodes, report)
    report.after = _render_nodes(nodes)
    if not report.changed:
        return tasks, {}, set(), report
    new_tasks, aliases = emit(nodes)
    removed = {id(t) for t in tasks if id(t) not in aliases}
    if removed:
        report.note(
            "%d intermediate result(s) optimized away; pin with "
            "persist()/yield to keep them addressable" % len(removed)
        )
    if stats is not None:
        stats.absorb(report)
    return new_tasks, aliases, removed, report


def explain_tasks(tasks: List[FugueTask], conf: Any) -> str:
    """Dry-run the optimizer and render the before/after plans."""
    _, _, _, report = optimize_tasks(tasks, conf)
    if not report.before:
        report.before = _render_nodes(build_graph(tasks))
    return report.render()
