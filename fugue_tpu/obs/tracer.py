"""Hierarchical span tracer — the measurement substrate (ISSUE 3 tentpole).

One process-wide :class:`Tracer` records nested spans from the workflow
layer down to individual streaming chunks:

    workflow.run → workflow.task → engine.<verb> → stream.chunk
                                 → map.parallel → map.worker_chunk → map.partition

Design constraints, in priority order:

- **Near-zero overhead when disabled.** ``tracer.span(...)`` returns one
  shared null context object when tracing is off: the cost is an attribute
  check and a no-op ``with`` — no allocation, no clock read, no lock. The
  hot paths (per-chunk, per-partition) stay instrumented permanently.
- **Nanosecond wall clock** (``time.perf_counter_ns``), comparable across
  threads AND forked children (CLOCK_MONOTONIC is process-shared on
  Linux), so worker spans shipped home line up with driver spans on one
  timeline.
- **XLA timeline alignment**: spans created with ``annotate=True`` also
  enter a ``jax.profiler.TraceAnnotation`` of the same name, so when a
  ``jax.profiler.trace`` capture is active the host-side span names appear
  on the device timeline in Perfetto/TensorBoard.
- **Fork-boundary transport**: completed spans are plain dicts of
  primitives. A forked pool worker records into its (copy-on-write)
  buffer, slices off what it produced (:meth:`Tracer.mark` /
  :meth:`Tracer.take_since`) and ships the records back with its chunk
  result; the driver :meth:`Tracer.ingest`\\ s them. Span ids are
  ``"<host>-<pid>:<seq>"`` strings so ids never collide across the fork
  nor across hosts sharing a store (:func:`proc_ident`).
- **Cluster trace context** (ISSUE 18): :func:`trace_scope` binds a
  Dapper-style ``{"trace", "parent"}`` context; :func:`trace_carrier`
  is the wire form every cross-process hop ships, so remote spans attach
  under the submitting run instead of floating as local roots.

Enablement: conf ``fugue.tpu.trace.enabled`` (checked at engine
construction via :func:`configure_from_conf`) or the ``FUGUE_TPU_TRACE``
env var (which overrides the conf either way). ``fugue.tpu.trace.xla``
(default true) gates the TraceAnnotation mirroring.
"""

import contextlib
import os
import socket
import threading
import time
import uuid
from contextvars import ContextVar
from typing import Any, Callable, Dict, Iterator, List, Optional

from .metrics import get_span_metrics

__all__ = [
    "Tracer",
    "get_tracer",
    "configure_from_conf",
    "traced_verb",
    "set_verb_observer",
    "NULL_SPAN",
    "proc_ident",
    "mint_trace_id",
    "trace_scope",
    "current_trace_id",
    "trace_carrier",
]

ENV_TRACE = "FUGUE_TPU_TRACE"

_DEFAULT_MAX_SPANS = 200_000

# short hostname, resolved once per process image (fork children inherit it,
# which is correct — they share the host)
_HOST = socket.gethostname().split(".")[0] or "localhost"


def proc_ident() -> str:
    """Cluster-unique process identity: ``"<host>-<pid>"``. Span ids and
    spool filenames are prefixed with this so nothing collides when two
    hosts hand out the same pid (the ISSUE 18 cross-host collision fix)."""
    return f"{_HOST}-{os.getpid()}"


# -- cluster trace context --------------------------------------------------
#
# A Dapper-style trace context rides a ContextVar (same shape as the
# run-label machinery in metrics.py): ``{"trace": <id>, "parent": <span id>}``.
# ``workflow.run`` / ``serve.submit`` mint a trace id; every outbound hop
# (HTTP request, board task spec, fleet claim) ships ``trace_carrier()``;
# the receiving process re-enters the context with ``trace_scope(...)`` so
# its spans (a) carry the trace id and (b) root under the carried parent
# span instead of floating as process-local roots.

_TRACE_CTX: ContextVar[Dict[str, str]] = ContextVar("fugue_tpu_trace_ctx", default={})


def mint_trace_id() -> str:
    """A cluster-unique trace id for one ``workflow.run``/``serve.submit``."""
    return uuid.uuid4().hex[:16]


def current_trace_id() -> Optional[str]:
    return _TRACE_CTX.get().get("trace")


@contextlib.contextmanager
def trace_scope(
    trace: Optional[str] = None, parent: Optional[str] = None
) -> Iterator[str]:
    """Bind a trace context for the duration (minting an id when ``trace``
    is None). Spans opened inside carry the trace id, and a span opened
    with no local parent attaches under ``parent`` — the remote submitting
    span. Nesting re-binds; the outer context is restored on exit."""
    ctx: Dict[str, str] = {"trace": trace or mint_trace_id()}
    if parent:
        ctx["parent"] = parent
    token = _TRACE_CTX.set(ctx)
    try:
        yield ctx["trace"]
    finally:
        _TRACE_CTX.reset(token)


def trace_carrier() -> Dict[str, str]:
    """The wire fields for one outbound hop: the bound trace id plus the
    innermost open span id as the causal parent. Empty when no trace
    context is bound (propagation stays opt-in and zero-cost)."""
    ctx = _TRACE_CTX.get()
    if not ctx:
        return {}
    out = {"trace": ctx["trace"]}
    sid = _TRACER.current_span_id() or ctx.get("parent")
    if sid:
        out["parent"] = sid
    return out


class _NullSpan:
    """Shared do-nothing span/context — the entire disabled-path cost."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


NULL_SPAN = _NullSpan()


class _SpanCtx:
    """A live span: context manager + attribute sink (``sp.set(rows=...)``)."""

    __slots__ = ("_tr", "_name", "_cat", "_annotate", "_parent", "_args", "_sid", "_ann", "_t0")

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        cat: str,
        annotate: bool,
        parent: Optional[str],
        args: Dict[str, Any],
    ):
        self._tr = tracer
        self._name = name
        self._cat = cat
        self._annotate = annotate
        self._parent = parent
        self._args = args
        self._ann: Any = None

    def __enter__(self) -> "_SpanCtx":
        tr = self._tr
        stack = tr._stack()
        if self._parent is None and stack:
            self._parent = stack[-1]
        elif self._parent is None:
            # no local ancestor: attach under the carried remote parent (the
            # submitting run's span) when a trace context is bound
            self._parent = _TRACE_CTX.get().get("parent")
        self._sid = tr._new_id()
        stack.append(self._sid)
        if self._annotate and tr.xla_annotate:
            cls = tr._annotation_cls()
            if cls is not None:
                try:
                    self._ann = cls(self._name)
                    self._ann.__enter__()
                except Exception:
                    self._ann = None
        self._t0 = time.perf_counter_ns()
        return self

    def set(self, **attrs: Any) -> None:
        self._args.update(attrs)

    def __exit__(self, et: Any, ev: Any, tb: Any) -> bool:
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(et, ev, tb)
            except Exception:
                pass
        tr = self._tr
        stack = tr._stack()
        if stack and stack[-1] == self._sid:
            stack.pop()
        elif self._sid in stack:  # defensive: mis-nested exit
            stack.remove(self._sid)
        if et is not None:
            self._args.setdefault("error", getattr(et, "__name__", str(et)))
        rec = {
            "name": self._name,
            "cat": self._cat,
            "ts": self._t0,
            "dur": t1 - self._t0,
            "pid": os.getpid(),
            "proc": proc_ident(),
            "tid": tr._tid(),
            "id": self._sid,
            "parent": self._parent,
            "args": self._args,
        }
        trace = _TRACE_CTX.get().get("trace")
        if trace:
            rec["trace"] = trace
        tr._emit(rec)
        return False


class Tracer:
    """Process-wide span recorder. Use the :func:`get_tracer` singleton."""

    def __init__(self, max_spans: int = _DEFAULT_MAX_SPANS):
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        self._tls = threading.local()
        self._seq = 0
        self._tids: Dict[int, int] = {}
        self._ann_cls: Any = False  # False = unresolved, None = unavailable
        self.enabled = False
        self.xla_annotate = True
        self.max_spans = max_spans
        self.dropped = 0

    # -- recording ----------------------------------------------------------
    def span(
        self,
        name: str,
        cat: str = "host",
        annotate: bool = False,
        parent: Optional[str] = None,
        **args: Any,
    ) -> Any:
        """Open a span context. When tracing is disabled this returns one
        shared null object — the instrumented call sites pay ~an attribute
        check, nothing else."""
        if not self.enabled:
            return NULL_SPAN
        return _SpanCtx(self, name, cat, annotate, parent, args)

    def _emit(self, rec: Dict[str, Any]) -> None:
        # every span close feeds the latency/rows/bytes histograms — BEFORE
        # the buffer-cap check: distributions must stay correct even when
        # the span buffer saturates and drops the raw record
        get_span_metrics().observe_record(rec)
        with self._lock:
            if len(self._records) >= self.max_spans:
                self.dropped += 1
                return
            self._records.append(rec)

    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def _new_id(self) -> str:
        # host+pid-prefixed: unique across forks AND across hosts sharing a
        # store (two hosts can hand out the same pid)
        with self._lock:
            self._seq += 1
            return f"{proc_ident()}:{self._seq}"

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            n = self._tids.get(ident)
            if n is None:
                n = len(self._tids) + 1
                self._tids[ident] = n
            return n

    def _annotation_cls(self) -> Any:
        if self._ann_cls is False:
            try:
                import jax

                cls: Any = jax.profiler.TraceAnnotation
            except Exception:
                cls = None
            # racing first-touchers resolve the IDENTICAL class; the lock
            # just makes the publish a clean single write
            with self._lock:
                self._ann_cls = cls
        return self._ann_cls

    def current_span_id(self) -> Optional[str]:
        """Id of the innermost open span on THIS thread (for explicit
        parenting across thread/process boundaries)."""
        st = self._stack()
        return st[-1] if st else None

    # -- buffer access ------------------------------------------------------
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self.dropped = 0

    def mark(self) -> int:
        """Current buffer length — pair with :meth:`take_since` to slice off
        the spans produced after this point (the fork-boundary protocol)."""
        with self._lock:
            return len(self._records)

    def take_since(self, mark: int) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records[mark:])

    def ingest(self, records: List[Dict[str, Any]]) -> None:
        """Append records produced elsewhere (forked worker, remote).

        Deliberately does NOT feed the span histograms: the recording
        process already fed its own at ``_emit`` time, and the fork
        protocol ships those observations home as an explicit mergeable
        histogram delta (``SpanMetrics.delta_since``) alongside the
        spans — feeding here too would double-count."""
        if not records:
            return
        with self._lock:
            room = self.max_spans - len(self._records)
            if room <= 0:
                self.dropped += len(records)
                return
            self._records.extend(records[:room])
            self.dropped += max(0, len(records) - room)

    # -- analysis -----------------------------------------------------------
    def span_tree(self) -> List[Dict[str, Any]]:
        """Reconstruct the span forest from parent links: a list of root
        nodes ``{"name", "cat", "ts", "dur", "args", "children": [...]}``
        ordered by start time."""
        recs = self.records()
        nodes = {
            r["id"]: dict(r, children=[]) for r in recs
        }
        roots: List[Dict[str, Any]] = []
        for r in recs:
            node = nodes[r["id"]]
            parent = nodes.get(r["parent"]) if r["parent"] else None
            if parent is not None:
                parent["children"].append(node)
            else:
                roots.append(node)
        for n in nodes.values():
            n["children"].sort(key=lambda c: c["ts"])
        roots.sort(key=lambda c: c["ts"])
        return roots

    # -- switches -----------------------------------------------------------
    def enable(self) -> None:
        with self._lock:
            self.enabled = True

    def disable(self) -> None:
        with self._lock:
            self.enabled = False


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def _truthy(v: Any) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


def configure_from_conf(conf: Any) -> None:
    """Apply trace switches from an engine conf. Called at engine
    construction. The ``FUGUE_TPU_TRACE`` env var overrides the conf in
    both directions; an absent conf key + absent env leaves the current
    state untouched (another engine may have enabled tracing already)."""
    from ..constants import (
        FUGUE_TPU_CONF_TRACE_ENABLED,
        FUGUE_TPU_CONF_TRACE_MAX_SPANS,
        FUGUE_TPU_CONF_TRACE_XLA,
    )

    tr = _TRACER
    try:
        raw = conf.get_or_none(FUGUE_TPU_CONF_TRACE_ENABLED, object)
        xla = conf.get_or_none(FUGUE_TPU_CONF_TRACE_XLA, object)
        cap = conf.get_or_none(FUGUE_TPU_CONF_TRACE_MAX_SPANS, object)
    except Exception:
        raw = xla = cap = None
    env = os.environ.get(ENV_TRACE)
    if env is not None and env != "":
        tr.enabled = _truthy(env)
    elif raw is not None:
        tr.enabled = _truthy(raw)
    if xla is not None:
        tr.xla_annotate = _truthy(xla)
    if cap is not None:
        tr.max_spans = int(cap)


# process-wide traced-verb close hook (ISSUE 18 roofline recording):
# called as (verb_name, wall_seconds, result) after a SUCCESSFUL traced
# verb while tracing is enabled. None = no observer = zero extra work.
_VERB_OBSERVER: Optional[Callable[[str, float, Any], None]] = None


def set_verb_observer(fn: Optional[Callable[[str, float, Any], None]]) -> None:
    """Install (or clear, with None) the traced-verb close observer. One
    slot per process — a newer install replaces the previous one."""
    global _VERB_OBSERVER
    _VERB_OBSERVER = fn


def traced_verb(name: str, cat: str = "engine", annotate: bool = True) -> Callable:
    """Decorator instrumenting an engine verb as one span. The disabled
    path is a single attribute check before delegating. While tracing is
    on, a successful close additionally feeds the registered verb
    observer (roofline recording) with the verb's wall time and result —
    failures are never folded into throughput ceilings."""
    import functools

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*a: Any, **k: Any) -> Any:
            tr = _TRACER
            if not tr.enabled:
                return fn(*a, **k)
            obs = _VERB_OBSERVER
            if obs is None:
                with tr.span(name, cat=cat, annotate=annotate):
                    return fn(*a, **k)
            t0 = time.perf_counter()
            with tr.span(name, cat=cat, annotate=annotate):
                out = fn(*a, **k)
            try:
                obs(name, time.perf_counter() - t0, out)
            except Exception:  # recording must never fail the verb
                pass
            return out

        return wrapper

    return deco
