"""Trace exporters: Chrome trace-event JSON (Perfetto / about:tracing) and
a plain-text top-N report.

The Chrome format is the `trace event format`_ "JSON object" flavor: a
``{"traceEvents": [...]}`` envelope of complete (``"ph": "X"``) events
with microsecond ``ts``/``dur``. Resource-sampler series additionally
export as counter (``"ph": "C"``) events — Perfetto renders each as a
counter track (device bytes, host RSS, overlap_fraction, ...) directly
under the span timeline, same clock. Perfetto and chrome://tracing both
load it; ``validate_chrome_trace`` is the CI gate (``make trace-smoke``,
``make telemetry-smoke``) asserting an exported file actually parses as
that shape.

.. _trace event format:
   https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU
"""

import json
import os
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "to_chrome_trace",
    "write_chrome_trace",
    "validate_chrome_trace",
    "render_report",
]


def to_chrome_trace(
    records: Iterable[Dict[str, Any]],
    counters: Optional[Iterable[Any]] = None,
    counter_tracks: Optional[Dict[int, Iterable[Any]]] = None,
    process_names: Optional[Dict[int, str]] = None,
) -> Dict[str, Any]:
    """Convert tracer records (ns timestamps) to a Chrome trace-event dict.

    ``counters`` is an optional resource-sampler series — an iterable of
    ``(ts_ns, {name: value})`` samples (``ResourceSampler.series()``);
    each name becomes one Perfetto counter track (``ph: "C"``) on the
    driver process, sharing the spans' clock so resource curves render
    directly under the span bars. ``counter_tracks`` pins additional
    series to explicit track pids (the cluster assembler ships each remote
    process's sampler ring home and renders it on that process's track).
    ``process_names`` overrides the default driver/worker track naming.
    Each span event carries its tracer span id as a top-level ``"id"`` so
    ``validate_chrome_trace`` can prove cluster-wide id uniqueness."""
    events: List[Dict[str, Any]] = []
    pids = set()
    for r in records:
        pids.add(r["pid"])
        ev = {
            "name": r["name"],
            "cat": r.get("cat", "host"),
            "ph": "X",
            "ts": r["ts"] / 1000.0,  # ns → µs
            "dur": max(r["dur"], 0) / 1000.0,
            "pid": r["pid"],
            "tid": r.get("tid", 1),
            "args": _jsonable(r.get("args", {})),
        }
        if r.get("id") is not None:
            ev["id"] = r["id"]
        if r.get("trace"):
            ev["args"]["trace"] = r["trace"]
        events.append(ev)
    tracks: Dict[int, Any] = dict(counter_tracks or {})
    if counters:
        tracks.setdefault(os.getpid(), counters)
    for cpid, series in tracks.items():
        for ts, vals in series:
            for cname, v in vals.items():
                events.append(
                    {
                        "name": cname,
                        "cat": "resource",
                        "ph": "C",
                        "ts": ts / 1000.0,
                        "pid": cpid,
                        "tid": 0,
                        "args": {"value": v},
                    }
                )
        pids.add(cpid)
    # metadata events name the process tracks (driver vs forked workers)
    first = min(pids) if pids else None
    names = process_names or {}
    for pid in sorted(pids):
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {
                    "name": names.get(
                        pid,
                        "fugue-tpu driver" if pid == first else f"fugue-tpu worker {pid}",
                    )
                },
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _jsonable(args: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in args.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = str(v)
    return out


def write_chrome_trace(
    path: str,
    records: Optional[Iterable[Dict[str, Any]]] = None,
    counters: Optional[Iterable[Any]] = None,
) -> str:
    """Write the (or the global tracer's) records as Chrome trace JSON.
    When ``counters`` is not given, the global resource sampler's ring is
    included automatically — a sampled run exports its resource curves as
    counter tracks with no extra plumbing."""
    if records is None:
        from .tracer import get_tracer

        records = get_tracer().records()
    if counters is None:
        from .sampler import get_sampler

        counters = get_sampler().series()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(to_chrome_trace(records, counters=counters), f)
    return path


def validate_chrome_trace(path: str) -> Dict[str, Any]:
    """Assert ``path`` is valid trace-event JSON; returns summary counts.

    Checks the envelope, the per-event required keys, that durations/
    timestamps are non-negative numbers — the properties Perfetto needs to
    render the file at all — and (ISSUE 18) that no two span events share
    one ``(pid, span id)`` pair, the regression the host+pid id prefix
    exists to prevent when multiple hosts' spans merge into one trace.
    """
    with open(path) as f:
        doc = json.load(f)
    assert isinstance(doc, dict) and "traceEvents" in doc, (
        f"{path}: expected a traceEvents envelope"
    )
    events = doc["traceEvents"]
    assert isinstance(events, list) and len(events) > 0, f"{path}: no events"
    n_spans = 0
    n_counters = 0
    names = set()
    counter_names = set()
    seen_ids = set()
    for ev in events:
        assert isinstance(ev, dict) and "ph" in ev and "name" in ev, ev
        assert "pid" in ev, ev
        if ev["ph"] == "X":
            n_spans += 1
            names.add(ev["name"])
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0, ev
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0, ev
            assert "tid" in ev, ev
            if ev.get("id") is not None:
                key = (ev["pid"], ev["id"])
                assert key not in seen_ids, (
                    f"{path}: duplicate (pid, span id) pair {key} — "
                    "colliding span ids corrupt parent links in merged traces"
                )
                seen_ids.add(key)
        elif ev["ph"] == "C":
            n_counters += 1
            counter_names.add(ev["name"])
            assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0, ev
            args = ev.get("args")
            assert isinstance(args, dict) and args, ev
            assert all(isinstance(v, (int, float)) for v in args.values()), ev
    assert n_spans > 0, f"{path}: no complete ('X') span events"
    return {
        "events": len(events),
        "spans": n_spans,
        "names": sorted(names),
        "counters": n_counters,
        "counter_names": sorted(counter_names),
    }


def render_report(
    records: List[Dict[str, Any]],
    stats: Optional[Dict[str, Any]] = None,
    top_n: int = 15,
    span_metrics: Any = None,
    rooflines: Optional[Dict[str, Dict[str, Any]]] = None,
) -> str:
    """Plain-text top-N report: spans grouped by name with count / total /
    self / mean / p50 / p95 / p99 / max wall, plus the metrics registry
    dump. Quantiles come from the span-latency histograms (the global
    :class:`~fugue_tpu.obs.metrics.SpanMetrics` store unless one is
    passed); a span name with no histogram series prints ``-``.
    ``rooflines`` (``<verb>|<dtype-class>|w<width>`` → throughput fold,
    the ISSUE 18 record-only table) renders as its own section when
    non-empty."""
    if span_metrics is None:
        from .metrics import get_span_metrics

        span_metrics = get_span_metrics()
    try:
        latency = span_metrics.summary()
    except Exception:
        latency = {}
    by_id = {r["id"]: r for r in records}
    child_time: Dict[str, int] = {}
    for r in records:
        p = r.get("parent")
        if p is not None and p in by_id:
            child_time[p] = child_time.get(p, 0) + r["dur"]
    agg: Dict[str, Dict[str, float]] = {}
    for r in records:
        a = agg.setdefault(
            r["name"], {"count": 0, "total": 0, "self": 0, "max": 0}
        )
        a["count"] += 1
        a["total"] += r["dur"]
        a["self"] += max(r["dur"] - child_time.get(r["id"], 0), 0)
        a["max"] = max(a["max"], r["dur"])
    lines = ["== span report (top %d by total wall) ==" % top_n]
    if not agg:
        lines.append("(no spans recorded — is tracing enabled?)")
    else:
        lines.append(
            f"{'span':<28}{'count':>8}{'total_ms':>12}{'self_ms':>12}"
            f"{'mean_ms':>10}{'p50_ms':>10}{'p95_ms':>10}{'p99_ms':>10}"
            f"{'max_ms':>10}"
        )

        def q(name: str, key: str) -> str:
            v = latency.get(name, {}).get(key)
            return f"{v:>10.3f}" if isinstance(v, (int, float)) else f"{'-':>10}"

        ranked = sorted(agg.items(), key=lambda kv: -kv[1]["total"])[:top_n]
        for name, a in ranked:
            lines.append(
                f"{name:<28}{int(a['count']):>8}"
                f"{a['total'] / 1e6:>12.3f}{a['self'] / 1e6:>12.3f}"
                f"{a['total'] / a['count'] / 1e6:>10.3f}"
                f"{q(name, 'p50_ms')}{q(name, 'p95_ms')}{q(name, 'p99_ms')}"
                f"{a['max'] / 1e6:>10.3f}"
            )
    if rooflines:
        lines.append("")
        lines.append("== verb rooflines (record-only; best achieved) ==")
        lines.append(
            f"{'verb|dtype|width':<36}{'obs':>6}{'best_MB/s':>12}"
            f"{'best_Mrow/s':>13}{'last_MB/s':>12}{'last_Mrow/s':>13}"
        )

        def mb(v: Any) -> str:
            return (
                f"{float(v) / 1e6:>12.2f}"
                if isinstance(v, (int, float))
                else f"{'-':>12}"
            )

        ranked_rl = sorted(
            rooflines.items(),
            key=lambda kv: -float(kv[1].get("best_bytes_s", 0) or 0),
        )
        for key, e in ranked_rl:
            lines.append(
                f"{key:<36}{int(e.get('obs', 0) or 0):>6}"
                f"{mb(e.get('best_bytes_s'))}"
                f"{mb(e.get('best_rows_s')):>13}"
                f"{mb(e.get('last_bytes_s'))}"
                f"{mb(e.get('last_rows_s')):>13}"
            )
    if stats:
        lines.append("")
        lines.append("== metrics ==")
        for group, vals in stats.items():
            lines.append(f"[{group}]")
            if isinstance(vals, dict):
                for k, v in sorted(vals.items()):
                    if isinstance(v, dict):
                        lines.append(f"  {k}: {json.dumps(v, sort_keys=True)}")
                    else:
                        lines.append(f"  {k}: {v}")
            else:
                lines.append(f"  {vals}")
    return "\n".join(lines)
