"""Continuous resource sampler (ISSUE 6 tentpole, piece 2).

A single daemon thread periodically reads a set of cheap **probes** —
device bytes (``jax.live_arrays``), host RSS, jit-cache and result-cache
occupancy, pipeline ``overlap_fraction`` — into a bounded ring buffer of
``(ts_ns, {name: value})`` samples. Timestamps use the SAME clock as the
span tracer (``time.perf_counter_ns``), so the series export directly as
Perfetto counter tracks under the span timeline (``ph: "C"`` events in
the Chrome trace — see ``export.to_chrome_trace``) and the last sample
serves as the gauge set on ``/metrics``.

Default **off** (conf ``fugue.tpu.telemetry.enabled``, env
``FUGUE_TPU_TELEMETRY`` overrides both ways — the tracer's enablement
contract): disabled there is no thread, no allocation, nothing. Enabled,
one sample every ``fugue.tpu.telemetry.interval`` seconds (default 0.25)
over ~5 cheap probes stays well under the 2% budget.

Probes are registered by name (engines register theirs at construction,
bound through a ``weakref`` so a collected engine's probes remove
themselves by raising :class:`ProbeGone`); ``start()``/``stop()`` are
idempotent; ``reset()`` clears the ring but KEEPS probes and the running
state — the keep-entries contract ``engine.reset_stats()`` applies to
every source.
"""

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "ProbeGone",
    "ResourceSampler",
    "configure_sampler_from_conf",
    "get_sampler",
]

ENV_TELEMETRY = "FUGUE_TPU_TELEMETRY"

_DEFAULT_INTERVAL_S = 0.25
_DEFAULT_RING_SIZE = 4096


class ProbeGone(Exception):
    """Raised by a probe whose subject no longer exists — the sampler
    unregisters it (the weakref-bound engine-probe cleanup path)."""


def _host_rss_bytes() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) * 1024.0
    except Exception:
        pass
    import resource

    # fallback: peak RSS (linux reports KiB) — monotone but better than nothing
    return float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024.0


_JAX: Any = False  # False = unresolved, None = unavailable


def _device_bytes() -> float:
    """Total live device-array bytes — the same accounting the streaming
    peak tracker uses (prefetched in-flight chunks count naturally)."""
    global _JAX
    if _JAX is False:
        try:
            import jax

            _JAX = jax
        except Exception:
            _JAX = None
    if _JAX is None:
        raise ProbeGone()
    total = 0
    for a in _JAX.live_arrays():
        try:
            if getattr(a, "is_deleted", lambda: False)() is False:
                total += a.nbytes
        except Exception:
            pass
    return float(total)


class ResourceSampler:
    """Daemon-thread sampler over named probes into a bounded ring."""

    def __init__(
        self,
        interval: float = _DEFAULT_INTERVAL_S,
        ring_size: int = _DEFAULT_RING_SIZE,
    ):
        self._lock = threading.Lock()
        self._probes: Dict[str, Callable[[], float]] = {}
        self._ring: "deque[Tuple[int, Dict[str, float]]]" = deque(maxlen=ring_size)
        self._interval = float(interval)
        self._thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()
        self.sample_errors = 0
        self.register_probe("host_rss_bytes", _host_rss_bytes)
        self.register_probe("device_bytes", _device_bytes)

    # -- probes --------------------------------------------------------------
    def register_probe(self, name: str, fn: Callable[[], float]) -> None:
        """Register (or replace) a named probe: a zero-arg callable
        returning a float. Raise :class:`ProbeGone` to self-unregister;
        any other exception skips the value for that tick only."""
        with self._lock:
            self._probes[name] = fn

    def unregister_probe(self, name: str) -> None:
        with self._lock:
            self._probes.pop(name, None)

    def probe_names(self) -> List[str]:
        with self._lock:
            return sorted(self._probes)

    # -- lifecycle (idempotent both ways) ------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def interval(self) -> float:
        return self._interval

    def configure(
        self, interval: Optional[float] = None, ring_size: Optional[int] = None
    ) -> None:
        with self._lock:
            if interval is not None:
                self._interval = max(float(interval), 0.001)
            if ring_size is not None and int(ring_size) != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(int(ring_size), 1))

    def start(
        self, interval: Optional[float] = None, ring_size: Optional[int] = None
    ) -> "ResourceSampler":
        self.configure(interval, ring_size)
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self  # already running — idempotent
            self._stop_ev = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="fugue-tpu-telemetry", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
            self._stop_ev.set()
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def _loop(self) -> None:
        ev = self._stop_ev
        while not ev.wait(self._interval):
            try:
                self.sample_once()
            except Exception:
                with self._lock:
                    self.sample_errors += 1

    # -- sampling ------------------------------------------------------------
    def sample_once(self) -> Dict[str, float]:
        """Take one sample now (the thread's body; also callable directly
        for a deterministic sample in tests/smoke)."""
        with self._lock:
            probes = list(self._probes.items())
        vals: Dict[str, float] = {}
        gone: List[str] = []
        for name, fn in probes:
            try:
                vals[name] = float(fn())
            except ProbeGone:
                gone.append(name)
            except Exception:
                with self._lock:
                    self.sample_errors += 1
        ts = time.perf_counter_ns()
        with self._lock:
            for name in gone:
                self._probes.pop(name, None)
            self._ring.append((ts, vals))
        return vals

    def series(self) -> List[Tuple[int, Dict[str, float]]]:
        """The ring's samples oldest-first — the Perfetto counter-track
        source (same ``perf_counter_ns`` clock as span timestamps)."""
        with self._lock:
            return list(self._ring)

    def last(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._ring[-1][1]) if self._ring else {}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- registry source contract -------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._ring)
            last = dict(self._ring[-1][1]) if self._ring else {}
            probes = sorted(self._probes)
        return {
            "running": self.running,
            "samples": n,
            "interval_s": self._interval,
            "probes": probes,
            "last": last,
        }

    def reset(self) -> None:
        """Clear the ring buffer. Probes stay registered and the thread
        keeps running — the keep-entries contract: a stats reset empties
        the recorded series without tearing the sampler down."""
        self.clear()


_SAMPLER = ResourceSampler()


def get_sampler() -> ResourceSampler:
    return _SAMPLER


def configure_sampler_from_conf(conf: Any) -> None:
    """Apply telemetry switches from an engine conf (engine construction
    path, next to the tracer's ``configure_from_conf``). The
    ``FUGUE_TPU_TELEMETRY`` env var overrides the conf in both
    directions; absent key + absent env leaves the current state
    untouched (another engine may have started the sampler already)."""
    from ..constants import (
        FUGUE_TPU_CONF_TELEMETRY_ENABLED,
        FUGUE_TPU_CONF_TELEMETRY_INTERVAL,
        FUGUE_TPU_CONF_TELEMETRY_RING,
    )
    from .tracer import _truthy

    try:
        raw = conf.get_or_none(FUGUE_TPU_CONF_TELEMETRY_ENABLED, object)
        interval = conf.get_or_none(FUGUE_TPU_CONF_TELEMETRY_INTERVAL, object)
        ring = conf.get_or_none(FUGUE_TPU_CONF_TELEMETRY_RING, object)
    except Exception:
        raw = interval = ring = None
    env = os.environ.get(ENV_TELEMETRY)
    enabled: Optional[bool] = None
    if env is not None and env != "":
        enabled = _truthy(env)
    elif raw is not None:
        enabled = _truthy(raw)
    s = get_sampler()
    s.configure(
        interval=float(interval) if interval is not None else None,
        ring_size=int(ring) if ring is not None else None,
    )
    if enabled is True:
        s.start()
    elif enabled is False:
        s.stop()
