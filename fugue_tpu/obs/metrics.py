"""Distribution metrics: bucketed histograms, labeled families, and the
process-global span-metrics store (ISSUE 6 tentpole, piece 1).

The PR 3 registry holds plain counters — enough for "how many", useless
for "how long". This module adds the distribution substrate:

- :class:`Histogram`: fixed exponential buckets with p50/p95/p99
  estimation (Prometheus-style linear interpolation inside the bucket
  containing the target rank, clamped to the observed min/max). The
  internal state is a **mergeable encoding** — plain lists/numbers that
  add associatively — so worker-recorded distributions ship across the
  fork boundary and merge into the driver's without loss.
- :class:`HistogramFamily`: one metric name fanned out over label sets
  (``family.observe(v, span="engine.aggregate", run="ab12")``), the
  attribution scheme a per-tenant serving layer reuses unchanged.
- :class:`SpanMetrics`: the process-global store fed by the tracer at
  every span close — every span name gets a latency distribution for
  free, and ``rows``/``bytes`` span attrs feed throughput histograms.
  Process-global like the tracer itself (one timeline, one metric
  store); ``engine.stats()["latency"]`` reads it, ``engine.reset_stats()``
  resets it under the keep-entries contract (series stay registered,
  observations zero — the ``JitCache.reset`` rule).

Run attribution: :func:`run_labels` is a context-local label scope the
workflow layer enters for the duration of a run; every observation made
while it is active carries the ``workflow``/``run`` labels. It is a
:class:`contextvars.ContextVar`, so two runs executing concurrently in
one process never see each other's labels; propagation to the places
observations actually happen is explicit: the workflow task pool submits
through ``contextvars.copy_context()``, the chunk prefetcher runs its
producer inside the consumer's context snapshot, and forked map workers
inherit the forking thread's context wholesale (``fork`` clones it —
the pool is forked per map call, inside the run).
"""

import contextvars
import itertools
import threading
from bisect import bisect_left
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "DEFAULT_LATENCY_BOUNDS",
    "DEFAULT_SIZE_BOUNDS",
    "Histogram",
    "HistogramFamily",
    "SpanMetrics",
    "active_run_labels",
    "current_run_labels",
    "get_span_metrics",
    "run_labels",
]

# latency buckets (seconds): 1µs … ~134s, ×2 per bucket — 28 buckets plus
# overflow covers a single jit dispatch through a full 1B-row pass
DEFAULT_LATENCY_BOUNDS: Tuple[float, ...] = tuple(1e-6 * (2**i) for i in range(28))
# size buckets (rows or bytes): 4 … ~1.1e12, ×4 per bucket
DEFAULT_SIZE_BOUNDS: Tuple[float, ...] = tuple(float(4**i) for i in range(1, 21))


def _quantile_from(
    enc: Dict[str, Any], bounds: Tuple[float, ...], q: float
) -> Optional[float]:
    """Quantile estimate over an :meth:`Histogram.encode` snapshot: linear
    interpolation inside the bucket containing the target rank, clamped to
    the snapshot's [min, max]. Pure function of the snapshot, so every
    field derived from one encode() is mutually consistent."""
    count = enc["count"]
    if not count:
        return None
    vmin, vmax = enc["min"], enc["max"]
    target = max(min(q, 1.0), 0.0) * count
    cum = 0
    lo = 0.0
    for i, c in enumerate(enc["counts"]):
        hi = bounds[i] if i < len(bounds) else (vmax if vmax is not None else lo)
        if cum + c >= target and c > 0:
            est = lo + (hi - lo) * ((target - cum) / c)
            break
        cum += c
        lo = hi
    else:
        est = vmax if vmax is not None else 0.0
    if vmin is not None:
        est = max(est, vmin)
    if vmax is not None:
        est = min(est, vmax)
    return est


class Histogram:
    """Fixed-bucket histogram with quantile estimation and merge support.

    ``counts[i]`` counts observations ``v <= bounds[i]`` (first matching
    bucket); ``counts[-1]`` is the overflow bucket. ``encode()`` returns
    the plain-data form that :meth:`merge` adds back in — counts, sum and
    count add associatively, min/max combine via min/max, so merging is
    order-independent across any number of workers.
    """

    def __init__(self, bounds: Tuple[float, ...] = DEFAULT_LATENCY_BOUNDS):
        self.bounds = tuple(bounds)
        self._lock = threading.Lock()
        self._zero_locked()

    def _zero_locked(self) -> None:
        # caller holds self._lock (construction is single-threaded)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.counts[bisect_left(self.bounds, v)] += 1
            self.sum += v
            self.count += 1
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    # -- mergeable encoding --------------------------------------------------
    def encode(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counts": list(self.counts),
                "sum": self.sum,
                "count": self.count,
                "min": self.min,
                "max": self.max,
            }

    def merge(self, enc: Dict[str, Any]) -> None:
        """Add an encoded delta in. Associative and commutative: merging
        worker A's delta then B's equals B's then A's equals observing
        every value locally."""
        if not enc or not enc.get("count"):
            return
        counts = enc["counts"]
        with self._lock:
            n = min(len(counts), len(self.counts))
            for i in range(n):
                self.counts[i] += counts[i]
            self.sum += enc["sum"]
            self.count += enc["count"]
            for key, pick in (("min", min), ("max", max)):
                v = enc.get(key)
                if v is not None:
                    cur = getattr(self, key)
                    setattr(self, key, v if cur is None else pick(cur, v))

    def subtract(self, enc: Dict[str, Any]) -> Dict[str, Any]:
        """Current state minus an earlier :meth:`encode` — the
        fork-boundary delta a worker ships home (its post-fork
        observations only; the COW copy inherited at fork subtracts out)."""
        cur = self.encode()
        if not enc:
            return cur
        base = enc.get("counts", [])
        counts = [
            c - (base[i] if i < len(base) else 0) for i, c in enumerate(cur["counts"])
        ]
        return {
            "counts": counts,
            "sum": cur["sum"] - enc.get("sum", 0.0),
            "count": cur["count"] - enc.get("count", 0),
            # min/max of just-the-delta is unrecoverable from two encodes;
            # the current values are a conservative superset (merging them
            # home can only widen the driver's range to values it, or its
            # fork parent, already saw)
            "min": cur["min"],
            "max": cur["max"],
        }

    # -- quantiles -----------------------------------------------------------
    # All quantile/summary readers derive from ONE encode() snapshot (a
    # single lock acquisition), so a reported p50/p95/p99 and the
    # count/mean beside it always describe the same distribution even
    # while observe() runs concurrently.
    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile (0..1) by linear interpolation within
        the bucket containing the target rank, clamped to the observed
        [min, max] so estimates never leave the data's actual range."""
        return _quantile_from(self.encode(), self.bounds, q)

    def percentiles(self) -> Dict[str, Optional[float]]:
        enc = self.encode()
        return {
            "p50": _quantile_from(enc, self.bounds, 0.50),
            "p95": _quantile_from(enc, self.bounds, 0.95),
            "p99": _quantile_from(enc, self.bounds, 0.99),
        }

    # -- registry source contract -------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        enc = self.encode()
        out: Dict[str, Any] = {
            "count": enc["count"],
            "sum": round(enc["sum"], 9),
            "min": enc["min"],
            "max": enc["max"],
            "mean": (enc["sum"] / enc["count"]) if enc["count"] else None,
        }
        for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            out[name] = _quantile_from(enc, self.bounds, q)
        return out

    def reset(self) -> None:
        with self._lock:
            self._zero_locked()


def _labels_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class HistogramFamily:
    """A labeled histogram family: one metric name, one series per label
    set. The unit of Prometheus exposition (each series renders its own
    ``_bucket``/``_sum``/``_count`` lines) and of fork-boundary transport
    (encode/merge/delta operate per series, matched by labels — never by
    pid, so two workers' series with equal labels merge additively)."""

    def __init__(
        self,
        name: str,
        bounds: Tuple[float, ...] = DEFAULT_LATENCY_BOUNDS,
        help: str = "",
    ):
        self.name = name
        self.bounds = tuple(bounds)
        self.help = help or name
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], Histogram] = {}

    def _get_or_create(self, key: Tuple[Tuple[str, str], ...]) -> Histogram:
        with self._lock:
            h = self._series.get(key)
            if h is None:
                h = Histogram(self.bounds)
                self._series[key] = h
            return h

    def observe(self, value: float, **labels: Any) -> None:
        self._get_or_create(_labels_key(labels)).observe(value)

    def get(self, **labels: Any) -> Optional[Histogram]:
        with self._lock:
            return self._series.get(_labels_key(labels))

    def series(self) -> List[Tuple[Dict[str, str], Histogram]]:
        with self._lock:
            items = list(self._series.items())
        return [(dict(k), h) for k, h in items]

    # -- mergeable encoding (fork-boundary transport) ------------------------
    def encode(self) -> List[Dict[str, Any]]:
        return [
            {"labels": labels, **h.encode()} for labels, h in self.series()
        ]

    def merge(self, encoded: List[Dict[str, Any]]) -> None:
        for enc in encoded or []:
            if enc.get("count"):
                self._get_or_create(_labels_key(enc.get("labels", {}))).merge(enc)

    def delta_since(self, snapshot: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        base = {
            _labels_key(e.get("labels", {})): e for e in (snapshot or [])
        }
        out: List[Dict[str, Any]] = []
        for labels, h in self.series():
            d = h.subtract(base.get(_labels_key(labels), {}))
            if d.get("count"):
                out.append({"labels": labels, **d})
        return out

    # -- registry source contract -------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for labels, h in self.series():
            if h.count == 0:
                continue  # reset series stay registered but don't report
            key = ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or "_"
            out[key] = h.as_dict()
        return out

    def reset(self) -> None:
        """Zero every series' observations. Series stay REGISTERED — the
        keep-entries contract (``JitCache.reset``): a stats reset must not
        tear down the metric schema a scraper is watching."""
        for _, h in self.series():
            h.reset()

    def prune(self, predicate: Callable[[Dict[str, str]], bool]) -> int:
        """Drop every series whose label dict matches ``predicate``;
        returns how many were dropped. Unlike :meth:`reset` this removes
        the registration itself — the run-label rotation uses it to bound
        per-run series cardinality (see :attr:`SpanMetrics.MAX_RUN_SERIES`)."""
        with self._lock:
            drop = [k for k in self._series if predicate(dict(k))]
            for k in drop:
                del self._series[k]
        return len(drop)

    def clear(self) -> None:
        """Drop every series (test isolation; NOT part of reset)."""
        with self._lock:
            self._series.clear()


# --------------------------------------------------------------------------
# run attribution labels
# --------------------------------------------------------------------------

_RUN_LABELS_VAR: "contextvars.ContextVar[Dict[str, str]]" = contextvars.ContextVar(
    "fugue_tpu_run_labels", default={}
)
# currently-entered label scopes, for introspection (/stats) from threads
# outside any run context (e.g. the HTTP server); insertion-ordered so the
# most recently entered run is last
_ACTIVE_LOCK = threading.Lock()
_ACTIVE_RUNS: "OrderedDict[int, Dict[str, str]]" = OrderedDict()
_ACTIVE_SEQ = itertools.count()


def current_run_labels() -> Dict[str, str]:
    """The labels attached to metric observations made from the calling
    context (``workflow``/``run`` inside a workflow run's context, else
    empty). Context-local: concurrent runs each see their own."""
    return dict(_RUN_LABELS_VAR.get())


def active_run_labels() -> List[Dict[str, str]]:
    """Label dicts of every :func:`run_labels` scope currently entered
    anywhere in the process, oldest first — the cross-thread view a
    telemetry endpoint reports when it is not itself inside a run."""
    with _ACTIVE_LOCK:
        return [dict(v) for v in _ACTIVE_RUNS.values()]


@contextmanager
def run_labels(**labels: Any) -> Iterator[None]:
    """Attach labels to every span-metric observation for the duration.

    Context-local (:mod:`contextvars`): concurrent runs in one process
    never cross-contaminate, and the token-based reset restores the right
    outer scope even under non-LIFO exits. Nested uses overlay (inner
    wins, outer restored on exit). Propagation is explicit where work
    leaves this context: thread pools submit through
    ``contextvars.copy_context()`` and forked workers inherit the forking
    thread's context."""
    merged = {
        **_RUN_LABELS_VAR.get(),
        **{str(k): str(v) for k, v in labels.items()},
    }
    token = _RUN_LABELS_VAR.set(merged)
    key = next(_ACTIVE_SEQ)
    with _ACTIVE_LOCK:
        _ACTIVE_RUNS[key] = merged
    try:
        yield
    finally:
        _RUN_LABELS_VAR.reset(token)
        with _ACTIVE_LOCK:
            _ACTIVE_RUNS.pop(key, None)


# --------------------------------------------------------------------------
# the process-global span-metrics store
# --------------------------------------------------------------------------


class SpanMetrics:
    """Latency/rows/bytes histogram families auto-fed at span close.

    Every tracer record feeds ``span_latency_seconds`` (labels: ``span``
    plus the current run labels); ``rows``/``rows_out`` span attrs feed
    ``span_rows``; ``bytes``/``bytes_in``/``bytes_out`` feed
    ``span_bytes``. The registry source contract (``as_dict``/``reset``)
    makes it mount directly as ``engine.stats()["latency"]``.

    Cardinality bound: the ``run`` label is fresh per workflow run, so a
    long-lived process would otherwise accumulate one series per
    (span x workflow x run) forever. Only the most recent
    :attr:`MAX_RUN_SERIES` distinct ``run`` values keep their series;
    when a newer run arrives, the oldest run's series are pruned from
    every family (the per-SPAN summaries and Prometheus page stay
    bounded; traces retain every run's spans untouched). The serving
    layer's ``tenant`` label (ISSUE 10) rides the same rotation with its
    own, larger window (:attr:`MAX_TENANT_SERIES`): tenant ids are
    client-supplied, so an unbounded id stream must age out the same way
    run ids do.
    """

    #: distinct ``run`` label values whose series are retained (LRU by
    #: first observation; older runs' series are pruned, not zeroed)
    MAX_RUN_SERIES = 16
    #: distinct ``tenant`` label values retained — larger than the run
    #: window (tenants are long-lived identities, runs are ephemeral)
    MAX_TENANT_SERIES = 32

    def __init__(self) -> None:
        self._runs_lock = threading.Lock()
        self._label_lru: Dict[str, "OrderedDict[str, None]"] = {
            "run": OrderedDict(),
            "tenant": OrderedDict(),
        }
        self.latency = HistogramFamily(
            "fugue_tpu_span_latency_seconds",
            DEFAULT_LATENCY_BOUNDS,
            help="wall-clock latency distribution per span name",
        )
        self.rows = HistogramFamily(
            "fugue_tpu_span_rows",
            DEFAULT_SIZE_BOUNDS,
            help="rows processed per span (rows/rows_out attrs)",
        )
        self.bytes = HistogramFamily(
            "fugue_tpu_span_bytes",
            DEFAULT_SIZE_BOUNDS,
            help="bytes moved per span (bytes/bytes_in/bytes_out attrs)",
        )

    def families(self) -> Tuple[HistogramFamily, ...]:
        return (self.latency, self.rows, self.bytes)

    def _label_cap(self, label: str) -> int:
        return self.MAX_TENANT_SERIES if label == "tenant" else self.MAX_RUN_SERIES

    def _note_label(self, label: str, value: str) -> None:
        """Record that ``value`` is a live id for ``label``; evict the
        oldest ids' series once more than the label's window has been
        seen. (``_note_run`` generalized for the tenant label.)"""
        lru = self._label_lru[label]
        evict: List[str] = []
        with self._runs_lock:
            if value in lru:
                lru.move_to_end(value)
            else:
                lru[value] = None
                while len(lru) > self._label_cap(label):
                    evict.append(lru.popitem(last=False)[0])
        for old in evict:
            for f in self.families():
                f.prune(
                    lambda labels, _old=old, _l=label: labels.get(_l) == _old
                )

    def _note_run(self, run_id: str) -> None:
        self._note_label("run", run_id)

    def observe_record(self, rec: Dict[str, Any]) -> None:
        """Feed one completed tracer record (called from ``Tracer._emit``
        — i.e. only while tracing is enabled; the disabled path never
        reaches here)."""
        labels = {"span": rec["name"], **_RUN_LABELS_VAR.get()}
        for rotated in ("run", "tenant"):
            if rotated in labels:
                self._note_label(rotated, labels[rotated])
        self.latency.observe(max(rec.get("dur", 0), 0) / 1e9, **labels)
        args = rec.get("args") or {}
        rows = args.get("rows", args.get("rows_out"))
        if isinstance(rows, (int, float)) and not isinstance(rows, bool):
            self.rows.observe(rows, **labels)
        nbytes = args.get("bytes")
        if nbytes is None:
            bi, bo = args.get("bytes_in"), args.get("bytes_out")
            if bi is not None or bo is not None:
                nbytes = (bi or 0) + (bo or 0)
        if isinstance(nbytes, (int, float)) and not isinstance(nbytes, bool):
            self.bytes.observe(nbytes, **labels)

    # -- fork-boundary transport --------------------------------------------
    def snapshot(self) -> Dict[str, List[Dict[str, Any]]]:
        """Full encode — a worker takes one at chunk start, ships
        :meth:`delta_since` home with the chunk result."""
        return {
            "latency": self.latency.encode(),
            "rows": self.rows.encode(),
            "bytes": self.bytes.encode(),
        }

    def delta_since(
        self, snap: Dict[str, List[Dict[str, Any]]]
    ) -> Dict[str, List[Dict[str, Any]]]:
        snap = snap or {}
        out = {
            "latency": self.latency.delta_since(snap.get("latency", [])),
            "rows": self.rows.delta_since(snap.get("rows", [])),
            "bytes": self.bytes.delta_since(snap.get("bytes", [])),
        }
        return {k: v for k, v in out.items() if v}

    def merge(self, delta: Dict[str, List[Dict[str, Any]]]) -> None:
        if not delta:
            return
        # worker deltas carry run/tenant labels too — count them against
        # the same rotation windows so merged series obey the bound
        for encs in delta.values():
            for enc in encs or []:
                lab = enc.get("labels") or {}
                for rotated in ("run", "tenant"):
                    v = lab.get(rotated)
                    if v:
                        self._note_label(rotated, v)
        self.latency.merge(delta.get("latency", []))
        self.rows.merge(delta.get("rows", []))
        self.bytes.merge(delta.get("bytes", []))

    # -- registry source contract (engine.stats()["latency"]) ----------------
    def summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-SPAN-NAME latency summary, merged across run-label series:
        ``{span: {count, mean_ms, p50_ms, p95_ms, p99_ms, max_ms}}``."""
        merged: Dict[str, Histogram] = {}
        for labels, h in self.latency.series():
            if h.count == 0:
                continue
            span = labels.get("span", "?")
            agg = merged.get(span)
            if agg is None:
                agg = merged[span] = Histogram(self.latency.bounds)
            agg.merge(h.encode())
        out: Dict[str, Dict[str, Any]] = {}
        for span, h in merged.items():
            p = h.percentiles()
            out[span] = {
                "count": h.count,
                "mean_ms": round(h.sum / h.count * 1e3, 6) if h.count else None,
                "p50_ms": round(p["p50"] * 1e3, 6) if p["p50"] is not None else None,
                "p95_ms": round(p["p95"] * 1e3, 6) if p["p95"] is not None else None,
                "p99_ms": round(p["p99"] * 1e3, 6) if p["p99"] is not None else None,
                "max_ms": round(h.max * 1e3, 6) if h.max is not None else None,
            }
        return out

    def as_dict(self) -> Dict[str, Any]:
        return self.summary()

    def reset(self) -> None:
        for f in self.families():
            f.reset()

    def clear(self) -> None:
        for f in self.families():
            f.clear()
        with self._runs_lock:
            for lru in self._label_lru.values():
                lru.clear()


_SPAN_METRICS = SpanMetrics()


def get_span_metrics() -> SpanMetrics:
    return _SPAN_METRICS
