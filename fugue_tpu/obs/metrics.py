"""Distribution metrics: bucketed histograms, labeled families, and the
process-global span-metrics store (ISSUE 6 tentpole, piece 1).

The PR 3 registry holds plain counters — enough for "how many", useless
for "how long". This module adds the distribution substrate:

- :class:`Histogram`: fixed exponential buckets with p50/p95/p99
  estimation (Prometheus-style linear interpolation inside the bucket
  containing the target rank, clamped to the observed min/max). The
  internal state is a **mergeable encoding** — plain lists/numbers that
  add associatively — so worker-recorded distributions ship across the
  fork boundary and merge into the driver's without loss.
- :class:`HistogramFamily`: one metric name fanned out over label sets
  (``family.observe(v, span="engine.aggregate", run="ab12")``), the
  attribution scheme a per-tenant serving layer reuses unchanged.
- :class:`SpanMetrics`: the process-global store fed by the tracer at
  every span close — every span name gets a latency distribution for
  free, and ``rows``/``bytes`` span attrs feed throughput histograms.
  Process-global like the tracer itself (one timeline, one metric
  store); ``engine.stats()["latency"]`` reads it, ``engine.reset_stats()``
  resets it under the keep-entries contract (series stay registered,
  observations zero — the ``JitCache.reset`` rule).

Run attribution: :func:`run_labels` is a module-global label context the
workflow layer enters for the duration of a run; every observation made
while it is active carries the ``workflow``/``run`` labels. Module-global
(not thread-local) on purpose: pool threads and forked map workers
inherit it, so worker samples attribute to the right run.
"""

import threading
from bisect import bisect_left
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "DEFAULT_LATENCY_BOUNDS",
    "DEFAULT_SIZE_BOUNDS",
    "Histogram",
    "HistogramFamily",
    "SpanMetrics",
    "current_run_labels",
    "get_span_metrics",
    "run_labels",
]

# latency buckets (seconds): 1µs … ~134s, ×2 per bucket — 28 buckets plus
# overflow covers a single jit dispatch through a full 1B-row pass
DEFAULT_LATENCY_BOUNDS: Tuple[float, ...] = tuple(1e-6 * (2**i) for i in range(28))
# size buckets (rows or bytes): 4 … ~1.1e12, ×4 per bucket
DEFAULT_SIZE_BOUNDS: Tuple[float, ...] = tuple(float(4**i) for i in range(1, 21))


class Histogram:
    """Fixed-bucket histogram with quantile estimation and merge support.

    ``counts[i]`` counts observations ``v <= bounds[i]`` (first matching
    bucket); ``counts[-1]`` is the overflow bucket. ``encode()`` returns
    the plain-data form that :meth:`merge` adds back in — counts, sum and
    count add associatively, min/max combine via min/max, so merging is
    order-independent across any number of workers.
    """

    def __init__(self, bounds: Tuple[float, ...] = DEFAULT_LATENCY_BOUNDS):
        self.bounds = tuple(bounds)
        self._lock = threading.Lock()
        self._zero()

    def _zero(self) -> None:
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.counts[bisect_left(self.bounds, v)] += 1
            self.sum += v
            self.count += 1
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v

    # -- mergeable encoding --------------------------------------------------
    def encode(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counts": list(self.counts),
                "sum": self.sum,
                "count": self.count,
                "min": self.min,
                "max": self.max,
            }

    def merge(self, enc: Dict[str, Any]) -> None:
        """Add an encoded delta in. Associative and commutative: merging
        worker A's delta then B's equals B's then A's equals observing
        every value locally."""
        if not enc or not enc.get("count"):
            return
        counts = enc["counts"]
        with self._lock:
            n = min(len(counts), len(self.counts))
            for i in range(n):
                self.counts[i] += counts[i]
            self.sum += enc["sum"]
            self.count += enc["count"]
            for key, pick in (("min", min), ("max", max)):
                v = enc.get(key)
                if v is not None:
                    cur = getattr(self, key)
                    setattr(self, key, v if cur is None else pick(cur, v))

    def subtract(self, enc: Dict[str, Any]) -> Dict[str, Any]:
        """Current state minus an earlier :meth:`encode` — the
        fork-boundary delta a worker ships home (its post-fork
        observations only; the COW copy inherited at fork subtracts out)."""
        cur = self.encode()
        if not enc:
            return cur
        base = enc.get("counts", [])
        counts = [
            c - (base[i] if i < len(base) else 0) for i, c in enumerate(cur["counts"])
        ]
        return {
            "counts": counts,
            "sum": cur["sum"] - enc.get("sum", 0.0),
            "count": cur["count"] - enc.get("count", 0),
            # min/max of just-the-delta is unrecoverable from two encodes;
            # the current values are a conservative superset (merging them
            # home can only widen the driver's range to values it, or its
            # fork parent, already saw)
            "min": cur["min"],
            "max": cur["max"],
        }

    # -- quantiles -----------------------------------------------------------
    def quantile(self, q: float) -> Optional[float]:
        """Estimate the q-quantile (0..1) by linear interpolation within
        the bucket containing the target rank, clamped to the observed
        [min, max] so estimates never leave the data's actual range."""
        with self._lock:
            if self.count == 0:
                return None
            target = max(min(q, 1.0), 0.0) * self.count
            cum = 0
            lo = 0.0
            for i, c in enumerate(self.counts):
                hi = (
                    self.bounds[i]
                    if i < len(self.bounds)
                    else (self.max if self.max is not None else lo)
                )
                if cum + c >= target and c > 0:
                    frac = (target - cum) / c
                    est = lo + (hi - lo) * frac
                    break
                cum += c
                lo = hi
            else:
                est = self.max if self.max is not None else 0.0
            if self.min is not None:
                est = max(est, self.min)
            if self.max is not None:
                est = min(est, self.max)
            return est

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    # -- registry source contract -------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        p = self.percentiles()
        with self._lock:
            out: Dict[str, Any] = {
                "count": self.count,
                "sum": round(self.sum, 9),
                "min": self.min,
                "max": self.max,
                "mean": (self.sum / self.count) if self.count else None,
            }
        out.update(p)
        return out

    def reset(self) -> None:
        with self._lock:
            self._zero()


def _labels_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class HistogramFamily:
    """A labeled histogram family: one metric name, one series per label
    set. The unit of Prometheus exposition (each series renders its own
    ``_bucket``/``_sum``/``_count`` lines) and of fork-boundary transport
    (encode/merge/delta operate per series, matched by labels — never by
    pid, so two workers' series with equal labels merge additively)."""

    def __init__(
        self,
        name: str,
        bounds: Tuple[float, ...] = DEFAULT_LATENCY_BOUNDS,
        help: str = "",
    ):
        self.name = name
        self.bounds = tuple(bounds)
        self.help = help or name
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], Histogram] = {}

    def _get_or_create(self, key: Tuple[Tuple[str, str], ...]) -> Histogram:
        with self._lock:
            h = self._series.get(key)
            if h is None:
                h = Histogram(self.bounds)
                self._series[key] = h
            return h

    def observe(self, value: float, **labels: Any) -> None:
        self._get_or_create(_labels_key(labels)).observe(value)

    def get(self, **labels: Any) -> Optional[Histogram]:
        with self._lock:
            return self._series.get(_labels_key(labels))

    def series(self) -> List[Tuple[Dict[str, str], Histogram]]:
        with self._lock:
            items = list(self._series.items())
        return [(dict(k), h) for k, h in items]

    # -- mergeable encoding (fork-boundary transport) ------------------------
    def encode(self) -> List[Dict[str, Any]]:
        return [
            {"labels": labels, **h.encode()} for labels, h in self.series()
        ]

    def merge(self, encoded: List[Dict[str, Any]]) -> None:
        for enc in encoded or []:
            if enc.get("count"):
                self._get_or_create(_labels_key(enc.get("labels", {}))).merge(enc)

    def delta_since(self, snapshot: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        base = {
            _labels_key(e.get("labels", {})): e for e in (snapshot or [])
        }
        out: List[Dict[str, Any]] = []
        for labels, h in self.series():
            d = h.subtract(base.get(_labels_key(labels), {}))
            if d.get("count"):
                out.append({"labels": labels, **d})
        return out

    # -- registry source contract -------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for labels, h in self.series():
            if h.count == 0:
                continue  # reset series stay registered but don't report
            key = ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or "_"
            out[key] = h.as_dict()
        return out

    def reset(self) -> None:
        """Zero every series' observations. Series stay REGISTERED — the
        keep-entries contract (``JitCache.reset``): a stats reset must not
        tear down the metric schema a scraper is watching."""
        for _, h in self.series():
            h.reset()

    def clear(self) -> None:
        """Drop every series (test isolation; NOT part of reset)."""
        with self._lock:
            self._series.clear()


# --------------------------------------------------------------------------
# run attribution labels
# --------------------------------------------------------------------------

_RUN_LABELS: Dict[str, str] = {}


def current_run_labels() -> Dict[str, str]:
    """The labels attached to every metric observation right now
    (``workflow``/``run`` while a workflow run is active, else empty)."""
    return _RUN_LABELS


@contextmanager
def run_labels(**labels: Any) -> Iterator[None]:
    """Attach labels to every span-metric observation for the duration.
    Module-global so pool threads and forked workers inherit it; nested
    uses overlay (inner wins, outer restored on exit)."""
    global _RUN_LABELS
    prev = _RUN_LABELS
    _RUN_LABELS = {**prev, **{str(k): str(v) for k, v in labels.items()}}
    try:
        yield
    finally:
        _RUN_LABELS = prev


# --------------------------------------------------------------------------
# the process-global span-metrics store
# --------------------------------------------------------------------------


class SpanMetrics:
    """Latency/rows/bytes histogram families auto-fed at span close.

    Every tracer record feeds ``span_latency_seconds`` (labels: ``span``
    plus the current run labels); ``rows``/``rows_out`` span attrs feed
    ``span_rows``; ``bytes``/``bytes_in``/``bytes_out`` feed
    ``span_bytes``. The registry source contract (``as_dict``/``reset``)
    makes it mount directly as ``engine.stats()["latency"]``.
    """

    def __init__(self) -> None:
        self.latency = HistogramFamily(
            "fugue_tpu_span_latency_seconds",
            DEFAULT_LATENCY_BOUNDS,
            help="wall-clock latency distribution per span name",
        )
        self.rows = HistogramFamily(
            "fugue_tpu_span_rows",
            DEFAULT_SIZE_BOUNDS,
            help="rows processed per span (rows/rows_out attrs)",
        )
        self.bytes = HistogramFamily(
            "fugue_tpu_span_bytes",
            DEFAULT_SIZE_BOUNDS,
            help="bytes moved per span (bytes/bytes_in/bytes_out attrs)",
        )

    def families(self) -> Tuple[HistogramFamily, ...]:
        return (self.latency, self.rows, self.bytes)

    def observe_record(self, rec: Dict[str, Any]) -> None:
        """Feed one completed tracer record (called from ``Tracer._emit``
        — i.e. only while tracing is enabled; the disabled path never
        reaches here)."""
        labels = {"span": rec["name"], **_RUN_LABELS}
        self.latency.observe(max(rec.get("dur", 0), 0) / 1e9, **labels)
        args = rec.get("args") or {}
        rows = args.get("rows", args.get("rows_out"))
        if isinstance(rows, (int, float)) and not isinstance(rows, bool):
            self.rows.observe(rows, **labels)
        nbytes = args.get("bytes")
        if nbytes is None:
            bi, bo = args.get("bytes_in"), args.get("bytes_out")
            if bi is not None or bo is not None:
                nbytes = (bi or 0) + (bo or 0)
        if isinstance(nbytes, (int, float)) and not isinstance(nbytes, bool):
            self.bytes.observe(nbytes, **labels)

    # -- fork-boundary transport --------------------------------------------
    def snapshot(self) -> Dict[str, List[Dict[str, Any]]]:
        """Full encode — a worker takes one at chunk start, ships
        :meth:`delta_since` home with the chunk result."""
        return {
            "latency": self.latency.encode(),
            "rows": self.rows.encode(),
            "bytes": self.bytes.encode(),
        }

    def delta_since(
        self, snap: Dict[str, List[Dict[str, Any]]]
    ) -> Dict[str, List[Dict[str, Any]]]:
        snap = snap or {}
        out = {
            "latency": self.latency.delta_since(snap.get("latency", [])),
            "rows": self.rows.delta_since(snap.get("rows", [])),
            "bytes": self.bytes.delta_since(snap.get("bytes", [])),
        }
        return {k: v for k, v in out.items() if v}

    def merge(self, delta: Dict[str, List[Dict[str, Any]]]) -> None:
        if not delta:
            return
        self.latency.merge(delta.get("latency", []))
        self.rows.merge(delta.get("rows", []))
        self.bytes.merge(delta.get("bytes", []))

    # -- registry source contract (engine.stats()["latency"]) ----------------
    def summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-SPAN-NAME latency summary, merged across run-label series:
        ``{span: {count, mean_ms, p50_ms, p95_ms, p99_ms, max_ms}}``."""
        merged: Dict[str, Histogram] = {}
        for labels, h in self.latency.series():
            if h.count == 0:
                continue
            span = labels.get("span", "?")
            agg = merged.get(span)
            if agg is None:
                agg = merged[span] = Histogram(self.latency.bounds)
            agg.merge(h.encode())
        out: Dict[str, Dict[str, Any]] = {}
        for span, h in merged.items():
            p = h.percentiles()
            out[span] = {
                "count": h.count,
                "mean_ms": round(h.sum / h.count * 1e3, 6) if h.count else None,
                "p50_ms": round(p["p50"] * 1e3, 6) if p["p50"] is not None else None,
                "p95_ms": round(p["p95"] * 1e3, 6) if p["p95"] is not None else None,
                "p99_ms": round(p["p99"] * 1e3, 6) if p["p99"] is not None else None,
                "max_ms": round(h.max * 1e3, 6) if h.max is not None else None,
            }
        return out

    def as_dict(self) -> Dict[str, Any]:
        return self.summary()

    def reset(self) -> None:
        for f in self.families():
            f.reset()

    def clear(self) -> None:
        for f in self.families():
            f.clear()


_SPAN_METRICS = SpanMetrics()


def get_span_metrics() -> SpanMetrics:
    return _SPAN_METRICS
