"""Per-process span spool — the cluster trace transport (ISSUE 18).

Remote processes (dist workers, serve replicas) cannot ship spans home
over the fork boundary the way ``parallel_map`` workers do, so each one
periodically **publishes its whole span buffer + resource-sampler ring +
stats** to a single per-process file in a shared spool directory:

    <spool_dir>/<host>-<pid>.spool.json

The publish is the repo's universal atomic discipline — temp write in the
same directory, ``os.replace`` — and each publish carries the FULL
cumulative buffer (bounded by the tracer's ``max_spans`` cap), so the
protocol is idempotent: last write wins, a crash mid-publish leaves the
previous complete file, and re-publishing after a retry is harmless.
Filenames are :func:`~fugue_tpu.obs.tracer.proc_ident`-prefixed so two
hosts sharing a store never collide.

``obs/assemble.py`` merges the spools (plus the local buffer) into ONE
Perfetto trace with one named track per process.
"""

import json
import os
import tempfile
from typing import Any, Dict, List, Optional

from .tracer import get_tracer, proc_ident

__all__ = ["SPOOL_SUFFIX", "publish_spool", "read_spools"]

SPOOL_SUFFIX = ".spool.json"
SPOOL_VERSION = 1


def publish_spool(
    spool_dir: str,
    records: Optional[List[Dict[str, Any]]] = None,
    counters: Optional[List[Any]] = None,
    stats: Optional[Dict[str, Any]] = None,
    label: str = "",
) -> str:
    """Atomically publish THIS process's spans (default: the global tracer
    buffer), sampler ring (default: the global sampler's series — the
    remote counter-track fix) and optional stats snapshot to its spool
    file. Returns the published path."""
    if records is None:
        records = get_tracer().records()
    if counters is None:
        from .sampler import get_sampler

        counters = get_sampler().series()
    doc = {
        "version": SPOOL_VERSION,
        "proc": proc_ident(),
        "pid": os.getpid(),
        "label": label,
        "spans": records,
        "counters": [[ts, vals] for ts, vals in counters],
        "stats": stats or {},
    }
    os.makedirs(spool_dir, exist_ok=True)
    path = os.path.join(spool_dir, proc_ident() + SPOOL_SUFFIX)
    fd, tmp = tempfile.mkstemp(dir=spool_dir, prefix=".spool-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def read_spools(spool_dir: str) -> List[Dict[str, Any]]:
    """Read every complete spool in ``spool_dir``, sorted by process
    identity. Torn/corrupt files are skipped (the atomic publish makes
    them impossible from this writer, but the directory is shared)."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(spool_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(SPOOL_SUFFIX):
            continue
        try:
            with open(os.path.join(spool_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("spans"), list):
            out.append(doc)
    return out
