"""Cluster flight recorder (ISSUE 18 tentpole, piece 2).

A structured, append-only event log recording every **recovery-ladder**
event the dist/serve tiers take — lease acquire/renew/steal, heartbeat
expiry, categorized re-dispatch, orphaned-output invalidation,
speculative twins, fleet claim steals and failovers, journal replays —
as typed JSON records carrying the cluster trace id + causal parent span,
so a chaos post-mortem ("worker-2 SIGKILLed at t+3.1s → lease stolen by
worker-0 at t+4.0s → map 7 re-dispatched") reconstructs from the log
alone, without grepping N processes' stderr.

Transport mirrors the span spool: each process appends JSON lines to its
own file in a shared directory —

    <events_dir>/<host>-<pid>.events.jsonl

One line per event, flushed on write (an append of one line is atomic for
these sizes on POSIX; a torn final line from a SIGKILLed writer is
skipped by :func:`read_events`). Timestamps are ``time.time()`` epoch
seconds — coarse but comparable across hosts, which a post-mortem needs
more than nanosecond precision.

Default **off** (conf ``fugue.tpu.events.enabled`` +
``fugue.tpu.events.dir``; env ``FUGUE_TPU_EVENTS`` / ``FUGUE_TPU_EVENTS_DIR``
override, the tracer's enablement contract). Disabled cost is one
attribute check per call site.
"""

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .tracer import current_trace_id, get_tracer, proc_ident

__all__ = [
    "EVENT_TYPES",
    "EventLog",
    "get_event_log",
    "configure_events_from_conf",
    "read_events",
    "render_timeline",
]

ENV_EVENTS = "FUGUE_TPU_EVENTS"
ENV_EVENTS_DIR = "FUGUE_TPU_EVENTS_DIR"

EVENTS_SUFFIX = ".events.jsonl"

# the recovery-ladder vocabulary — every emitter uses one of these, so the
# timeline renderer and the completeness gate enumerate a closed set
EVENT_TYPES = frozenset(
    {
        "lease.acquire",  # clean lease grant
        "lease.renew",  # keeper heartbeat on a held lease
        "lease.steal",  # takeover of a dead/expired holder's lease
        "hb.expired",  # holder's heartbeat proven stale (precedes a steal)
        "task.redispatch",  # stolen task re-executed by the new holder
        "task.orphan",  # done record invalidated (missing/torn artifact)
        "task.speculative",  # straggler marked for a speculative twin
        "task.failed",  # categorized task failure recorded on the board
        "fleet.claim_steal",  # serve-fleet claim lease taken from a dead replica
        "fleet.failover",  # FleetClient re-placed a submission elsewhere
        "serve.journal_replay",  # replica resubmitted journaled work on restart
        "chaos.inject",  # fault injected by a smoke/chaos harness
        "view.register",  # standing view registered (continuous pipelines)
        "view.unregister",  # standing view retired; lease released
        "view.lease.acquire",  # replica became a view's maintainer
        "view.lease.steal",  # maintenance moved off a dead/expired replica
        "view.refresh",  # maintainer pushed fresh partitions through the queue
        "view.publish",  # a new view generation reached the fleet store
        "view.slo_breach",  # view staleness exceeded its tenant freshness SLO
    }
)


class EventLog:
    """Per-process appender. Use the :func:`get_event_log` singleton."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._dir: Optional[str] = None
        self._fh: Any = None
        self.enabled = False
        self.emitted = 0
        self.errors = 0

    def configure(self, events_dir: Optional[str], enabled: bool) -> None:
        with self._lock:
            if events_dir is not None and events_dir != self._dir:
                if self._fh is not None:
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                self._fh = None
                self._dir = events_dir
            self.enabled = bool(enabled) and self._dir is not None

    def path(self) -> Optional[str]:
        with self._lock:
            if self._dir is None:
                return None
            return os.path.join(self._dir, proc_ident() + EVENTS_SUFFIX)

    def emit(self, etype: str, **detail: Any) -> None:
        """Append one typed record. No-op when disabled. Never raises —
        a full disk must not take the recovery path down with it."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {
            "ts": time.time(),
            "type": etype,
            "proc": proc_ident(),
            "pid": os.getpid(),
        }
        trace = current_trace_id()
        if trace:
            rec["trace"] = trace
        parent = get_tracer().current_span_id()
        if parent:
            rec["parent"] = parent
        for k, v in detail.items():
            if v is not None:
                rec[k] = v
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            try:
                if self._fh is None:
                    if self._dir is None:
                        return
                    os.makedirs(self._dir, exist_ok=True)
                    # pid can change across a fork that inherited this
                    # object — reopening per identity keeps files per-process
                    self._fh = open(
                        os.path.join(self._dir, proc_ident() + EVENTS_SUFFIX), "a"
                    )
                self._fh.write(line + "\n")
                self._fh.flush()
                self.emitted += 1
            except OSError:
                self.errors += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "dir": self._dir,
                "emitted": self.emitted,
                "errors": self.errors,
            }


_EVENT_LOG = EventLog()


def get_event_log() -> EventLog:
    return _EVENT_LOG


def configure_events_from_conf(conf: Any) -> None:
    """Apply flight-recorder switches from an engine conf (engine
    construction path, next to the tracer's ``configure_from_conf``).
    Env vars override; absent key + absent env leaves state untouched."""
    from ..constants import (
        FUGUE_TPU_CONF_EVENTS_DIR,
        FUGUE_TPU_CONF_EVENTS_ENABLED,
    )
    from .tracer import _truthy

    try:
        raw = conf.get_or_none(FUGUE_TPU_CONF_EVENTS_ENABLED, object)
        d = conf.get_or_none(FUGUE_TPU_CONF_EVENTS_DIR, object)
    except Exception:
        raw = d = None
    env = os.environ.get(ENV_EVENTS)
    env_dir = os.environ.get(ENV_EVENTS_DIR)
    if env_dir:
        d = env_dir
    enabled: Optional[bool] = None
    if env is not None and env != "":
        enabled = _truthy(env)
    elif raw is not None:
        enabled = _truthy(raw)
    log = _EVENT_LOG
    if d is not None or enabled is not None:
        log.configure(
            str(d) if d is not None else None,
            log.enabled if enabled is None else enabled,
        )


def read_events(events_dir: str) -> List[Dict[str, Any]]:
    """Merge every process's event file in ``events_dir`` into one list
    sorted by timestamp. Torn trailing lines (SIGKILLed writer) and
    foreign files are skipped."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(events_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(EVENTS_SUFFIX):
            continue
        try:
            with open(os.path.join(events_dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "type" in rec and "ts" in rec:
                        out.append(rec)
        except OSError:
            continue
    out.sort(key=lambda r: (r.get("ts", 0.0), r.get("proc", ""), r.get("type", "")))
    return out


_RENDER = {
    "lease.acquire": lambda r: f"lease acquired for {r.get('task')} by {r.get('owner')}",
    "lease.renew": lambda r: f"lease renewed for {r.get('task')} by {r.get('owner')}",
    "lease.steal": lambda r: (
        f"lease for {r.get('task')} stolen by {r.get('owner')} "
        f"from {r.get('prev_owner')} ({r.get('reason')})"
    ),
    "hb.expired": lambda r: (
        f"heartbeat of {r.get('holder')} proven stale "
        f"(age {r.get('age_s', '?')}s, task {r.get('task')})"
    ),
    "task.redispatch": lambda r: (
        f"task {r.get('task')} re-dispatched on {r.get('owner')} "
        f"({r.get('reason', 'stolen')})"
    ),
    "task.orphan": lambda r: (
        f"orphaned output of {r.get('task')} invalidated ({r.get('why')})"
    ),
    "task.speculative": lambda r: (
        f"speculative twin marked for straggler {r.get('task')}"
    ),
    "task.failed": lambda r: (
        f"task {r.get('task')} failed on {r.get('worker')} "
        f"({r.get('category')}: {r.get('error', '')})"
    ),
    "fleet.claim_steal": lambda r: (
        f"fleet claim {r.get('key')} stolen by {r.get('owner')} "
        f"from {r.get('prev_owner')}"
    ),
    "fleet.failover": lambda r: (
        f"submission {r.get('key')} failed over from replica "
        f"{r.get('from_replica')} to {r.get('to_replica')}"
    ),
    "serve.journal_replay": lambda r: (
        f"replica {r.get('replica')} replayed {r.get('entries')} journaled "
        f"submission(s)"
    ),
    "chaos.inject": lambda r: (
        f"{r.get('fault', 'fault')} injected into {r.get('target')}"
    ),
    "view.register": lambda r: (
        f"view {r.get('view')} registered by tenant {r.get('tenant')} "
        f"on {r.get('source')}"
    ),
    "view.unregister": lambda r: f"view {r.get('view')} unregistered",
    "view.lease.acquire": lambda r: (
        f"view {r.get('view')} watch lease acquired by {r.get('owner')}"
    ),
    "view.lease.steal": lambda r: (
        f"view {r.get('view')} watch lease stolen by {r.get('owner')} "
        f"from {r.get('prev_owner')} ({r.get('reason')})"
    ),
    "view.refresh": lambda r: (
        f"view {r.get('view')} refresh -> gen {r.get('gen')} "
        f"({r.get('mode')}: {r.get('fresh')}/{r.get('total')} partition(s) fresh)"
    ),
    "view.publish": lambda r: (
        f"view {r.get('view')} generation {r.get('gen')} published "
        f"(as_of {r.get('as_of')})"
    ),
    "view.slo_breach": lambda r: (
        f"view {r.get('view')} freshness SLO breached "
        f"(lag {r.get('lag_s')}s > {r.get('slo_s')}s)"
    ),
}


def render_timeline(
    events: List[Dict[str, Any]],
    t0: Optional[float] = None,
    trace: Optional[str] = None,
) -> str:
    """Human-readable post-mortem: one ``t+<s>`` line per event, relative
    to ``t0`` (default: the first event). ``trace`` keeps only one run's
    events (records with no trace id — e.g. chaos injections — are kept)."""
    if trace is not None:
        events = [e for e in events if e.get("trace") in (trace, None)]
    if not events:
        return "(no events recorded — is fugue.tpu.events.enabled on?)"
    if t0 is None:
        t0 = min(e.get("ts", 0.0) for e in events)
    lines = [f"== cluster timeline ({len(events)} events) =="]
    for e in events:
        fn = _RENDER.get(e["type"])
        text = fn(e) if fn else json.dumps(e, sort_keys=True)
        lines.append(f"t+{e.get('ts', t0) - t0:6.2f}s  [{e.get('proc', '?')}] {text}")
    return "\n".join(lines)
