"""Unified observability: hierarchical span tracing, one metrics
registry, live distribution metrics, and a continuous resource sampler.

See ``docs/observability.md``. Quick start::

    from fugue_tpu.obs import get_tracer, get_sampler
    from fugue_tpu.obs.export import write_chrome_trace

    get_tracer().enable()          # or conf fugue.tpu.trace.enabled=True
    get_sampler().start()          # or conf fugue.tpu.telemetry.enabled=True
    ...run workflows...
    write_chrome_trace("/tmp/trace.json")   # spans + resource counter tracks
    print(engine.report())                  # top-N report w/ p50/p95/p99
    engine.stats()["latency"]               # per-span latency distributions
    to_prometheus_text(engine)              # what GET /metrics serves
    engine.reset_stats()                    # consistent reset across all
"""

from .assemble import assemble_trace
from .events import (
    EVENT_TYPES,
    EventLog,
    configure_events_from_conf,
    get_event_log,
    read_events,
    render_timeline,
)
from .export import (
    render_report,
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from .metrics import (
    Histogram,
    HistogramFamily,
    SpanMetrics,
    active_run_labels,
    current_run_labels,
    get_span_metrics,
    run_labels,
)
from .prom import to_prometheus_text, validate_prometheus_text
from .registry import MetricsRegistry
from .sampler import (
    ResourceSampler,
    configure_sampler_from_conf,
    get_sampler,
)
from .spool import publish_spool, read_spools
from .tracer import (
    NULL_SPAN,
    Tracer,
    configure_from_conf,
    current_trace_id,
    get_tracer,
    mint_trace_id,
    proc_ident,
    set_verb_observer,
    trace_carrier,
    trace_scope,
    traced_verb,
)

__all__ = [
    "EVENT_TYPES",
    "EventLog",
    "Histogram",
    "HistogramFamily",
    "MetricsRegistry",
    "NULL_SPAN",
    "ResourceSampler",
    "SpanMetrics",
    "Tracer",
    "active_run_labels",
    "assemble_trace",
    "configure_events_from_conf",
    "configure_from_conf",
    "configure_sampler_from_conf",
    "current_run_labels",
    "current_trace_id",
    "get_event_log",
    "get_sampler",
    "get_span_metrics",
    "get_tracer",
    "mint_trace_id",
    "proc_ident",
    "publish_spool",
    "read_events",
    "read_spools",
    "render_report",
    "render_timeline",
    "run_labels",
    "set_verb_observer",
    "to_chrome_trace",
    "to_prometheus_text",
    "trace_carrier",
    "trace_scope",
    "traced_verb",
    "validate_chrome_trace",
    "validate_prometheus_text",
    "write_chrome_trace",
]
