"""Unified observability: hierarchical span tracing + one metrics registry.

See ``docs/observability.md``. Quick start::

    from fugue_tpu.obs import get_tracer
    from fugue_tpu.obs.export import write_chrome_trace

    get_tracer().enable()          # or conf fugue.tpu.trace.enabled=True
    ...run workflows...
    write_chrome_trace("/tmp/trace.json")   # load in Perfetto
    print(engine.report())                  # top-N text report
    engine.stats()                          # every registry as one dict
    engine.reset_stats()                    # consistent reset across all
"""

from .export import (
    render_report,
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)
from .registry import MetricsRegistry
from .tracer import (
    NULL_SPAN,
    Tracer,
    configure_from_conf,
    get_tracer,
    traced_verb,
)

__all__ = [
    "MetricsRegistry",
    "NULL_SPAN",
    "Tracer",
    "configure_from_conf",
    "get_tracer",
    "render_report",
    "to_chrome_trace",
    "traced_verb",
    "validate_chrome_trace",
    "write_chrome_trace",
]
