"""Prometheus text exposition (ISSUE 6 tentpole, piece 3 rendering).

``to_prometheus_text`` renders the process-global span histograms, the
resource sampler's latest gauges, and (optionally) one engine's
flattened counter registry into the `text exposition format`_ version
0.0.4 — what ``GET /metrics`` on :class:`~fugue_tpu.rpc.http.HttpRPCServer`
serves and any Prometheus-compatible scraper ingests. Histogram series
keep their full label sets (``span``/``workflow``/``run``) — the
attribution a per-tenant serving layer reuses unchanged.

``validate_prometheus_text`` is the CI gate (``make telemetry-smoke``):
it asserts the line grammar, label syntax, that no name gets a second
``# TYPE`` line and no (name, label-set) sample repeats, cumulative-bucket
monotonicity, the ``+Inf`` bucket, and ``_count``/``+Inf`` agreement —
the properties a scraper needs to ingest the page at all.

.. _text exposition format:
   https://prometheus.io/docs/instrumenting/exposition_formats/
"""

import math
import re
from typing import Any, Dict, List, Optional

__all__ = ["to_prometheus_text", "validate_prometheus_text"]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
    r"(\{[^{}]*\})?"  # optional label set
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN|\+Inf)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _name(*parts: str) -> str:
    n = _NAME_BAD.sub("_", "_".join(p for p in parts if p))
    return n if not n[:1].isdigit() else "_" + n


def _escape(v: Any) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_NAME_BAD.sub("_", str(k))}="{_escape(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _num(v: float) -> str:
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _render_histogram_family(family: Any, lines: List[str]) -> int:
    """Render one HistogramFamily; returns the number of series emitted."""
    name = _name(family.name)
    emitted = 0
    header = False
    for labels, hist in family.series():
        enc = hist.encode()
        if not enc["count"]:
            continue
        if not header:
            lines.append(f"# HELP {name} {family.help}")
            lines.append(f"# TYPE {name} histogram")
            header = True
        emitted += 1
        cum = 0
        for bound, c in zip(family.bounds, enc["counts"]):
            cum += c
            lines.append(
                f"{name}_bucket{_labels({**labels, 'le': '%g' % bound})} {cum}"
            )
        cum += enc["counts"][-1]
        lines.append(f"{name}_bucket{_labels({**labels, 'le': '+Inf'})} {cum}")
        lines.append(f"{name}_sum{_labels(labels)} {_num(float(enc['sum']))}")
        lines.append(f"{name}_count{_labels(labels)} {enc['count']}")
    return emitted


def _flatten_numeric(d: Any, prefix: str, out: Dict[str, float]) -> None:
    if not isinstance(d, dict):
        return
    for k, v in d.items():
        path = f"{prefix}_{k}" if prefix else str(k)
        if isinstance(v, dict):
            _flatten_numeric(v, path, out)
        elif isinstance(v, bool):
            out[path] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[path] = float(v)


def to_prometheus_text(
    engine: Any = None,
    span_metrics: Any = None,
    sampler: Any = None,
) -> str:
    """Render the current telemetry as Prometheus text exposition.

    Included, in order: every span histogram family (latency / rows /
    bytes, fully labeled), the sampler's latest sample as
    ``fugue_tpu_resource_*`` gauges (+ ring/running meta), and — when an
    engine is given — its ``engine.stats()`` numeric leaves flattened to
    ``fugue_tpu_<group>_<key>`` gauges."""
    if span_metrics is None:
        from .metrics import get_span_metrics

        span_metrics = get_span_metrics()
    if sampler is None:
        from .sampler import get_sampler

        sampler = get_sampler()
    lines: List[str] = []
    for family in span_metrics.families():
        _render_histogram_family(family, lines)
    last = sampler.last()
    for k in sorted(last):
        n = _name("fugue_tpu_resource", k)
        lines.append(f"# HELP {n} resource sampler gauge {k}")
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_num(float(last[k]))}")
    # sampler meta is emitted here unconditionally and ONLY here — the
    # engine-stats flatten below skips the "telemetry" group so these
    # names never appear twice on one page (a duplicate TYPE/sample makes
    # Prometheus reject the whole scrape)
    meta = sampler.as_dict()
    lines.append("# TYPE fugue_tpu_telemetry_samples gauge")
    lines.append(f"fugue_tpu_telemetry_samples {meta['samples']}")
    lines.append("# TYPE fugue_tpu_telemetry_running gauge")
    lines.append(f"fugue_tpu_telemetry_running {1 if meta['running'] else 0}")
    if engine is not None:
        flat: Dict[str, float] = {}
        jit_labels: Dict[str, float] = {}
        try:
            for group, vals in engine.stats().items():
                if group in ("latency", "telemetry"):
                    # latency: already exposed as real histograms above;
                    # telemetry: the sampler gauges + meta above are the
                    # single source for those names
                    continue
                if (
                    group == "jit_cache"
                    and isinstance(vals, dict)
                    and isinstance(vals.get("by_label"), dict)
                ):
                    # per-program entry counts go out as ONE labeled gauge
                    # family — flattening them would mint a new metric NAME
                    # per compiled program (segment fingerprints are
                    # content-addressed, so unbounded over a server's life)
                    jit_labels = {
                        str(k): float(v)
                        for k, v in vals["by_label"].items()
                        if isinstance(v, (int, float))
                    }
                    vals = {k: v for k, v in vals.items() if k != "by_label"}
                _flatten_numeric(vals, str(group), flat)
        except Exception:
            flat = {}
            jit_labels = {}
        for k in sorted(flat):
            n = _name("fugue_tpu", k)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {_num(flat[k])}")
        if jit_labels:
            n = "fugue_tpu_jit_cache_entries_by_label"
            lines.append(f"# TYPE {n} gauge")
            for k in sorted(jit_labels):
                lines.append(f"{n}{_labels({'label': k})} {_num(jit_labels[k])}")
    return "\n".join(lines) + "\n"


def validate_prometheus_text(text: str) -> Dict[str, Any]:
    """Assert ``text`` is scrapeable exposition; returns summary counts.

    Checks every sample line against the exposition grammar, label-pair
    syntax, that no metric name gets a second ``# TYPE`` line, that no
    (name, label-set) sample appears twice (either duplicate makes a real
    Prometheus scrape fail), and for each histogram series: cumulative
    buckets non-decreasing, a ``+Inf`` bucket present, and ``_count``
    equal to the ``+Inf`` bucket."""
    samples = 0
    names = set()
    typed: Dict[str, int] = {}  # name -> lineno of its TYPE line
    seen: Dict[Any, int] = {}  # (name, sorted labels) -> lineno
    # (base_name, labels-minus-le) -> {"buckets": [(le, v)], "count": v}
    hists: Dict[Any, Dict[str, Any]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                tname = parts[2]
                assert tname not in typed, (
                    f"line {lineno}: duplicate TYPE for {tname} "
                    f"(first at line {typed[tname]})"
                )
                typed[tname] = lineno
            continue
        m = _LINE_RE.match(line)
        assert m is not None, f"line {lineno} not valid exposition: {line!r}"
        name, labelstr, value = m.group(1), m.group(2) or "", m.group(3)
        labels: Dict[str, str] = {}
        if labelstr:
            body = labelstr[1:-1]
            matched = _LABEL_RE.findall(body)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            assert rebuilt == body, f"line {lineno} bad labels: {labelstr!r}"
            labels = dict(matched)
        ident = (name, tuple(sorted(labels.items())))
        assert ident not in seen, (
            f"line {lineno}: duplicate sample {name}{labelstr} "
            f"(first at line {seen[ident]})"
        )
        seen[ident] = lineno
        samples += 1
        names.add(name)
        if name.endswith("_bucket") and "le" in labels:
            base = name[: -len("_bucket")]
            key = (base, tuple(sorted((k, v) for k, v in labels.items() if k != "le")))
            h = hists.setdefault(key, {"buckets": [], "count": None})
            le = labels["le"]
            h["buckets"].append(
                (math.inf if le == "+Inf" else float(le), float(value))
            )
        elif name.endswith("_count"):
            base = name[: -len("_count")]
            key = (base, tuple(sorted(labels.items())))
            hists.setdefault(key, {"buckets": [], "count": None})["count"] = float(
                value
            )
    for (base, lbl), h in hists.items():
        if not h["buckets"]:
            continue
        bs = sorted(h["buckets"])
        assert bs[-1][0] == math.inf, f"{base}{dict(lbl)}: no +Inf bucket"
        vals = [v for _, v in bs]
        assert all(
            a <= b for a, b in zip(vals, vals[1:])
        ), f"{base}{dict(lbl)}: buckets not cumulative: {vals}"
        if h["count"] is not None:
            assert h["count"] == bs[-1][1], (
                f"{base}{dict(lbl)}: _count {h['count']} != +Inf {bs[-1][1]}"
            )
    n_hist = sum(1 for h in hists.values() if h["buckets"])
    assert samples > 0, "no samples in exposition"
    return {"samples": samples, "names": sorted(names), "histogram_series": n_hist}
