"""MetricsRegistry — one surface over every stats object an engine owns.

Before ISSUE 3 the engine exposed three disconnected ad-hoc stat objects
(``engine.pipeline_stats``, ``engine.jit_cache_stats``,
``engine.resilience_stats``) with inconsistent lifecycles (the first two
were per-engine cumulative with no reset; resilience had ``reset()`` but
nothing called it). The registry absorbs them behind one contract:

- every source exposes ``as_dict()`` and ``reset()``;
- ``engine.stats()`` → ``registry.as_dict()`` (all sources, one dict);
- ``engine.reset_stats()`` → ``registry.reset()`` (every source, one
  consistent reset);
- per-run deltas: ``before = registry.snapshot()`` … run …
  ``registry.delta(before)`` — what ``bench.py`` now records per case
  instead of cumulative values.

Sources register lazily (name → object or zero-arg provider) so engines
can register ``lambda: self.resilience_stats`` without forcing creation.
"""

import copy
import threading
from typing import Any, Callable, Dict, List, Union

__all__ = ["MetricsRegistry"]


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: Dict[str, Union[Any, Callable[[], Any]]] = {}

    def register(self, name: str, source: Any) -> None:
        """Register a stats source: any object with ``as_dict()`` and
        ``reset()``, or a zero-arg callable returning one (resolved at
        every read, so lazily-created sources work)."""
        with self._lock:
            self._sources[name] = source

    def family(self, name: str, bounds: Any = None, help: str = "") -> Any:
        """Create-or-get a labeled :class:`~fugue_tpu.obs.metrics.HistogramFamily`
        owned by this registry (registered as a source under ``name``, so
        it shows in ``as_dict()``/``stats()`` and resets with
        ``reset()``). The distribution-metric counterpart of
        ``register()`` for plain counters."""
        with self._lock:
            src = self._sources.get(name)
            if src is None:
                from .metrics import DEFAULT_LATENCY_BOUNDS, HistogramFamily

                src = HistogramFamily(
                    name,
                    bounds if bounds is not None else DEFAULT_LATENCY_BOUNDS,
                    help=help,
                )
                self._sources[name] = src
            return src

    def names(self) -> List[str]:
        with self._lock:
            return list(self._sources)

    def get(self, name: str) -> Any:
        with self._lock:
            src = self._sources[name]
        return src() if callable(src) else src

    def as_dict(self) -> Dict[str, Dict[str, Any]]:
        return {name: self.get(name).as_dict() for name in self.names()}

    def reset(self) -> None:
        for name in self.names():
            self.get(name).reset()

    # -- per-run snapshots ---------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Deep copy of the current values — take one before a run."""
        return copy.deepcopy(self.as_dict())

    def delta(self, before: Dict[str, Any]) -> Dict[str, Any]:
        """Numeric difference current − ``before`` (recursive over nested
        dicts; non-numeric leaves report their current value)."""
        return _delta(self.as_dict(), before)


def _delta(cur: Any, before: Any) -> Any:
    if isinstance(cur, dict):
        b = before if isinstance(before, dict) else {}
        return {k: _delta(v, b.get(k)) for k, v in cur.items()}
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return cur
    if isinstance(before, (int, float)) and not isinstance(before, bool):
        d = cur - before
        return round(d, 6) if isinstance(d, float) else d
    return cur
