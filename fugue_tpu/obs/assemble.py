"""Cluster trace assembler (ISSUE 18): merge per-process span spools into
ONE Perfetto-loadable Chrome trace.

Input: a spool directory written by :func:`~fugue_tpu.obs.spool.publish_spool`
(one file per remote process) plus, optionally, the local driver buffer.
Output: one trace file where

- every process gets its own **named track** ("fugue-tpu driver",
  "fugue-tpu worker <host>-<pid>", ...) under a **synthetic pid** — raw
  OS pids can collide across hosts, so track pids are remapped to a dense
  1..N ordering with the driver first;
- spans are **deduplicated by (process identity, span id)**: the driver's
  buffer may already hold worker spans ingested from done records, and
  those same spans appear in the worker's spool;
- each remote process's resource-sampler ring renders as counter tracks
  (``device_bytes``, ``host_rss_bytes``, ...) on that process's track —
  the ISSUE 18 small fix: before, only the local ring exported.

All span timestamps are ``perf_counter_ns`` — comparable across forked
processes of ONE host. Cross-host spools still merge into one file (ids
cannot collide — they are host+pid-prefixed), but their clocks are only
aligned per host.
"""

import json
import os
from typing import Any, Dict, List, Optional

from .export import to_chrome_trace, validate_chrome_trace
from .spool import read_spools
from .tracer import proc_ident

__all__ = ["assemble_trace"]


def assemble_trace(
    spool_dir: str,
    out_path: str,
    include_local: bool = True,
    local_records: Optional[List[Dict[str, Any]]] = None,
    local_counters: Optional[List[Any]] = None,
    trace_id: Optional[str] = None,
) -> Dict[str, Any]:
    """Merge every spool in ``spool_dir`` (plus the local tracer buffer
    unless ``include_local=False``) into one validated Chrome trace at
    ``out_path``. ``trace_id`` keeps only spans of that trace (counter
    tracks are kept regardless — resource curves have no trace identity).
    Returns the ``validate_chrome_trace`` summary extended with the
    per-process breakdown and the set of trace ids seen."""
    sources: List[Dict[str, Any]] = []
    if include_local:
        if local_records is None:
            from .tracer import get_tracer

            local_records = get_tracer().records()
        if local_counters is None:
            from .sampler import get_sampler

            local_counters = get_sampler().series()
        sources.append(
            {
                "proc": proc_ident(),
                "label": "driver",
                "spans": local_records,
                "counters": local_counters,
            }
        )
    local_proc = proc_ident() if include_local else None
    for doc in read_spools(spool_dir):
        if doc.get("proc") == local_proc:
            continue  # local buffer already included (and is fresher)
        sources.append(doc)

    # spans may appear in two sources (worker spool + driver ingest of the
    # done-record copy): first occurrence wins, keyed by process identity +
    # span id — exactly the pair validate_chrome_trace proves unique
    seen: set = set()
    merged: List[Dict[str, Any]] = []
    by_proc_spans: Dict[str, int] = {}
    traces: set = set()
    pid_of_proc: Dict[str, int] = {}

    def _proc_of(rec: Dict[str, Any], source_proc: str) -> str:
        return str(rec.get("proc") or rec.get("pid") or source_proc)

    ordered_procs: List[str] = []
    for src in sources:
        sproc = str(src.get("proc") or "unknown")
        for rec in src.get("spans", []):
            if not isinstance(rec, dict) or "id" not in rec:
                continue
            p = _proc_of(rec, sproc)
            key = (p, rec["id"])
            if key in seen:
                continue
            seen.add(key)
            if trace_id is not None and rec.get("trace") != trace_id:
                continue
            if p not in pid_of_proc:
                pid_of_proc[p] = len(pid_of_proc) + 1
                ordered_procs.append(p)
            merged.append(dict(rec, pid=pid_of_proc[p]))
            by_proc_spans[p] = by_proc_spans.get(p, 0) + 1
            if rec.get("trace"):
                traces.add(rec["trace"])

    counter_tracks: Dict[int, Any] = {}
    process_names: Dict[int, str] = {}
    for src in sources:
        sproc = str(src.get("proc") or "unknown")
        if sproc not in pid_of_proc:
            if not src.get("counters"):
                continue
            pid_of_proc[sproc] = len(pid_of_proc) + 1
            ordered_procs.append(sproc)
        spid = pid_of_proc[sproc]
        label = src.get("label") or "worker"
        process_names[spid] = (
            "fugue-tpu driver" if label == "driver" else f"fugue-tpu {label} {sproc}"
        )
        series = [(ts, vals) for ts, vals in src.get("counters", [])]
        if series:
            counter_tracks[spid] = series

    doc = to_chrome_trace(
        merged,
        counters=None,
        counter_tracks=counter_tracks,
        process_names=process_names,
    )
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out_path)

    summary = validate_chrome_trace(out_path)
    summary["path"] = out_path
    summary["processes"] = len(ordered_procs)
    summary["process_spans"] = {p: by_proc_spans.get(p, 0) for p in ordered_procs}
    summary["process_names"] = {
        p: process_names.get(pid_of_proc[p], "") for p in ordered_procs
    }
    summary["traces"] = sorted(traces)
    return summary
