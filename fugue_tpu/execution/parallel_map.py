"""Process-pool execution of per-partition UDFs, with supervised recovery.

The reference runs transformers concurrently across cluster workers (Spark
``mapInPandas`` over executors, ``fugue_spark/execution_engine.py:237-330``;
Dask ``map_partitions``, ``fugue_dask/execution_engine.py:93-183``). The
TPU-native equivalent for the HOST side of the map path is a fork-based
process pool over logical partitions: pandas UDFs hold the GIL, so threads
don't help, while ``fork`` gives every worker copy-on-write access to the
parent's already-materialized pandas frame — no input serialization at all.
Only the (usually much smaller) per-partition outputs cross back, as arrow
tables.

Partitions are split into more chunks than workers (dynamic balancing for
skewed group sizes), each chunk a contiguous partition range so global
partition numbering is preserved.

Dispatch is SUPERVISED (``fugue_tpu/resilience``): chunks go out via
``apply_async`` with a per-chunk deadline, the driver watches the pool's
worker processes, and recovery follows the graceful-degradation order
**parallel → retry → serial → raise**:

1. a dead worker (OOM-kill, segfault, injected SIGKILL) or an expired
   chunk deadline tears down the wave; finished chunk results are kept;
2. lost/failed chunks retry on a FRESH fork pool under the engine's
   ``fugue.tpu.retry.*`` policy;
3. chunks that exhaust retries (or fail deterministically — "poison"
   partitions) are quarantined to serial in-driver execution, which also
   yields clean tracebacks;
4. only if the serial path fails too does the map raise, with a
   per-partition failure report (``ParallelMapError``).

Every recovery step increments the engine's ``resilience_stats``.

Not engaged when:
- the platform has no ``fork`` (non-Linux/macOS spawn semantics),
- the transformer carries a worker→driver RPC callback (the in-process
  ``NativeRPCServer`` can't cross a process boundary; such transformers run
  serially, matching the reference's local engine),
- the frame is below ``fugue.tpu.map.parallel_min_rows`` (pool setup costs
  ~100ms — tiny frames are faster serial),
- everything fits one chunk (``len(chunks) <= 1``): a pool of one worker
  has no concurrency to offer, so the chunk runs serially in-driver.
"""

import multiprocessing as mp
import os
import threading
import time
import warnings
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from ..resilience import (
    NULL_INJECTOR,
    SITE_MAP_CHUNK,
    SITE_MAP_DISPATCH,
    ChunkTimeoutError,
    Deadline,
    FailureCategory,
    FaultInjector,
    ParallelMapError,
    ResilienceStats,
    RetryPolicy,
    WorkerLostError,
    classify_failure,
)

# set in the parent immediately before forking; children inherit the memory
# image, so the frame and the (arbitrary, unpicklable) UDF need no transport.
# the lock spans set-state → fork → drain: concurrent map calls (workflow
# concurrency > 1) must not clobber each other's state mid-fork
_FORK_STATE: dict = {}
_FORK_LOCK = threading.Lock()

# polling cadence of the supervision loop; cheap (ready()/exitcode checks)
_POLL_INTERVAL = 0.01


def fork_available() -> bool:
    try:
        return "fork" in mp.get_all_start_methods()
    except Exception:
        return False


def map_func_parallel_safe(map_func: Callable) -> bool:
    """True when the UDF can run in a forked worker.

    A transformer holding an in-process RPC callback must stay in the
    driver process: a forked child would invoke its own copy of the handler
    and the driver would never see the calls.
    """
    runner = getattr(map_func, "__self__", None)
    tf = getattr(runner, "transformer", None)
    if tf is None:
        return True
    return getattr(tf, "_callback", None) is None


def split_chunks(sizes: Sequence[int], n_chunks: int) -> List[Any]:
    """Split partition ids [0..len) into ≤n_chunks contiguous runs balanced
    by total row count (greedy quantile cuts over the cumulative sizes)."""
    n = len(sizes)
    if n == 0:
        return []
    n_chunks = max(1, min(n_chunks, n))
    cum = np.cumsum(np.asarray(sizes, dtype=np.int64))
    total = int(cum[-1])
    bounds = [0]
    for q in range(1, n_chunks):
        target = total * q // n_chunks
        pos = int(np.searchsorted(cum, target, side="left")) + 1
        if pos > bounds[-1] and pos < n:
            bounds.append(pos)
    bounds.append(n)
    return [range(a, b) for a, b in zip(bounds[:-1], bounds[1:]) if b > a]


def _exec_partition(
    no: int,
    pdf: pd.DataFrame,
    groups: List[Any],
    map_func: Callable,
    cursor: Any,
    schema: Any,
    output_schema: Any,
    wrap: Callable,
    to_tbl: Callable,
) -> pa.Table:
    """Run the UDF over one logical partition — shared by the forked worker
    body and the driver's serial/quarantine paths."""
    idx = groups[no]
    if isinstance(idx, slice):
        sub = pdf.iloc[idx].reset_index(drop=True)
    else:
        sub = pdf.take(idx).reset_index(drop=True)
    part = wrap(sub, schema)
    cursor.set(lambda p=part: p.peek_array(), no, 0)
    res = map_func(cursor, part)
    return to_tbl(res, output_schema)


def _run_chunk(part_ids: Any) -> Dict[str, Any]:
    """Worker body: run the inherited UDF over a contiguous partition range.

    Results serialize as arrow IPC streams — pyarrow tables cross process
    boundaries far cheaper than pickled pandas frames. The return payload
    also carries the worker's OBSERVABILITY delta across the fork
    boundary: per-chunk resilience counters and any trace spans recorded
    while the chunk ran (a forked child's in-memory increments are
    otherwise invisible to the driver). Failed/killed chunks can't ship a
    delta — by design the payload rides the success path only.
    """
    from ..obs import get_span_metrics, get_tracer

    st = _FORK_STATE
    injector: FaultInjector = st.get("injector", NULL_INJECTOR)
    tracer = get_tracer()
    mark = tracer.mark()
    # histogram counterpart of the span mark: snapshot the (fork-inherited,
    # copy-on-write) span-metric state so only THIS chunk's observations
    # ship home as a mergeable delta
    hist_mark = get_span_metrics().snapshot() if tracer.enabled else None
    counters: Dict[str, int] = {"map.worker_chunks": 1}
    rows_out = 0
    out: List[bytes] = []
    with tracer.span(
        "map.worker_chunk",
        cat="worker",
        parent=st.get("trace_parent"),
        worker_pid=os.getpid(),
        partitions=len(part_ids),
    ) as chunk_sp:
        # fault-injection site: a `kill` here SIGKILLs this worker
        # mid-chunk, exactly the OOM-killer scenario the supervisor must
        # recover from
        injector.fire(SITE_MAP_CHUNK)
        for no in part_ids:
            with tracer.span("map.partition", cat="worker", partition=no) as sp:
                tbl = _exec_partition(
                    no,
                    st["pdf"],
                    st["groups"],
                    st["map_func"],
                    st["cursor"],
                    st["schema"],
                    st["output_schema"],
                    st["wrap_df"],
                    st["to_arrow"],
                )
                sp.set(rows_out=tbl.num_rows)
            counters["map.worker_partitions"] = (
                counters.get("map.worker_partitions", 0) + 1
            )
            rows_out += tbl.num_rows
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, tbl.schema) as w:
                w.write_table(tbl)
            out.append(sink.getvalue().to_pybytes())
        chunk_sp.set(rows_out=rows_out)
    counters["map.worker_rows_out"] = rows_out
    payload: Dict[str, Any] = {
        "blobs": out,
        "counters": counters,
        "spans": tracer.take_since(mark),
    }
    if hist_mark is not None:
        payload["hist"] = get_span_metrics().delta_since(hist_mark)
    return payload


def _harvest_chunk(payload: Any, stats: ResilienceStats) -> List[pa.Table]:
    """Driver side of the fork-boundary protocol: merge the worker's
    counter delta into the driver registry, ingest its spans into the
    global tracer, merge its histogram delta into the span-metrics store
    (label-keyed, never pid-keyed — associative across any worker order),
    and decode the arrow blobs."""
    if isinstance(payload, dict):
        stats.merge(payload.get("counters", {}))
        spans = payload.get("spans")
        if spans:
            from ..obs import get_tracer

            get_tracer().ingest(spans)
        hist = payload.get("hist")
        if hist:
            from ..obs import get_span_metrics

            get_span_metrics().merge(hist)
        blobs = payload["blobs"]
    else:  # defensive: pre-ISSUE-3 plain-list payload
        blobs = payload
    return [_decode_blob(b) for b in blobs]


def _decode_blob(blob: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.BufferReader(blob)) as r:
        return r.read_all()


@contextmanager
def _quiet_fork_warnings():
    """children never touch JAX (host-only pandas UDFs by the format-hint
    gate). On the CPU backend the fork-vs-threads warning is noise; on an
    accelerator backend (libtpu holds runtime threads) keep the warning
    visible — forking there is riskier and worth the operator's attention.
    The filter spans the whole supervised phase because ``Pool`` forks
    again mid-wave when it respawns a dead worker."""
    import jax

    with warnings.catch_warnings():
        if jax.default_backend() == "cpu":
            warnings.filterwarnings(
                "ignore", message=".*fork.*", category=RuntimeWarning
            )
            warnings.filterwarnings(
                "ignore", message=".*fork.*", category=DeprecationWarning
            )
        yield


def _make_pool(n: int) -> Tuple[Any, List[Any]]:
    """Fork a pool of ``n`` workers; returns (pool, worker process snapshot).

    The snapshot keeps references to the ORIGINAL worker ``Process``
    objects: ``Pool`` silently respawns dead workers (mutating its internal
    list), but a respawn never resurrects the task the dead worker was
    running — the original objects' ``exitcode`` is the reliable death
    signal."""
    ctx = mp.get_context("fork")
    pool = ctx.Pool(n)
    return pool, list(getattr(pool, "_pool", []))


def run_partitions_forked(
    pdf: pd.DataFrame,
    schema: Any,
    groups: List[Any],
    map_func: Callable,
    cursor: Any,
    output_schema: Any,
    n_workers: int,
    wrap_df: Callable,
    to_arrow: Callable,
    chunk_timeout: float = 0.0,
    policy: Optional[RetryPolicy] = None,
    injector: Optional[FaultInjector] = None,
    stats: Optional[ResilienceStats] = None,
) -> List[pa.Table]:
    """Run ``map_func`` over every logical partition using a supervised fork
    pool.

    ``groups`` is a list of positional row selections (ndarray or slice),
    one per logical partition, in partition order. Returns the per-partition
    arrow tables in the same order. ``chunk_timeout`` bounds each chunk's
    wall clock (0 = unbounded); ``policy``/``injector``/``stats`` are the
    resilience plumbing (see module docstring) and default to fail-safe
    no-ops.
    """
    policy = policy or RetryPolicy()
    injector = injector or NULL_INJECTOR
    stats = stats or ResilienceStats()
    sizes = [
        (idx.stop - idx.start) if isinstance(idx, slice) else len(idx)
        for idx in groups
    ]
    chunks = split_chunks(sizes, n_workers * 4)

    def _serial(part_ids: Any) -> List[pa.Table]:
        return [
            _exec_partition(
                no, pdf, groups, map_func, cursor, schema, output_schema,
                wrap_df, to_arrow,
            )
            for no in part_ids
        ]

    from ..obs import get_tracer

    tracer = get_tracer()
    # a single chunk gains nothing from a one-worker pool — skip the ~100ms
    # fork/teardown entirely and run in-driver
    if len(chunks) <= 1:
        if not chunks:
            return []
        with tracer.span(
            "map.serial", cat="engine", partitions=len(groups)
        ):
            return _serial(chunks[0])

    with _FORK_LOCK, tracer.span(
        "map.parallel",
        cat="engine",
        chunks=len(chunks),
        workers=n_workers,
        partitions=len(groups),
    ):
        _FORK_STATE.clear()
        _FORK_STATE.update(
            pdf=pdf,
            groups=groups,
            map_func=map_func,
            cursor=cursor,
            schema=schema,
            output_schema=output_schema,
            wrap_df=wrap_df,
            to_arrow=to_arrow,
            injector=injector,
            # children inherit this by fork: worker spans parent onto the
            # driver's map.parallel span so the tree stays connected
            trace_parent=tracer.current_span_id(),
        )
        try:
            with _quiet_fork_warnings():
                results, quarantined, failures = _supervise(
                    chunks, n_workers, chunk_timeout, policy, injector, stats
                )
            # quarantine phase: poison/exhausted chunks degrade to serial
            # in-driver execution, partition by partition, so the failure
            # report pinpoints the exact offending partitions
            report: Dict[int, str] = {}
            for ci in quarantined:
                tables: List[pa.Table] = []
                for no in chunks[ci]:
                    try:
                        tables.append(_serial([no])[0])
                    except Exception as ex:
                        history = "; ".join(failures.get(ci, []))
                        report[no] = (
                            f"{type(ex).__name__}: {ex}"
                            + (f" (pool attempts: {history})" if history else "")
                        )
                results[ci] = tables
                if not any(no in report for no in chunks[ci]):
                    stats.inc("map.serial_fallbacks")
            if report:
                raise ParallelMapError(report)
        finally:
            _FORK_STATE.clear()
    tables_out: List[pa.Table] = []
    for ci in range(len(chunks)):
        tables_out.extend(results[ci])
    return tables_out


def _supervise(
    chunks: List[Any],
    n_workers: int,
    chunk_timeout: float,
    policy: RetryPolicy,
    injector: FaultInjector,
    stats: ResilienceStats,
) -> Tuple[Dict[int, List[pa.Table]], List[int], Dict[int, List[str]]]:
    """Supervised dispatch of ``chunks`` over fork pools.

    Returns ``(results, quarantined_chunk_ids, failure_history)`` where
    ``results`` maps chunk id → decoded per-partition tables for every
    chunk that succeeded in a pool.
    """
    results: Dict[int, List[pa.Table]] = {}
    quarantined: List[int] = []
    failures: Dict[int, List[str]] = {}
    attempts: Dict[int, int] = {ci: 0 for ci in range(len(chunks))}
    pending: deque = deque(range(len(chunks)))

    def fail(ci: int, ex: BaseException) -> None:
        cat = classify_failure(ex)
        if cat is FailureCategory.FATAL:
            raise ex
        attempts[ci] += 1
        failures.setdefault(ci, []).append(
            f"attempt {attempts[ci]} [{cat.value}] {type(ex).__name__}: {ex}"
        )
        if policy.should_retry(cat, attempts[ci]):
            stats.inc("map.chunk_retries")
            pending.append(ci)
        else:
            stats.inc("map.quarantined_chunks")
            stats.inc("map.quarantined_partitions", len(chunks[ci]))
            quarantined.append(ci)

    # hard backstop against pathological requeue loops (e.g. a deadline
    # that keeps evicting collateral chunks): once crossed, everything
    # still pending degrades to the serial quarantine path
    max_waves = (policy.max_attempts + 1) * len(chunks) + 4
    wave = 0
    while pending:
        wave += 1
        if wave > max_waves:
            for ci in pending:
                stats.inc("map.quarantined_chunks")
                stats.inc("map.quarantined_partitions", len(chunks[ci]))
                quarantined.append(ci)
            pending.clear()
            break
        if wave > 1:
            stats.inc("map.pool_rebuilds")
        pool, procs = _make_pool(min(n_workers, len(pending)))
        # in-flight cap == pool size: every dispatched chunk starts on an
        # idle worker immediately, so its deadline measures real run time
        capacity = min(n_workers, len(pending))
        inflight: Dict[int, Tuple[Any, Deadline]] = {}
        try:
            rebuild = False
            while (pending or inflight) and not rebuild:
                while pending and len(inflight) < capacity:
                    ci = pending.popleft()
                    try:
                        # driver-side injection site (synthetic dispatch
                        # errors); `kill` is driver-safe (degrades to raise)
                        injector.fire(SITE_MAP_DISPATCH)
                    except Exception as ex:
                        fail(ci, ex)
                        continue
                    inflight[ci] = (
                        pool.apply_async(_run_chunk, (chunks[ci],)),
                        Deadline.after(chunk_timeout),
                    )
                progressed = False
                for ci in list(inflight):
                    ar, dl = inflight[ci]
                    if ar.ready():
                        del inflight[ci]
                        progressed = True
                        try:
                            results[ci] = _harvest_chunk(ar.get(), stats)
                            stats.inc("map.chunks_ok")
                        except Exception as ex:
                            fail(ci, ex)
                    elif dl.expired:
                        # a pool can't cancel one task — tear down the wave;
                        # only the expired chunk is charged an attempt,
                        # collateral in-flight chunks requeue for free
                        stats.inc("map.deadline_expiries")
                        del inflight[ci]
                        fail(
                            ci,
                            ChunkTimeoutError(
                                f"chunk exceeded {chunk_timeout}s deadline"
                            ),
                        )
                        pending.extend(inflight.keys())
                        inflight.clear()
                        rebuild = True
                        break
                if rebuild:
                    break
                dead = [p for p in procs if p.exitcode is not None]
                if dead:
                    # harvest whatever completed, then charge the chunks
                    # whose results can never arrive (the pool respawns
                    # workers but NOT their lost tasks)
                    stats.inc("map.worker_lost", len(dead))
                    for ci in list(inflight):
                        ar, _ = inflight.pop(ci)
                        if ar.ready():
                            try:
                                results[ci] = _harvest_chunk(ar.get(), stats)
                                stats.inc("map.chunks_ok")
                            except Exception as ex:
                                fail(ci, ex)
                        else:
                            fail(
                                ci,
                                WorkerLostError(
                                    "pool worker died mid-chunk (exitcodes: "
                                    f"{[p.exitcode for p in dead]})"
                                ),
                            )
                    rebuild = True
                    break
                if not progressed:
                    time.sleep(_POLL_INTERVAL)
        finally:
            pool.terminate()
            pool.join()
        if pending and wave < max_waves:
            # backoff before re-forking; seed by wave so concurrent maps
            # don't thunder in lockstep
            time.sleep(min(policy.delay(wave, seed=id(chunks)), 1.0))
    return results, quarantined, failures
