"""Process-pool execution of per-partition UDFs.

The reference runs transformers concurrently across cluster workers (Spark
``mapInPandas`` over executors, ``fugue_spark/execution_engine.py:237-330``;
Dask ``map_partitions``, ``fugue_dask/execution_engine.py:93-183``). The
TPU-native equivalent for the HOST side of the map path is a fork-based
process pool over logical partitions: pandas UDFs hold the GIL, so threads
don't help, while ``fork`` gives every worker copy-on-write access to the
parent's already-materialized pandas frame — no input serialization at all.
Only the (usually much smaller) per-partition outputs cross back, as arrow
tables.

Partitions are split into more chunks than workers (dynamic balancing for
skewed group sizes), each chunk a contiguous partition range so global
partition numbering is preserved.

Not engaged when:
- the platform has no ``fork`` (non-Linux/macOS spawn semantics),
- the transformer carries a worker→driver RPC callback (the in-process
  ``NativeRPCServer`` can't cross a process boundary; such transformers run
  serially, matching the reference's local engine),
- the frame is below ``fugue.tpu.map.parallel_min_rows`` (pool setup costs
  ~100ms — tiny frames are faster serial).
"""

import multiprocessing as mp
import threading
import warnings
from typing import Any, Callable, List, Optional, Sequence

import numpy as np
import pandas as pd
import pyarrow as pa

# set in the parent immediately before forking; children inherit the memory
# image, so the frame and the (arbitrary, unpicklable) UDF need no transport.
# the lock spans set-state → fork → drain: concurrent map calls (workflow
# concurrency > 1) must not clobber each other's state mid-fork
_FORK_STATE: dict = {}
_FORK_LOCK = threading.Lock()


def fork_available() -> bool:
    try:
        return "fork" in mp.get_all_start_methods()
    except Exception:
        return False


def map_func_parallel_safe(map_func: Callable) -> bool:
    """True when the UDF can run in a forked worker.

    A transformer holding an in-process RPC callback must stay in the
    driver process: a forked child would invoke its own copy of the handler
    and the driver would never see the calls.
    """
    runner = getattr(map_func, "__self__", None)
    tf = getattr(runner, "transformer", None)
    if tf is None:
        return True
    return getattr(tf, "_callback", None) is None


def split_chunks(sizes: Sequence[int], n_chunks: int) -> List[Any]:
    """Split partition ids [0..len) into ≤n_chunks contiguous runs balanced
    by total row count (greedy quantile cuts over the cumulative sizes)."""
    n = len(sizes)
    if n == 0:
        return []
    n_chunks = max(1, min(n_chunks, n))
    cum = np.cumsum(np.asarray(sizes, dtype=np.int64))
    total = int(cum[-1])
    bounds = [0]
    for q in range(1, n_chunks):
        target = total * q // n_chunks
        pos = int(np.searchsorted(cum, target, side="left")) + 1
        if pos > bounds[-1] and pos < n:
            bounds.append(pos)
    bounds.append(n)
    return [range(a, b) for a, b in zip(bounds[:-1], bounds[1:]) if b > a]


def _run_chunk(part_ids: Any) -> List[bytes]:
    """Worker body: run the inherited UDF over a contiguous partition range.

    Results serialize as arrow IPC streams — pyarrow tables cross process
    boundaries far cheaper than pickled pandas frames.
    """
    st = _FORK_STATE
    pdf: pd.DataFrame = st["pdf"]
    groups: List[Any] = st["groups"]
    map_func: Callable = st["map_func"]
    cursor = st["cursor"]
    schema = st["schema"]
    output_schema = st["output_schema"]
    wrap = st["wrap_df"]
    to_tbl = st["to_arrow"]
    out: List[bytes] = []
    for no in part_ids:
        idx = groups[no]
        if isinstance(idx, slice):
            sub = pdf.iloc[idx].reset_index(drop=True)
        else:
            sub = pdf.take(idx).reset_index(drop=True)
        part = wrap(sub, schema)
        cursor.set(lambda p=part: p.peek_array(), no, 0)
        res = map_func(cursor, part)
        tbl = to_tbl(res, output_schema)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, tbl.schema) as w:
            w.write_table(tbl)
        out.append(sink.getvalue().to_pybytes())
    return out


def run_partitions_forked(
    pdf: pd.DataFrame,
    schema: Any,
    groups: List[Any],
    map_func: Callable,
    cursor: Any,
    output_schema: Any,
    n_workers: int,
    wrap_df: Callable,
    to_arrow: Callable,
) -> List[pa.Table]:
    """Run ``map_func`` over every logical partition using a fork pool.

    ``groups`` is a list of positional row selections (ndarray or slice),
    one per logical partition, in partition order. Returns the per-partition
    arrow tables in the same order.
    """
    sizes = [
        (idx.stop - idx.start) if isinstance(idx, slice) else len(idx)
        for idx in groups
    ]
    chunks = split_chunks(sizes, n_workers * 4)
    with _FORK_LOCK:
        _FORK_STATE.clear()
        _FORK_STATE.update(
            pdf=pdf,
            groups=groups,
            map_func=map_func,
            cursor=cursor,
            schema=schema,
            output_schema=output_schema,
            wrap_df=wrap_df,
            to_arrow=to_arrow,
        )
        try:
            import jax

            ctx = mp.get_context("fork")
            with warnings.catch_warnings():
                # children never touch JAX (host-only pandas UDFs by the
                # format-hint gate). On the CPU backend the fork-vs-threads
                # warning is noise; on an accelerator backend (libtpu holds
                # runtime threads) keep the warning visible — forking there
                # is riskier and worth the operator's attention.
                if jax.default_backend() == "cpu":
                    warnings.filterwarnings(
                        "ignore", message=".*fork.*", category=RuntimeWarning
                    )
                    warnings.filterwarnings(
                        "ignore", message=".*fork.*", category=DeprecationWarning
                    )
                with ctx.Pool(min(n_workers, len(chunks))) as pool:
                    chunk_results = pool.map(_run_chunk, chunks, chunksize=1)
        finally:
            _FORK_STATE.clear()
    tables: List[pa.Table] = []
    for blobs in chunk_results:
        for blob in blobs:
            with pa.ipc.open_stream(pa.BufferReader(blob)) as r:
                tables.append(r.read_all())
    return tables
