"""NativeExecutionEngine — single-process pandas engine, the correctness oracle.

Parity with the reference (`fugue/execution/native_execution_engine.py:172`):
``PandasMapEngine`` does sort + groupby-apply per logical partition
(reference ``:81-169``); all relational ops run on pandas with SQL NULL
semantics (null keys never match in joins). The derived
select/filter/assign/aggregate come from the base class's column-IR path.
"""

import logging
from typing import Any, Callable, List, Optional, Union

import numpy as np
import pandas as pd

from .._utils.io import load_df as _io_load_df
from .._utils.io import save_df as _io_save_df
from ..collections.partition import (
    EMPTY_PARTITION_SPEC,
    PartitionCursor,
    PartitionSpec,
    parse_presort_exp,
)
from .._utils.assertion import assert_or_throw
from ..dataframe import (
    ArrowDataFrame,
    DataFrame,
    DataFrames,
    LocalBoundedDataFrame,
    LocalDataFrame,
    LocalDataFrameIterableDataFrame,
    PandasDataFrame,
)
from ..dataframe.api import as_fugue_df
from ..dataframe.utils import get_join_schemas, parse_join_type
from ..exceptions import FugueInvalidOperation
from ..schema import Schema
from .execution_engine import ExecutionEngine, MapEngine, SQLEngine


class PandasMapEngine(MapEngine):
    """Sort + groupby-apply map engine (reference ``:81-169``) with a
    fork-pool parallel path over logical partitions.

    ``parallelism_engine`` supplies CONCURRENCY for partition-number
    expressions AND sizes the process pool — distributed engines delegating
    their general map path here pass themselves so both reflect the real
    mesh (the reference's cluster engines run transformers concurrently
    across workers; see ``parallel_map``).
    """

    def __init__(self, execution_engine: Any, parallelism_engine: Any = None):
        super().__init__(execution_engine)
        self._parallelism_engine = parallelism_engine or execution_engine

    def _pool_workers(self, map_func: Callable, n_rows: int, n_parts: int) -> int:
        """Process-pool size for this map call; ≤1 = run serial."""
        from ..constants import (
            FUGUE_TPU_CONF_MAP_PARALLELISM,
            FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS,
        )
        from .parallel_map import fork_available, map_func_parallel_safe

        conf = self.execution_engine.conf
        workers = int(conf.get(FUGUE_TPU_CONF_MAP_PARALLELISM, -1))
        if workers < 0:
            # auto: the pool runs HOST-side pandas — cap the mesh-derived
            # parallelism by the actual host core count (a 1-core host with
            # an 8-device virtual mesh gains nothing from 8 forked workers)
            import os

            workers = min(
                int(self._parallelism_engine.get_current_parallelism()),
                os.cpu_count() or 1,
            )
        min_rows = int(conf.get(FUGUE_TPU_CONF_MAP_PARALLEL_MIN_ROWS, 100_000))
        if (
            workers <= 1
            or n_parts <= 1
            or n_rows < min_rows
            or not fork_available()
            or not map_func_parallel_safe(map_func)
        ):
            return 1
        return workers

    @property
    def is_distributed(self) -> bool:
        return False

    @property
    def map_handles_repartition(self) -> bool:
        """Logical grouping happens inside map_dataframe — no physical
        exchange needed before a map (see RunTransformer)."""
        return True

    @property
    def execution_engine_constraint(self) -> type:
        return NativeExecutionEngine

    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        output_schema = (
            output_schema if isinstance(output_schema, Schema) else Schema(output_schema)
        )
        input_df = self.to_df(df).as_local_bounded()
        if input_df.empty:
            return PandasDataFrame(None, output_schema)
        cursor = partition_spec.get_cursor(input_df.schema, 0)
        if on_init is not None:
            on_init(0, input_df)
        keys = partition_spec.partition_by
        pdf = input_df.as_pandas()
        sorts = partition_spec.get_sorts(input_df.schema, with_partition_keys=len(keys) > 0)
        if len(sorts) > 0:
            pdf = pdf.sort_values(
                list(sorts.keys()),
                ascending=list(sorts.values()),
                na_position="first",
            ).reset_index(drop=True)
        schema = input_df.schema
        if len(keys) == 0:
            num = partition_spec.get_num_partitions(
                ROWCOUNT=lambda: len(pdf),
                CONCURRENCY=self._parallelism_engine.get_current_parallelism,
            )
            if num <= 1:
                part = PandasDataFrame(pdf, schema, pandas_df_wrapper=True)
                cursor.set(lambda: part.peek_array(), 0, 0)
                out = map_func(cursor, part)
                return _to_output(out, output_schema)
            # no keys but an explicit partition count (e.g. per_row =
            # num:ROWCOUNT): split into even contiguous chunks (empty input
            # returned above, so every chunk is non-empty)
            n_chunks = min(num, len(pdf))
            bounds = np.linspace(0, len(pdf), n_chunks + 1).astype(np.int64)
            groups: List[Any] = [
                slice(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
            ]
            workers = self._pool_workers(map_func, len(pdf), len(groups))
            if workers > 1:
                return self._run_forked(
                    pdf, schema, groups, map_func, cursor, output_schema, workers
                )
            results: List[LocalDataFrame] = []
            for no, sl in enumerate(groups):
                sub = pdf.iloc[sl].reset_index(drop=True)
                part = PandasDataFrame(sub, schema, pandas_df_wrapper=True)
                cursor.set(lambda p=part: p.peek_array(), no, 0)
                results.append(map_func(cursor, part).as_local_bounded())
            return _to_output(
                LocalDataFrameIterableDataFrame(iter(results), output_schema),
                output_schema,
            )
        # ONE global gather into group-clustered order, then each logical
        # partition is a contiguous zero-copy slice — the per-group
        # ``take(idx)`` row copies (one gather per partition) collapse into
        # a single reorder per map call
        gid = pdf.groupby(keys, dropna=False, sort=False).ngroup().to_numpy()
        if len(gid) > 0 and gid.min() < 0:  # defensive: shouldn't happen w/ dropna=False
            gid = np.where(gid < 0, gid.max() + 1, gid)
        counts = np.bincount(gid, minlength=gid.max() + 1 if len(gid) else 0)
        counts = counts[counts > 0]
        if len(counts) == 0:
            return PandasDataFrame(None, output_schema)
        if len(counts) == len(gid) or (np.diff(gid) >= 0).all():
            # already clustered (sorted input, or all-singleton groups in
            # appearance order == input order): skip the reorder entirely
            sorted_pdf = pdf
        else:
            order = np.argsort(gid, kind="stable")
            sorted_pdf = pdf.take(order).reset_index(drop=True)
        bounds = np.concatenate([[0], np.cumsum(counts)])
        groups: List[Any] = [
            slice(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
        ]
        workers = self._pool_workers(map_func, len(sorted_pdf), len(groups))
        if workers > 1:
            return self._run_forked(
                sorted_pdf, schema, groups, map_func, cursor, output_schema, workers
            )
        results: List[LocalDataFrame] = []
        for no, sl in enumerate(groups):
            part = PandasDataFrame(
                sorted_pdf.iloc[sl].reset_index(drop=True),
                schema,
                pandas_df_wrapper=True,
            )
            cursor.set(lambda p=part: p.peek_array(), no, 0)
            results.append(map_func(cursor, part).as_local_bounded())
        return _to_output(
            LocalDataFrameIterableDataFrame(iter(results), output_schema), output_schema
        )

    def _run_forked(
        self,
        pdf: pd.DataFrame,
        schema: Schema,
        groups: List[Any],
        map_func: Callable,
        cursor: PartitionCursor,
        output_schema: Schema,
        workers: int,
    ) -> DataFrame:
        from ..constants import FUGUE_TPU_CONF_MAP_CHUNK_TIMEOUT
        from ..resilience import FaultInjector, RetryPolicy
        from .parallel_map import run_partitions_forked

        engine = self.execution_engine
        tables = run_partitions_forked(
            pdf,
            schema,
            groups,
            map_func,
            cursor,
            output_schema,
            workers,
            wrap_df=_wrap_pandas_part,
            to_arrow=_result_to_arrow,
            chunk_timeout=float(
                engine.conf.get(FUGUE_TPU_CONF_MAP_CHUNK_TIMEOUT, 0.0)
            ),
            policy=RetryPolicy.from_conf(engine.conf),
            # fresh injector per map call: fault budgets ("kill one worker")
            # are per-map, not per-process
            injector=FaultInjector.from_conf(engine.conf),
            stats=engine.resilience_stats,
        )
        tables = [t for t in tables if t.num_rows > 0]
        if len(tables) == 0:
            return PandasDataFrame(None, output_schema)
        import pyarrow as pa

        target = output_schema.pa_schema
        tables = [t if t.schema == target else t.cast(target) for t in tables]
        return ArrowDataFrame(pa.concat_tables(tables), output_schema)


def _wrap_pandas_part(sub: pd.DataFrame, schema: Schema) -> PandasDataFrame:
    return PandasDataFrame(sub, schema, pandas_df_wrapper=True)


def _result_to_arrow(res: DataFrame, output_schema: Schema) -> Any:
    local = _to_output(res, output_schema)
    return local.as_arrow()


def _to_output(out: DataFrame, output_schema: Schema) -> LocalBoundedDataFrame:
    res = out.as_local_bounded()
    assert_or_throw(
        res.schema == output_schema,
        lambda: FugueInvalidOperation(
            f"map output schema {res.schema} != declared {output_schema}"
        ),
    )
    return res


class _PlaceholderSQLEngine(SQLEngine):
    """Delegates lazily to the in-tree SQL layer (no qpd/duckdb here); the
    indirection avoids an import cycle at module load."""

    @property
    def is_distributed(self) -> bool:
        return False

    def _local(self) -> SQLEngine:
        try:
            from ..sql.local_sql import LocalSQLEngine
        except ImportError as e:  # SQL layer not built yet
            raise NotImplementedError("in-tree SQL engine not available") from e
        return LocalSQLEngine(self.execution_engine)

    def select(self, dfs: DataFrames, statement: Any) -> DataFrame:
        return self._local().select(dfs, statement)

    def table_exists(self, table: str) -> bool:
        return self._local().table_exists(table)

    def save_table(self, df: DataFrame, table: str, **kwargs: Any) -> None:
        self._local().save_table(df, table, **kwargs)

    def load_table(self, table: str, **kwargs: Any) -> DataFrame:
        return self._local().load_table(table, **kwargs)


class NativeExecutionEngine(ExecutionEngine):
    def __init__(self, conf: Any = None):
        super().__init__(conf)

    @property
    def is_distributed(self) -> bool:
        return False

    @property
    def log(self) -> logging.Logger:
        return logging.getLogger("NativeExecutionEngine")

    def create_default_map_engine(self) -> MapEngine:
        return PandasMapEngine(self)

    def create_default_sql_engine(self) -> SQLEngine:
        return _PlaceholderSQLEngine(self)

    def get_current_parallelism(self) -> int:
        return 1

    def to_df(self, df: Any, schema: Any = None) -> LocalBoundedDataFrame:
        if isinstance(df, DataFrame):
            res = df.as_local_bounded()
            if schema is not None and res.schema != Schema(schema):
                res = ArrowDataFrame(res.as_arrow(), Schema(schema))
            if df.has_metadata:
                res.reset_metadata(df.metadata)
            return res
        if isinstance(df, (list, tuple)) or (
            hasattr(df, "__iter__") and not hasattr(df, "columns") and not hasattr(df, "schema")
        ):
            from ..dataframe import ArrayDataFrame

            return ArrayDataFrame(df, schema)
        fdf = as_fugue_df(df, schema=schema) if schema is not None else as_fugue_df(df)
        return fdf.as_local_bounded()

    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        # single-process engine: logical partitioning happens in map_dataframe
        return df

    def broadcast(self, df: DataFrame) -> DataFrame:
        return df

    def persist(self, df: DataFrame, lazy: bool = False, **kwargs: Any) -> DataFrame:
        res = self.to_df(df)
        if df.has_metadata:
            res.reset_metadata(df.metadata)
        return res

    def join(
        self,
        df1: DataFrame,
        df2: DataFrame,
        how: str,
        on: Optional[List[str]] = None,
    ) -> DataFrame:
        how = parse_join_type(how)
        key_schema, output_schema = get_join_schemas(df1, df2, how=how, on=on)
        keys = key_schema.names
        d1 = self.to_df(df1).as_pandas()
        d2 = self.to_df(df2).as_pandas()
        if how == "cross":
            res = d1.merge(d2, how="cross")
            return PandasDataFrame(res, output_schema)
        d1nn = d1.dropna(subset=keys)
        d2nn = d2.dropna(subset=keys)
        if how == "inner":
            res = d1nn.merge(d2nn, how="inner", on=keys)
        elif how == "left_outer":
            res = d1.merge(d2nn, how="left", on=keys)
        elif how == "right_outer":
            res = d1nn.merge(d2, how="right", on=keys)
        elif how == "full_outer":
            matched = d1nn.merge(d2nn, how="outer", on=keys)
            null1 = d1[d1[keys].isna().any(axis=1)]
            null2 = d2[d2[keys].isna().any(axis=1)]
            parts = [matched]
            if len(null1) > 0:
                parts.append(null1)
            if len(null2) > 0:
                parts.append(null2)
            res = pd.concat(parts, ignore_index=True) if len(parts) > 1 else matched
        elif how == "left_semi":
            res = d1.merge(
                d2nn[keys].drop_duplicates(), how="inner", on=keys
            )
        elif how == "left_anti":
            merged = d1.merge(
                d2nn[keys].drop_duplicates(),
                how="left",
                on=keys,
                indicator=True,
            )
            res = merged[merged["_merge"] == "left_only"].drop(columns=["_merge"])
        else:  # pragma: no cover
            raise NotImplementedError(how)
        res = res.reindex(columns=output_schema.names)
        return PandasDataFrame(res.reset_index(drop=True), output_schema)

    def union(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        assert_or_throw(
            df1.schema == df2.schema,
            lambda: FugueInvalidOperation(f"schema mismatch {df1.schema} vs {df2.schema}"),
        )
        d1 = self.to_df(df1).as_pandas()
        d2 = self.to_df(df2).as_pandas()
        res = pd.concat([d1, d2], ignore_index=True)
        if distinct:
            res = _drop_duplicates(res)
        return PandasDataFrame(res, df1.schema)

    def subtract(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        assert_or_throw(
            df1.schema == df2.schema,
            lambda: FugueInvalidOperation(f"schema mismatch {df1.schema} vs {df2.schema}"),
        )
        assert_or_throw(
            distinct, NotImplementedError("EXCEPT ALL is not supported")
        )
        d1 = _drop_duplicates(self.to_df(df1).as_pandas())
        d2 = self.to_df(df2).as_pandas()
        merged = d1.merge(d2.drop_duplicates(), how="left", on=list(d1.columns), indicator=True)
        res = merged[merged["_merge"] == "left_only"].drop(columns=["_merge"])
        return PandasDataFrame(res.reset_index(drop=True), df1.schema)

    def intersect(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        assert_or_throw(
            df1.schema == df2.schema,
            lambda: FugueInvalidOperation(f"schema mismatch {df1.schema} vs {df2.schema}"),
        )
        assert_or_throw(
            distinct, NotImplementedError("INTERSECT ALL is not supported")
        )
        d1 = _drop_duplicates(self.to_df(df1).as_pandas())
        d2 = _drop_duplicates(self.to_df(df2).as_pandas())
        res = d1.merge(d2, how="inner", on=list(d1.columns))
        return PandasDataFrame(res.reset_index(drop=True), df1.schema)

    def distinct(self, df: DataFrame) -> DataFrame:
        res = _drop_duplicates(self.to_df(df).as_pandas())
        return PandasDataFrame(res, df.schema)

    def dropna(
        self,
        df: DataFrame,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> DataFrame:
        kw: dict = dict(subset=subset)
        if thresh is not None:
            kw["thresh"] = thresh
        else:
            kw["how"] = how
        res = self.to_df(df).as_pandas().dropna(**kw)
        return PandasDataFrame(res.reset_index(drop=True), df.schema)

    def fillna(self, df: DataFrame, value: Any, subset: Optional[List[str]] = None) -> DataFrame:
        assert_or_throw(
            (not isinstance(value, list)) and (value is not None),
            FugueInvalidOperation("fillna value can't be None or a list"),
        )
        if isinstance(value, dict):
            assert_or_throw(
                all(v is not None for v in value.values()) and len(value) > 0,
                FugueInvalidOperation("fillna dict can't contain None values"),
            )
            mapping = value
        else:
            subset = subset or df.schema.names
            mapping = {c: value for c in subset}
        pdf = self.to_df(df).as_pandas().fillna(mapping)
        return PandasDataFrame(pdf, df.schema)

    def sample(
        self,
        df: DataFrame,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> DataFrame:
        assert_or_throw(
            (n is None and frac is not None) or (n is not None and frac is None),
            FugueInvalidOperation("one and only one of n and frac should be set"),
        )
        res = self.to_df(df).as_pandas().sample(
            n=n, frac=frac, replace=replace, random_state=seed
        )
        return PandasDataFrame(res.reset_index(drop=True), df.schema)

    def take(
        self,
        df: DataFrame,
        n: int,
        presort: str,
        na_position: str = "last",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        assert_or_throw(
            isinstance(n, int),
            FugueInvalidOperation("n needs to be an integer"),
        )
        spec = partition_spec or EMPTY_PARTITION_SPEC
        pdf = self.to_df(df).as_pandas()
        sorts = parse_presort_exp(presort) if presort else spec.presort
        names = list(sorts.keys())
        asc = list(sorts.values())
        if len(spec.partition_by) == 0:
            if len(names) > 0:
                pdf = pdf.sort_values(names, ascending=asc, na_position=na_position)
            res = pdf.head(n)
        else:
            if len(names) > 0:
                pdf = pdf.sort_values(names, ascending=asc, na_position=na_position)
            res = pdf.groupby(spec.partition_by, dropna=False, sort=False).head(n)
        return PandasDataFrame(res.reset_index(drop=True), df.schema)

    def load_df(
        self,
        path: Union[str, List[str]],
        format_hint: Any = None,
        columns: Any = None,
        **kwargs: Any,
    ) -> DataFrame:
        tbl, schema = _io_load_df(path, format_hint=format_hint, columns=columns, **kwargs)
        return ArrowDataFrame(tbl)

    def save_df(
        self,
        df: DataFrame,
        path: str,
        format_hint: Any = None,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        force_single: bool = False,
        **kwargs: Any,
    ) -> DataFrame:
        partition_cols = (
            list(partition_spec.partition_by)
            if partition_spec is not None and len(partition_spec.partition_by) > 0
            else None
        )
        _io_save_df(
            self.to_df(df).as_arrow(),
            path,
            format_hint=format_hint,
            mode=mode,
            partition_cols=partition_cols,
            **kwargs,
        )
        return df


def _drop_duplicates(pdf: pd.DataFrame) -> pd.DataFrame:
    """drop_duplicates that treats NaN == NaN (SQL DISTINCT semantics)."""
    try:
        return pdf.drop_duplicates(ignore_index=True)
    except TypeError:  # unhashable columns (lists/dicts)
        key = pdf.apply(lambda r: repr(list(r)), axis=1)
        return pdf[~key.duplicated()].reset_index(drop=True)
