from .execution_engine import (
    EngineFacet,
    ExecutionEngine,
    FugueEngineBase,
    MapEngine,
    SQLEngine,
)
from .factory import (
    infer_execution_engine,
    make_execution_engine,
    make_sql_engine,
    parse_execution_engine,
    register_default_execution_engine,
    register_default_sql_engine,
    register_execution_engine,
    register_sql_engine,
    try_get_context_execution_engine,
)
from .native_execution_engine import NativeExecutionEngine, PandasMapEngine

# engine-injection annotated param: functions may take ExecutionEngine (code e)
from ..dataframe.function_wrapper import AnnotatedParam, fugue_annotated_param


@fugue_annotated_param(
    code="e",
    matcher=lambda a: isinstance(a, type) and issubclass(a, (ExecutionEngine, FugueEngineBase)),
)
class ExecutionEngineParam(AnnotatedParam):
    pass


register_execution_engine("native", lambda conf, **kwargs: NativeExecutionEngine(conf))
register_execution_engine("pandas", lambda conf, **kwargs: NativeExecutionEngine(conf))


def _lazy_jax_engine(conf: object, **kwargs: object) -> "ExecutionEngine":
    from ..jax import JaxExecutionEngine  # registers the full backend

    return JaxExecutionEngine(conf, **kwargs)


# lazy: importing fugue_tpu.jax pulls in jax itself, so defer to first use
register_execution_engine("jax", _lazy_jax_engine)
register_execution_engine("tpu", _lazy_jax_engine)


def _lazy_sqlite_engine(conf, **kwargs):
    from ..warehouse import SQLiteExecutionEngine  # registers the full backend

    return SQLiteExecutionEngine(conf, **kwargs)


register_execution_engine("sqlite", _lazy_sqlite_engine)


def _lazy_sqlite_jax_engine(conf, **kwargs):
    from ..warehouse import WarehouseJaxExecutionEngine

    return WarehouseJaxExecutionEngine(conf, **kwargs)


# the DuckDask-analog hybrid: warehouse SQL + jax-mesh maps in ONE engine
register_execution_engine("sqlite_jax", _lazy_sqlite_jax_engine)


def _lazy_sqlite_sql_engine(engine):
    from ..warehouse import WarehouseSQLEngine

    return WarehouseSQLEngine(engine)


register_sql_engine("sqlite", _lazy_sqlite_sql_engine)

__all__ = [
    "EngineFacet",
    "ExecutionEngine",
    "ExecutionEngineParam",
    "FugueEngineBase",
    "MapEngine",
    "SQLEngine",
    "NativeExecutionEngine",
    "PandasMapEngine",
    "infer_execution_engine",
    "make_execution_engine",
    "make_sql_engine",
    "parse_execution_engine",
    "register_default_execution_engine",
    "register_default_sql_engine",
    "register_execution_engine",
    "register_sql_engine",
    "try_get_context_execution_engine",
]
