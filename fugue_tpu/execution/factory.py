"""Engine factory and registry.

Parity with the reference (`fugue/execution/factory.py`):
``register_execution_engine``/``register_sql_engine`` by name or type,
``make_execution_engine`` with the documented resolution order
(explicit → context → global → infer_by → default, reference ``:258-276``),
and the ``parse_execution_engine`` / ``infer_execution_engine`` plugins.
"""

import inspect
from threading import RLock
from typing import Any, Callable, Dict, List, Optional, Tuple, Type, Union

from .._utils.assertion import assert_or_throw
from .._utils.params import ParamDict
from .._utils.registry import fugue_plugin
from ..exceptions import FuguePluginsRegistrationError
from .execution_engine import (
    _CONTEXT_ENGINE,
    _GLOBAL_ENGINE,
    ExecutionEngine,
    SQLEngine,
)

_LOCK = RLock()
_EXECUTION_ENGINE_REGISTRY: Dict[str, Callable] = {}
_EXECUTION_ENGINE_TYPE_REGISTRY: Dict[Type, Callable] = {}
_SQL_ENGINE_REGISTRY: Dict[str, Callable] = {}
_DEFAULT_EXECUTION_ENGINE: List[Optional[Callable]] = [None]
_DEFAULT_SQL_ENGINE: List[Optional[Callable]] = [None]


def register_execution_engine(
    name_or_type: Union[str, Type], func: Callable, on_dup: str = "overwrite"
) -> None:
    """Register an engine factory ``func(conf, **kwargs) -> ExecutionEngine``
    under a name (e.g. ``"native"``) or a type (engine inference by object)."""
    with _LOCK:
        if isinstance(name_or_type, str):
            if name_or_type in _EXECUTION_ENGINE_REGISTRY and on_dup == "throw":
                raise FuguePluginsRegistrationError(f"{name_or_type} already registered")
            if name_or_type in _EXECUTION_ENGINE_REGISTRY and on_dup == "ignore":
                return
            _EXECUTION_ENGINE_REGISTRY[name_or_type] = func
        else:
            _EXECUTION_ENGINE_TYPE_REGISTRY[name_or_type] = func


def register_default_execution_engine(func: Callable, on_dup: str = "overwrite") -> None:
    with _LOCK:
        if _DEFAULT_EXECUTION_ENGINE[0] is not None and on_dup == "throw":
            raise FuguePluginsRegistrationError("default engine already registered")
        if _DEFAULT_EXECUTION_ENGINE[0] is not None and on_dup == "ignore":
            return
        _DEFAULT_EXECUTION_ENGINE[0] = func


def register_sql_engine(name: str, func: Callable, on_dup: str = "overwrite") -> None:
    """Register ``func(execution_engine) -> SQLEngine`` under a name."""
    with _LOCK:
        if name in _SQL_ENGINE_REGISTRY and on_dup == "throw":
            raise FuguePluginsRegistrationError(f"{name} already registered")
        if name in _SQL_ENGINE_REGISTRY and on_dup == "ignore":
            return
        _SQL_ENGINE_REGISTRY[name] = func


def register_default_sql_engine(func: Callable, on_dup: str = "overwrite") -> None:
    with _LOCK:
        if _DEFAULT_SQL_ENGINE[0] is not None and on_dup == "throw":
            raise FuguePluginsRegistrationError("default sql engine already registered")
        if _DEFAULT_SQL_ENGINE[0] is not None and on_dup == "ignore":
            return
        _DEFAULT_SQL_ENGINE[0] = func


@fugue_plugin
def parse_execution_engine(engine: Any, conf: Any, **kwargs: Any) -> ExecutionEngine:
    """Plugin: convert an engine spec into an ExecutionEngine
    (reference ``factory.py:343``)."""
    if isinstance(engine, str):
        with _LOCK:
            if engine in _EXECUTION_ENGINE_REGISTRY:
                return _EXECUTION_ENGINE_REGISTRY[engine](conf, **kwargs)
        raise FuguePluginsRegistrationError(
            f"{engine!r} is not a registered execution engine"
        )
    if inspect.isclass(engine) and issubclass(engine, ExecutionEngine):
        return engine(conf, **kwargs)
    with _LOCK:
        for tp, func in _EXECUTION_ENGINE_TYPE_REGISTRY.items():
            if isinstance(engine, tp):
                return func(engine, conf, **kwargs)
    raise FuguePluginsRegistrationError(f"can't parse engine spec {engine!r}")


@fugue_plugin
def infer_execution_engine(objs: List[Any]) -> Any:
    """Plugin: infer an engine spec from input objects
    (reference ``factory.py:421``)."""
    return None


def try_get_context_execution_engine() -> Optional[ExecutionEngine]:
    e = _CONTEXT_ENGINE.get()
    if e is not None:
        return e
    return _GLOBAL_ENGINE[0]


def is_pandas_or(objs: List[Any], obj_type: Any) -> bool:
    """Whether all objs are local-ish or of obj_type (engine inference aid)."""
    import pandas as pd

    from ..dataframe.dataframe import LocalDataFrame

    return all(
        isinstance(o, (pd.DataFrame, LocalDataFrame, list, tuple)) or isinstance(o, obj_type)
        for o in objs
    )


def make_execution_engine(
    engine: Any = None,
    conf: Any = None,
    infer_by: Optional[List[Any]] = None,
    **kwargs: Any,
) -> ExecutionEngine:
    """Resolution order (reference docstring ``factory.py:258-276``):
    explicit → context engine → global engine → infer_by → registered default
    → NativeExecutionEngine."""
    sql_engine_spec: Any = None
    if isinstance(engine, tuple):
        engine, sql_engine_spec = engine
    result: Optional[ExecutionEngine] = None
    if engine is None:
        ctx = try_get_context_execution_engine()
        if ctx is not None:
            result = ctx
        elif infer_by is not None:
            inferred = infer_execution_engine(infer_by)
            if inferred is not None:
                result = parse_execution_engine(inferred, conf, **kwargs)
        if result is None:
            with _LOCK:
                default = _DEFAULT_EXECUTION_ENGINE[0]
            if default is not None:
                result = default(conf, **kwargs)
            else:
                from .native_execution_engine import NativeExecutionEngine

                result = NativeExecutionEngine(conf)
    elif isinstance(engine, ExecutionEngine):
        if conf is not None:
            engine.conf.update(ParamDict(conf))
        result = engine
    else:
        result = parse_execution_engine(engine, conf, **kwargs)
    if sql_engine_spec is not None:
        result.set_sql_engine(make_sql_engine(sql_engine_spec, result))
    elif _DEFAULT_SQL_ENGINE[0] is not None and result._sql_engine is None:
        try:
            result.set_sql_engine(_DEFAULT_SQL_ENGINE[0](result))
        except Exception:
            pass
    return result


def make_sql_engine(
    engine: Any = None,
    execution_engine: Optional[ExecutionEngine] = None,
    **kwargs: Any,
) -> SQLEngine:
    if engine is None:
        assert_or_throw(
            execution_engine is not None,
            FuguePluginsRegistrationError("execution_engine is required"),
        )
        return execution_engine.sql_engine  # type: ignore
    if isinstance(engine, SQLEngine):
        return engine
    if isinstance(engine, str):
        with _LOCK:
            if engine in _SQL_ENGINE_REGISTRY:
                return _SQL_ENGINE_REGISTRY[engine](execution_engine, **kwargs)
        raise FuguePluginsRegistrationError(f"{engine!r} is not a registered sql engine")
    if inspect.isclass(engine) and issubclass(engine, SQLEngine):
        return engine(execution_engine, **kwargs)
    raise FuguePluginsRegistrationError(f"can't parse sql engine spec {engine!r}")
