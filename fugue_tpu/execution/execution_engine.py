"""The engine contract: ExecutionEngine + MapEngine + SQLEngine.

Parity with the reference (`fugue/execution/execution_engine.py`):

- ``FugueEngineBase`` (``:92``): to_df/log/conf
- ``EngineFacet`` (``:143``): sub-engine bound to a parent engine
- ``SQLEngine`` (``:183``): SQL over named frames
- ``MapEngine`` (``:277``): ``map_dataframe`` — THE distributed primitive
- ``ExecutionEngine`` (``:338``): physical ops + derived ops + context
  management + the zip/comap co-partition protocol (``:962-1111``)

TPU-first redesigns vs the reference:
- derived ``select/filter/assign/aggregate`` default to the column-IR
  evaluators instead of generated-SQL (SQL engines may override);
- the zip/comap wire format is arrow IPC (columnar), not pickle blobs;
- engine context uses ``contextvars`` for thread/async safety (same
  semantics as reference ``:1182-1212``).
"""

import logging
from abc import ABC, abstractmethod
from contextlib import contextmanager
from contextvars import ContextVar
from threading import RLock
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

from .._utils.assertion import assert_or_throw
from .._utils.hash import to_uuid
from .._utils.params import ParamDict
from ..collections.partition import (
    EMPTY_PARTITION_SPEC,
    PartitionCursor,
    PartitionSpec,
)
from ..collections.sql import StructuredRawSQL
from ..collections.yielded import PhysicalYielded, Yielded
from ..column import ColumnExpr, SelectColumns
from ..constants import _FUGUE_GLOBAL_CONF
from ..dataframe import (
    AnySchema,
    ArrayDataFrame,
    DataFrame,
    DataFrames,
    LocalBoundedDataFrame,
    LocalDataFrame,
    YieldedDataFrame,
    deserialize_df,
    get_join_schemas,
    serialize_df,
)
from ..dataframe.utils import get_temp_df_path
from ..exceptions import FugueBug, FugueInvalidOperation
from ..schema import Schema

_FUGUE_BLOB_PREFIX = "__fugue_blob_"

_CONTEXT_ENGINE: ContextVar[Optional["ExecutionEngine"]] = ContextVar(
    "fugue_tpu_execution_engine", default=None
)
_GLOBAL_ENGINE_LOCK = RLock()
_GLOBAL_ENGINE: List[Optional["ExecutionEngine"]] = [None]

# run-scoped conf overlays (docs/serving.md "Per-run conf scoping"):
# ``workflow.run`` used to write workflow conf into the shared engine's
# conf dict, where it leaked into every later run on the same engine.
# Instead each run enters ``engine.run_conf_scope(overlay)``, which binds
# a merged base+overlay view to THIS context only; ``engine.conf`` reads
# resolve through it. Context-local, so concurrent runs on one engine
# each see their own conf; task threads (copy_context in
# _workflow_context) and fork workers inherit the scope, exactly like
# run_labels. The list holds (engine id, merged view) pairs so nested
# runs on DIFFERENT engines don't shadow each other's overlays.
_RUN_CONF: ContextVar[tuple] = ContextVar("fugue_tpu_run_conf", default=())


class FugueEngineBase(ABC):
    @property
    @abstractmethod
    def conf(self) -> ParamDict:
        raise NotImplementedError

    @property
    @abstractmethod
    def log(self) -> logging.Logger:
        raise NotImplementedError

    @property
    @abstractmethod
    def is_distributed(self) -> bool:
        raise NotImplementedError

    @abstractmethod
    def to_df(self, df: Any, schema: Any = None) -> DataFrame:
        raise NotImplementedError


class EngineFacet(FugueEngineBase):
    """A sub-engine bound to a parent ExecutionEngine (reference ``:143``)."""

    def __init__(self, execution_engine: "ExecutionEngine"):
        self._execution_engine = execution_engine

    @property
    def execution_engine(self) -> "ExecutionEngine":
        return self._execution_engine

    @property
    def conf(self) -> ParamDict:
        return self._execution_engine.conf

    @property
    def log(self) -> logging.Logger:
        return self._execution_engine.log

    def to_df(self, df: Any, schema: Any = None) -> DataFrame:
        return self._execution_engine.to_df(df, schema)

    @property
    def execution_engine_constraint(self) -> type:
        """The engine type this facet requires (for set_sql_engine checks)."""
        return ExecutionEngine


class SQLEngine(EngineFacet):
    """SQL execution over a dict of named DataFrames (reference ``:183``)."""

    @property
    def dialect(self) -> Optional[str]:
        return None

    def encode_name(self, name: str) -> str:
        return name

    @abstractmethod
    def select(self, dfs: DataFrames, statement: StructuredRawSQL) -> DataFrame:
        raise NotImplementedError

    def table_exists(self, table: str) -> bool:
        raise NotImplementedError(f"{type(self)} doesn't support tables")

    def save_table(
        self,
        df: DataFrame,
        table: str,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        **kwargs: Any,
    ) -> None:
        raise NotImplementedError(f"{type(self)} doesn't support tables")

    def load_table(self, table: str, **kwargs: Any) -> DataFrame:
        raise NotImplementedError(f"{type(self)} doesn't support tables")


class MapEngine(EngineFacet):
    """Per-partition mapping — THE distributed primitive (reference ``:277``)."""

    @abstractmethod
    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        raise NotImplementedError

    def map_bag(
        self,
        bag: Any,
        map_func: Callable,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable] = None,
    ) -> Any:
        raise NotImplementedError(f"{type(self)} doesn't support bags")


class ExecutionEngine(FugueEngineBase):
    """The backend contract every engine implements (reference ``:338``)."""

    def __init__(self, conf: Any = None):
        _conf = ParamDict(conf)
        self._conf = ParamDict(_FUGUE_GLOBAL_CONF)
        self._conf.update(_conf)
        self._rlock = RLock()
        self._map_engine: Optional[MapEngine] = None
        self._sql_engine: Optional[SQLEngine] = None
        self._stopped = False
        self._ctx_count = 0
        self._is_global = False
        self._compile_conf = ParamDict()
        self._rpc_server: Any = None
        self._resilience_stats: Any = None
        self._plan_stats: Any = None
        self._analysis_stats: Any = None
        self._tuner: Any = None
        self._metrics: Any = None
        self._active_runs = 0
        # apply trace switches (fugue.tpu.trace.* / FUGUE_TPU_TRACE) so
        # constructing an engine with tracing conf turns the tracer on
        from ..obs import (
            configure_events_from_conf,
            configure_from_conf,
            configure_sampler_from_conf,
        )

        configure_from_conf(self._conf)
        # ditto for the continuous resource sampler (fugue.tpu.telemetry.*
        # / FUGUE_TPU_TELEMETRY), plus this engine's occupancy probes
        configure_sampler_from_conf(self._conf)
        # and the cluster flight recorder (fugue.tpu.events.*)
        configure_events_from_conf(self._conf)
        self._register_resource_probes()

    def __repr__(self) -> str:
        return f"{type(self).__name__}"

    @property
    def conf(self) -> ParamDict:
        scopes = _RUN_CONF.get()
        if scopes:
            me = id(self)
            for eng_id, view in reversed(scopes):
                if eng_id == me:
                    return view
        return self._conf

    @property
    def base_conf(self) -> ParamDict:
        """The engine-level conf dict itself, ignoring any active
        run-scope overlay — what a deliberate engine-global write should
        target, and what run-scope leak tests assert against."""
        return self._conf

    @contextmanager
    def run_conf_scope(self, overlay: Any = None) -> Iterator[ParamDict]:
        """Bind ``overlay`` over this engine's conf for the current
        context only (and everything it forks via ``copy_context`` /
        ``fork``). Reads through ``engine.conf`` resolve overlay-first;
        writes land in the scoped view and vanish at exit — a run can no
        longer mutate a shared engine's conf. Nestable; inner scopes see
        outer overlays (merged at entry)."""
        if not overlay:
            yield self.conf
            return
        merged = ParamDict(self.conf)  # current view: nested scopes stack
        merged.update(overlay)
        scopes = _RUN_CONF.get()
        token = _RUN_CONF.set(scopes + ((id(self), merged),))
        try:
            yield merged
        finally:
            _RUN_CONF.reset(token)

    @property
    def log(self) -> logging.Logger:
        return logging.getLogger(type(self).__name__)

    # ---- sub-engines ------------------------------------------------------
    @abstractmethod
    def create_default_map_engine(self) -> MapEngine:
        raise NotImplementedError

    @abstractmethod
    def create_default_sql_engine(self) -> SQLEngine:
        raise NotImplementedError

    @property
    def map_engine(self) -> MapEngine:
        # lazy singletons double-checked under the engine lock (ISSUE 10
        # audit): two concurrent sessions' first touch must not build two
        # sub-engines and split state between them
        if self._map_engine is None:
            with self._rlock:
                if self._map_engine is None:
                    self._map_engine = self.create_default_map_engine()
        return self._map_engine

    @property
    def sql_engine(self) -> SQLEngine:
        if self._sql_engine is None:
            with self._rlock:
                if self._sql_engine is None:
                    self._sql_engine = self.create_default_sql_engine()
        return self._sql_engine

    def set_sql_engine(self, engine: "SQLEngine") -> None:
        assert_or_throw(
            isinstance(self, engine.execution_engine_constraint),
            lambda: FugueInvalidOperation(
                f"{type(engine)} requires {engine.execution_engine_constraint}"
            ),
        )
        with self._rlock:
            self._sql_engine = engine

    # ---- context management (reference :50-89, 362-421, 1182-1212) -------
    @property
    def in_context(self) -> bool:
        return self._ctx_count > 0

    @property
    def is_global(self) -> bool:
        return self._is_global

    @contextmanager
    def _as_context(self) -> Iterator["ExecutionEngine"]:
        with self._rlock:
            self._ctx_count += 1
        token = _CONTEXT_ENGINE.set(self)
        try:
            yield self
        finally:
            _CONTEXT_ENGINE.reset(token)
            with self._rlock:
                self._ctx_count -= 1
                if self._ctx_count == 0 and not self._is_global:
                    self.stop()

    @contextmanager
    def _as_borrowed_context(self) -> Iterator["ExecutionEngine"]:
        """Set as the context engine WITHOUT stop-on-last-exit ownership.

        Workflow runs BORROW the engine: the reference's ``dag.run(engine)``
        never stops a user-held engine (no as_context in
        `/root/reference/fugue/workflow/workflow.py`), so the same engine
        instance can run many workflows. Stop-on-exit remains the contract
        of the explicit ``engine_context``/``as_context`` API only."""
        with self._rlock:
            self._ctx_count += 1
        token = _CONTEXT_ENGINE.set(self)
        try:
            yield self
        finally:
            _CONTEXT_ENGINE.reset(token)
            with self._rlock:
                self._ctx_count -= 1

    def set_global(self) -> "ExecutionEngine":
        # lock order matches stop(): the module-wide global-engine lock
        # first, then the engine's own rlock for its shared flag
        with _GLOBAL_ENGINE_LOCK:
            old = _GLOBAL_ENGINE[0]
            if old is not None and old is not self:
                with old._rlock:
                    old._is_global = False
                if not old.in_context:
                    old.stop()
            with self._rlock:
                self._is_global = True
            _GLOBAL_ENGINE[0] = self
        return self

    @staticmethod
    def clear_global() -> None:
        with _GLOBAL_ENGINE_LOCK:
            old = _GLOBAL_ENGINE[0]
            if old is not None:
                old._is_global = False
                if not old.in_context:
                    old.stop()
            _GLOBAL_ENGINE[0] = None

    def stop(self) -> None:
        with self._rlock:
            if not self._stopped:
                self._stopped = True
                self.stop_engine()

    def stop_engine(self) -> None:
        """Subclass hook for resource cleanup."""

    # ---- concurrent-run accounting (ISSUE 10) -----------------------------
    @property
    def active_runs(self) -> int:
        """How many ``workflow.run`` graphs are executing on this engine
        RIGHT NOW — the serving layer's readiness/occupancy gauge."""
        with self._rlock:
            return self._active_runs

    def _run_started(self) -> None:
        with self._rlock:
            self._active_runs += 1

    def _run_finished(self) -> None:
        with self._rlock:
            self._active_runs = max(0, self._active_runs - 1)

    # ---- rpc server binding (set by workflow context) ---------------------
    @property
    def rpc_server(self) -> Any:
        if self._rpc_server is None:
            with self._rlock:
                if self._rpc_server is None:
                    from ..rpc.base import make_rpc_server

                    # conf-driven: "fugue.rpc.server" names the server class
                    # (reference fugue/rpc/base.py:268); default is in-process
                    server = make_rpc_server(self.conf)
                    self._bind_rpc_metrics(server)
                    self._rpc_server = server
        return self._rpc_server

    def set_rpc_server(self, server: Any) -> None:
        with self._rlock:
            self._rpc_server = server
        self._bind_rpc_metrics(server)

    def _bind_rpc_metrics(self, server: Any) -> None:
        # a server with exposure endpoints (HttpRPCServer's /metrics,
        # /healthz, /stats) scrapes THIS engine's registry
        if hasattr(server, "bind_engine"):
            server.bind_engine(self)

    # ---- observability ----------------------------------------------------
    @property
    def metrics(self) -> Any:
        """The engine's :class:`~fugue_tpu.obs.MetricsRegistry` — one
        surface over every stats object (resilience on all engines;
        pipeline + jit_cache on the jax engine). The legacy
        ``engine.*_stats`` attributes delegate to the same objects."""
        if self._metrics is None:
            with self._rlock:
                if self._metrics is None:
                    from ..obs import MetricsRegistry, get_sampler, get_span_metrics

                    reg = MetricsRegistry()
                    reg.register("resilience", lambda: self.resilience_stats)
                    reg.register("plan", lambda: self.plan_stats)
                    reg.register("analysis", lambda: self.analysis_stats)
                    reg.register("cache", lambda: self.result_cache.stats)
                    reg.register("tuning", lambda: self.tuner)
                    # distribution + resource sources are process-global (like
                    # the tracer feeding them) but mounted here so
                    # engine.stats() carries them and engine.reset_stats()
                    # resets them under the keep-entries contract (series/
                    # probes stay registered, observations/ring zero)
                    reg.register("latency", get_span_metrics)
                    reg.register("telemetry", get_sampler)
                    self._metrics = reg
        return self._metrics

    def _register_resource_probes(self) -> None:
        """Register this engine's occupancy probes on the global resource
        sampler. Probes bind through a ``weakref`` — once the engine is
        collected they raise :class:`~fugue_tpu.obs.sampler.ProbeGone`
        and the sampler drops them; a newer engine's registration under
        the same name simply replaces an older one's."""
        import weakref

        from ..obs import get_sampler

        ref = weakref.ref(self)

        def _bound(fn: Callable[["ExecutionEngine"], float]) -> Callable[[], float]:
            def probe() -> float:
                from ..obs.sampler import ProbeGone

                e = ref()
                if e is None:
                    raise ProbeGone()
                return fn(e)

            return probe

        sampler = get_sampler()
        for name, fn in self._resource_probe_fns().items():
            sampler.register_probe(name, _bound(fn))

    def _resource_probe_fns(self) -> Dict[str, Callable[["ExecutionEngine"], float]]:
        """Name → (engine → value) probe map; subclasses extend. Probes
        must guard lazily-created attributes — they run later, on the
        sampler thread, and must never force creation (reading occupancy
        should not allocate the thing it measures)."""

        def _rc(attr: str) -> Callable[["ExecutionEngine"], float]:
            def fn(e: "ExecutionEngine") -> float:
                rc = getattr(e, "_result_cache", None)
                return float(getattr(rc.mem, attr)) if rc is not None else 0.0

            return fn

        return {
            "result_cache_mem_bytes": _rc("bytes"),
            "result_cache_mem_entries": _rc("entries"),
        }

    def stats(self) -> Dict[str, Any]:
        """All registered stats as one dict — the unified replacement for
        reading ``pipeline_stats`` / ``jit_cache_stats`` /
        ``resilience_stats`` separately."""
        return self.metrics.as_dict()

    def reset_stats(self) -> None:
        """Reset every registered stats source (consistent semantics:
        counters to zero; entries kept — the jit cache keeps its compiled
        entries, histogram families keep their registered series, the
        sampler keeps its probes and keeps running; only the recorded
        observations/ring samples zero)."""
        self.metrics.reset()

    def report(self, top_n: int = 15) -> str:
        """Plain-text observability report: top-N spans by total wall from
        the global tracer — with p50/p95/p99 columns from the span-latency
        histograms — plus this engine's metrics."""
        from ..obs import get_span_metrics, get_tracer, render_report

        rooflines = None
        tuner = getattr(self, "_tuner", None)  # never force lazy creation
        if tuner is not None:
            try:
                rooflines = tuner.roofline.snapshot() or None
            except Exception:
                rooflines = None
        return render_report(
            get_tracer().records(),
            self.stats(),
            top_n=top_n,
            span_metrics=get_span_metrics(),
            rooflines=rooflines,
        )

    @property
    def resilience_stats(self) -> Any:
        """Structured recovery counters (``fugue_tpu.resilience``): every
        retry, quarantine and fallback on this engine increments one — the
        graceful-degradation machinery is observable, never silent.

        Kept as a stable alias of ``engine.metrics.get("resilience")`` —
        prefer ``engine.stats()["resilience"]`` for reads."""
        if self._resilience_stats is None:
            with self._rlock:
                if self._resilience_stats is None:
                    from ..resilience import ResilienceStats

                    self._resilience_stats = ResilienceStats()
        return self._resilience_stats

    @property
    def plan_stats(self) -> Any:
        """Cumulative logical-plan-optimizer counters for workflows run on
        this engine (cols_pruned / filters_pushed / verbs_fused /
        bytes_skipped). Alias of ``engine.metrics.get("plan")`` — prefer
        ``engine.stats()["plan"]`` for reads."""
        if getattr(self, "_plan_stats", None) is None:
            with self._rlock:
                if getattr(self, "_plan_stats", None) is None:
                    from ..plan import PlanStats

                    self._plan_stats = PlanStats()
        return self._plan_stats

    @property
    def analysis_stats(self) -> Any:
        """Cumulative UDF static-analyzer counters for workflows run on
        this engine (``fugue_tpu/analysis``, docs/analysis.md):
        udfs_analyzed / udfs_translated / udfs_refused by canonical
        reason code. Alias of ``engine.metrics.get("analysis")`` — prefer
        ``engine.stats()["analysis"]`` for reads."""
        if getattr(self, "_analysis_stats", None) is None:
            with self._rlock:
                if getattr(self, "_analysis_stats", None) is None:
                    from ..analysis import AnalysisStats

                    self._analysis_stats = AnalysisStats()
        return self._analysis_stats

    @property
    def tuner(self) -> Any:
        """This engine's :class:`~fugue_tpu.tuning.Tuner` — cost-based
        adaptive execution (``fugue_tpu/tuning``, docs/tuning.md): stream
        chunk size / prefetch depth, shuffle bucket sizing and join-side
        estimates learned from the engine's own telemetry, keyed by plan
        fingerprint and persisted across restarts. Decisions and counters
        live in ``engine.stats()["tuning"]``; ``engine.reset_stats()``
        zeroes counters without forgetting learned settings."""
        if getattr(self, "_tuner", None) is None:
            with self._rlock:
                if getattr(self, "_tuner", None) is None:
                    from ..tuning import Tuner

                    self._tuner = Tuner(self.conf)
        return self._tuner

    @property
    def result_cache(self) -> Any:
        """This engine's :class:`~fugue_tpu.cache.ResultCache` — the
        cross-run memoization layer (``fugue_tpu/cache``, docs/cache.md).
        The memory tier is scoped to this engine (device frames are laid
        out for its mesh); the disk tier is shared by every engine whose
        conf points at the same ``fugue.tpu.cache.dir``. Counters live in
        ``engine.stats()["cache"]``; ``engine.reset_stats()`` zeroes them
        without evicting entries (the ``JitCache.reset`` contract)."""
        if getattr(self, "_result_cache", None) is None:
            with self._rlock:
                if getattr(self, "_result_cache", None) is None:
                    from ..cache import ResultCache

                    self._result_cache = ResultCache(self.conf, log=self.log)
        return self._result_cache

    # ---- physical ops (abstract) ------------------------------------------
    @abstractmethod
    def get_current_parallelism(self) -> int:
        raise NotImplementedError

    @abstractmethod
    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def broadcast(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def persist(
        self,
        df: DataFrame,
        lazy: bool = False,
        **kwargs: Any,
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def join(
        self,
        df1: DataFrame,
        df2: DataFrame,
        how: str,
        on: Optional[List[str]] = None,
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def union(self, df1: DataFrame, df2: DataFrame, distinct: bool = True) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def subtract(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def intersect(
        self, df1: DataFrame, df2: DataFrame, distinct: bool = True
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def distinct(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def dropna(
        self,
        df: DataFrame,
        how: str = "any",
        thresh: Optional[int] = None,
        subset: Optional[List[str]] = None,
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def fillna(self, df: DataFrame, value: Any, subset: Optional[List[str]] = None) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def sample(
        self,
        df: DataFrame,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        seed: Optional[int] = None,
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def take(
        self,
        df: DataFrame,
        n: int,
        presort: str,
        na_position: str = "last",
        partition_spec: Optional[PartitionSpec] = None,
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def load_df(
        self,
        path: Union[str, List[str]],
        format_hint: Any = None,
        columns: Any = None,
        **kwargs: Any,
    ) -> DataFrame:
        raise NotImplementedError

    @abstractmethod
    def save_df(
        self,
        df: DataFrame,
        path: str,
        format_hint: Any = None,
        mode: str = "overwrite",
        partition_spec: Optional[PartitionSpec] = None,
        force_single: bool = False,
        **kwargs: Any,
    ) -> DataFrame:
        raise NotImplementedError

    # ---- derived ops (reference :736-939), IR-evaluated by default --------
    def select(
        self,
        df: DataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> DataFrame:
        from ..column.eval import eval_select

        local = self.to_df(df).as_local_bounded()
        res = eval_select(local.as_pandas(), local.schema, cols, where, having)
        schema = cols.replace_wildcard(local.schema).infer_schema(local.schema)
        from ..dataframe import PandasDataFrame

        out = PandasDataFrame(res, schema)
        return self.to_df(out)

    def filter(self, df: DataFrame, condition: ColumnExpr) -> DataFrame:
        from ..column import all_cols

        return self.select(df, SelectColumns(all_cols()), where=condition)

    def assign(self, df: DataFrame, columns: List[ColumnExpr]) -> DataFrame:
        """Update or add columns (reference ``:859``)."""
        from ..column import all_cols, col

        assert_or_throw(
            all(c.output_name != "" for c in columns),
            FugueInvalidOperation("all assignments must have output names"),
        )
        existing = df.schema.names
        replaced = {c.output_name: c for c in columns}
        sel: List[ColumnExpr] = []
        for name in existing:
            # replaced columns take the NEW expression's type (reference
            # ``:868``: assigning a constant may change the column type)
            sel.append(replaced.pop(name) if name in replaced else col(name))
        sel.extend(replaced.values())
        return self.select(df, SelectColumns(*sel))

    def fused_apply(self, df: DataFrame, steps: List[Any]) -> DataFrame:
        """Execute a fused chain of row-local verbs (see
        ``fugue_tpu/plan/fused.py``). The default interprets the steps
        sequentially with this engine's own verbs — bit-identical to the
        unfused task chain; engines may override with a compiled
        single-step implementation."""
        from ..plan.fused import apply_steps_engine

        return apply_steps_engine(self, df, steps)

    def lowered_segment(
        self,
        dfs: List[DataFrame],
        steps: List[Any],
        terminal: Any,
        partition_spec: Optional[PartitionSpec],
        fingerprint: str = "",
    ) -> DataFrame:
        """Execute a device-resident plan segment (see
        ``fugue_tpu/plan/lowering.py``): a row-local verb chain flowing
        into a terminal aggregate / take / distinct / join. The default
        interprets the segment per-verb — ``fused_apply`` then the
        terminal with this engine's own verb, exactly what the unlowered
        task pair runs; the jax engine overrides with a single compiled
        SPMD program and falls back here on any lowering refusal."""
        from ..plan.lowering import apply_terminal_engine

        return apply_terminal_engine(
            self, dfs, steps, tuple(terminal), partition_spec
        )

    def aggregate(
        self,
        df: DataFrame,
        partition_spec: Optional[PartitionSpec],
        agg_cols: List[ColumnExpr],
    ) -> DataFrame:
        from ..column import col
        from ..column.functions import is_agg

        assert_or_throw(len(agg_cols) > 0, FugueInvalidOperation("agg_cols is empty"))
        assert_or_throw(
            all(is_agg(c) for c in agg_cols),
            FugueInvalidOperation("all agg_cols must contain aggregation"),
        )
        keys: List[ColumnExpr] = []
        if partition_spec is not None and len(partition_spec.partition_by) > 0:
            keys = [col(k) for k in partition_spec.partition_by]
        return self.select(df, SelectColumns(*keys, *agg_cols))

    # ---- zip/comap: the co-partition protocol (reference :962-1111) ------
    def zip(
        self,
        dfs: DataFrames,
        how: str = "inner",
        partition_spec: Optional[PartitionSpec] = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> DataFrame:
        """Co-partition multiple frames into one serialized frame.

        Each logical partition of each input serializes into an arrow IPC
        blob row; rows from all inputs union into one frame whose metadata
        carries the per-input schemas (redesign of reference ``:962-1057``).
        """
        assert_or_throw(len(dfs) > 0, FugueInvalidOperation("dfs is empty"))
        how = how.lower()
        assert_or_throw(
            how in ("inner", "left_outer", "right_outer", "full_outer", "cross"),
            lambda: FugueInvalidOperation(f"invalid zip type {how}"),
        )
        spec = partition_spec or EMPTY_PARTITION_SPEC
        keys = list(spec.partition_by)
        if how == "cross":
            assert_or_throw(
                len(keys) == 0, FugueInvalidOperation("cross zip can't have keys")
            )
        elif len(keys) == 0:
            # infer keys: intersection of all schemas
            keys = [
                n
                for n in dfs[0].schema.names
                if all(n in d.schema for d in dfs.values())
            ]
            assert_or_throw(
                len(keys) > 0,
                FugueInvalidOperation("can't infer zip keys: no common columns"),
            )
        serialized: List[DataFrame] = []
        schemas: List[str] = []
        names: List[str] = []
        n = len(dfs)
        for i, (name, df) in enumerate(dfs.items()):
            dfs_keys = [k for k in keys]
            sub_spec = PartitionSpec(spec, by=dfs_keys) if len(keys) > 0 else PartitionSpec()
            sdf = self._serialize_by_partition(
                df,
                sub_spec,
                df_index=i,
                df_count=n,
                temp_path=temp_path,
                to_file_threshold=to_file_threshold,
            )
            serialized.append(sdf)
            schemas.append(str(df.schema))
            names.append(name)
        res = serialized[0]
        for s in serialized[1:]:
            res = self.union(res, s, distinct=False)
        res.reset_metadata(
            {
                "serialized": True,
                "serialized_cols": [f"{_FUGUE_BLOB_PREFIX}{i}" for i in range(n)],
                "schemas": schemas,
                "serialized_has_name": dfs.has_key,
                "names": names,
                "how": how,
                "keys": keys,
            }
        )
        return res

    def _serialize_by_partition(
        self,
        df: DataFrame,
        partition_spec: PartitionSpec,
        df_index: int,
        df_count: int,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> DataFrame:
        keys = list(partition_spec.partition_by)
        key_schema = df.schema.extract(keys) if len(keys) > 0 else Schema()
        blob_fields = ",".join(
            f"{_FUGUE_BLOB_PREFIX}{i}:binary" for i in range(df_count)
        )
        out_schema = (
            Schema(str(key_schema) + "," + blob_fields)
            if len(keys) > 0
            else Schema(blob_fields)
        )
        serializer = _PartitionSerializer(
            df_index, df_count, keys, temp_path, to_file_threshold
        )
        return self.map_engine.map_dataframe(
            df, serializer.run, out_schema, partition_spec
        )

    def comap(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, DataFrames], LocalDataFrame],
        output_schema: Any,
        partition_spec: Optional[PartitionSpec] = None,
        on_init: Optional[Callable[[int, DataFrames], Any]] = None,
    ) -> DataFrame:
        """Apply a function over co-partitioned (zipped) groups
        (reference ``:1059-1111``)."""
        assert_or_throw(
            df.metadata.get("serialized", False),
            FugueInvalidOperation("df is not serialized (run zip first)"),
        )
        meta = dict(df.metadata)
        keys = list(meta.get("keys", []))
        spec = partition_spec or EMPTY_PARTITION_SPEC
        if len(keys) > 0:
            spec = PartitionSpec(spec, by=keys)
        out_schema = (
            output_schema if isinstance(output_schema, Schema) else Schema(output_schema)
        )
        comap_runner = _Comap(meta, map_func, on_init, out_schema)
        return self.map_engine.map_dataframe(
            df, comap_runner.run, out_schema, spec, on_init=comap_runner.on_init
        )

    # ---- yields (reference :941, :1113) -----------------------------------
    def convert_yield_dataframe(self, df: DataFrame, as_local: bool) -> DataFrame:
        return df.as_local() if as_local else df

    def load_yielded(self, df: Yielded) -> DataFrame:
        if isinstance(df, YieldedDataFrame):
            return self.to_df(df.result)
        if isinstance(df, PhysicalYielded):
            if df.storage_type == "file":
                return self.load_df(df.name)
            return self.sql_engine.load_table(df.name)
        raise FugueBug(f"unknown yield type {type(df)}")

    def __uuid__(self) -> str:
        return to_uuid(str(type(self)), id(self))


def _is_plain_col(c: ColumnExpr, name: str) -> bool:
    from ..column.expressions import _NamedColumnExpr

    return isinstance(c, _NamedColumnExpr) and c.name == name


class _PartitionSerializer:
    """Serialize each logical partition into one blob row (arrow IPC)."""

    def __init__(
        self,
        df_index: int,
        df_count: int,
        keys: List[str],
        temp_path: Optional[str],
        to_file_threshold: int,
    ):
        self.df_index = df_index
        self.df_count = df_count
        self.keys = keys
        self.temp_path = temp_path
        self.to_file_threshold = to_file_threshold

    def run(self, cursor: PartitionCursor, df: LocalDataFrame) -> LocalDataFrame:
        data = df.as_local_bounded()
        file_path = (
            get_temp_df_path(self.temp_path) if self.temp_path is not None else None
        )
        blob = serialize_df(data, self.to_file_threshold, file_path)
        row: List[Any] = []
        if len(self.keys) > 0:
            row.extend(cursor.key_value_array)
        blobs: List[Any] = [None] * self.df_count
        blobs[self.df_index] = blob
        row.extend(blobs)
        key_schema = (
            cursor.row_schema.extract(self.keys) if len(self.keys) > 0 else Schema()
        )
        blob_fields = ",".join(
            f"{_FUGUE_BLOB_PREFIX}{i}:binary" for i in range(self.df_count)
        )
        out_schema = (
            Schema(str(key_schema) + "," + blob_fields)
            if len(self.keys) > 0
            else Schema(blob_fields)
        )
        return ArrayDataFrame([row], out_schema)


class _Comap:
    """Reassemble per-key DataFrames from blob rows and run the cotransform
    (reference ``:1293-1353``)."""

    def __init__(
        self,
        meta: Dict[str, Any],
        func: Callable,
        on_init: Optional[Callable],
        output_schema: Schema,
    ):
        self.schemas = [Schema(s) for s in meta["schemas"]]
        self.output_schema = output_schema
        self.named = meta.get("serialized_has_name", False)
        self.names = meta.get("names", [])
        self.how = meta.get("how", "inner")
        self.keys = meta.get("keys", [])
        self.func = func
        self._on_init = on_init

    def on_init(self, partition_no: int, df: DataFrame) -> None:
        if self._on_init is None:
            return
        empty = DataFrames(
            {self._name(i): ArrayDataFrame([], s) for i, s in enumerate(self.schemas)}
        )
        self._on_init(partition_no, empty)

    def _name(self, i: int) -> str:
        if self.named and i < len(self.names):
            return self.names[i]
        return f"_{i}"

    def run(self, cursor: PartitionCursor, df: LocalDataFrame) -> LocalDataFrame:
        import pyarrow as pa

        data = df.as_local_bounded().as_array()
        schema = df.schema
        blob_idx = [
            schema.index_of_key(f"{_FUGUE_BLOB_PREFIX}{i}")
            for i in range(len(self.schemas))
        ]
        frames: List[Optional[LocalBoundedDataFrame]] = []
        for i, s in enumerate(self.schemas):
            tables = []
            for row in data:
                blob = row[blob_idx[i]]
                if blob is not None:
                    tables.append(deserialize_df(blob).native)
            if len(tables) == 0:
                frames.append(None)
            else:
                from ..dataframe import ArrowDataFrame

                frames.append(ArrowDataFrame(pa.concat_tables(tables)))
        # zip-join semantics on missing sides
        if self.how == "inner" and any(f is None for f in frames):
            return ArrayDataFrame([], self.output_schema)
        if self.how == "left_outer" and frames[0] is None:
            return ArrayDataFrame([], self.output_schema)
        if self.how == "right_outer" and frames[-1] is None:
            return ArrayDataFrame([], self.output_schema)
        dfs = DataFrames(
            {
                self._name(i): (
                    f if f is not None else ArrayDataFrame([], self.schemas[i])
                )
                for i, f in enumerate(frames)
            }
        )
        return self.func(cursor, dfs)

