"""Functional engine API: engine context + engine verbs on any dataframe.

Parity with the reference (`fugue/execution/api.py`): ``engine_context``,
``set_global_engine``, and engine-level verbs (repartition/broadcast/persist/
join/union/.../select/filter/assign/aggregate) usable on *any* supported
dataframe object.
"""

from contextlib import contextmanager
from typing import Any, Callable, Iterator, List, Optional, Union

from ..collections.partition import PartitionSpec
from ..column import ColumnExpr, SelectColumns
from ..dataframe import DataFrame
from ..dataframe.api import as_fugue_df, get_native_as_df
from .execution_engine import ExecutionEngine
from .factory import make_execution_engine, try_get_context_execution_engine

AnyDataFrame = Any
AnyExecutionEngine = Any


@contextmanager
def engine_context(
    engine: AnyExecutionEngine = None,
    conf: Any = None,
    infer_by: Optional[List[Any]] = None,
) -> Iterator[ExecutionEngine]:
    """Context manager making ``engine`` the contextual engine
    (reference ``execution/api.py:22``)."""
    e = make_execution_engine(engine, conf, infer_by=infer_by)
    with e._as_context() as ctx:
        yield ctx


def as_fugue_engine_df(
    engine: ExecutionEngine, df: Any, schema: Any = None
) -> DataFrame:
    """Convert any dataframe-like object into ``engine``'s native
    DataFrame (reference ``execution/api.py:125``) — used by workflow
    internals and tests; prefer ``engine.to_df`` in user code."""
    fdf = as_fugue_df(df) if schema is None else as_fugue_df(df, schema=schema)
    return engine.to_df(fdf)


def set_global_engine(engine: AnyExecutionEngine, conf: Any = None) -> ExecutionEngine:
    """Make an engine the process-global default
    (reference ``execution/api.py:53``)."""
    from .._utils.assertion import assert_or_throw

    assert_or_throw(engine is not None, ValueError("engine can't be None"))
    return make_execution_engine(engine, conf).set_global()


def clear_global_engine() -> None:
    ExecutionEngine.clear_global()


def get_context_engine() -> ExecutionEngine:
    """The current contextual or global engine; raises when none is set."""
    e = try_get_context_execution_engine()
    if e is None:
        raise RuntimeError("no execution engine in context")
    return e


def run_engine_function(
    func: Callable[[ExecutionEngine], Any],
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    as_local: bool = False,
    infer_by: Optional[List[Any]] = None,
) -> Any:
    """Run a function with a resolved engine (reference ``:145``)."""
    e = make_execution_engine(engine, engine_conf, infer_by=infer_by)
    with e._as_context():
        res = func(e)
        if isinstance(res, DataFrame):
            res = e.convert_yield_dataframe(res, as_local)
            if not as_fugue:
                return get_native_as_df(res)
        return res


def _engine_verb(
    func: Callable[[ExecutionEngine, List[DataFrame]], Any],
    dfs: List[AnyDataFrame],
    engine: AnyExecutionEngine,
    engine_conf: Any,
    as_fugue: bool,
    as_local: bool = False,
) -> Any:
    return run_engine_function(
        lambda e: func(e, [e.to_df(as_fugue_df(d) if not isinstance(d, DataFrame) else d) for d in dfs]),
        engine=engine,
        engine_conf=engine_conf,
        as_fugue=as_fugue or any(isinstance(d, DataFrame) for d in dfs),
        as_local=as_local,
        infer_by=dfs,
    )


def repartition(
    df: AnyDataFrame,
    partition: Any,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(
        lambda e, d: e.repartition(d[0], PartitionSpec(partition)),
        [df], engine, engine_conf, as_fugue,
    )


def broadcast(
    df: AnyDataFrame,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(lambda e, d: e.broadcast(d[0]), [df], engine, engine_conf, as_fugue)


def persist(
    df: AnyDataFrame,
    lazy: bool = False,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    **kwargs: Any,
) -> AnyDataFrame:
    return _engine_verb(
        lambda e, d: e.persist(d[0], lazy=lazy, **kwargs),
        [df], engine, engine_conf, as_fugue,
    )


def distinct(
    df: AnyDataFrame,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(lambda e, d: e.distinct(d[0]), [df], engine, engine_conf, as_fugue)


def dropna(
    df: AnyDataFrame,
    how: str = "any",
    thresh: Optional[int] = None,
    subset: Optional[List[str]] = None,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(
        lambda e, d: e.dropna(d[0], how=how, thresh=thresh, subset=subset),
        [df], engine, engine_conf, as_fugue,
    )


def fillna(
    df: AnyDataFrame,
    value: Any,
    subset: Optional[List[str]] = None,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(
        lambda e, d: e.fillna(d[0], value, subset=subset),
        [df], engine, engine_conf, as_fugue,
    )


def sample(
    df: AnyDataFrame,
    n: Optional[int] = None,
    frac: Optional[float] = None,
    replace: bool = False,
    seed: Optional[int] = None,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(
        lambda e, d: e.sample(d[0], n=n, frac=frac, replace=replace, seed=seed),
        [df], engine, engine_conf, as_fugue,
    )


def take(
    df: AnyDataFrame,
    n: int,
    presort: str = "",
    na_position: str = "last",
    partition: Any = None,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(
        lambda e, d: e.take(
            d[0],
            n,
            presort=presort,
            na_position=na_position,
            partition_spec=None if partition is None else PartitionSpec(partition),
        ),
        [df], engine, engine_conf, as_fugue,
    )


def join(
    df1: AnyDataFrame,
    df2: AnyDataFrame,
    *dfs: AnyDataFrame,
    how: str = "inner",
    on: Optional[List[str]] = None,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    def _join(e: ExecutionEngine, d: List[DataFrame]) -> DataFrame:
        res = e.join(d[0], d[1], how=how, on=on)
        for x in d[2:]:
            res = e.join(res, x, how=how, on=on)
        return res

    return _engine_verb(_join, [df1, df2, *dfs], engine, engine_conf, as_fugue)


def semi_join(df1, df2, *dfs, on=None, engine=None, engine_conf=None, as_fugue=False):
    return join(df1, df2, *dfs, how="semi", on=on, engine=engine, engine_conf=engine_conf, as_fugue=as_fugue)


def anti_join(df1, df2, *dfs, on=None, engine=None, engine_conf=None, as_fugue=False):
    return join(df1, df2, *dfs, how="anti", on=on, engine=engine, engine_conf=engine_conf, as_fugue=as_fugue)


def inner_join(df1, df2, *dfs, on=None, engine=None, engine_conf=None, as_fugue=False):
    return join(df1, df2, *dfs, how="inner", on=on, engine=engine, engine_conf=engine_conf, as_fugue=as_fugue)


def left_outer_join(df1, df2, *dfs, on=None, engine=None, engine_conf=None, as_fugue=False):
    return join(df1, df2, *dfs, how="left_outer", on=on, engine=engine, engine_conf=engine_conf, as_fugue=as_fugue)


def right_outer_join(df1, df2, *dfs, on=None, engine=None, engine_conf=None, as_fugue=False):
    return join(df1, df2, *dfs, how="right_outer", on=on, engine=engine, engine_conf=engine_conf, as_fugue=as_fugue)


def full_outer_join(df1, df2, *dfs, on=None, engine=None, engine_conf=None, as_fugue=False):
    return join(df1, df2, *dfs, how="full_outer", on=on, engine=engine, engine_conf=engine_conf, as_fugue=as_fugue)


def cross_join(df1, df2, *dfs, engine=None, engine_conf=None, as_fugue=False):
    return join(df1, df2, *dfs, how="cross", engine=engine, engine_conf=engine_conf, as_fugue=as_fugue)


def union(
    df1: AnyDataFrame,
    df2: AnyDataFrame,
    *dfs: AnyDataFrame,
    distinct: bool = True,  # noqa: A002
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    def _union(e: ExecutionEngine, d: List[DataFrame]) -> DataFrame:
        res = e.union(d[0], d[1], distinct=distinct)
        for x in d[2:]:
            res = e.union(res, x, distinct=distinct)
        return res

    return _engine_verb(_union, [df1, df2, *dfs], engine, engine_conf, as_fugue)


def subtract(
    df1: AnyDataFrame,
    df2: AnyDataFrame,
    *dfs: AnyDataFrame,
    distinct: bool = True,  # noqa: A002
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    def _sub(e: ExecutionEngine, d: List[DataFrame]) -> DataFrame:
        res = e.subtract(d[0], d[1], distinct=distinct)
        for x in d[2:]:
            res = e.subtract(res, x, distinct=distinct)
        return res

    return _engine_verb(_sub, [df1, df2, *dfs], engine, engine_conf, as_fugue)


def intersect(
    df1: AnyDataFrame,
    df2: AnyDataFrame,
    *dfs: AnyDataFrame,
    distinct: bool = True,  # noqa: A002
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    def _int(e: ExecutionEngine, d: List[DataFrame]) -> DataFrame:
        res = e.intersect(d[0], d[1], distinct=distinct)
        for x in d[2:]:
            res = e.intersect(res, x, distinct=distinct)
        return res

    return _engine_verb(_int, [df1, df2, *dfs], engine, engine_conf, as_fugue)


def select(
    df: AnyDataFrame,
    *columns: Union[str, ColumnExpr],
    where: Optional[ColumnExpr] = None,
    having: Optional[ColumnExpr] = None,
    distinct: bool = False,  # noqa: A002
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    from ..column import col as _col

    cols = SelectColumns(
        *[_col(c) if isinstance(c, str) else c for c in columns],
        arg_distinct=distinct,
    )
    return _engine_verb(
        lambda e, d: e.select(d[0], cols, where=where, having=having),
        [df], engine, engine_conf, as_fugue,
    )


def filter(  # noqa: A001
    df: AnyDataFrame,
    condition: ColumnExpr,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
) -> AnyDataFrame:
    return _engine_verb(
        lambda e, d: e.filter(d[0], condition), [df], engine, engine_conf, as_fugue
    )


def assign(
    df: AnyDataFrame,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    **columns: Any,
) -> AnyDataFrame:
    from ..column import lit

    cols = [
        (v if isinstance(v, ColumnExpr) else lit(v)).alias(k) for k, v in columns.items()
    ]
    return _engine_verb(
        lambda e, d: e.assign(d[0], cols), [df], engine, engine_conf, as_fugue
    )


def aggregate(
    df: AnyDataFrame,
    partition_by: Any = None,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    **agg_kwcols: ColumnExpr,
) -> AnyDataFrame:
    cols = [v.alias(k) for k, v in agg_kwcols.items()]
    spec = (
        None
        if partition_by is None
        else PartitionSpec(by=[partition_by] if isinstance(partition_by, str) else list(partition_by))
    )
    return _engine_verb(
        lambda e, d: e.aggregate(d[0], spec, cols), [df], engine, engine_conf, as_fugue
    )


def load(
    path: Union[str, List[str]],
    format_hint: Any = None,
    columns: Any = None,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    as_fugue: bool = False,
    **kwargs: Any,
) -> AnyDataFrame:
    return run_engine_function(
        lambda e: e.load_df(path, format_hint=format_hint, columns=columns, **kwargs),
        engine=engine,
        engine_conf=engine_conf,
        as_fugue=as_fugue,
    )


def save(
    df: AnyDataFrame,
    path: str,
    format_hint: Any = None,
    mode: str = "overwrite",
    partition: Any = None,
    force_single: bool = False,
    engine: AnyExecutionEngine = None,
    engine_conf: Any = None,
    **kwargs: Any,
) -> None:
    run_engine_function(
        lambda e: e.save_df(
            e.to_df(as_fugue_df(df) if not isinstance(df, DataFrame) else df),
            path,
            format_hint=format_hint,
            mode=mode,
            partition_spec=None if partition is None else PartitionSpec(partition),
            force_single=force_single,
            **kwargs,
        ),
        engine=engine,
        engine_conf=engine_conf,
        infer_by=[df],
    )


def get_current_parallelism(engine: AnyExecutionEngine = None, engine_conf: Any = None) -> int:
    return run_engine_function(
        lambda e: e.get_current_parallelism(), engine=engine, engine_conf=engine_conf
    )


def get_current_conf() -> Any:
    """The conf of the current context engine (or global defaults)."""
    from ..constants import _FUGUE_GLOBAL_CONF

    e = try_get_context_execution_engine()
    if e is not None:
        return e.conf
    return _FUGUE_GLOBAL_CONF
