from .base import (
    EmptyRPCHandler,
    NativeRPCClient,
    NativeRPCServer,
    RPCClient,
    RPCFunc,
    RPCHandler,
    RPCServer,
    make_rpc_server,
    to_rpc_handler,
)

__all__ = [
    "EmptyRPCHandler",
    "NativeRPCClient",
    "NativeRPCServer",
    "RPCClient",
    "RPCFunc",
    "RPCHandler",
    "RPCServer",
    "make_rpc_server",
    "to_rpc_handler",
]
