"""HTTP RPC server — worker→driver callbacks over the network.

Replaces the reference's flask server (`fugue/rpc/flask.py:17` — flask is
not in this environment) with a stdlib ``ThreadingHTTPServer``. Payloads are
cloudpickle over POST. Conf keys mirror the reference, plus resilience
controls:

- ``fugue.rpc.http_server.host`` (default 127.0.0.1)
- ``fugue.rpc.http_server.port`` (default 0 = ephemeral)
- ``fugue.rpc.http_server.timeout`` (legacy single client timeout seconds;
  still honoured as the read-timeout default)
- ``fugue.rpc.http_client.connect_timeout`` (default 5s)
- ``fugue.rpc.http_client.read_timeout`` (default = legacy timeout, 30s)
- ``fugue.tpu.retry.rpc.attempts`` (+ ``fugue.tpu.retry.*`` backoff keys)

Every request is bounded: connect and read each have their own deadline —
a driver that vanished mid-call can no longer hang a worker forever.

Retry semantics respect idempotency: a failure BEFORE the request is sent
(refused/unreachable/connect timeout) is always retried with backoff — the
server never saw it. A failure AFTER the request went out is only retried
when the client was built with ``idempotent=True``; blindly re-sending a
stateful callback could double-apply it.
"""

import base64
import http.client
import json
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import cloudpickle

from ..resilience import (
    SITE_RPC_REQUEST,
    FaultInjector,
    NULL_INJECTOR,
    ResilienceStats,
    RetryPolicy,
    classify_failure,
)  # classify_failure also stamps /serve/poll's error_code (ISSUE 14)
from .base import RPCClient, RPCServer

# cluster trace propagation (ISSUE 18): every hop ships the submitting
# run's trace id + the caller's innermost span id; the receiving process
# re-enters the context so its spans attach under the submitting run
TRACE_HEADER = "X-Fugue-Trace"
PARENT_HEADER = "X-Fugue-Parent"


def trace_headers() -> dict:
    """The outbound trace-context headers for the current caller (empty
    when no trace context is bound)."""
    from ..obs.tracer import trace_carrier

    c = trace_carrier()
    if not c:
        return {}
    out = {TRACE_HEADER: c["trace"]}
    if "parent" in c:
        out[PARENT_HEADER] = c["parent"]
    return out


def _scope_from_headers(headers: Any) -> Any:
    """A ``trace_scope`` bound from inbound request headers, or a no-op
    context when the request carries none."""
    trace = headers.get(TRACE_HEADER) if headers is not None else None
    if not trace:
        import contextlib

        return contextlib.nullcontext()
    from ..obs.tracer import trace_scope

    return trace_scope(str(trace), headers.get(PARENT_HEADER))


class HttpRPCClient(RPCClient):
    """Picklable client stub carrying only (host, port, key) + timeouts.

    The retry policy travels with the stub (it's plain data); the stats
    sink and fault injector do not — a forked/remote worker increments its
    own copies, and only driver-side counters are observable anyway.
    """

    def __init__(
        self,
        host: str,
        port: int,
        key: str,
        timeout: float = 30.0,
        connect_timeout: float = 5.0,
        policy: Optional[RetryPolicy] = None,
        idempotent: bool = False,
        stats: Optional[ResilienceStats] = None,
        injector: Optional[FaultInjector] = None,
    ):
        self._host = host
        self._port = port
        self._key = key
        self._timeout = timeout
        self._connect_timeout = connect_timeout
        self._policy = policy or RetryPolicy(max_attempts=1)
        self._idempotent = idempotent
        self._stats = stats
        self._injector = injector

    def __getstate__(self) -> dict:
        # stats/injector hold locks & shared memory — strip them so the
        # stub stays cloudpickle-able into any worker
        state = dict(self.__dict__)
        state["_stats"] = None
        state["_injector"] = None
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)

    def _invoke_once(self, payload: bytes) -> bytes:
        """One request; exceptions carry ``_fugue_request_sent`` so the
        retry loop can honour idempotency."""
        sent = False
        conn = http.client.HTTPConnection(
            self._host, self._port, timeout=self._connect_timeout
        )
        try:
            conn.connect()
            # connected: switch the socket to the (usually longer) read
            # deadline for the request/response exchange
            if conn.sock is not None:
                conn.sock.settimeout(self._timeout)
            sent = True
            headers = {"Content-Length": str(len(payload))}
            headers.update(trace_headers())
            conn.request(
                "POST",
                "/invoke",
                body=payload,
                headers=headers,
            )
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise ConnectionError(f"RPC server returned HTTP {resp.status}")
            return body
        except Exception as ex:
            ex._fugue_request_sent = sent  # type: ignore[attr-defined]
            raise
        finally:
            conn.close()

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        from ..obs import get_tracer

        payload = base64.b64encode(cloudpickle.dumps((self._key, args, kwargs)))
        policy = self._policy
        attempts = 0
        with get_tracer().span(
            "rpc.invoke", cat="rpc", key=self._key, bytes_out=len(payload)
        ) as sp:
            while True:
                try:
                    (self._injector or NULL_INJECTOR).fire(SITE_RPC_REQUEST)
                    body = self._invoke_once(payload)
                    break
                except Exception as ex:
                    attempts += 1
                    sent = getattr(ex, "_fugue_request_sent", False)
                    retryable = (self._idempotent or not sent) and policy.should_retry(
                        classify_failure(ex), attempts
                    )
                    if not retryable:
                        sp.set(attempts=attempts)
                        raise
                    if self._stats is not None:
                        self._stats.inc("rpc.retries")
                    time.sleep(policy.delay(attempts, seed=self._key))
            sp.set(attempts=attempts + 1, bytes_in=len(body))
        ok, result = cloudpickle.loads(base64.b64decode(body))
        if not ok:
            raise result
        return result


class HttpRPCServer(RPCServer):
    """Stdlib HTTP RPC server (reference flask parity) — doubling as the
    engine's telemetry exposure surface (ISSUE 6) and the serving layer's
    network front end (ISSUE 10): alongside the POST ``/invoke`` callback
    channel it serves

    - ``GET /metrics`` — Prometheus text exposition: labeled span-latency
      /rows/bytes histograms, resource-sampler gauges, and the bound
      engine's flattened counters (scrapeable while a run is in flight);
    - ``GET /healthz`` — liveness JSON (process up; NEVER load-aware —
      a load balancer must not restart a merely busy server);
    - ``GET /readyz`` — readiness: queue depth/capacity and active runs
      of the bound :class:`~fugue_tpu.serve.EngineServer`; answers 503
      with the same JSON shape when the admission queue is full, so
      traffic sheds at the balancer before the server rejects;
    - ``GET /stats`` — one JSON snapshot (engine registry + latency
      summary + sampler state + current run labels + serve stats);
    - ``POST /serve/submit``, ``GET /serve/poll``, ``GET /serve/result``,
      ``POST /serve/cancel`` — the remote session surface over a bound
      EngineServer (see docs/serving.md; idempotency keys make submit
      safe under the retry policy);
    - ``POST /serve/register`` / ``POST /serve/unregister``,
      ``GET /serve/views``, ``GET /serve/view?id=`` (plus ``DELETE``) —
      the continuous-view surface (ISSUE 20, docs/views.md); all answer
      a bare 404 when ``fugue.tpu.views.enabled`` is off, keeping the
      disabled-mode wire contract identical;
    - ``GET /dist/fetch?path=<rel>`` — the worker tier's shuffle-fragment
      channel (ISSUE 14, docs/distributed.md): a bound
      :class:`~fugue_tpu.dist.DistWorker` serves files from its OWN data
      dir (path-jailed) so another host's reduce task can pull this
      worker's bucket fragments without a shared filesystem.

    Bind an engine with :meth:`bind_engine` (the engine does this itself
    when it creates or is handed the server), a serving front end with
    :meth:`bind_serve`, and a dist worker with :meth:`bind_dist`;
    unbound, the global span metrics and sampler still serve and the
    serve/dist routes answer 404."""

    def __init__(self, conf: Any = None):
        super().__init__(conf)
        from ..constants import (
            FUGUE_RPC_CONF_HTTP_CONNECT_TIMEOUT,
            FUGUE_RPC_CONF_HTTP_READ_TIMEOUT,
        )

        self._host = self.conf.get("fugue.rpc.http_server.host", "127.0.0.1")
        self._port = int(self.conf.get("fugue.rpc.http_server.port", 0))
        # legacy single-timeout key remains the read-timeout default
        legacy = float(self.conf.get("fugue.rpc.http_server.timeout", 30.0))
        self._timeout = float(
            self.conf.get(FUGUE_RPC_CONF_HTTP_READ_TIMEOUT, legacy)
        )
        self._connect_timeout = float(
            self.conf.get(FUGUE_RPC_CONF_HTTP_CONNECT_TIMEOUT, 5.0)
        )
        self._client_policy = RetryPolicy.from_conf(
            self.conf, prefix="fugue.tpu.retry.rpc", default_attempts=3
        )
        self._stats = ResilienceStats()
        self._httpd: Any = None
        self._thread: Any = None
        self._engine_ref: Any = None
        self._serve_ref: Any = None
        self._dist_ref: Any = None
        self._started_at = time.time()

    # -- telemetry binding ---------------------------------------------------
    def bind_engine(self, engine: Any) -> None:
        """Point /metrics and /stats at ``engine``'s registry (held weakly
        — a collected engine silently unbinds)."""
        self._engine_ref = weakref.ref(engine)

    def bind_serve(self, server: Any) -> None:
        """Point the /serve/* routes and /readyz at an
        :class:`~fugue_tpu.serve.EngineServer` (held weakly)."""
        self._serve_ref = weakref.ref(server)

    def bind_dist(self, worker: Any) -> None:
        """Point /dist/fetch at a :class:`~fugue_tpu.dist.DistWorker`
        (held weakly) — anything with ``read_blob(rel) -> bytes|None``."""
        self._dist_ref = weakref.ref(worker)

    def _metrics_engine(self) -> Any:
        return self._engine_ref() if self._engine_ref is not None else None

    def _serve_server(self) -> Any:
        return self._serve_ref() if self._serve_ref is not None else None

    def _get_body(self, path: str, query: str = "") -> Optional[Any]:
        """Build (status, content_type, body_bytes) for a GET route, or
        None for an unknown path."""
        if path == "/healthz":
            # the LIVENESS contract: process up + uptime, nothing else —
            # never made load-aware (that's /readyz), or a busy-but-
            # healthy server would get restarted by its balancer
            payload = {
                "status": "ok",
                "pid": os.getpid(),
                "uptime_s": round(time.time() - self._started_at, 3),
            }
            return 200, "application/json", json.dumps(payload).encode()
        if path == "/readyz":
            return self._readyz()
        if path == "/metrics":
            from ..obs import to_prometheus_text

            text = to_prometheus_text(engine=self._metrics_engine())
            return 200, "text/plain; version=0.0.4; charset=utf-8", text.encode()
        if path == "/metrics/snapshot":
            # metrics federation (ISSUE 18): the machine-readable form —
            # this replica's span-histogram families in the mergeable
            # encoding. A FleetClient merges N of these associatively and
            # renders ONE fleet-level exposition (federated_metrics())
            from ..obs import get_span_metrics
            from ..obs.tracer import proc_ident

            srv = self._serve_server()
            payload = {
                "replica": getattr(srv, "replica_id", None),
                "proc": proc_ident(),
                "spans": get_span_metrics().snapshot(),
            }
            return 200, "application/json", json.dumps(payload).encode()
        if path == "/stats":
            from ..obs import active_run_labels, get_sampler, get_span_metrics

            eng = self._metrics_engine()
            srv = self._serve_server()
            # run labels are context-local to the run's own threads; from
            # the server thread report the scopes currently entered
            # anywhere in the process (most recent under the legacy key)
            active = active_run_labels()
            payload = {
                "engine": eng.stats() if eng is not None else None,
                "latency": get_span_metrics().summary(),
                "telemetry": get_sampler().as_dict(),
                "run_labels": active[-1] if active else {},
                "active_runs": active,
                "serve": srv.stats() if srv is not None else None,
            }
            return 200, "application/json", json.dumps(payload, default=str).encode()
        if path == "/serve/poll":
            return self._serve_poll(query)
        if path == "/serve/result":
            return self._serve_result(query)
        if path == "/serve/views":
            return self._serve_views()
        if path == "/serve/view":
            return self._serve_view(query)
        if path == "/dist/fetch":
            return self._dist_fetch(query)
        return None

    # -- dist worker routes (ISSUE 14; see docs/distributed.md) --------------
    def _dist_fetch(self, query: str) -> Any:
        """Serve one shuffle fragment from the bound worker's data dir.
        404 covers everything the caller treats as "unavailable": no
        worker bound, missing file, or a path outside the jail — the
        consumer's orphan-recovery ladder takes it from there."""
        from urllib.parse import parse_qs

        from ..obs import get_tracer

        worker = self._dist_ref() if self._dist_ref is not None else None
        if worker is None:
            return 404, "application/json", b'{"error": "no dist worker bound"}'
        vals = parse_qs(query).get("path")
        rel = vals[0] if vals else ""
        with get_tracer().span("rpc.dist_fetch", cat="rpc", path=rel):
            blob = worker.read_blob(rel) if rel else None
        if blob is None:
            return (
                404,
                "application/json",
                json.dumps({"error": f"no fragment at {rel!r}"}).encode(),
            )
        return 200, "application/octet-stream", blob

    # -- serving routes (ISSUE 10; see docs/serving.md) ----------------------
    def _readyz(self) -> Any:
        srv = self._serve_server()
        if srv is None:
            # no serving front end bound: readiness degrades to liveness
            payload = {"status": "ready", "serve_bound": False}
            return 200, "application/json", json.dumps(payload).encode()
        st = srv.stats()
        full = st["queue_depth"] >= st["queue_capacity"] or not srv.running
        # shared-store health (ISSUE 13 satellite): a replica whose cache
        # or journal disk died must be DRAINED by the balancer — it can
        # neither journal admissions nor publish fleet results — so it
        # answers 503 with its own status, distinct from "overloaded"
        health = srv.store_health()
        unwritable = not health.get("writable", True)
        status = (
            "store_unwritable"
            if unwritable
            else ("overloaded" if full else "ready")
        )
        payload = {
            "status": status,
            "serve_bound": True,
            "accepting": bool(srv.running),
            "queue_depth": st["queue_depth"],
            "queue_capacity": st["queue_capacity"],
            "queue_free": max(0, st["queue_capacity"] - st["queue_depth"]),
            "active_runs": st["active_runs"],
            "max_concurrent": st["max_concurrent"],
            "replica_id": st.get("replica_id"),
            "store": health,
        }
        views = getattr(srv, "views", None)
        if views is not None:
            # watcher-loop health (ISSUE 20): a dead maintainer loop is a
            # readiness fact — views it holds leases on go stale until
            # another replica steals them. Only present when views are on,
            # so the disabled-mode /readyz payload is unchanged.
            payload["views"] = views.health()
        # 503 on full/unwritable: the shape a load balancer sheds on —
        # BEFORE the admission queue starts rejecting sessions outright
        code = 503 if (full or unwritable) else 200
        return code, "application/json", json.dumps(payload).encode()

    @staticmethod
    def _query_id(query: str) -> Optional[str]:
        from urllib.parse import parse_qs

        vals = parse_qs(query).get("id")
        return vals[0] if vals else None

    def _serve_sub(self, query: str) -> Any:
        srv = self._serve_server()
        if srv is None:
            return None, (404, "application/json", b'{"error": "no serve bound"}')
        sid = self._query_id(query)
        sub = srv.get(sid) if sid else None
        if sub is None:
            return None, (
                404,
                "application/json",
                json.dumps({"error": f"unknown submission {sid!r}"}).encode(),
            )
        return sub, None

    def _sub_payload(self, sub: Any) -> dict:
        out = {
            "id": sub.id,
            "status": sub.status,
            "tenant": sub.tenant,
            "priority": sub.priority,
            "deduped": sub.deduped,
            "queue_wait_s": sub.queue_wait_s,
            "run_s": sub.run_s,
        }
        err = sub._execution.error if sub._execution is not None else None
        if sub.status == "failed" and err is not None:
            out["error"] = f"{type(err).__name__}: {err}"
            # the PR 1 taxonomy travels with the error so a remote caller
            # can distinguish retryable (worker_lost/transient/timeout)
            # from fatal (poison) without parsing message strings
            out["error_code"] = classify_failure(err).value
        return out

    def _serve_poll(self, query: str) -> Any:
        sub, err = self._serve_sub(query)
        if err is not None:
            return err
        return 200, "application/json", json.dumps(self._sub_payload(sub)).encode()

    def _serve_result(self, query: str) -> Any:
        """The result channel: yielded frames as host pandas (cloudpickle
        over the wire — device frames are laid out for THIS process's
        mesh and never serialize). 202 + status JSON while pending."""
        sub, err = self._serve_sub(query)
        if err is not None:
            return err
        if sub.status in ("queued", "running"):
            return 202, "application/json", json.dumps(self._sub_payload(sub)).encode()
        try:
            # status is terminal but the waiter event is set a beat later
            # (the execution's finish path runs stats/publish first) —
            # a short bounded wait instead of timeout=0 absorbs the race
            res = sub.result(timeout=5)
            frames = {}
            for name, y in res.yields.items():
                df = getattr(y, "result", None)
                frames[name] = df.as_pandas() if df is not None else None
            body = (True, frames)
        except Exception as e:
            body = (False, e)
        made = (
            200,
            "application/octet-stream",
            base64.b64encode(cloudpickle.dumps(body)),
        )
        # staleness metadata (ISSUE 20): only when the views subsystem is
        # on — with it off the reply stays byte- and header-identical to
        # the PR 13/16 wire contract
        if self._views_service() is not None:
            ex = sub._execution
            if ex is not None and ex.finished_at is not None:
                # finished_at is monotonic; rebase onto the wall clock
                as_of = time.time() - (time.monotonic() - ex.finished_at)
                made = made + (
                    {
                        "X-Fugue-As-Of": repr(round(as_of, 6)),
                        "X-Fugue-Staleness-S": repr(
                            round(max(0.0, time.time() - as_of), 6)
                        ),
                    },
                )
        return made

    # -- continuous-view routes (ISSUE 20; see docs/views.md) ----------------
    # Kill-switch contract: when ``fugue.tpu.views.enabled`` is off the
    # server has no ViewService, every handler below returns None, and the
    # caller answers a BARE 404 — byte-identical to an unknown route, so
    # the PR 13/16 serve wire contract is unchanged with views disabled.
    def _views_service(self) -> Any:
        srv = self._serve_server()
        return getattr(srv, "views", None) if srv is not None else None

    def _serve_views(self) -> Any:
        vs = self._views_service()
        if vs is None:
            return None
        return 200, "application/json", json.dumps({"views": vs.list()}).encode()

    def _serve_view(self, query: str) -> Any:
        """One view's latest published generation: 202 + describe JSON
        before the first publish, else the frames as b64 cloudpickle with
        ``X-Fugue-As-Of`` / ``X-Fugue-Staleness-S`` / ``X-Fugue-Generation``
        response headers carrying the staleness metadata."""
        vs = self._views_service()
        if vs is None:
            return None
        vid = self._query_id(query)
        desc = vs.describe(vid) if vid else None
        if desc is None:
            return (
                404,
                "application/json",
                json.dumps({"error": f"unknown view {vid!r}"}).encode(),
            )
        res = vs.result(vid)
        if res is None:
            # registered but nothing published yet — poll like /serve/result
            return 202, "application/json", json.dumps(desc).encode()
        headers = {
            "X-Fugue-As-Of": repr(res["as_of"]),
            "X-Fugue-Staleness-S": repr(res["staleness_s"]),
            "X-Fugue-Generation": str(res["generation"]),
        }
        body = base64.b64encode(cloudpickle.dumps(res))
        return 200, "application/octet-stream", body, headers

    def _serve_register(self, raw: bytes) -> Any:
        vs = self._views_service()
        if vs is None:
            return None
        req = cloudpickle.loads(base64.b64decode(raw))
        try:
            desc = vs.register(
                str(req["id"]),
                req["factory"],
                str(req["source"]),
                fmt=str(req.get("format", "") or ""),
                tenant=str(req.get("tenant", "default")),
            )
        except ValueError as e:
            return 400, "application/json", json.dumps({"error": str(e)}).encode()
        return 200, "application/json", json.dumps(desc).encode()

    def _serve_unregister(self, raw: bytes) -> Any:
        vs = self._views_service()
        if vs is None:
            return None
        req = json.loads(raw.decode() or "{}")
        return self._unregister_reply(vs, str(req.get("id", "")))

    def _serve_view_delete(self, query: str) -> Any:
        # DELETE /serve/view?id=<id> — same semantics as /serve/unregister
        vs = self._views_service()
        if vs is None:
            return None
        return self._unregister_reply(vs, self._query_id(query) or "")

    @staticmethod
    def _unregister_reply(vs: Any, vid: str) -> Any:
        if not vid or not vs.unregister(vid):
            return (
                404,
                "application/json",
                json.dumps({"error": f"unknown view {vid!r}"}).encode(),
            )
        return 200, "application/json", json.dumps({"unregistered": vid}).encode()

    def _serve_submit(self, raw: bytes) -> Any:
        srv = self._serve_server()
        if srv is None:
            return 404, "application/json", b'{"error": "no serve bound"}'
        from ..serve import ServeRejected

        req = cloudpickle.loads(base64.b64decode(raw))
        try:
            sub = srv.submit(
                req["dag"],
                tenant=req.get("tenant", "default"),
                priority=req.get("priority"),
                idempotency_key=req.get("idempotency_key"),
                reserve_bytes=req.get("reserve_bytes"),
            )
        except ServeRejected as e:
            # 429-style shed: the reason travels; the client raises it
            payload = {"rejected": e.reason, "error": str(e)}
            return 429, "application/json", json.dumps(payload).encode()
        return 200, "application/json", json.dumps(self._sub_payload(sub)).encode()

    def _serve_cancel(self, raw: bytes) -> Any:
        srv = self._serve_server()
        if srv is None:
            return 404, "application/json", b'{"error": "no serve bound"}'
        req = json.loads(raw.decode() or "{}")
        sub = srv.get(str(req.get("id", "")))
        if sub is None:
            return (
                404,
                "application/json",
                json.dumps({"error": f"unknown submission {req.get('id')!r}"}).encode(),
            )
        changed = sub.cancel()
        payload = dict(self._sub_payload(sub), canceled=changed)
        return 200, "application/json", json.dumps(payload).encode()

    @property
    def host(self) -> str:
        return self._host

    @property
    def port(self) -> int:
        return self._port

    @property
    def resilience_stats(self) -> ResilienceStats:
        return self._stats

    def create_client(self, key: str) -> RPCClient:
        return HttpRPCClient(
            self._host,
            self._port,
            key,
            timeout=self._timeout,
            connect_timeout=self._connect_timeout,
            policy=self._client_policy,
            stats=self._stats,
            injector=FaultInjector.from_conf(self.conf),
        )

    def start_server(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(
                self,
                status: int,
                ctype: str,
                body: bytes,
                headers: Any = None,
            ) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                # optional 4th tuple element from a route: extra response
                # headers (views staleness metadata); routes that return
                # 3-tuples are wire-identical to before the field existed
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:  # noqa: N802
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    raw = self.rfile.read(length)
                    path = self.path.split("?", 1)[0]
                    from ..obs import get_tracer

                    # adopt the caller's trace context (X-Fugue-Trace /
                    # X-Fugue-Parent): spans below land under the
                    # submitting run instead of floating as local roots
                    with _scope_from_headers(self.headers):
                        if path == "/serve/submit":
                            with get_tracer().span("rpc.serve_submit", cat="rpc"):
                                self._reply(*server._serve_submit(raw))
                            return
                        if path == "/serve/cancel":
                            self._reply(*server._serve_cancel(raw))
                            return
                        if path in ("/serve/register", "/serve/unregister"):
                            made = (
                                server._serve_register(raw)
                                if path == "/serve/register"
                                else server._serve_unregister(raw)
                            )
                            if made is None:  # views disabled: bare 404
                                self.send_response(404)
                                self.end_headers()
                                return
                            self._reply(*made)
                            return
                        key, args, kwargs = cloudpickle.loads(
                            base64.b64decode(raw)
                        )
                        try:
                            with get_tracer().span("rpc.serve", cat="rpc", key=key):
                                result = (True, server.invoke(key, *args, **kwargs))
                        except Exception as e:  # result is the exception itself
                            result = (False, e)
                        body = base64.b64encode(cloudpickle.dumps(result))
                        self._reply(200, "application/octet-stream", body)
                except Exception:  # pragma: no cover - transport error
                    self.send_response(500)
                    self.end_headers()

            def do_DELETE(self) -> None:  # noqa: N802 — view retirement
                try:
                    path, _, query = self.path.partition("?")
                    made = (
                        server._serve_view_delete(query)
                        if path == "/serve/view"
                        else None
                    )
                    if made is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    self._reply(*made)
                except Exception:
                    try:
                        self.send_response(500)
                        self.end_headers()
                    except Exception:
                        pass

            def do_GET(self) -> None:  # noqa: N802 — telemetry/serve routes
                try:
                    path, _, query = self.path.partition("?")
                    with _scope_from_headers(self.headers):
                        made = server._get_body(path, query)
                        if made is None:
                            self.send_response(404)
                            self.end_headers()
                            return
                        self._reply(*made)
                except Exception:  # telemetry must never crash the server
                    try:
                        self.send_response(500)
                        self.end_headers()
                    except Exception:
                        pass

            def log_message(self, *args: Any) -> None:  # silence
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop_server(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
