"""HTTP RPC server — worker→driver callbacks over the network.

Replaces the reference's flask server (`fugue/rpc/flask.py:17` — flask is
not in this environment) with a stdlib ``ThreadingHTTPServer``. Payloads are
cloudpickle over POST. Conf keys mirror the reference:

- ``fugue.rpc.http_server.host`` (default 127.0.0.1)
- ``fugue.rpc.http_server.port`` (default 0 = ephemeral)
- ``fugue.rpc.http_server.timeout`` (client timeout seconds)
"""

import base64
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any
from urllib import request as _urlrequest

import cloudpickle

from .base import RPCClient, RPCServer


class HttpRPCClient(RPCClient):
    """Picklable client stub carrying only (host, port, key)."""

    def __init__(self, host: str, port: int, key: str, timeout: float = 30.0):
        self._host = host
        self._port = port
        self._key = key
        self._timeout = timeout

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        payload = base64.b64encode(cloudpickle.dumps((self._key, args, kwargs)))
        req = _urlrequest.Request(
            f"http://{self._host}:{self._port}/invoke",
            data=payload,
            method="POST",
        )
        with _urlrequest.urlopen(req, timeout=self._timeout) as resp:
            body = resp.read()
        ok, result = cloudpickle.loads(base64.b64decode(body))
        if not ok:
            raise result
        return result


class HttpRPCServer(RPCServer):
    """Stdlib HTTP RPC server (reference flask parity)."""

    def __init__(self, conf: Any = None):
        super().__init__(conf)
        self._host = self.conf.get("fugue.rpc.http_server.host", "127.0.0.1")
        self._port = int(self.conf.get("fugue.rpc.http_server.port", 0))
        self._timeout = float(self.conf.get("fugue.rpc.http_server.timeout", 30.0))
        self._httpd: Any = None
        self._thread: Any = None

    @property
    def host(self) -> str:
        return self._host

    @property
    def port(self) -> int:
        return self._port

    def create_client(self, key: str) -> RPCClient:
        return HttpRPCClient(self._host, self._port, key, self._timeout)

    def start_server(self) -> None:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    key, args, kwargs = cloudpickle.loads(
                        base64.b64decode(self.rfile.read(length))
                    )
                    try:
                        result = (True, server.invoke(key, *args, **kwargs))
                    except Exception as e:  # result is the exception itself
                        result = (False, e)
                    body = base64.b64encode(cloudpickle.dumps(result))
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception:  # pragma: no cover - transport error
                    self.send_response(500)
                    self.end_headers()

            def log_message(self, *args: Any) -> None:  # silence
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop_server(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
