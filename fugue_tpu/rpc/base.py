"""RPC: worker→driver callback channel.

Parity with the reference (`fugue/rpc/base.py:11,18,105,197,221,268`):
``RPCHandler`` wraps driver-side callables; ``RPCServer`` hands out
``RPCClient`` stubs that serialize into workers and call back into the
driver. ``NativeRPCServer`` is the in-process implementation; an HTTP
implementation lives in ``fugue_tpu/rpc/http.py`` (stdlib, no flask in this
environment).
"""

import pickle
import uuid
from threading import RLock
from typing import Any, Callable, Dict, Optional

from .._utils.assertion import assert_or_throw
from .._utils.convert import to_type
from .._utils.hash import to_uuid
from .._utils.params import ParamDict
from ..exceptions import FugueInvalidOperation


class RPCClient:
    """Stub callable on workers; routes back to a driver-side handler."""

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError


class RPCHandler(RPCClient):
    """Driver-side callback handler with a start/stop lifecycle."""

    def __init__(self):
        self._lock = RLock()
        self._running = 0

    @property
    def running(self) -> bool:
        return self._running > 0

    def __uuid__(self) -> str:
        return to_uuid(str(type(self)), id(self))

    def start_handler(self) -> None:
        """Subclass hook."""

    def stop_handler(self) -> None:
        """Subclass hook."""

    def start(self) -> "RPCHandler":
        with self._lock:
            if self._running == 0:
                self.start_handler()
            self._running += 1
        return self

    def stop(self) -> None:
        with self._lock:
            if self._running == 1:
                self.stop_handler()
            self._running = max(0, self._running - 1)

    def __enter__(self) -> "RPCHandler":
        assert_or_throw(
            self._running > 0,
            FugueInvalidOperation("use RPCHandler.start() before entering"),
        )
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> None:
        self.stop()

    def __getstate__(self) -> Any:
        raise pickle.PicklingError(f"{self} is not serializable")


class EmptyRPCHandler(RPCHandler):
    """The handler representing "no callback"."""

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise FugueInvalidOperation("no RPC callback was set")


class RPCFunc(RPCHandler):
    """Wrap a plain callable as a handler (reference ``:197``)."""

    def __init__(self, func: Callable):
        super().__init__()
        assert_or_throw(callable(func), FugueInvalidOperation(f"{func} is not callable"))
        self._func = func

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._func(*args, **kwargs)


def to_rpc_handler(obj: Any) -> RPCHandler:
    if obj is None:
        return EmptyRPCHandler()
    if isinstance(obj, RPCHandler):
        return obj
    if callable(obj):
        return RPCFunc(obj)
    raise ValueError(f"can't convert {obj} to RPCHandler")


class RPCServer(RPCHandler):
    """Manages handlers and creates worker-side clients (reference ``:105``)."""

    def __init__(self, conf: Any = None):
        super().__init__()
        self._conf = ParamDict(conf)
        self._handlers: Dict[str, RPCHandler] = {}
        self._server_lock = RLock()

    @property
    def conf(self) -> ParamDict:
        return self._conf

    def invoke(self, key: str, *args: Any, **kwargs: Any) -> Any:
        with self._server_lock:
            handler = self._handlers[key]
        return handler(*args, **kwargs)

    def register(self, handler: Any) -> str:
        with self._server_lock:
            key = "_" + str(uuid.uuid4()).split("-")[-1]
            assert_or_throw(key not in self._handlers, FugueInvalidOperation(key))
            self._handlers[key] = to_rpc_handler(handler).start()
            return key

    def make_client(self, handler: Any) -> RPCClient:
        key = self.register(handler)
        return self.create_client(key)

    def create_client(self, key: str) -> RPCClient:
        """Create the serializable stub for a registered handler."""
        raise NotImplementedError

    def start_server(self) -> None:
        """Subclass hook."""

    def stop_server(self) -> None:
        """Subclass hook."""

    def start_handler(self) -> None:
        self.start_server()

    def stop_handler(self) -> None:
        self.stop_server()
        with self._server_lock:
            for h in self._handlers.values():
                h.stop()
            self._handlers.clear()


class NativeRPCClient(RPCClient):
    """In-process client; holds only the key, resolves through the server."""

    def __init__(self, server: "NativeRPCServer", key: str):
        self._key = key
        self._server = server

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._server.invoke(self._key, *args, **kwargs)

    def __getstate__(self) -> Any:
        raise pickle.PicklingError(f"{self} is not serializable")


class NativeRPCServer(RPCServer):
    """In-process RPC server (reference ``:221``)."""

    def create_client(self, key: str) -> RPCClient:
        return NativeRPCClient(self, key)


def make_rpc_server(conf: Any = None) -> RPCServer:
    """Build the configured RPC server (conf key ``fugue.rpc.server``)."""
    conf = ParamDict(conf)
    tp = conf.get_or_none("fugue.rpc.server", str)
    t_server = NativeRPCServer if tp is None else to_type(tp, RPCServer)
    return t_server(conf)  # type: ignore
