"""Functional Dataset API (plugin-dispatched).

Parity with the reference (`fugue/dataset/api.py`).
"""

from typing import Any, Optional

from .._utils.registry import fugue_plugin
from .dataset import Dataset


@fugue_plugin
def as_fugue_dataset(data: Any, **kwargs: Any) -> Dataset:
    """Convert any supported object to a Dataset (plugin hook)."""
    if isinstance(data, Dataset):
        return data
    from ..dataframe.api import as_fugue_df

    return as_fugue_df(data, **kwargs)


def count(data: Any) -> int:
    return as_fugue_dataset(data).count()


def is_empty(data: Any) -> bool:
    return as_fugue_dataset(data).empty


def is_local(data: Any) -> bool:
    return as_fugue_dataset(data).is_local


def is_bounded(data: Any) -> bool:
    return as_fugue_dataset(data).is_bounded


def get_num_partitions(data: Any) -> int:
    return as_fugue_dataset(data).num_partitions


def show(data: Any, n: int = 10, with_count: bool = False, title: Optional[str] = None) -> None:
    as_fugue_dataset(data).show(n=n, with_count=with_count, title=title)
