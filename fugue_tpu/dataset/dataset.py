"""Dataset — the root abstraction for any data collection.

Parity with the reference (`fugue/dataset/dataset.py:14-110`): metadata,
locality/boundedness flags, counting, and a pluggable display. DataFrame and
Bag both derive from this.
"""

from abc import ABC, abstractmethod
from typing import Any, Optional

from .._utils.hash import to_uuid
from .._utils.params import ParamDict
from .._utils.registry import fugue_plugin
from ..exceptions import FugueDatasetEmptyError


class Dataset(ABC):
    """An abstract collection of data with metadata."""

    def __init__(self):
        self._metadata: Optional[ParamDict] = None

    @property
    def metadata(self) -> ParamDict:
        if self._metadata is None:
            self._metadata = ParamDict()
        return self._metadata

    @property
    def has_metadata(self) -> bool:
        return self._metadata is not None and len(self._metadata) > 0

    def reset_metadata(self, metadata: Any) -> None:
        self._metadata = ParamDict(metadata) if metadata is not None else None

    @property
    def native(self) -> Any:
        """The underlying object this dataset wraps (self if none)."""
        return self

    @property
    @abstractmethod
    def is_local(self) -> bool:
        """Whether the data fully resides in the driver process."""
        raise NotImplementedError

    @property
    @abstractmethod
    def is_bounded(self) -> bool:
        """Whether the data size is known/finite."""
        raise NotImplementedError

    @property
    @abstractmethod
    def num_partitions(self) -> int:
        """Number of physical partitions (1 for local data)."""
        raise NotImplementedError

    @property
    @abstractmethod
    def empty(self) -> bool:
        raise NotImplementedError

    @abstractmethod
    def count(self) -> int:
        raise NotImplementedError

    def assert_not_empty(self) -> None:
        if self.empty:
            raise FugueDatasetEmptyError("dataset is empty")

    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:
        get_dataset_display(self).show(n=n, with_count=with_count, title=title)

    def _repr_html_(self) -> str:
        """Rich rendering hook (notebooks) via the display plugin chain
        (reference ``fugue/dataset/dataset.py`` repr_html)."""
        return get_dataset_display(self).repr_html()

    def __uuid__(self) -> str:
        # intentionally object-identity based: a raw in-memory dataset is NOT
        # cross-run deterministic, so workflow nodes rooted on one never
        # false-hit a deterministic checkpoint (reference semantics; true
        # resume is for creator-rooted chains and literal data)
        return to_uuid(str(type(self)), id(self))


class DatasetDisplay(ABC):
    """Pluggable renderer for :meth:`Dataset.show`.

    Reference: ``fugue/dataset/dataset.py:151`` display plugin chain.
    """

    def __init__(self, ds: Dataset):
        self._ds = ds

    @abstractmethod
    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:
        raise NotImplementedError

    def repr(self) -> str:
        return str(type(self._ds).__name__)

    def repr_html(self) -> str:
        return "<pre>" + self.repr() + "</pre>"


@fugue_plugin
def get_dataset_display(ds: Dataset) -> DatasetDisplay:
    """Resolve the display implementation for a dataset (plugin hook)."""
    raise NotImplementedError(f"no display registered for {type(ds)}")
