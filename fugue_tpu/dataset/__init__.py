from .dataset import Dataset, DatasetDisplay, get_dataset_display
from .api import (
    as_fugue_dataset,
    count,
    get_num_partitions,
    is_bounded,
    is_empty,
    is_local,
    show,
)

__all__ = [
    "Dataset",
    "DatasetDisplay",
    "get_dataset_display",
    "as_fugue_dataset",
    "count",
    "get_num_partitions",
    "is_bounded",
    "is_empty",
    "is_local",
    "show",
]
