"""Per-group reduction helpers for compiled keyed transformers.

A jax-annotated transformer with ``partition_by`` receives its shard's
columns as ``Dict[str, jax.Array]`` plus reserved arrays describing the
grouping. The engine picks one of two physical plans:

- **dense** (no presort, integer keys with a bounded value range): segment
  ids are globally consistent dense bucket ids; rows stay in place and
  groups SPAN shards, so per-group tables must merge across shards with a
  collective.
- **sorted** (everything else): rows are hash-co-located and shard-sorted;
  segment ids are shard-local and every group is complete on its shard —
  no collective needed.

These helpers encode the plan difference ONCE so the same transformer runs
correctly under either plan — always reduce through ``group_ops``, never
with raw ``jax.ops.segment_*`` (raw ops silently under-merge in the dense
plan). The plan is visible at trace time through reserved dict keys, so the
branch costs nothing at runtime.

Example (demean per group)::

    from fugue_tpu.jax import group_ops as go

    def demean(cols: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        mean = go.mean(cols, cols["v"])
        return {"k": cols["k"], "v": cols["v"],
                "d": cols["v"] - go.per_row(cols, mean)}

String (dictionary-encoded) partition keys are admitted: the UDF sees
their int32 CODES (-1 = NULL), which group exactly; treat them as opaque
— pass them through to the output unchanged and the engine reattaches
the dictionary. Interpreting code values inside the UDF is undefined.

Reference parity: this is the device-native group-map path, replacing the
reference's per-group pandas apply (``fugue_spark/execution_engine.py:192``).
"""

from typing import Any, Dict

SEGMENTS = "__segments__"
VALID = "__valid__"
# dense-plan markers (present in cols only under the dense plan)
SEGMENT_SPACE = "__segment_space__"  # dummy array; shape[0] = id space size
SPANS_SHARDS = "__segments_span_shards__"


def num_segments(cols: Dict[str, Any]) -> int:
    """Static upper bound of the segment-id space (for ``num_segments=``)."""
    if SEGMENT_SPACE in cols:
        return cols[SEGMENT_SPACE].shape[0]
    return cols[SEGMENTS].shape[0]


def _merge(cols: Dict[str, Any], table: Any, kind: str) -> Any:
    if SPANS_SHARDS in cols:
        from ..ops import collectives
        from ..parallel.mesh import ROW_AXIS

        op = {
            "sum": collectives.psum,
            "min": collectives.pmin,
            "max": collectives.pmax,
        }[kind]
        table = op(table, ROW_AXIS)
    return table


def segment_sum(cols: Dict[str, Any], x: Any) -> Any:
    """Per-group sum of ``x`` (padding/invalid rows excluded) — returns the
    group table (index with ``per_row`` to broadcast back)."""
    import jax.numpy as jnp
    from jax.ops import segment_sum as _ss

    xv = jnp.where(cols[VALID], x, jnp.zeros((), dtype=x.dtype))
    return _merge(
        cols, _ss(xv, cols[SEGMENTS], num_segments=num_segments(cols)), "sum"
    )


def segment_count(cols: Dict[str, Any], dtype: Any = None) -> Any:
    """Per-group count of valid rows."""
    import jax.numpy as jnp

    dt = dtype if dtype is not None else jnp.float64
    return segment_sum(cols, cols[VALID].astype(dt))


def segment_min(cols: Dict[str, Any], x: Any) -> Any:
    import jax.numpy as jnp
    from jax.ops import segment_min as _sm

    fill = jnp.array(_minmax_identity(jnp, x.dtype, "min"), dtype=x.dtype)
    xv = jnp.where(cols[VALID], x, fill)
    return _merge(
        cols, _sm(xv, cols[SEGMENTS], num_segments=num_segments(cols)), "min"
    )


def segment_max(cols: Dict[str, Any], x: Any) -> Any:
    import jax.numpy as jnp
    from jax.ops import segment_max as _sm

    fill = jnp.array(_minmax_identity(jnp, x.dtype, "max"), dtype=x.dtype)
    xv = jnp.where(cols[VALID], x, fill)
    return _merge(
        cols, _sm(xv, cols[SEGMENTS], num_segments=num_segments(cols)), "max"
    )


def mean(cols: Dict[str, Any], x: Any) -> Any:
    """Per-group mean of ``x`` over valid rows."""
    import jax.numpy as jnp

    s = segment_sum(cols, x)
    c = segment_count(cols, dtype=x.dtype)
    return s / jnp.maximum(c, jnp.ones((), dtype=c.dtype))


def per_row(cols: Dict[str, Any], table: Any) -> Any:
    """Broadcast a group table back to rows (``table[segment_id]``)."""
    return table[cols[SEGMENTS]]


def _require_ordered(cols: Dict[str, Any], what: str) -> None:
    if SPANS_SHARDS in cols:
        from ..exceptions import FugueInvalidOperation

        raise FugueInvalidOperation(
            f"{what} needs ordered, shard-complete groups (the sorted plan);"
            " the dense plan leaves groups spanning shards in input order."
            " Add a presort to the partition spec to force the sorted plan."
        )


def running_sum(cols: Dict[str, Any], x: Any) -> Any:
    """Per-row RUNNING sum of ``x`` within its group, in sort order — the
    ``SUM(...) OVER (PARTITION BY k ORDER BY ... ROWS UNBOUNDED PRECEDING)``
    window kernel. Sorted-plan only (groups must be contiguous + ordered);
    invalid/padding rows contribute 0. Row-aligned output."""
    import jax.numpy as jnp

    _require_ordered(cols, "running_sum")
    # accumulate in the widest type: a global f32/i32 prefix sum would
    # leak the SHARD's absolute rounding/overflow into every group's
    # c - base subtraction; the result casts back at the end
    acc_dt = (
        jnp.float64 if jnp.issubdtype(x.dtype, jnp.floating) else jnp.int64
    )
    xv = jnp.where(cols[VALID], x, jnp.zeros((), dtype=x.dtype)).astype(acc_dt)
    c = jnp.cumsum(xv)
    # first row index of each segment -> the cumsum base to subtract
    idx = jnp.arange(c.shape[0])
    from jax.ops import segment_min as _sm

    first = _sm(idx, cols[SEGMENTS], num_segments=num_segments(cols))
    firstc = jnp.where(
        cols[VALID], c[first[cols[SEGMENTS]]] - xv[first[cols[SEGMENTS]]], 0
    )
    run = jnp.where(cols[VALID], c - firstc, jnp.zeros((), dtype=acc_dt))
    return run.astype(x.dtype)


def row_number(cols: Dict[str, Any], dtype: Any = None) -> Any:
    """Per-row 1-based position within its group, in sort order — the
    ``ROW_NUMBER() OVER (PARTITION BY k ORDER BY ...)`` window kernel.
    Sorted-plan only. Row-aligned output."""
    import jax.numpy as jnp

    _require_ordered(cols, "row_number")
    dt = dtype if dtype is not None else jnp.int64
    return running_sum(cols, cols[VALID].astype(dt))


def _minmax_identity(jnp: Any, dtype: Any, kind: str) -> Any:
    """The min/max identity for ``dtype`` (shared by segment_* and
    running_* kernels)."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if kind == "min" else -jnp.inf
    if dtype == jnp.bool_:
        return True if kind == "min" else False
    ii = jnp.iinfo(dtype)
    return ii.max if kind == "min" else ii.min


def _segmented_scan(cols: Dict[str, Any], x: Any, combine: Any, identity: Any) -> Any:
    """Generic inclusive per-group scan via ``lax.associative_scan`` over
    (value, segment-start flag) pairs — the classic segmented-scan
    construction: a start flag resets the accumulation. NaN inputs (the
    device NULL) are masked to the identity, matching the engine's SQL
    window semantics (NULLs are skipped, not propagated)."""
    import jax
    import jax.numpy as jnp

    seg = cols[SEGMENTS]
    start = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), seg[1:] != seg[:-1]]
    )
    ident = jnp.full((), identity, dtype=x.dtype)
    mask = cols[VALID]
    is_float = jnp.issubdtype(x.dtype, jnp.floating)
    if is_float:
        mask = mask & jnp.logical_not(jnp.isnan(x))
    xv = jnp.where(mask, x, ident)

    def op(a, b):
        av, am, af = a
        bv, bm, bf = b
        return (
            jnp.where(bf, bv, combine(av, bv)),
            jnp.where(bf, bm, am | bm),  # any non-NULL value seen so far
            af | bf,
        )

    out, seen, _ = jax.lax.associative_scan(op, (xv, mask, start))
    if is_float:
        # a frame with no non-NULL values yet is NULL (SQL), not the
        # scan identity — e.g. the leading NULL row's own running MIN
        out = jnp.where(seen, out, jnp.nan)
    return jnp.where(cols[VALID], out, ident)


def running_min(cols: Dict[str, Any], x: Any) -> Any:
    """Per-row running MIN within its group, in sort order (the
    ``MIN(...) OVER (... ROWS UNBOUNDED PRECEDING)`` kernel); NaN (NULL)
    inputs are skipped, SQL-style. Sorted-plan only."""
    import jax.numpy as jnp

    _require_ordered(cols, "running_min")
    return _segmented_scan(
        cols, x, jnp.minimum, _minmax_identity(jnp, x.dtype, "min")
    )


def running_max(cols: Dict[str, Any], x: Any) -> Any:
    """Per-row running MAX within its group, in sort order; NaN (NULL)
    inputs are skipped, SQL-style. Sorted-plan only."""
    import jax.numpy as jnp

    _require_ordered(cols, "running_max")
    return _segmented_scan(
        cols, x, jnp.maximum, _minmax_identity(jnp, x.dtype, "max")
    )


def _shift(cols: Dict[str, Any], x: Any, n: int, fill: Any, forward: bool) -> Any:
    """Shared LAG/LEAD body: shift ``x`` by ``n`` rows within its group."""
    import jax.numpy as jnp

    from .._utils.assertion import assert_or_throw
    from ..exceptions import FugueInvalidOperation

    assert_or_throw(
        isinstance(n, int) and n >= 1,
        FugueInvalidOperation(f"lag/lead offset must be an int >= 1, got {n!r}"),
    )
    if fill is None:
        assert_or_throw(
            jnp.issubdtype(x.dtype, jnp.floating),
            FugueInvalidOperation(
                "lag/lead over a non-float column needs an explicit fill "
                "value (there is no integer NULL on this path; a silent 0 "
                "would be indistinguishable from data)"
            ),
        )
        fill = jnp.nan
    fv = jnp.full((), fill, dtype=x.dtype)
    seg = cols[SEGMENTS]
    pad_v = jnp.full((n,), fv)
    pad_s = jnp.full((n,), -1, dtype=seg.dtype)
    if forward:  # lag: value from n rows EARLIER
        shifted = jnp.concatenate([pad_v, x[:-n]])
        seg_shift = jnp.concatenate([pad_s, seg[:-n]])
    else:  # lead: value from n rows LATER
        shifted = jnp.concatenate([x[n:], pad_v])
        seg_shift = jnp.concatenate([seg[n:], pad_s])
    ok = (seg_shift == seg) & cols[VALID]
    return jnp.where(ok, shifted, fv)


def lag(cols: Dict[str, Any], x: Any, n: int = 1, fill: Any = None) -> Any:
    """Value of ``x`` ``n`` rows EARLIER within the same group (SQL
    ``LAG(x, n)``); rows with no predecessor get ``fill`` (NaN for floats
    when unset; non-float columns require an explicit fill).
    Sorted-plan only."""
    _require_ordered(cols, "lag")
    return _shift(cols, x, n, fill, forward=True)


def lead(cols: Dict[str, Any], x: Any, n: int = 1, fill: Any = None) -> Any:
    """Value of ``x`` ``n`` rows LATER within the same group (SQL
    ``LEAD(x, n)``); non-float columns require an explicit fill.
    Sorted-plan only."""
    _require_ordered(cols, "lead")
    return _shift(cols, x, n, fill, forward=False)
