"""Back-compat shim: the Dict[str, jax.Array] annotated param now lives in
``fugue_tpu.jax_annotations`` so it registers at package import without
pulling in jax itself."""

from ..jax_annotations import JaxDictParam  # noqa: F401
