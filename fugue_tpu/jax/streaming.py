"""Streaming (out-of-core) device execution — SURVEY §5.7's TPU answer.

The reference never materializes a whole partition when it can stream:
Spark's pandas-UDF path iterates record batches through the executor
(`/root/reference/fugue_spark/execution_engine.py:262-294`) and chunked
map outputs flow as `LocalDataFrameIterableDataFrame`
(`/root/reference/fugue/dataframe/dataframe_iterable_dataframe.py:21`).
A `JaxDataFrame` instead puts every column fully on device, capping the
engine at HBM (~16GB on a v5e chip). This module removes that cap for
the engine verbs:

- **aggregate** — `streaming_dense_aggregate`: arrow/pandas chunks feed
  the dense-bucket groupby kernel (`ops/segment.py`) one fixed-capacity
  device batch at a time; per-bucket SUM/COUNT/MIN/MAX tables are
  DEVICE-RESIDENT accumulators merged chunk-by-chunk in one jitted step
  (donated, so XLA updates them in place). Device working set =
  O(chunk_rows × columns + buckets), independent of dataset size — the
  road to the 1B-row north star (`BASELINE.json`, NORTH_STAR.json).
- **transform** — `streaming_compiled_map`: a jax-annotated row-wise UDF
  compiled ONCE for a fixed chunk capacity, applied chunk-wise; outputs
  stream back to the host as a one-pass `LocalDataFrameIterableDataFrame`
  so neither input nor output ever fully materializes on device.
- **keyed transform / windows** — `streaming_keyed_compiled_map`: keyed
  compiled maps over KEY-CLUSTERED streams; chunks re-batch at key
  boundaries so groups stay whole, each batch runs the regular keyed
  map at one fixed capacity. With `group_ops.running_sum`/`row_number`
  this is the running-window kernel over key-partitioned streams.
- **join** — `streaming_hash_join`: stream ⋈ dimension table; sorted
  build keys replicated on device, per-chunk `searchsorted` probe,
  payloads host-side (any dtype, NULLs intact).
- **take / distinct** — running top-n / running-dedupe buffers, memory
  O(output + chunk); unsorted global take early-stops the stream.

Every path bounds device memory by `fugue.tpu.stream.chunk_rows`
(default 2^20 rows). `last_run_stats` records the measured peak live
device bytes of the most recent streaming run so tests (and users) can
PROVE the bound held.
"""

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..constants import (
    FUGUE_TPU_CONF_STREAM_CHUNK_ROWS,
    FUGUE_TPU_CONF_STREAM_KEY_RANGE,
)
from ..dataframe import (
    ArrowDataFrame,
    DataFrame,
    IterableDataFrame,
    LocalDataFrame,
    LocalDataFrameIterableDataFrame,
    PandasDataFrame,
)
from ..exceptions import FugueInvalidOperation
from ..schema import Schema
from .._utils.jax_compat import shard_map

DEFAULT_CHUNK_ROWS = 1 << 20

# peak live device bytes + chunk count of the most recent streaming run —
# the proof artifact that out-of-core execution really is out-of-core
last_run_stats: Dict[str, Any] = {}


def is_stream_frame(df: Any) -> bool:
    """Frames that are one-pass row streams (must NOT be materialized)."""
    return isinstance(df, (IterableDataFrame, LocalDataFrameIterableDataFrame))


def stream_parquet(
    path: Any, columns: Optional[List[str]] = None, chunk_rows: int = DEFAULT_CHUNK_ROWS
) -> LocalDataFrameIterableDataFrame:
    """Open parquet file(s) as a one-pass stream of arrow chunks — the
    out-of-core loader (datasets ≫ host/device memory never materialize).
    """
    import pyarrow.parquet as pq

    paths = [path] if isinstance(path, str) else list(path)
    first_schema = pq.ParquetFile(paths[0]).schema_arrow
    if columns is not None:
        first_schema = pa.schema([first_schema.field(c) for c in columns])

    def gen() -> Iterator[pa.Table]:
        for p in paths:
            f = pq.ParquetFile(p)
            for batch in f.iter_batches(batch_size=chunk_rows, columns=columns):
                yield pa.Table.from_batches([batch])

    return LocalDataFrameIterableDataFrame(
        (ArrowDataFrame(t) for t in gen()), schema=Schema(first_schema)
    )


# --------------------------------------------------------------------------
# chunk normalization: any stream frame -> iterator of column dicts
# --------------------------------------------------------------------------


def _iter_local_frames(df: Any, chunk_rows: int) -> Iterator[LocalDataFrame]:
    if isinstance(df, LocalDataFrameIterableDataFrame):
        yield from df.native
    elif isinstance(df, IterableDataFrame):
        # row stream -> bounded row batches
        from itertools import islice

        it = iter(df.native)
        schema = df.schema
        while True:
            rows = list(islice(it, chunk_rows))
            if len(rows) == 0:
                return
            from ..dataframe import ArrayDataFrame

            yield ArrayDataFrame(rows, schema)
    elif isinstance(df, DataFrame):
        yield df.as_local_bounded()
    else:
        raise FugueInvalidOperation(f"can't stream from {type(df)}")


def _rechunk(
    frames: Iterator[LocalDataFrame], capacity: int
) -> Iterator[LocalDataFrame]:
    """Split oversized chunks so no device batch exceeds ``capacity``
    (undersized chunks pass through; padding absorbs them)."""
    for f in frames:
        n = f.count()
        if n <= capacity:
            if n > 0:
                yield f
            continue
        if isinstance(f, ArrowDataFrame):
            tbl = f.native
            for s in range(0, n, capacity):
                yield ArrowDataFrame(tbl.slice(s, min(capacity, n - s)))
        else:
            pdf = f.as_pandas()
            for s in range(0, n, capacity):
                yield PandasDataFrame(
                    pdf.iloc[s : s + capacity], f.schema
                )


def _chunk_columns(
    f: LocalDataFrame, names: List[str]
) -> Tuple[int, Dict[str, np.ndarray], Dict[str, int]]:
    """(row_count, {name: numpy}, {name: null_count}) for one chunk.

    Float nulls surface as NaN (the device NULL); int/bool null counts are
    returned so the caller can reject them (the streaming plan has no mask
    channel — a later chunk must not silently change the type contract
    the first chunk established).
    """
    cols: Dict[str, np.ndarray] = {}
    nulls: Dict[str, int] = {}
    if isinstance(f, ArrowDataFrame):
        tbl = f.native
        n = tbl.num_rows
        for name in names:
            col = tbl.column(name)
            nulls[name] = col.null_count
            cols[name] = np.asarray(col.to_numpy(zero_copy_only=False))
    else:
        pdf = f.as_pandas()
        n = len(pdf)
        for name in names:
            s = pdf[name]
            dt = s.dtype
            if isinstance(dt, np.dtype) and dt.kind in "iubf":
                # plain numpy int/uint/bool cannot hold NULL, and float NaN
                # IS the device NULL — skip the O(n) isna scan either way
                nulls[name] = 0
            else:
                nulls[name] = int(s.isna().sum())
            cols[name] = s.to_numpy()
    return n, cols, nulls


def _device_peak_bytes() -> int:
    import jax

    return sum(
        a.nbytes for a in jax.live_arrays() if getattr(a, "is_deleted", lambda: False)() is False
    )


def _closing(chunks_it: Any) -> Iterator[Any]:
    """Consume a (possibly prefetched) chunk iterator, guaranteeing its
    producer thread is stopped on exhaustion, error, or an abandoned
    downstream generator (GeneratorExit reaches the finally)."""
    try:
        yield from chunks_it
    finally:
        chunks_it.close()


def _prefetched_pandas_chunks(
    engine: Any, df: Any, chunk_rows: int, verb: str, tune: Any = None
) -> Any:
    """The host-side chunk pipeline: decode chunks to pandas in the
    background thread while the caller consumes — used by the paths whose
    per-chunk device work happens downstream (keyed map, take, distinct,
    join probe)."""
    from .pipeline import engine_prefetcher

    frames = _maybe_coalesce(_iter_local_frames(df, chunk_rows), chunk_rows, tune)
    return engine_prefetcher(
        engine,
        (f.as_pandas() for f in frames),
        verb,
    )


def _tuned_chunk_rows(engine: Any, verb: str) -> Tuple[int, Any]:
    """Resolve one stream's chunk size: the static
    ``fugue.tpu.stream.chunk_rows`` conf, overridden by the adaptive
    tuner (``fugue_tpu/tuning``, docs/tuning.md) when an enabled run
    scope holds observations for this plan fingerprint. The returned
    handle also reaches ``engine_prefetcher`` (same verb, same run) for
    the learned prefetch depth and the telemetry feedback; outside a run
    scope — direct engine calls, ``fugue.tpu.tuning.enabled=false`` —
    this is exactly the old static resolution."""
    static = int(
        engine.conf.get(FUGUE_TPU_CONF_STREAM_CHUNK_ROWS, DEFAULT_CHUNK_ROWS)
    )
    tuner = getattr(engine, "tuner", None)
    if tuner is None:
        return static, None
    h = tuner.stream_params(verb, static)
    if h is None:
        return static, None
    return int(h.chunk_rows), h


def _maybe_coalesce(
    frames: Iterator[LocalDataFrame], target_rows: int, tune: Any
) -> Iterator[LocalDataFrame]:
    """Merge undersized source chunks up to ``target_rows`` when an
    ADAPTIVE chunk setting asks for it (``_rechunk`` only splits —
    without this, a source pre-chunked smaller than the tuned size would
    keep its per-chunk overhead no matter what the tuner learned). The
    static path never coalesces: pre-tuning chunk shapes stay
    bit-identical."""
    if tune is None or not getattr(tune, "coalesce", False) or target_rows <= 0:
        yield from frames
        return
    buf: List[LocalDataFrame] = []
    have = 0
    for f in frames:
        n = f.count()
        if n <= 0:
            continue
        if n >= target_rows and not buf:
            yield f
            continue
        buf.append(f)
        have += n
        if have >= target_rows:
            yield _concat_local(buf)
            buf, have = [], 0
    if buf:
        yield buf[0] if len(buf) == 1 else _concat_local(buf)


def _concat_local(frames: List[LocalDataFrame]) -> LocalDataFrame:
    """One frame from many (same schema — one stream's chunks)."""
    if all(isinstance(f, ArrowDataFrame) for f in frames):
        try:
            return ArrowDataFrame(pa.concat_tables([f.native for f in frames]))
        except Exception:
            pass
    import pandas as _pd

    return PandasDataFrame(
        _pd.concat([f.as_pandas() for f in frames], ignore_index=True),
        frames[0].schema,
    )


# --------------------------------------------------------------------------
# streaming dense aggregate
# --------------------------------------------------------------------------


def _fold_dense_acc(agg_sig: Tuple, acc: Tuple, outs: Tuple) -> Tuple:
    """Merge one chunk's dense-kernel output tables into the running
    device accumulators — the single fold used by the streaming aggregate
    AND the lowered-segment program (they must stay in lockstep: NaN is
    the merge identity for nullable floats, plain adds / min / max
    otherwise)."""
    import jax.numpy as jnp

    new = [acc[0] + outs[0]]  # present counts: plain int add
    for (name, agg, vi, nullable), a, b in zip(agg_sig, acc[1:], outs[1:]):
        if agg == "count":
            new.append(a + b)
        elif agg == "sum":
            if nullable:
                # NaN marks an all-NULL (or absent) bucket in a chunk
                # table — it is the merge identity
                new.append(
                    jnp.where(
                        jnp.isnan(a),
                        b,
                        jnp.where(jnp.isnan(b), a, a + b),
                    )
                )
            else:
                new.append(a + b)
        elif agg == "min":
            new.append(jnp.fmin(a, b) if nullable else jnp.minimum(a, b))
        elif agg == "max":
            new.append(jnp.fmax(a, b) if nullable else jnp.maximum(a, b))
        else:  # pragma: no cover - plan gates exclude others
            raise AssertionError(agg)
    return tuple(new)


def _identity_dense_acc(
    mesh: Any, buckets: int, agg_sig: Tuple, value_dtypes: List[np.dtype]
) -> Tuple:
    """Merge-identity accumulator tables, replicated on the mesh: folding
    a chunk's kernel output into these yields exactly that output, so the
    lowered-segment program needs ONE compiled step (no separate
    first-chunk program — one jit-cache entry per segment)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    arrs: List[np.ndarray] = [np.zeros(buckets, dtype=np.int64)]  # present
    for _, agg, vi, nullable in agg_sig:
        dt = value_dtypes[vi]
        if agg == "count":
            arrs.append(np.zeros(buckets, dtype=np.int64))
        elif agg == "sum":
            arrs.append(
                np.full(buckets, np.nan, dtype=dt)
                if nullable
                else np.zeros(buckets, dtype=dt)
            )
        elif agg == "min":
            arrs.append(
                np.full(buckets, np.nan, dtype=dt)
                if nullable
                else np.full(buckets, np.iinfo(dt).max, dtype=dt)
            )
        elif agg == "max":
            arrs.append(
                np.full(buckets, np.nan, dtype=dt)
                if nullable
                else np.full(buckets, np.iinfo(dt).min, dtype=dt)
            )
        else:  # pragma: no cover - plan gates exclude others
            raise AssertionError(agg)
    rep = NamedSharding(mesh, P())
    return tuple(jax.device_put(a, rep) for a in arrs)


def _finish_dense_host(
    engine: Any,
    acc: Tuple,
    agg_sig: Tuple,
    key: str,
    key_np: np.dtype,
    kmin: int,
    plan: dict,
    track: Optional[Callable[[], None]] = None,
) -> DataFrame:
    """ONE host transfer of the merged O(buckets) tables, then the host
    finish (avg = sum/count, declared dtypes/order) — shared by the
    streaming aggregate and the lowered-segment runner."""
    import jax

    for a in acc:
        a.copy_to_host_async()
    host = [np.asarray(jax.device_get(a)) for a in acc]
    if track is not None:
        track()
    present = host[0]
    (idx,) = np.nonzero(present > 0)
    merged: Dict[str, Any] = {key: idx.astype(np.int64) + kmin}
    for (name, _, _, _), table in zip(agg_sig, host[1:]):
        merged[name] = table[idx]
    mdf = pd.DataFrame(merged)
    out = pd.DataFrame()
    out[key] = mdf[key].astype(key_np)
    for spec in plan["post"]:
        out[spec["name"]] = spec["fn"](mdf)
    return engine.to_df(PandasDataFrame(out, plan["schema"]))


def _parse_key_range(conf: Any) -> Optional[Tuple[int, int]]:
    raw = conf.get_or_none(FUGUE_TPU_CONF_STREAM_KEY_RANGE, str)
    if raw is None or raw == "":
        return None
    try:
        lo, hi = (int(x) for x in str(raw).split(","))
    except Exception:
        raise FugueInvalidOperation(
            f"{FUGUE_TPU_CONF_STREAM_KEY_RANGE} must be 'lo,hi' ints, got {raw!r}"
        )
    assert_or_throw(lo <= hi, ValueError(f"empty key range {raw!r}"))
    return lo, hi


def streaming_dense_aggregate(
    engine: Any,
    df: Any,
    partition_spec: Any,
    agg_cols: List[Any],
) -> Optional[DataFrame]:
    """Keyed aggregate over a one-pass stream with device-resident
    accumulators. Returns None when the plan is ineligible (caller falls
    back to materializing) — eligibility mirrors the dense device
    aggregate: ONE plain int key with a bounded range, un-encoded numeric
    values, sum/count/avg/min/max only.
    """
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows
    from ..ops.segment import (
        _DENSE_MAX_RANGE,
        _get_compiled_dense,
        dense_buckets,
    )
    from .dataframe import JaxDataFrame
    from .execution_engine import _plan_device_agg

    keys = list(partition_spec.partition_by) if partition_spec is not None else []
    if len(keys) != 1:
        return None
    mesh = engine._mesh
    shards = num_row_shards(mesh)
    chunk_rows, tune = _tuned_chunk_rows(engine, "aggregate")
    capacity = pad_rows(max(chunk_rows, shards), shards)

    # eligibility is decided from the SCHEMA alone (via an empty probe
    # frame) BEFORE any chunk is consumed — a one-pass stream must not
    # lose its head to a plan that then falls back to materialization
    empty = pa.Table.from_pylist([], schema=Schema(df.schema).pa_schema)
    jdf0 = JaxDataFrame(ArrowDataFrame(empty), mesh=mesh)
    plan = _plan_device_agg(jdf0, keys, agg_cols)
    if (
        plan is None
        or plan["virtual"]
        or plan["dict_srcs"]
        or plan["masked_srcs"]
        or any(p.get("kind") not in ("pass", "avg") for p in plan["post"])
    ):
        return None
    key = keys[0]
    key_np = np.dtype(jdf0.device_cols[key].dtype)
    if key_np.kind not in ("i", "u"):
        return None

    srcs = sorted({s for _, _, s in plan["aggs"]})
    src_np: Dict[str, np.dtype] = {}
    for s in srcs:
        dt = np.dtype(jdf0.device_cols[s].dtype)
        if dt.kind not in ("i", "u", "f"):
            return None
        src_np[s] = dt
    del jdf0

    key_range = _parse_key_range(engine.conf)
    if key_range is not None:
        kmin, kmax = key_range
        if not (0 < kmax - kmin + 1 <= _DENSE_MAX_RANGE):
            return None  # declared range too wide for the dense plan

    # ---- the stream is consumed from here on; failures now RAISE ------
    frames = _rechunk(
        _maybe_coalesce(_iter_local_frames(df, chunk_rows), chunk_rows, tune),
        capacity,
    )
    try:
        first = next(frames)
    except StopIteration:
        # empty stream: zero groups, correctly-shaped empty result
        out0 = pd.DataFrame({n: pd.Series(dtype=object) for n in plan["schema"].names})
        return engine.to_df(PandasDataFrame(out0, plan["schema"]))

    n0, cols0, nulls0 = _chunk_columns(first, [key] + srcs)
    assert_or_throw(
        nulls0[key] == 0,
        FugueInvalidOperation(f"streaming aggregate: NULL in key column {key!r}"),
    )
    probed = key_range is None
    if probed:
        key_range = (int(cols0[key].min()), int(cols0[key].max()))
    kmin, kmax = key_range
    rng = kmax - kmin + 1
    if not (0 < rng <= _DENSE_MAX_RANGE):
        raise FugueInvalidOperation(
            f"streaming aggregate: first-chunk key range [{kmin},{kmax}] "
            f"exceeds the dense plan bound ({_DENSE_MAX_RANGE}); set "
            f"{FUGUE_TPU_CONF_STREAM_KEY_RANGE} or pre-bucket the key"
        )
    buckets = dense_buckets(rng)

    # value columns dedupe by source; floats are ALWAYS NaN-aware here — a
    # later chunk may carry NaN where the first did not
    vidx = {s: i for i, s in enumerate(srcs)}
    agg_sig = tuple(
        (name, agg, vidx[src], src_np[src].kind == "f")
        for name, agg, src in plan["aggs"]
    )
    kernel = _get_compiled_dense(mesh, buckets, agg_sig)
    sharding = NamedSharding(mesh, P(ROW_AXIS))
    kmin_s = np.int64(kmin)

    # kmin is baked into the traced step as a constant — it MUST key the
    # cache or a later stream with a shifted range would reuse a stale
    # shift and scatter into wrong buckets
    cache_key = ("stream_agg_step", mesh, buckets, agg_sig, capacity, kmin)
    cache = engine._jit_cache
    if cache_key not in cache:

        def step(acc: Tuple[Any, ...], k: Any, valid: Any, *vals: Any):
            outs = kernel(k, kmin_s, *vals, valid)
            return _fold_dense_acc(agg_sig, acc, outs)

        cache[cache_key] = jax.jit(step, donate_argnums=0)
    step_fn = cache[cache_key]

    # full-capacity chunks skip the zero+copy staging buffers entirely and
    # share ONE device-resident all-valid mask (the kernel never donates
    # its chunk inputs, so the mask is reusable across every chunk)
    full_valid_dev: List[Any] = []

    def _valid_for(n: int) -> Any:
        if n == capacity:
            if not full_valid_dev:
                full_valid_dev.append(
                    jax.device_put(np.ones(capacity, dtype=bool), sharding)
                )
            return full_valid_dev[0]
        valid = np.zeros(capacity, dtype=bool)
        valid[:n] = True
        return valid

    def put_chunk(n: int, cols: Dict[str, np.ndarray], nulls: Dict[str, int]):
        assert_or_throw(
            nulls[key] == 0,
            FugueInvalidOperation(
                f"streaming aggregate: NULL in key column {key!r}"
            ),
        )
        ck = cols[key]
        lo, hi = int(ck.min()), int(ck.max())
        if lo < kmin or hi > kmax:
            hint = (
                f"probed from the first chunk as [{kmin},{kmax}]; set "
                f"{FUGUE_TPU_CONF_STREAM_KEY_RANGE}='lo,hi' to cover the "
                "full stream"
                if probed
                else f"conf {FUGUE_TPU_CONF_STREAM_KEY_RANGE} was [{kmin},{kmax}]"
            )
            raise FugueInvalidOperation(
                f"streaming aggregate: key {key!r} value outside range "
                f"([{lo},{hi}] seen): {hint}"
            )
        full = n == capacity
        if full:
            kb = np.ascontiguousarray(ck.astype(key_np, copy=False))
        else:
            kb = np.zeros(capacity, dtype=key_np)
            kb[:n] = ck
        vals = []
        for s in srcs:
            if src_np[s].kind != "f":
                assert_or_throw(
                    nulls[s] == 0,
                    FugueInvalidOperation(
                        f"streaming aggregate: NULL in non-float column "
                        f"{s!r} (first chunk established a null-free int "
                        "contract)"
                    ),
                )
            if full:
                vb = np.ascontiguousarray(
                    cols[s].astype(src_np[s], copy=False)
                )
            else:
                vb = np.zeros(capacity, dtype=src_np[s])
                vb[:n] = cols[s].astype(src_np[s], copy=False)
            vals.append(vb)
        vd = _valid_for(n)
        put = jax.device_put([kb, vd] + vals, sharding)
        return put[0], put[1], put[2:]

    stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}

    def track() -> None:
        stats["peak_device_bytes"] = max(
            stats["peak_device_bytes"], _device_peak_bytes()
        )

    def produce() -> Iterator[Tuple[int, Any]]:
        nonlocal cols0, nulls0, first
        yield n0, put_chunk(n0, cols0, nulls0)
        cols0 = nulls0 = first = None  # release the head chunk's host copy
        for f in frames:
            n, cols, nulls = _chunk_columns(f, [key] + srcs)
            yield n, put_chunk(n, cols, nulls)

    # DOUBLE-BUFFERED ingest (ISSUE 2 tentpole): the producer thread
    # decodes + device_puts chunk i+1..i+depth while the jitted step folds
    # chunk i into the donated device accumulators
    from .pipeline import engine_prefetcher

    chunks_it = engine_prefetcher(engine, produce(), "aggregate")
    acc: Any = None
    try:
        for n, (kd, vd, ad) in chunks_it:
            if acc is None:
                acc = kernel(kd, kmin_s, *ad, vd)
            else:
                acc = step_fn(acc, kd, vd, *ad)
            stats["chunks"] += 1
            stats["rows"] += n
            del kd, vd, ad
            track()
    finally:
        chunks_it.close()

    # ONE host transfer: the merged tables (O(buckets), not O(rows))
    res = _finish_dense_host(
        engine, acc, agg_sig, key, key_np, kmin, plan, track=track
    )
    global last_run_stats
    last_run_stats = dict(stats, verb="aggregate")
    return res


# --------------------------------------------------------------------------
# lowered plan segments over one-pass streams (fugue_tpu/plan/lowering.py)
# --------------------------------------------------------------------------


def _np_dtype_of(tp: pa.DataType) -> Optional[np.dtype]:
    """Device-representable numpy dtype of an arrow type, else None."""
    try:
        if pa.types.is_boolean(tp):
            return np.dtype(bool)
        if pa.types.is_integer(tp) or pa.types.is_floating(tp):
            return np.dtype(tp.to_pandas_dtype())
    except Exception:
        return None
    return None


def _plan_lowered_chain(schema: Schema, steps: Any) -> Optional[dict]:
    """Schema-only composition of a fused step chain into its
    single-program form over RAW stream columns.

    Returns ``dict(pred, outputs, outs_by_name, need, in_np, out_np,
    schema)`` — the (possibly rewritten) Kleene-AND predicate, the output
    expressions, the input columns the program reads with their numpy
    dtypes, the EXACT device dtype of every output (zero-row eager
    probe), and the post-chain schema — or None when any step resists
    composition or device lowering. Nothing here touches data: a one-pass
    stream must not lose its head to a plan that then refuses."""
    from ..column.jax_eval import (
        can_evaluate_on_device,
        device_predicate_plan,
        evaluate_jnp,
    )
    from ..plan.fused import compose_steps
    from ..plan.ir import ALL, expr_columns

    composed = compose_steps(list(schema.names), steps)
    if composed is None:
        return None
    pred, outputs = composed
    need: set = set()
    for e in outputs:
        cols = expr_columns(e)
        if cols is ALL:
            return None
        need |= cols
    if pred is not None:
        pcols = expr_columns(pred)
        if pcols is ALL:
            return None
        need |= pcols
    in_np: Dict[str, np.dtype] = {}
    for name in sorted(need):
        if name not in schema:
            return None
        dt = _np_dtype_of(schema[name].type)
        if dt is None:
            return None
        in_np[name] = dt
    cond = None
    if pred is not None:
        p = device_predicate_plan(pred, in_np, {})
        if p is None:
            return None
        tables, cond = p
        if tables:  # pragma: no cover - raw streams carry no dict columns
            return None
    if not all(can_evaluate_on_device(e, in_np) for e in outputs):
        return None
    import jax.numpy as jnp

    zcols = {n: jnp.zeros((0,), dtype=in_np[n]) for n in sorted(need)}
    out_np: Dict[str, np.dtype] = {}
    outs_by_name: Dict[str, Any] = {}
    fields: List[pa.Field] = []
    for e in outputs:
        name = e.output_name
        if name == "" or name in outs_by_name:
            return None
        try:
            arr = jnp.asarray(evaluate_jnp(zcols, e))
        except Exception:
            return None
        out_np[name] = np.dtype(arr.dtype)
        try:
            tp = e.infer_type(schema)
        except Exception:
            tp = None
        fields.append(
            pa.field(name, tp if tp is not None else pa.from_numpy_dtype(out_np[name]))
        )
        outs_by_name[name] = e
    return dict(
        pred=cond,
        outputs=list(outputs),
        outs_by_name=outs_by_name,
        need=sorted(need),
        in_np=in_np,
        out_np=out_np,
        schema=Schema(fields),
    )


def plan_streaming_lowered_aggregate(
    engine: Any,
    df: Any,
    steps: Any,
    keys: List[str],
    agg_cols: List[Any],
    fingerprint: str,
) -> Optional[Callable[[], DataFrame]]:
    """Phase-1 (schema-only) eligibility for the flagship lowered segment:
    a fused row-local chain flowing into a dense streaming aggregate.

    Returns a zero-arg runner or None (caller falls back per-verb). The
    runner consumes the one-pass stream: the producer thread decodes and
    ``device_put``s each chunk's RAW needed columns ONCE, and a single
    jitted ``shard_map``-partitioned program — chain predicate (3-valued)
    + projections + dense-bucket kernel with in-program ``psum``/``pmin``/
    ``pmax`` cross-shard combine + accumulator fold (donated) — advances
    the device accumulators. Chunks never return to host between verbs;
    the host sees only the final O(buckets) tables. Eligibility mirrors
    the streaming dense aggregate (one plain int key, numeric un-encoded
    values, sum/count/avg/min/max) plus: every step composes and lowers
    to jnp, and the key passes through a raw input column. NOTE the key
    range and NULL contract apply to the RAW chunks — rows the fused
    filter would drop still count (the per-verb path filters first; set
    ``fugue.tpu.stream.key_range`` when that distinction matters)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..column.expressions import _NamedColumnExpr
    from ..column.jax_eval import evaluate_jnp, evaluate_jnp_3v
    from ..ops.segment import (
        _DENSE_MAX_RANGE,
        _DENSE_SUM_BACKEND,
        _get_compiled_dense,
        dense_buckets,
    )
    from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows
    from .dataframe import JaxDataFrame
    from .execution_engine import _plan_device_agg

    if len(keys) != 1 or len(steps) == 0:
        return None
    chain = _plan_lowered_chain(Schema(df.schema), steps)
    if chain is None:
        return None
    mesh = engine._mesh
    empty = pa.Table.from_pylist([], schema=chain["schema"].pa_schema)
    try:
        jdf0 = JaxDataFrame(ArrowDataFrame(empty), mesh=mesh)
    except Exception:
        return None
    plan = _plan_device_agg(jdf0, keys, agg_cols)
    if (
        plan is None
        or plan["virtual"]
        or plan["dict_srcs"]
        or plan["masked_srcs"]
        or any(p.get("kind") not in ("pass", "avg") for p in plan["post"])
    ):
        return None
    key = keys[0]
    key_expr = chain["outs_by_name"].get(key)
    if (
        not isinstance(key_expr, _NamedColumnExpr)
        or key_expr.wildcard
        or key_expr.as_type is not None
    ):
        return None  # the group key must pass through a raw input column
    raw_key = key_expr.name
    key_np = np.dtype(jdf0.device_cols[key].dtype)
    if key_np.kind not in ("i", "u") or chain["in_np"][raw_key].kind not in ("i", "u"):
        return None
    srcs = sorted({s for _, _, s in plan["aggs"]})
    src_np: Dict[str, np.dtype] = {}
    src_expr: Dict[str, Any] = {}
    for s in srcs:
        e = chain["outs_by_name"].get(s)
        if e is None:
            return None
        dt = np.dtype(jdf0.device_cols[s].dtype)
        if dt.kind not in ("i", "u", "f"):
            return None
        src_np[s] = dt
        src_expr[s] = e
    del jdf0
    key_range = _parse_key_range(engine.conf)
    if key_range is not None and not (
        0 < key_range[1] - key_range[0] + 1 <= _DENSE_MAX_RANGE
    ):
        return None  # declared range too wide for the dense plan
    cond = chain["pred"]
    needed: List[str] = chain["need"]
    in_np: Dict[str, np.dtype] = chain["in_np"]
    shards = num_row_shards(mesh)
    label = f"segment:{fingerprint or 'anon'}"
    chunk_rows, tune = _tuned_chunk_rows(engine, label)
    capacity = pad_rows(max(chunk_rows, shards), shards)
    vidx = {s: i for i, s in enumerate(srcs)}
    # value columns dedupe by source; floats are ALWAYS NaN-aware (a later
    # chunk may carry NaN where the first did not)
    agg_sig = tuple(
        (name, agg, vidx[src], src_np[src].kind == "f")
        for name, agg, src in plan["aggs"]
    )

    def run() -> DataFrame:
        # ---- the stream is consumed from here on; failures RAISE ------
        frames = _rechunk(
            _maybe_coalesce(_iter_local_frames(df, chunk_rows), chunk_rows, tune),
            capacity,
        )
        try:
            first = next(frames)
        except StopIteration:
            out0 = pd.DataFrame(
                {n: pd.Series(dtype=object) for n in plan["schema"].names}
            )
            return engine.to_df(PandasDataFrame(out0, plan["schema"]))
        n0, cols0, nulls0 = _chunk_columns(first, needed)
        assert_or_throw(
            nulls0[raw_key] == 0,
            FugueInvalidOperation(
                f"lowered segment: NULL in key column {raw_key!r}"
            ),
        )
        probed = key_range is None
        if probed:
            kmin, kmax = int(cols0[raw_key].min()), int(cols0[raw_key].max())
        else:
            kmin, kmax = key_range
        rng = kmax - kmin + 1
        if not (0 < rng <= _DENSE_MAX_RANGE):
            raise FugueInvalidOperation(
                f"lowered segment: first-chunk RAW key range [{kmin},{kmax}] "
                f"exceeds the dense plan bound ({_DENSE_MAX_RANGE}); set "
                f"{FUGUE_TPU_CONF_STREAM_KEY_RANGE}, pre-bucket the key, or "
                "disable fugue.tpu.plan.lower_segments"
            )
        buckets = dense_buckets(rng)
        kernel = _get_compiled_dense(mesh, buckets, agg_sig)
        sharding = NamedSharding(mesh, P(ROW_AXIS))
        kmin_s = np.int64(kmin)
        cache = engine._jit_cache
        # kmin is baked into the traced step as a constant — it MUST key
        # the cache (see the streaming aggregate's identical note)
        cache_key = (
            label, mesh, buckets, agg_sig, capacity, kmin, _DENSE_SUM_BACKEND[0]
        )
        if cache_key not in cache:

            def seg_step(acc: Tuple[Any, ...], valid: Any, *arrs: Any):
                import jax.numpy as jnp

                cols = dict(zip(needed, arrs))
                v = valid
                if cond is not None:
                    pv, nl = evaluate_jnp_3v(cols, {}, {}, cond, frozenset())
                    v = v & jnp.asarray(pv, dtype=bool) & jnp.logical_not(nl)
                karr = jnp.asarray(cols[raw_key]).astype(key_np)
                vals = []
                for s in srcs:
                    a = evaluate_jnp(cols, src_expr[s])
                    if not hasattr(a, "shape") or getattr(a, "ndim", 0) == 0:
                        a = jnp.full((capacity,), a)
                    vals.append(jnp.asarray(a).astype(src_np[s]))
                outs = kernel(karr, kmin_s, *vals, v)
                return _fold_dense_acc(agg_sig, acc, outs)

            cache[cache_key] = jax.jit(seg_step, donate_argnums=0)
        step_fn = cache[cache_key]
        acc: Any = _identity_dense_acc(
            mesh, buckets, agg_sig, [src_np[s] for s in srcs]
        )
        full_valid_dev: List[Any] = []

        def _valid_for(n: int) -> Any:
            if n == capacity:
                if not full_valid_dev:
                    full_valid_dev.append(
                        jax.device_put(np.ones(capacity, dtype=bool), sharding)
                    )
                return full_valid_dev[0]
            valid = np.zeros(capacity, dtype=bool)
            valid[:n] = True
            return valid

        def put_chunk(n: int, cols: Dict[str, np.ndarray], nulls: Dict[str, int]):
            assert_or_throw(
                nulls[raw_key] == 0,
                FugueInvalidOperation(
                    f"lowered segment: NULL in key column {raw_key!r}"
                ),
            )
            ck = cols[raw_key]
            lo, hi = int(ck.min()), int(ck.max())
            if lo < kmin or hi > kmax:
                hint = (
                    f"probed from the first RAW chunk as [{kmin},{kmax}]; "
                    f"set {FUGUE_TPU_CONF_STREAM_KEY_RANGE}='lo,hi' to "
                    "cover the full stream"
                    if probed
                    else f"conf {FUGUE_TPU_CONF_STREAM_KEY_RANGE} was "
                    f"[{kmin},{kmax}]"
                )
                raise FugueInvalidOperation(
                    f"lowered segment: key {raw_key!r} value outside range "
                    f"([{lo},{hi}] seen): {hint}"
                )
            full = n == capacity
            bufs = []
            for name in needed:
                dt = in_np[name]
                if dt.kind != "f":
                    assert_or_throw(
                        nulls[name] == 0,
                        FugueInvalidOperation(
                            f"lowered segment: NULL in non-float column "
                            f"{name!r} (RAW chunks feed the device program; "
                            "rows the fused filter would drop still count)"
                        ),
                    )
                if full:
                    b = np.ascontiguousarray(cols[name].astype(dt, copy=False))
                else:
                    b = np.zeros(capacity, dtype=dt)
                    b[:n] = cols[name].astype(dt, copy=False)
                bufs.append(b)
            vd = _valid_for(n)
            put = jax.device_put([vd] + bufs, sharding)
            return put[0], tuple(put[1:])

        stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}

        def track() -> None:
            stats["peak_device_bytes"] = max(
                stats["peak_device_bytes"], _device_peak_bytes()
            )

        def produce() -> Iterator[Tuple[int, Any]]:
            nonlocal cols0, nulls0, first
            yield n0, put_chunk(n0, cols0, nulls0)
            cols0 = nulls0 = first = None  # release the head chunk
            for f in frames:
                n, cols, nulls = _chunk_columns(f, needed)
                yield n, put_chunk(n, cols, nulls)

        # the ChunkPrefetcher feeds WHOLE segments: the producer thread
        # decodes + H2Ds raw chunks while the consumer runs the one
        # compiled program per chunk (ISSUE 7; docs/streaming.md)
        from .pipeline import engine_prefetcher

        chunks_it = engine_prefetcher(engine, produce(), label)
        try:
            for n, (vd, ad) in chunks_it:
                acc = step_fn(acc, vd, *ad)
                stats["chunks"] += 1
                stats["rows"] += n
                del vd, ad
                track()
        finally:
            chunks_it.close()
        res = _finish_dense_host(
            engine, acc, agg_sig, key, key_np, kmin, plan, track=track
        )
        global last_run_stats
        last_run_stats = dict(stats, verb=label)
        return res

    return run


def plan_lowered_steps_stream(
    engine: Any, df: Any, steps: Any, fingerprint: str
) -> Optional[Callable[[], DataFrame]]:
    """Phase-1 eligibility for a lowered chain feeding a host-buffered
    terminal (take / distinct / broadcast-join probe).

    Returns a factory producing a one-pass stream whose chunks each ran
    ONE jitted device program (raw columns H2D once; predicate +
    projections in a single dispatch; survivors compacted on host for
    the terminal's running buffer), or None. A chunk that violates the
    streaming NULL contract (NULL in a non-float column) degrades to the
    per-verb path FOR THAT CHUNK — bit-identical, never an error."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..column.jax_eval import evaluate_jnp, evaluate_jnp_3v
    from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows

    if len(steps) == 0:
        return None
    chain = _plan_lowered_chain(Schema(df.schema), steps)
    if chain is None:
        return None
    out_schema: Schema = chain["schema"]
    if any(_np_dtype_of(f.type) is None for f in out_schema.fields):
        return None  # outputs must round-trip through numpy numerics
    mesh = engine._mesh
    shards = num_row_shards(mesh)
    chunk_rows = int(
        engine.conf.get(FUGUE_TPU_CONF_STREAM_CHUNK_ROWS, DEFAULT_CHUNK_ROWS)
    )
    capacity = pad_rows(max(chunk_rows, shards), shards)
    cond = chain["pred"]
    needed: List[str] = chain["need"]
    in_np: Dict[str, np.dtype] = chain["in_np"]
    out_np: Dict[str, np.dtype] = chain["out_np"]
    outputs = chain["outputs"]
    label = f"segment:{fingerprint or 'anon'}"
    sharding = NamedSharding(mesh, P(ROW_AXIS))

    def make_stream() -> DataFrame:
        cache = engine._jit_cache
        cache_key = (label, mesh, capacity, "chain")
        if cache_key not in cache:

            def seg_chunk(valid: Any, *arrs: Any):
                import jax.numpy as jnp

                cols = dict(zip(needed, arrs))
                v = valid
                if cond is not None:
                    pv, nl = evaluate_jnp_3v(cols, {}, {}, cond, frozenset())
                    v = v & jnp.asarray(pv, dtype=bool) & jnp.logical_not(nl)
                outs = []
                for e in outputs:
                    a = evaluate_jnp(cols, e)
                    if not hasattr(a, "shape") or getattr(a, "ndim", 0) == 0:
                        a = jnp.full((capacity,), a)
                    outs.append(
                        jnp.asarray(a).astype(out_np[e.output_name])
                    )
                return v, tuple(outs)

            cache[cache_key] = jax.jit(seg_chunk)
        fn = cache[cache_key]

        def gen() -> Iterator[LocalDataFrame]:
            for f in _rechunk(_iter_local_frames(df, chunk_rows), capacity):
                n, cols, nulls = _chunk_columns(f, needed)
                if any(
                    nulls[c] > 0 and in_np[c].kind != "f" for c in needed
                ):
                    # per-chunk graceful degradation: this chunk runs the
                    # per-verb path (bit-identical), the stream continues
                    from ..plan.fused import apply_steps_engine

                    out = apply_steps_engine(engine, f, steps)
                    if out.count() > 0:
                        yield out.as_local_bounded()
                    continue
                full = n == capacity
                bufs = []
                for name in needed:
                    dt = in_np[name]
                    if full:
                        b = np.ascontiguousarray(
                            cols[name].astype(dt, copy=False)
                        )
                    else:
                        b = np.zeros(capacity, dtype=dt)
                        b[:n] = cols[name].astype(dt, copy=False)
                    bufs.append(b)
                valid = np.zeros(capacity, dtype=bool)
                valid[:n] = True
                put = jax.device_put([valid] + bufs, sharding)
                v, outs = fn(put[0], *put[1:])
                hv = np.asarray(jax.device_get(v))
                (idx,) = np.nonzero(hv)
                if len(idx) == 0:
                    continue
                data = {}
                for fld, arr in zip(out_schema.fields, outs):
                    data[fld.name] = np.asarray(jax.device_get(arr))[idx]
                yield PandasDataFrame(pd.DataFrame(data), out_schema)

        return LocalDataFrameIterableDataFrame(gen(), schema=out_schema)

    return make_stream


def streaming_hash_join(
    engine: Any, df1: Any, df2: Any, how: str, on: Optional[List[str]] = None
) -> Optional[DataFrame]:
    """Join a one-pass stream against a materialized build side with a
    bounded device working set — the fact-stream ⋈ dimension-table shape.

    The build side (the non-stream input) is sorted by key; the sorted KEY
    column goes on device REPLICATED. Each probe chunk row-shards its key
    onto the mesh, binary-searches the build keys (``jnp.searchsorted``),
    and fetches back (hit, position); payload columns — both sides — never
    touch the device, so they keep arbitrary dtypes (strings, nullable
    ints) and NULLs. Device memory = O(build key + chunk key), independent
    of stream length — the streaming analog of the reference's per-batch
    map over a broadcast table
    (`/root/reference/fugue_spark/execution_engine.py:262-294`).
    Proof artifact: ``last_run_stats`` (verb="join").

    Eligibility (else return None → caller materializes): exactly one
    input is a stream; inner join, or the outer side IS the stream
    (left_outer with stream left, right_outer with stream right); ONE
    numeric join key; build keys unique and non-NULL (duplicate build keys
    need the expansion kernel, which has no fixed-size output per chunk).
    NULL stream keys follow SQL: never match, kept on outer joins."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..dataframe.utils import get_join_schemas, parse_join_type
    from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows

    jt = parse_join_type(how)
    s1, s2 = is_stream_frame(df1), is_stream_frame(df2)
    if s1 == s2:
        return None
    stream_df, build_df = (df1, df2) if s1 else (df2, df1)
    if not (
        jt == "inner"
        or (jt == "left_outer" and s1)
        or (jt == "right_outer" and s2)
    ):
        return None
    key_schema, out_schema = get_join_schemas(df1, df2, how=jt, on=on)
    if len(key_schema) != 1:
        return None
    key = key_schema.names[0]
    for sch in (stream_df.schema, build_df.schema):
        f = sch[key]
        if not (pa.types.is_integer(f.type) or pa.types.is_floating(f.type)):
            return None
    outer = jt != "inner"

    if stream_df.schema[key].type != build_df.schema[key].type:
        # a dtype cast on the probe key (e.g. float->int) would truncate
        # values into false matches; value-equality across types is the
        # general path's job
        return None
    bpdf = build_df.as_local_bounded().as_pandas()
    if len(bpdf) > 0 and bpdf[key].isna().any():
        return None  # NULL build keys: let the general path handle them
    bkeys = bpdf[key].to_numpy()
    order = np.argsort(bkeys, kind="stable")
    bsorted = bkeys[order]
    if len(bsorted) > 1 and (bsorted[1:] == bsorted[:-1]).any():
        return None  # duplicates need the 1:N expansion kernel
    payload_names = [n for n in build_df.schema.names if n != key]
    n_build = len(bkeys)
    key_np = np.dtype(
        build_df.schema[key].type.to_pandas_dtype()
        if n_build > 0
        else stream_df.schema[key].type.to_pandas_dtype()
    )

    mesh = engine._mesh
    shards = num_row_shards(mesh)
    chunk_rows, tune = _tuned_chunk_rows(engine, "join")
    capacity = pad_rows(max(chunk_rows, shards), shards)

    if n_build == 0 and not outer:
        # inner ⋈ empty build = empty result; the one-pass stream need not
        # even be consumed
        empty = pd.DataFrame(
            {
                n: pd.Series(
                    dtype=np.dtype(out_schema[n].type.to_pandas_dtype())
                )
                for n in out_schema.names
            }
        )
        return engine.to_df(PandasDataFrame(empty, out_schema))

    def _extract_key(pf: pd.DataFrame):
        """(padded key buffer, null-key mask) for one chunk — NULL keys
        never match (SQL), so they probe as a harmless fill value."""
        s = pf[key]
        isna = s.isna().to_numpy()
        if isna.any():
            s = s.fillna(0)
        arr = s.to_numpy()
        if arr.dtype != key_np:
            arr = arr.astype(key_np)
        return arr, isna

    if n_build > 0:
        rep = NamedSharding(mesh, P())  # build keys: replicated on the mesh
        sharding = NamedSharding(mesh, P(ROW_AXIS))
        bk_dev = jax.device_put(bsorted.astype(key_np, copy=False), rep)
        # sorted build payload, host-side; nullable dtypes for outer joins
        # so the miss-NULLs keep their declared types (Int64/boolean/...)
        bs = bpdf.iloc[order].reset_index(drop=True)
        if outer:
            bs = pd.DataFrame(
                {n: bs[n].convert_dtypes() for n in payload_names}
            )

        cache = engine._jit_cache
        cache_key = ("stream_join", mesh, capacity, key_np.str, n_build)
        if cache_key not in cache:

            def probe(bk: Any, pk: Any, valid: Any):
                idx = jnp.searchsorted(bk, pk)
                idxc = jnp.clip(idx, 0, bk.shape[0] - 1)
                hit = (bk[idxc] == pk) & valid  # NaN keys never match (SQL)
                return hit, idxc

            cache[cache_key] = jax.jit(probe)
        probe_fn = cache[cache_key]

    def gen() -> Iterator[LocalDataFrame]:
        stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}
        full_valid_dev: List[Any] = []
        from .pipeline import engine_prefetcher

        chunks_it = engine_prefetcher(
            engine,
            (
                f.as_pandas().reset_index(drop=True)
                for f in _rechunk(
                    _maybe_coalesce(
                        _iter_local_frames(stream_df, chunk_rows), chunk_rows, tune
                    ),
                    capacity,
                )
            ),
            "join",
        )
        for pf in _closing(chunks_it):
            n = len(pf)
            stats["chunks"] += 1
            stats["rows"] += n
            if n_build == 0:  # outer ⋈ empty build: all payloads NULL
                data = {
                    nm: (
                        pf[nm]
                        if nm in pf.columns
                        else pd.Series([pd.NA] * n).convert_dtypes()
                    )
                    for nm in out_schema.names
                }
                yield PandasDataFrame(pd.DataFrame(data), out_schema)
                continue
            karr, knull = _extract_key(pf)
            has_null = bool(knull.any())
            if n == capacity and not has_null:
                # full-capacity chunk: probe the key column directly and
                # share one device-resident all-valid mask — no staging
                kb = np.ascontiguousarray(karr)
                if not full_valid_dev:
                    full_valid_dev.append(
                        jax.device_put(np.ones(capacity, dtype=bool), sharding)
                    )
                kd, vd = jax.device_put([kb, full_valid_dev[0]], sharding)
            else:
                kb = np.zeros(capacity, dtype=key_np)
                kb[:n] = karr
                valid = np.zeros(capacity, dtype=bool)
                valid[:n] = True
                if has_null:
                    valid[:n] &= ~knull
                kd, vd = jax.device_put([kb, valid], sharding)
            hit_d, idx_d = probe_fn(bk_dev, kd, vd)
            hit_d.copy_to_host_async()
            idx_d.copy_to_host_async()
            hit = np.asarray(jax.device_get(hit_d))[:n]
            pos = np.asarray(jax.device_get(idx_d))[:n]
            stats["peak_device_bytes"] = max(
                stats["peak_device_bytes"], _device_peak_bytes()
            )
            del kd, vd, hit_d, idx_d
            data = {}
            if outer:
                hit_s = pd.Series(hit)
                for nm in out_schema.names:
                    if nm in pf.columns:
                        data[nm] = pf[nm]
                    else:
                        g = bs[nm].take(pos).reset_index(drop=True)
                        data[nm] = g.where(hit_s)
            elif hit.all():
                # every probe hit (the dimension-table norm): skip the
                # nonzero + per-column gathers — rows pass through as-is
                for nm in out_schema.names:
                    if nm in pf.columns:
                        data[nm] = pf[nm]
                    else:
                        data[nm] = bs[nm].take(pos).reset_index(drop=True)
            else:
                (sel,) = np.nonzero(hit)
                for nm in out_schema.names:
                    if nm in pf.columns:
                        data[nm] = pf[nm].take(sel).reset_index(drop=True)
                    else:
                        data[nm] = (
                            bs[nm].take(pos[sel]).reset_index(drop=True)
                        )
            yield PandasDataFrame(pd.DataFrame(data), out_schema)
        global last_run_stats
        last_run_stats = dict(stats, verb="join")

    return LocalDataFrameIterableDataFrame(gen(), schema=out_schema)


# --------------------------------------------------------------------------
# streaming compiled map
# --------------------------------------------------------------------------


def streaming_compiled_map(
    engine: Any,
    df: Any,
    fn: Callable,
    output_schema: Schema,
    on_init: Optional[Callable] = None,
) -> DataFrame:
    """Chunk-wise compiled row map over a one-pass stream.

    The jax-annotated UDF is compiled ONCE for a fixed chunk capacity
    (padding + the ``__valid__`` mask absorb short chunks) and applied per
    chunk; each output chunk is fetched to the host and yielded, so the
    result is a one-pass `LocalDataFrameIterableDataFrame` and device
    memory stays O(chunk) end to end. The streaming analog of
    `_compiled_map` (same UDF contract: dict of row-aligned arrays in,
    dict out, ``__valid__`` marks real rows).
    """
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows

    mesh = engine._mesh
    shards = num_row_shards(mesh)
    chunk_rows, tune = _tuned_chunk_rows(engine, "map")
    capacity = pad_rows(max(chunk_rows, shards), shards)
    in_schema = df.schema
    names = list(in_schema.names)
    np_dtypes: Dict[str, np.dtype] = {}
    for f in in_schema.fields:
        if not (pa.types.is_integer(f.type) or pa.types.is_floating(f.type) or pa.types.is_boolean(f.type)):
            raise FugueInvalidOperation(
                f"streaming compiled map needs numeric/bool columns; "
                f"{f.name} is {f.type} (use a pandas-annotated transformer)"
            )
        np_dtypes[f.name] = np.dtype(f.type.to_pandas_dtype())
    sharding = NamedSharding(mesh, P(ROW_AXIS))

    cache = engine._jit_cache
    cache_key = ("stream_map", fn, mesh, capacity)
    if cache_key not in cache:
        cache[cache_key] = jax.jit(
            shard_map(fn, mesh=mesh, in_specs=(P(ROW_AXIS),), out_specs=P(ROW_AXIS))
        )
    mapped = cache[cache_key]
    if on_init is not None:
        on_init(0, df)

    out_schema = Schema(output_schema)
    out_names = list(out_schema.names)
    out_pd_dtypes = {
        f.name: np.dtype(f.type.to_pandas_dtype()) for f in out_schema.fields
    }

    def gen() -> Iterator[LocalDataFrame]:
        stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}
        # one device-resident all-valid mask shared by every full chunk
        # (mapped() never donates inputs, so reuse is safe)
        full_valid_dev: List[Any] = []

        def produce() -> Iterator[Tuple[int, Any]]:
            for f in _rechunk(
                _maybe_coalesce(
                    _iter_local_frames(df, chunk_rows), chunk_rows, tune
                ),
                capacity,
            ):
                n, cols, nulls = _chunk_columns(f, names)
                full = n == capacity
                buf: Dict[str, Any] = {}
                for c in names:
                    if np_dtypes[c].kind != "f":
                        assert_or_throw(
                            nulls[c] == 0,
                            FugueInvalidOperation(
                                f"streaming compiled map: NULL in non-float "
                                f"column {c!r}"
                            ),
                        )
                    if full:
                        # full-capacity chunk: no staging copy at all
                        buf[c] = np.ascontiguousarray(
                            cols[c].astype(np_dtypes[c], copy=False)
                        )
                    else:
                        b = np.zeros(capacity, dtype=np_dtypes[c])
                        b[:n] = cols[c].astype(np_dtypes[c], copy=False)
                        buf[c] = b
                if full:
                    if not full_valid_dev:
                        full_valid_dev.append(
                            jax.device_put(
                                np.ones(capacity, dtype=bool), sharding
                            )
                        )
                    buf["__valid__"] = full_valid_dev[0]
                else:
                    valid = np.zeros(capacity, dtype=bool)
                    valid[:n] = True
                    buf["__valid__"] = valid
                # device_put is a no-op for the already-committed mask
                yield n, jax.device_put(buf, sharding)

        from .pipeline import engine_prefetcher

        chunks_it = engine_prefetcher(engine, produce(), "map")
        for n, dev in _closing(chunks_it):
            out = mapped(dev)
            assert_or_throw(
                isinstance(out, dict),
                FugueInvalidOperation(
                    "compiled transformer must return Dict[str, jax.Array]"
                ),
            )
            out = {k: v for k, v in out.items() if k != "__valid__"}
            missing = [c for c in out_names if c not in out]
            assert_or_throw(
                len(missing) == 0,
                FugueInvalidOperation(
                    f"compiled transformer output missing columns {missing}"
                ),
            )
            for v in out.values():
                assert_or_throw(
                    v.shape[0] == capacity,
                    FugueInvalidOperation(
                        "streaming compiled transformers must return "
                        "row-aligned arrays (padding preserved; reductions "
                        "must mask with __valid__)"
                    ),
                )
            for v in out.values():
                v.copy_to_host_async()
            host = {
                c: np.asarray(jax.device_get(out[c]))[:n] for c in out_names
            }
            stats["chunks"] += 1
            stats["rows"] += n
            stats["peak_device_bytes"] = max(
                stats["peak_device_bytes"], _device_peak_bytes()
            )
            del dev, out
            pdf = pd.DataFrame(
                {c: host[c].astype(out_pd_dtypes[c], copy=False) for c in host}
            )
            yield PandasDataFrame(pdf, out_schema)
        global last_run_stats
        last_run_stats = dict(stats, verb="map")

    return LocalDataFrameIterableDataFrame(gen(), schema=out_schema)


# --------------------------------------------------------------------------
# streaming take / distinct
# --------------------------------------------------------------------------


def streaming_take(
    engine: Any,
    df: Any,
    n: int,
    presort: Any,
    na_position: str = "last",
    partition_spec: Any = None,
) -> DataFrame:
    """``take`` over a one-pass stream with a bounded working set.

    - no presort, no keys: consume until ``n`` rows (early stop — the
      stream's tail is never generated);
    - presort: a running top-``n`` buffer merged per chunk (O(n + chunk));
    - partition keys: a running per-key head buffer (O(keys·n + chunk)).

    All row movement is host-side pandas per chunk — take outputs are
    O(n·keys), far below device-offload profitability."""
    from ..collections.partition import parse_presort_exp

    chunk_rows, tune = _tuned_chunk_rows(engine, "take")
    sorts = (
        parse_presort_exp(presort)
        if presort
        else (partition_spec.presort if partition_spec is not None else {})
    )
    keys = (
        list(partition_spec.partition_by) if partition_spec is not None else []
    )
    names = list(sorts.keys())
    asc = list(sorts.values())
    schema = Schema(df.schema)
    buf: Optional[pd.DataFrame] = None
    stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}
    chunks_it = _prefetched_pandas_chunks(engine, df, chunk_rows, "take", tune)
    try:
        for pf in chunks_it:
            stats["chunks"] += 1
            stats["rows"] += len(pf)
            buf = pf if buf is None else pd.concat([buf, pf], ignore_index=True)
            if len(names) > 0:
                buf = buf.sort_values(
                    names, ascending=asc, na_position=na_position, kind="stable"
                )
            if len(keys) == 0:
                buf = buf.head(n)
                if len(names) == 0 and len(buf) >= n:
                    # unsorted global take: the rest of the stream is moot —
                    # close() also stops the producer's read-ahead
                    break
            else:
                buf = buf.groupby(keys, dropna=False, sort=False).head(n)
            buf = buf.reset_index(drop=True)
    finally:
        chunks_it.close()
    global last_run_stats
    last_run_stats = dict(stats, verb="take")
    out = buf if buf is not None else pd.DataFrame(columns=schema.names)
    return engine.to_df(PandasDataFrame(out, schema))


def streaming_fused_steps(engine: Any, df: Any, steps: Any) -> DataFrame:
    """Fused select/filter/assign chain applied INSIDE the chunk producer
    of a one-pass stream (plan optimizer, docs/plan.md): each chunk runs
    the chain with the engine's own verbs (device-eligible chunks take
    the same device mask/projection path the materialized frame would
    have taken — bit-identical results), and only surviving rows flow to
    the downstream jitted step. The stream stays one-pass/out-of-core:
    device working set is O(chunk), never O(dataset)."""
    from ..dataframe import ArrayDataFrame
    from ..plan.fused import apply_steps_engine

    chunk_rows = engine.conf.get(
        FUGUE_TPU_CONF_STREAM_CHUNK_ROWS, DEFAULT_CHUNK_ROWS
    )
    # schema probe on an empty frame — same inference the chunks will use
    out_schema = apply_steps_engine(
        engine, ArrayDataFrame([], df.schema), steps
    ).schema

    def gen() -> Iterator[LocalDataFrame]:
        for f in _iter_local_frames(df, chunk_rows):
            out = apply_steps_engine(engine, f, steps)
            if out.count() > 0:
                yield out.as_local_bounded()

    return LocalDataFrameIterableDataFrame(gen(), schema=out_schema)


def streaming_distinct(engine: Any, df: Any) -> DataFrame:
    """DISTINCT over a one-pass stream: chunk-wise dedupe against the
    running distinct set — memory is O(distinct rows + chunk), independent
    of stream length (SQL NaN==NaN semantics, matching the engines)."""
    chunk_rows, tune = _tuned_chunk_rows(engine, "distinct")
    from ..execution.native_execution_engine import _drop_duplicates

    schema = Schema(df.schema)
    buf: Optional[pd.DataFrame] = None
    stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}
    chunks_it = _prefetched_pandas_chunks(engine, df, chunk_rows, "distinct", tune)
    try:
        for pf in chunks_it:
            stats["chunks"] += 1
            stats["rows"] += len(pf)
            merged = pf if buf is None else pd.concat([buf, pf], ignore_index=True)
            buf = _drop_duplicates(merged)
    finally:
        chunks_it.close()
    global last_run_stats
    last_run_stats = dict(stats, verb="distinct")
    out = buf if buf is not None else pd.DataFrame(columns=schema.names)
    return engine.to_df(PandasDataFrame(out, schema))


# --------------------------------------------------------------------------
# streaming KEYED compiled map (the out-of-core window/groupby-apply path)
# --------------------------------------------------------------------------


def streaming_keyed_compiled_map(
    engine: Any,
    df: Any,
    fn: Callable,
    output_schema: Schema,
    partition_spec: Any,
    on_init: Optional[Callable] = None,
) -> Optional[DataFrame]:
    """Keyed compiled map over a KEY-CLUSTERED one-pass stream.

    Contract: all rows of one partition key are contiguous in the stream
    (the natural layout of key-sorted files). Chunks re-batch at key
    boundaries — the trailing key's rows carry into the next batch so no
    group is ever split — then each batch runs the regular compiled keyed
    map (`JaxMapEngine._compiled_keyed_map`) on a FIXED-capacity padded
    device frame (one XLA compilation for the whole stream). With
    ``group_ops.running_sum``/``row_number`` inside the UDF this is the
    window kernel over key-partitioned streams: device memory stays
    O(capacity), independent of stream length.

    A key that reappears after its batch closed raises (the contract is
    checkable, not assumed). A single key run larger than the chunk
    capacity raises with a remediation hint. Returns None (caller
    materializes) when the schema is ineligible (non-numeric columns)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows
    from .dataframe import JaxDataFrame

    keys = list(partition_spec.partition_by)
    if len(keys) == 0:
        return None
    in_schema = Schema(df.schema)
    np_dtypes: Dict[str, np.dtype] = {}
    for f in in_schema.fields:
        if not (
            pa.types.is_integer(f.type)
            or pa.types.is_floating(f.type)
            or pa.types.is_boolean(f.type)
        ):
            # raising (not a materializing fallback) matches the keyless
            # streaming map: a one-pass stream exists precisely because it
            # must not be materialized on device
            raise FugueInvalidOperation(
                f"streaming keyed compiled map needs numeric/bool columns; "
                f"{f.name} is {f.type} (use a pandas-annotated transformer)"
            )
        np_dtypes[f.name] = np.dtype(f.type.to_pandas_dtype())
    mesh = engine._mesh
    shards = num_row_shards(mesh)
    chunk_rows, tune = _tuned_chunk_rows(engine, "keyed_map")
    capacity = pad_rows(max(chunk_rows, shards), shards)
    sharding = NamedSharding(mesh, P(ROW_AXIS))
    out_schema = Schema(output_schema)
    map_engine = engine.map_engine
    names = list(in_schema.names)

    def run_batch(batch: pd.DataFrame, closed: set, first: List[bool]):
        uk = set(
            map(tuple, batch[keys].drop_duplicates().itertuples(index=False, name=None))
        )
        overlap = uk & closed
        assert_or_throw(
            len(overlap) == 0,
            FugueInvalidOperation(
                "streaming keyed map: the stream is not key-clustered — "
                f"key(s) {sorted(overlap)[:3]} reappeared after their rows "
                "were already processed. Sort/cluster the stream by "
                f"{keys} first."
            ),
        )
        closed |= uk
        k = len(batch)
        assert_or_throw(
            k <= capacity,
            FugueInvalidOperation(
                f"streaming keyed map: a contiguous key run ({k} rows) "
                f"exceeds the chunk capacity ({capacity}); raise "
                f"{FUGUE_TPU_CONF_STREAM_CHUNK_ROWS}"
            ),
        )
        bufs: Dict[str, Any] = {}
        for c in names:
            s = batch[c]
            assert_or_throw(
                np_dtypes[c].kind == "f" or not s.isna().any(),
                FugueInvalidOperation(
                    f"streaming keyed map: NULL in non-float column {c!r}"
                ),
            )
            b = np.zeros(capacity, dtype=np_dtypes[c])
            b[:k] = s.to_numpy().astype(np_dtypes[c], copy=False)
            bufs[c] = b
        put = jax.device_put([bufs[c] for c in names], sharding)
        jdf = JaxDataFrame(
            mesh=mesh,
            _internal=dict(
                device_cols=dict(zip(names, put)),
                host_tbl=None,
                row_count=k,  # tail-padding validity semantics
                valid_mask=None,
                schema=in_schema,
            ),
        )
        res = map_engine._compiled_keyed_map(
            jdf,
            fn,
            out_schema,
            partition_spec,
            on_init if first[0] else None,
        )
        first[0] = False
        peak = _device_peak_bytes()  # input + output batches both live here
        return res.as_pandas(), peak

    def gen() -> Iterator[LocalDataFrame]:
        stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}
        carry: Optional[pd.DataFrame] = None
        closed: set = set()
        first = [True]
        # prefetch the host decode of the NEXT chunk while run_batch runs
        # the compiled keyed map on the current batch
        chunks_it = _prefetched_pandas_chunks(
            engine, df, chunk_rows, "keyed_map", tune
        )
        for pf in _closing(chunks_it):
            stats["chunks"] += 1
            stats["rows"] += len(pf)
            merged = (
                pf
                if carry is None or len(carry) == 0
                else pd.concat([carry, pf], ignore_index=True)
            )
            if len(merged) == 0:
                carry = None
                continue
            assert_or_throw(
                not merged[keys].isna().any().any(),
                FugueInvalidOperation(
                    "streaming keyed map: NULL/NaN partition keys are not "
                    "supported (NaN breaks key-run detection); filter or "
                    "fill the key column first"
                ),
            )
            eq_last = (
                (merged[keys] == merged[keys].iloc[-1].values)
                .all(axis=1)
                .to_numpy()
            )
            if eq_last.all():
                # one key so far: keep accumulating — but fail fast once
                # the run can no longer fit (it would only grow, with
                # quadratic host copying, before run_batch raised anyway)
                assert_or_throw(
                    len(merged) <= capacity,
                    FugueInvalidOperation(
                        f"streaming keyed map: a contiguous key run "
                        f"({len(merged)}+ rows) exceeds the chunk capacity "
                        f"({capacity}); raise {FUGUE_TPU_CONF_STREAM_CHUNK_ROWS}"
                    ),
                )
                carry = merged
                continue
            tail = int(np.argmin(eq_last[::-1]))  # trailing run length
            emit = merged.iloc[: len(merged) - tail]
            carry = merged.iloc[len(merged) - tail :].reset_index(drop=True)
            for sub in _key_aligned_splits(emit, keys, capacity):
                out, peak = run_batch(sub, closed, first)
                stats["peak_device_bytes"] = max(
                    stats["peak_device_bytes"], peak
                )
                yield PandasDataFrame(out, out_schema)
        if carry is not None and len(carry) > 0:
            for sub in _key_aligned_splits(carry, keys, capacity):
                out, peak = run_batch(sub, closed, first)
                stats["peak_device_bytes"] = max(
                    stats["peak_device_bytes"], peak
                )
                yield PandasDataFrame(out, out_schema)
        global last_run_stats
        last_run_stats = dict(stats, verb="keyed_map")

    return LocalDataFrameIterableDataFrame(gen(), schema=out_schema)


def _key_aligned_splits(
    batch: pd.DataFrame, keys: List[str], capacity: int
) -> Iterator[pd.DataFrame]:
    """Split a group-complete batch into <=capacity pieces WITHOUT cutting
    any key's run (greedy accumulation of whole groups)."""
    if len(batch) <= capacity:
        yield batch
        return
    sizes = batch.groupby(keys, dropna=False, sort=False).size().to_numpy()
    bounds = np.concatenate([[0], np.cumsum(sizes)])
    start = 0
    cur = 0
    for gi in range(len(sizes)):
        if bounds[gi + 1] - start > capacity:
            if bounds[gi] == start:  # single group larger than capacity
                yield batch.iloc[start : bounds[gi + 1]]  # run_batch raises
                start = int(bounds[gi + 1])
                continue
            yield batch.iloc[start : bounds[gi]].reset_index(drop=True)
            start = int(bounds[gi])
        cur = int(bounds[gi + 1])
    if cur > start:
        yield batch.iloc[start:cur].reset_index(drop=True)


# --------------------------------------------------------------------------
# streaming zip/comap (key-SORTED streams, co-batched at key horizons)
# --------------------------------------------------------------------------


class ZippedStreamDataFrame(DataFrame):
    """``zip`` of key-SORTED one-pass streams (+ optionally bounded
    frames, treated as single-chunk streams).

    A thin metadata holder, like ``ZippedJaxDataFrame``: presents the blob
    protocol's logical schema so workflow metadata checks are identical,
    but physically carries the stream objects. The only consumer is
    ``comap`` (via ``streaming_comap``) — any other access raises, because
    a one-pass zipped stream cannot be materialized twice."""

    def __init__(
        self,
        streams: List[Any],
        names: List[str],
        named: bool,
        how: str,
        keys: List[str],
        schemas: List[Schema],
        presort: Dict[str, bool],
    ):
        key_schema = schemas[0].extract(keys)
        blob_fields = ",".join(
            f"__fugue_blob__{i}:binary" for i in range(len(streams))
        )
        super().__init__(Schema(str(key_schema) + "," + blob_fields))
        self.zip_streams = streams
        self.zip_names = names
        self.zip_named = named
        self.zip_how = how
        self.zip_keys = keys
        self.zip_schemas = schemas
        self.zip_presort = presort
        # the cotransform processor recognizes zipped inputs (and rebuilds
        # their empty frames) from this metadata — same contract as the
        # blob protocol and ZippedJaxDataFrame
        self.reset_metadata(
            {
                "serialized": True,
                "serialized_cols": [
                    f"__fugue_blob__{i}" for i in range(len(streams))
                ],
                "schemas": [str(s) for s in schemas],
                "serialized_has_name": named,
                "names": names,
                "how": how,
                "keys": keys,
                "stream_zip": True,
            }
        )

    @property
    def is_local(self) -> bool:
        return True

    @property
    def is_bounded(self) -> bool:
        return False  # one-pass

    @property
    def num_partitions(self) -> int:
        return 1

    @property
    def empty(self) -> bool:
        return False

    def _no(self, what: str) -> Any:
        raise FugueInvalidOperation(
            f"{what} is not available on a zipped one-pass stream; "
            "apply a cotransformer (comap) to consume it"
        )

    def peek_array(self) -> List[Any]:
        return self._no("peek")

    def count(self) -> int:
        return self._no("count")

    def as_local_bounded(self) -> Any:
        return self._no("as_local_bounded")

    def as_array(self, columns: Any = None, type_safe: bool = False) -> Any:
        return self._no("as_array")

    def as_array_iterable(self, columns: Any = None, type_safe: bool = False) -> Any:
        return self._no("as_array_iterable")

    def _drop_cols(self, cols: Any) -> Any:
        return self._no("drop")

    def _select_cols(self, cols: Any) -> Any:
        return self._no("select")

    def rename(self, columns: Any) -> Any:
        return self._no("rename")

    def alter_columns(self, columns: Any) -> Any:
        return self._no("alter_columns")

    def head(self, n: int, columns: Any = None) -> Any:
        return self._no("head")


def streaming_zip(
    engine: Any,
    dfs: Any,
    how: str,
    partition_spec: Any,
) -> Optional[DataFrame]:
    """Build a :class:`ZippedStreamDataFrame` when any zip input is a
    one-pass stream. Eligibility: a non-cross zip with explicit or
    inferable keys, and no NULL keys in the BOUNDED inputs (those need
    the blob protocol; stream inputs are checked chunk by chunk).
    Bounded inputs are host-sorted by the zip keys and ride along as
    single-chunk streams — only actual streams must arrive pre-sorted."""
    if how.lower() == "cross":
        return None
    keys = list(partition_spec.partition_by) if partition_spec is not None else []
    if len(keys) == 0 and len(dfs) > 0:
        keys = [
            n
            for n in dfs[0].schema.names
            if all(n in d.schema for d in dfs.values())
        ]
    if len(keys) == 0:
        return None
    schemas = [Schema(d.schema) for d in dfs.values()]
    inputs: List[Any] = []
    for d in dfs.values():
        if is_stream_frame(d):
            inputs.append(d)
            continue
        pf = d.as_pandas()
        if len(pf) > 0 and pf[keys].isna().any().any():
            # NULL keys need the blob protocol's NULL-group handling
            return None
        inputs.append(
            PandasDataFrame(
                pf.sort_values(keys, kind="stable").reset_index(drop=True),
                Schema(d.schema),
            )
        )
    presort = dict(partition_spec.presort) if partition_spec is not None else {}
    return ZippedStreamDataFrame(
        streams=inputs,
        names=list(dfs.keys()),
        named=dfs.has_key,
        how=how.lower(),
        keys=keys,
        schemas=schemas,
        presort=presort,
    )


def _key_view(frame: pd.DataFrame, keys: List[str]) -> Any:
    """A lexicographically comparable view of the key columns: the bare
    numpy column for one key (fast path), a MultiIndex otherwise."""
    if len(keys) == 1:
        return frame[keys[0]].to_numpy()
    return pd.MultiIndex.from_frame(frame[keys])


def _is_sorted(kv: Any) -> bool:
    if isinstance(kv, pd.MultiIndex):
        return kv.is_monotonic_increasing
    return bool(np.all(kv[1:] >= kv[:-1])) if len(kv) > 1 else True


def _split_below(b: pd.DataFrame, keys: List[str], horizon: Tuple) -> int:
    """Index of the first row with key >= horizon (buffer is sorted)."""
    kv = _key_view(b, keys)
    if isinstance(kv, pd.MultiIndex):
        # lexicographic binary search over the sorted MultiIndex
        lo, hi = 0, len(kv)
        while lo < hi:
            mid = (lo + hi) // 2
            if tuple(kv[mid]) < horizon:
                lo = mid + 1
            else:
                hi = mid
        return lo
    return int(np.searchsorted(kv, horizon[0], side="left"))


def streaming_comap(
    engine: Any,
    zdf: "ZippedStreamDataFrame",
    map_func: Callable,
    output_schema: Any,
    partition_spec: Any = None,
    on_init: Optional[Callable] = None,
) -> DataFrame:
    """Cotransform over zipped key-SORTED streams with bounded memory.

    The classic sorted-merge co-batching: each input keeps a buffer; the
    emit horizon is the smallest "last key seen" over non-exhausted
    inputs; rows strictly below the horizon are complete on every input
    (ascending-sorted contract, validated chunk by chunk) and batch
    through the regular zip+comap; rows at/above it carry. Memory is
    O(chunk × inputs), independent of stream length."""
    from ..dataframe import DataFrames

    out_schema = (
        output_schema if isinstance(output_schema, Schema) else Schema(output_schema)
    )
    keys = zdf.zip_keys
    chunk_rows = int(
        engine.conf.get(FUGUE_TPU_CONF_STREAM_CHUNK_ROWS, DEFAULT_CHUNK_ROWS)
    )
    from ..collections.partition import PartitionSpec as _PSpec

    # presort precedence matches the non-streaming comap: a comap-time
    # presort overrides the zip-time one
    presort = dict(zdf.zip_presort)
    if partition_spec is not None and len(partition_spec.presort) > 0:
        presort = dict(partition_spec.presort)
    spec = (
        _PSpec(partition_spec, by=keys, presort=presort)
        if partition_spec is not None
        else _PSpec(by=keys, presort=presort)
    )

    def gen() -> Iterator[LocalDataFrame]:
        stats = {"chunks": 0, "rows": 0, "peak_device_bytes": 0}
        iters = [
            _iter_local_frames(s, chunk_rows) for s in zdf.zip_streams
        ]
        # chunk LISTS, concatenated only at emit time: per-pull concat
        # would be O(run^2) copying while a hot key spans many chunks
        bufs: List[List[pd.DataFrame]] = [[] for _ in iters]
        last_key: List[Optional[Tuple]] = [None] * len(iters)
        done = [False] * len(iters)
        first = [True]

        def _nrows(i: int) -> int:
            return sum(len(c) for c in bufs[i])

        def pull(i: int) -> bool:
            """Append ONE validated chunk to input i's buffer; False at
            stream end. The one place every chunk enters a buffer — the
            sorted-contract checks live here and only here."""
            try:
                f = next(iters[i])
            except StopIteration:
                done[i] = True
                return False
            pf = f.as_pandas().reset_index(drop=True)
            stats["chunks"] += 1
            stats["rows"] += len(pf)
            if len(pf) == 0:
                return True
            kv = pf[keys]
            assert_or_throw(
                not kv.isna().any().any(),
                FugueInvalidOperation(
                    "streaming zip: NULL keys are not supported on the "
                    "sorted-stream path"
                ),
            )
            assert_or_throw(
                _is_sorted(_key_view(pf, keys)),
                FugueInvalidOperation(
                    f"streaming zip: input {i} is not sorted ascending "
                    f"by {keys} within a chunk"
                ),
            )
            lo = tuple(pf[keys].iloc[0])
            if last_key[i] is not None:
                assert_or_throw(
                    lo >= last_key[i],
                    FugueInvalidOperation(
                        f"streaming zip: input {i} is not sorted "
                        f"ascending by {keys} ({lo!r} after {last_key[i]!r})"
                    ),
                )
            bufs[i].append(pf)
            last_key[i] = tuple(pf[keys].iloc[-1])
            return True

        def run_batch(parts: List[pd.DataFrame]):
            pieces = DataFrames(
                dict(zip(zdf.zip_names, (
                    PandasDataFrame(p, s)
                    for p, s in zip(parts, zdf.zip_schemas)
                )))
                if zdf.zip_named
                else [
                    PandasDataFrame(p, s)
                    for p, s in zip(parts, zdf.zip_schemas)
                ]
            )
            z = engine.zip(pieces, how=zdf.zip_how, partition_spec=spec)
            res = engine.comap(
                z,
                map_func,
                out_schema,
                partition_spec=spec,
                on_init=on_init if first[0] else None,
            )
            first[0] = False
            out = res.as_pandas()
            stats["peak_device_bytes"] = max(
                stats["peak_device_bytes"], _device_peak_bytes()
            )
            return out

        while True:
            for i in range(len(iters)):
                while not done[i] and _nrows(i) == 0:
                    pull(i)
            live = [i for i in range(len(iters)) if _nrows(i) > 0]
            if len(live) == 0:
                break
            # horizon: the smallest last-key over inputs that may still grow
            horizons = [last_key[i] for i in live if not done[i]]
            horizon = min(horizons) if len(horizons) > 0 else None
            parts: List[pd.DataFrame] = []
            any_rows = False
            for i in range(len(iters)):
                if _nrows(i) == 0:
                    parts.append(pd.DataFrame(columns=zdf.zip_schemas[i].names))
                    continue
                if horizon is not None and tuple(
                    bufs[i][0][keys].iloc[0]
                ) >= horizon:
                    # whole buffer at/above the horizon: nothing to emit —
                    # skip the concat (a stalled input must not be
                    # re-copied every round)
                    parts.append(pd.DataFrame(columns=zdf.zip_schemas[i].names))
                    continue
                b = (
                    bufs[i][0]
                    if len(bufs[i]) == 1
                    else pd.concat(bufs[i], ignore_index=True)
                )
                cut = len(b) if horizon is None else _split_below(b, keys, horizon)
                parts.append(b.iloc[:cut].reset_index(drop=True))
                rest = b.iloc[cut:].reset_index(drop=True)
                bufs[i] = [rest] if len(rest) > 0 else []
                any_rows = any_rows or cut > 0
            if any_rows:
                yield PandasDataFrame(run_batch(parts), out_schema)
            elif horizon is not None:
                # nothing below the horizon: only the inputs PINNED at the
                # horizon can extend it — drain one chunk from each (ahead
                # inputs must not grow, or the memory bound erodes)
                progressed = False
                for i in range(len(iters)):
                    if (
                        not done[i]
                        and _nrows(i) > 0
                        and last_key[i] == horizon
                    ):
                        pull(i)
                        progressed = True
                assert_or_throw(
                    progressed,
                    FugueInvalidOperation(
                        "streaming zip: no progress possible (internal)"
                    ),
                )
        if first[0] and on_init is not None:
            # zero non-empty batches: on_init still fires once over empty
            # frames (non-streaming comap parity)
            on_init(
                0,
                DataFrames(
                    dict(zip(zdf.zip_names, (
                        PandasDataFrame(
                            pd.DataFrame(columns=s.names), s
                        )
                        for s in zdf.zip_schemas
                    )))
                    if zdf.zip_named
                    else [
                        PandasDataFrame(pd.DataFrame(columns=s.names), s)
                        for s in zdf.zip_schemas
                    ]
                ),
            )
        global last_run_stats
        last_run_stats = dict(stats, verb="comap")

    return LocalDataFrameIterableDataFrame(gen(), schema=out_schema)
