"""Device window-function evaluation.

Lowers ``OVER (PARTITION BY ... ORDER BY ...)`` onto the device sort +
segment machinery (SURVEY §7.8): hash-repartition co-locates each
partition on one shard, ONE ``shard_map`` sorts the shard by
(validity, partition keys, order keys) and computes every window column
with prefix sums / segmented scans — no host materialization (the
reference runs OVER clauses through backend SQL on the cluster,
``fugue/execution/execution_engine.py:183-274``; pandas remains the
fallback for shapes this plan doesn't cover).

Supported here: ROW_NUMBER / RANK / DENSE_RANK / LAG / LEAD (literal
offset/default) and SUM/AVG/MIN/MAX/COUNT/FIRST/LAST over
- the whole partition (no ORDER BY, or UNBOUNDED..UNBOUNDED),
- running ROWS UNBOUNDED PRECEDING..CURRENT ROW,
- RANGE UNBOUNDED..CURRENT (peer rows share the running value),
- bounded ROWS frames for SUM/COUNT/AVG (prefix-sum differences).

NULL semantics mirror the host evaluator (``column/window.py``): NaN is
the device NULL; aggregates skip NULLs; running aggregates are NULL until
the first non-NULL; FIRST/LAST are positional. Everything else returns
None → host fallback.
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..column.expressions import _LitColumnExpr, _NamedColumnExpr, _WindowExpr
from ..schema import Schema
from .._utils.jax_compat import shard_map

_AGGS = {"SUM", "AVG", "MIN", "MAX", "COUNT", "FIRST", "LAST"}
_RANKS = {"ROW_NUMBER", "RANK", "DENSE_RANK"}
_NO_LIT = object()


def _safe_mask_prefix(names: Any) -> str:
    """Sort-payload mask-column prefix that can't shadow a user column."""
    from .execution_engine import _safe_prefix

    return _safe_prefix("__wmask__", names)


def _norm_frame(expr: _WindowExpr) -> Optional[Tuple]:
    """Normalize an aggregate's frame to a hashable plan tag, or None when
    the shape needs the host evaluator."""
    has_order = len(expr.order_by) > 0
    frame = expr.frame
    if not has_order:
        return ("whole",)
    if frame is None:
        frame = ("range", "unb_prec", "current")
    kind, start, end = frame
    if start == "unb_prec" and end == "unb_foll":
        return ("whole",)
    if kind == "rows" and start == "unb_prec" and end == "current":
        return ("running",)
    if kind == "range" and start == "unb_prec" and end == "current":
        return ("peers",)
    if expr.func not in ("SUM", "COUNT", "AVG", "MIN", "MAX"):
        return None

    def off(b):
        if b == "current":
            return 0
        if isinstance(b, tuple):
            return -b[1] if b[0] == "prec" else b[1]
        return None  # unbounded

    # None offsets mean "to the segment edge" — handled statically
    if kind == "rows":
        return ("rows_bounded", off(start), off(end))
    # RANGE with value offsets: per-row frame bounds come from a binary
    # search over the (sorted) single order key — includes offset-0 bounds,
    # where value equality IS the peer group
    return ("range_bounded", off(start), off(end))


def _plan_items(
    jdf: Any, items: List[Tuple[str, _WindowExpr]]
) -> Optional[Tuple[Tuple, List[str], List[Tuple[str, bool]]]]:
    """Gate + normalize. Returns (specs, pkeys, order_items) or None."""
    if len(items) == 0:
        return None
    first = items[0][1]
    pkeys = list(first.partition_by)
    # pkeys == [] is the GLOBAL window: run_device_windows routes every row
    # to one shard (the same single-partition serialization every backend
    # pays for a global OVER) and the segment machinery sees one segment
    # one physical sort serves every spec whose ORDER BY is a PREFIX of the
    # longest one (peer detection runs per spec on its own keys)
    order_items: List[Tuple[str, bool]] = []
    for _, expr in items:
        oi = [(n, bool(a)) for n, a in expr.order_by]
        if len(oi) > len(order_items):
            if order_items != oi[: len(order_items)]:
                return None
            order_items = oi
        elif oi != order_items[: len(oi)]:
            return None
    plain = (
        lambda c: c in jdf.device_cols
        and c not in jdf.encodings
        and c not in jdf.null_masks
    )

    def groupable(c: str) -> bool:
        """Usable as a partition/order key: plain, or a SORTED dictionary
        (codes group exactly and code order == lexicographic order; -1 is
        the NULL code, flagged separately in the sort)."""
        if plain(c):
            return True
        enc = jdf.encodings.get(c)
        return (
            c in jdf.device_cols
            and c not in jdf.null_masks
            and enc is not None
            and enc.get("kind") == "dict"
            and bool(enc.get("sorted"))
        )

    def masked(c: str) -> bool:
        """A null-masked plain device column (nullable int/bool)."""
        return (
            c in jdf.device_cols
            and c in jdf.null_masks
            and c not in jdf.encodings
        )

    def orderable(c: str) -> bool:
        """Order keys additionally admit null-masked (nullable int/bool)
        columns — the mask rides the sort and flags NULL-last ordering."""
        return groupable(c) or masked(c)

    if not all(groupable(k) and not jdf.maybe_nan(k) for k in pkeys):
        return None
    if not all(orderable(n) for n, _ in order_items):
        return None
    specs: List[Tuple] = []
    for out_name, expr in items:
        if list(expr.partition_by) != pkeys:
            return None  # mixed partitions — host fallback
        func = expr.func
        n_ord = len(expr.order_by)
        if func in _RANKS:
            if func != "ROW_NUMBER" and n_ord == 0:
                return None
            specs.append((out_name, func, n_ord))
            continue
        if func in ("LAG", "LEAD"):
            if len(expr.args) < 1 or not isinstance(
                expr.args[0], _NamedColumnExpr
            ):
                return None
            arg = expr.args[0].name
            if not plain(arg):
                return None
            def lit_value(a: Any) -> Any:
                if isinstance(a, _LitColumnExpr):
                    return a.value
                # "-1.0" parses as unary negation of a literal
                from ..column.expressions import _UnaryOpExpr

                if (
                    isinstance(a, _UnaryOpExpr)
                    and a.op == "-"
                    and isinstance(a.col, _LitColumnExpr)
                    and isinstance(a.col.value, (int, float))
                ):
                    return -a.col.value
                return _NO_LIT

            offset, default = 1, None
            if len(expr.args) > 1:
                off_v = lit_value(expr.args[1])
                if off_v is _NO_LIT:
                    return None
                offset = int(off_v)
                if offset < 0:  # negative offsets flip direction — host path
                    return None
            if len(expr.args) > 2:
                default = lit_value(expr.args[2])
                if default is _NO_LIT:
                    return None
                if default is not None and not isinstance(
                    default, (int, float, bool)
                ):
                    return None
            if default is None and np.dtype(
                jdf.device_cols[arg].dtype
            ) != np.dtype(np.float64):
                # NULL fills force a float64 result — the host path keeps
                # the arg's type (incl. float32); don't let the plan change
                # output schemas
                return None
            specs.append((out_name, func, arg, offset, default))
            continue
        if func in _AGGS:
            if len(expr.args) != 1 or not isinstance(
                expr.args[0], _NamedColumnExpr
            ):
                return None
            arg = expr.args[0].name
            masked_arg = masked(arg)
            if not plain(arg) and not masked_arg:
                return None
            tag = _norm_frame(expr)
            if tag is None:
                return None
            bounded = tag[0] in ("rows_bounded", "range_bounded")
            if func in ("FIRST", "LAST") and (
                masked_arg or jdf.maybe_nan(arg)
            ):
                return None  # positional semantics vs NULL ambiguity
            if (
                not bounded
                and func not in ("COUNT", "FIRST", "LAST")
                and not masked_arg
                and np.dtype(jdf.device_cols[arg].dtype)
                != np.dtype(np.float64)
            ):
                # non-float64 SUM/MIN/MAX/AVG over running/whole/peer
                # frames: float64 accumulation would change the output type
                # (host keeps long/float) and lose int precision past 2^53
                # — host fallback. Masked args are exempt, and so are
                # bounded frames: the host evaluator itself computes those
                # in float64 and coerces back to the declared type.
                return None
            exact64 = False
            if (
                not bounded
                and masked_arg
                and func not in ("COUNT", "FIRST", "LAST")
                and np.dtype(jdf.device_cols[arg].dtype).itemsize >= 8
            ):
                # masked 64-bit ints on running/whole/peer frames: the host
                # computes these EXACTLY over extension dtypes
                # (_utils/arrow.py), so the float64 round trip (lossy past
                # 2^53) is not enough. int64 gets the exact device path
                # (hi/lo split sums, int-domain MIN/MAX — mirroring
                # ops/segment.py); uint64 falls back to the host. Bounded
                # frames stay on float64: the host itself computes those
                # in float64.
                if np.dtype(jdf.device_cols[arg].dtype) != np.dtype(
                    np.int64
                ):
                    return None
                exact64 = True
            if tag[0] == "range_bounded":
                # value-offset bounds need ONE plain numeric NaN-free
                # ORDER BY key (the host evaluator requires exactly one,
                # and NULL keys make the searched ranges ill-defined)
                if len(expr.order_by) != 1:
                    return None
                okey = expr.order_by[0][0]
                kd = (
                    np.dtype(jdf.device_cols[okey].dtype)
                    if okey in jdf.device_cols
                    else None
                )
                if (
                    not plain(okey)
                    or jdf.maybe_nan(okey)
                    or kd is None
                    or kd == np.dtype(np.bool_)
                    or not np.issubdtype(kd, np.number)
                ):
                    return None
                if not all(
                    o is None or isinstance(o, (int, float))
                    for o in tag[1:]
                ):
                    return None
            out_cast = None
            if exact64:
                specs.append((out_name, func, arg, tag, n_ord, "int64_exact"))
                continue
            if (masked_arg or bounded) and func in (
                "SUM",
                "MIN",
                "MAX",
                "AVG",
            ):
                # the host declares the ARG's type for these (int/long/
                # float/bool); the device computes float64 — mark for
                # conversion back to the EXACT declared dtype (values
                # ≤2^53 exact; the host passes through float64 too)
                import pyarrow as _pa

                tp = expr.infer_type(jdf.schema)
                if tp is not None and (
                    _pa.types.is_integer(tp)
                    or _pa.types.is_boolean(tp)
                    or tp == _pa.float32()
                ):
                    out_cast = np.dtype(tp.to_pandas_dtype()).name
            specs.append((out_name, func, arg, tag, n_ord, out_cast))
            continue
        return None
    return tuple(specs), pkeys, order_items


def plan_device_windows(
    jdf: Any, items: List[Tuple[str, _WindowExpr]]
) -> Optional[Tuple]:
    """Cheap eligibility gate — run BEFORE paying for WHERE filters or
    repartitions. Returns an opaque plan for :func:`run_device_windows`,
    or None for host fallback."""
    from .dataframe import JaxDataFrame

    if not isinstance(jdf, JaxDataFrame) or jdf.host_table is not None:
        return None
    if len(jdf.device_cols) != len(jdf.schema):
        return None
    return _plan_items(jdf, items)


def try_device_windows(
    engine: Any,
    jdf: Any,
    items: List[Tuple[str, _WindowExpr]],
) -> Optional[Any]:
    """Gate + run in one step (single-phase callers)."""
    plan = plan_device_windows(jdf, items)
    if plan is None:
        return None
    return run_device_windows(engine, jdf, plan)


def run_device_windows(engine: Any, jdf: Any, plan: Tuple) -> Optional[Any]:
    """Evaluate all window expressions on device; returns a JaxDataFrame of
    (original columns + one column per item), or None if the frame stopped
    being device-eligible since planning (e.g. a host-fallback filter)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as JP

    from ..collections.partition import PartitionSpec
    from ..parallel.mesh import ROW_AXIS
    from .dataframe import JaxDataFrame

    if not isinstance(jdf, JaxDataFrame) or jdf.host_table is not None:
        return None
    specs, pkeys, order_items = plan
    if len(pkeys) > 0:
        jdf = engine.repartition(jdf, PartitionSpec(algo="hash", by=pkeys))
    else:
        # global window: one partition ⇒ one shard (the serialization any
        # backend pays for a global OVER; other shards carry padding only)
        jdf = engine._repartition_single(jdf)
    if any(len(s) >= 6 and s[5] == "int64_exact" for s in specs):
        # the hi/lo split's float64 prefix sums are exact only while a
        # shard's low-word sum stays under 2^53: rows/shard < 2^21.
        # Checked AFTER the repartition — the exchange (hash skew, or the
        # global single-shard route) is what sets the real shard length.
        from ..parallel.mesh import num_row_shards

        padded = next(iter(jdf.device_cols.values())).shape[0]
        if padded // max(1, num_row_shards(jdf.mesh)) > (1 << 21):
            return None
    mesh = jdf.mesh
    cache = engine._jit_cache
    # null masks ride the sort as extra payload columns (mangled names) so
    # masked order keys / aggregate args keep NULL semantics
    mask_prefix = _safe_mask_prefix(jdf.schema.names)
    masked_cols = frozenset(jdf.null_masks)
    dict_cols = frozenset(
        c for c, enc in jdf.encodings.items() if enc.get("kind") == "dict"
    )
    # only ORDER-key dict membership shapes the compiled kernel (pkeys
    # compare as plain codes; payload-only encodings just ride the sort) —
    # keying on it alone keeps jit reuse across frames
    dict_order_cols = frozenset(
        n for n, _ in order_items if n in dict_cols
    )
    cache_key = (
        "window", mesh, specs, tuple(pkeys), tuple(order_items),
        dict_order_cols, masked_cols,
    )
    names_sig = tuple(jdf.schema.names)

    if (cache_key, names_sig) not in cache:

        def compute(cols: Dict[str, Any], valid: Any):
            def shard_fn(c: Dict[str, Any], v: Any):
                big = jnp.iinfo(jnp.int32).max
                ops: List[Any] = [jnp.logical_not(v)]
                for k in pkeys:
                    ops.append(c[k])
                for n, asc in order_items:
                    key = c[n]
                    if n in masked_cols:
                        # nullable int/bool: the mask flags NULL-last order
                        isnull = c[f"{mask_prefix}{n}"]
                        ops.append(isnull)
                        key = jnp.where(isnull, jnp.zeros((), key.dtype), key)
                        if not asc:
                            key = (
                                jnp.logical_not(key)
                                if key.dtype == jnp.bool_
                                else ~key
                            )
                        ops.append(key)
                    elif jnp.issubdtype(key.dtype, jnp.floating):
                        # host sorts with na_position="last"
                        isnan = jnp.isnan(key)
                        ops.append(isnan)
                        key = jnp.where(isnan, jnp.zeros((), key.dtype), key)
                        ops.append(-key if not asc else key)
                    elif n in dict_cols:
                        # sorted-dictionary codes: code order == lex order;
                        # -1 is NULL → order it LAST like the host
                        isnull = key < 0
                        ops.append(isnull)
                        ops.append(~key if not asc else key)
                    elif not asc:
                        ops.append(
                            jnp.logical_not(key)
                            if key.dtype == jnp.bool_
                            else ~key
                        )
                    else:
                        ops.append(key)
                names = list(c.keys())
                res = jax.lax.sort(
                    tuple(ops) + tuple(c[n] for n in names) + (v,),
                    num_keys=len(ops),
                )
                payload = res[len(ops):]
                sc = dict(zip(names, payload[: len(names)]))
                sv = payload[len(names)]
                n_rows = sv.shape[0]
                iota = jax.lax.iota(jnp.int32, n_rows)

                def nan_eq_diff(col: Any, mask: Any = None) -> Any:
                    a, b = col[1:], col[:-1]
                    neq = a != b
                    if jnp.issubdtype(col.dtype, jnp.floating):
                        neq = neq & ~(jnp.isnan(a) & jnp.isnan(b))
                    if mask is not None:
                        # NULLs compare equal to each other, never to values
                        ma, mb = mask[1:], mask[:-1]
                        neq = (neq & ~(ma & mb)) | (ma != mb)
                    return jnp.concatenate([jnp.ones((1,), bool), neq])

                def key_diff(n: str) -> Any:
                    m = (
                        sc[f"{mask_prefix}{n}"]
                        if n in masked_cols
                        else None
                    )
                    return nan_eq_diff(sc[n], m)

                seg_change = jnp.logical_not(sv)
                for k in pkeys:
                    seg_change = seg_change | key_diff(k)
                seg_change = seg_change.at[0].set(True)
                seg_start = jax.lax.cummax(
                    jnp.where(seg_change, iota, jnp.int32(-1))
                )

                def end_of_run(change: Any, cap_at: Any) -> Any:
                    """Last index of the run each row belongs to (a run
                    starts wherever ``change`` is True)."""
                    return jnp.minimum(
                        jnp.flip(
                            jax.lax.cummin(
                                jnp.flip(
                                    jnp.concatenate(
                                        [
                                            jnp.where(change, iota, big)[1:],
                                            jnp.full((1,), big, jnp.int32),
                                        ]
                                    )
                                )
                            )
                        )
                        - 1,
                        cap_at,
                    )

                seg_end = end_of_run(seg_change, jnp.int32(n_rows - 1))

                # peer (tied-order-key) machinery per ORDER BY prefix length
                peer_change_by: Dict[int, Any] = {0: seg_change}
                pc = seg_change
                for j, (n, _) in enumerate(order_items):
                    pc = pc | key_diff(n)
                    peer_change_by[j + 1] = pc
                peer_end_by = {
                    j: end_of_run(ch, seg_end) for j, ch in peer_change_by.items()
                }

                def seg_scan(op, x):
                    def combine(a, b):
                        af, av = a
                        bf, bv = b
                        return (af | bf, jnp.where(bf, bv, op(av, bv)))

                    _, out = jax.lax.associative_scan(
                        combine, (seg_change, x)
                    )
                    return out

                def prefix_tables(arg: Any):
                    """(masked values xm, running count n_run, running sum
                    c_run) with segment resets; NULL-skipping."""
                    x = sc[arg]
                    xf = x.astype(jnp.float64)
                    nn = sv & ~jnp.isnan(xf)
                    if arg in masked_cols:
                        nn = nn & jnp.logical_not(sc[f"{mask_prefix}{arg}"])
                    xm = jnp.where(nn, xf, 0.0)
                    c = jnp.cumsum(xm)
                    cnt = jnp.cumsum(nn.astype(jnp.float64))
                    # segment-relative prefixes via the value at seg_start
                    c0 = c[seg_start] - xm[seg_start]
                    n0 = cnt[seg_start] - nn[seg_start].astype(jnp.float64)
                    return xf, nn, xm, c - c0, cnt - n0, c, cnt

                outs: Dict[str, Any] = {}
                for spec in specs:
                    out_name, func = spec[0], spec[1]
                    if func == "ROW_NUMBER":
                        outs[out_name] = (iota - seg_start + 1).astype(jnp.int64)
                        continue
                    if func == "RANK":
                        pch = peer_change_by[spec[2]]
                        rank_start = jax.lax.cummax(
                            jnp.where(pch, iota, jnp.int32(-1))
                        )
                        outs[out_name] = (rank_start - seg_start + 1).astype(
                            jnp.int64
                        )
                        continue
                    if func == "DENSE_RANK":
                        pcum = jnp.cumsum(
                            peer_change_by[spec[2]].astype(jnp.int64)
                        )
                        outs[out_name] = pcum - pcum[seg_start] + 1
                        continue
                    if func in ("LAG", "LEAD"):
                        _, _, arg, offset, default = spec
                        x = sc[arg]
                        shift = offset if func == "LAG" else -offset
                        idx = iota - shift
                        ok = (
                            (idx >= seg_start) & (idx <= seg_end)
                            if func == "LEAD"
                            else (idx >= seg_start)
                        )
                        val = x[jnp.clip(idx, 0, n_rows - 1)]
                        if default is None:
                            valf = val.astype(jnp.float64)
                            outs[out_name] = jnp.where(ok, valf, jnp.nan)
                        else:
                            outs[out_name] = jnp.where(
                                ok, val, jnp.asarray(default, dtype=x.dtype)
                            )
                        continue
                    # aggregates
                    _, _, arg, tag, n_ord = spec[:5]
                    oc = spec[5] if len(spec) >= 6 else None
                    if oc == "int64_exact":
                        # masked int64 over running/peers/whole frames:
                        # EXACT semantics mirroring ops/segment.py — hi/lo
                        # 32-bit split sums (each side's float64 prefix sum
                        # stays exact for shards < 2^21 rows, guarded at
                        # plan-run time), recombined in wrapping int64
                        # arithmetic like the pandas oracle's cumsum;
                        # MIN/MAX scan the raw int domain.
                        x = sc[arg]
                        nnm = sv & jnp.logical_not(
                            sc[f"{mask_prefix}{arg}"]
                        )
                        nn64 = nnm.astype(jnp.float64)

                        def rel_prefix(cvals: Any) -> Any:
                            cc = jnp.cumsum(cvals)
                            return cc - (cc[seg_start] - cvals[seg_start])

                        at = (
                            iota
                            if tag[0] == "running"
                            else (
                                peer_end_by[n_ord]
                                if tag[0] == "peers"
                                else seg_end
                            )
                        )
                        count = rel_prefix(nn64)[at]
                        if func in ("SUM", "AVG"):
                            xm64 = jnp.where(nnm, x, jnp.int64(0))
                            lo32 = (
                                xm64 & jnp.int64(0xFFFFFFFF)
                            ).astype(jnp.float64)
                            hi32 = (xm64 >> 32).astype(jnp.float64)
                            s_int = (
                                rel_prefix(hi32)[at].astype(jnp.int64) << 32
                            ) + rel_prefix(lo32)[at].astype(jnp.int64)
                            if func == "SUM":
                                outs[out_name] = s_int
                                outs[f"{mask_prefix}{out_name}"] = count == 0
                            else:  # AVG: exact int sum → one f64 rounding
                                outs[out_name] = jnp.where(
                                    count > 0,
                                    s_int.astype(jnp.float64)
                                    / jnp.where(count > 0, count, 1.0),
                                    jnp.nan,
                                )
                            continue
                        # MIN/MAX in the int domain
                        op = jnp.minimum if func == "MIN" else jnp.maximum
                        fillv = (
                            jnp.iinfo(jnp.int64).max
                            if func == "MIN"
                            else jnp.iinfo(jnp.int64).min
                        )
                        xs64 = jnp.where(nnm, x, jnp.int64(fillv))
                        outs[out_name] = seg_scan(op, xs64)[at]
                        outs[f"{mask_prefix}{out_name}"] = count == 0
                        continue
                    xf, nn, xm, c_rel, n_rel, c_abs, n_abs = prefix_tables(arg)
                    if tag[0] == "whole":
                        total = c_rel[seg_end]
                        count = n_rel[seg_end]
                        if func == "COUNT":
                            outs[out_name] = count.astype(jnp.int64)
                        elif func == "SUM":
                            outs[out_name] = total
                        elif func == "AVG":
                            outs[out_name] = total / jnp.where(count > 0, count, jnp.nan)
                        elif func in ("MIN", "MAX"):
                            op = jnp.minimum if func == "MIN" else jnp.maximum
                            fill = jnp.inf if func == "MIN" else -jnp.inf
                            xs = jnp.where(nn, xf, fill)
                            run = seg_scan(op, xs)
                            ext = run[seg_end]
                            outs[out_name] = jnp.where(
                                n_rel[seg_end] > 0, ext, jnp.nan
                            )
                        elif func == "FIRST":
                            outs[out_name] = sc[arg][seg_start]
                        else:  # LAST
                            outs[out_name] = sc[arg][seg_end]
                        continue
                    if tag[0] in ("running", "peers"):
                        at = peer_end_by[n_ord] if tag[0] == "peers" else iota
                        count = n_rel[at]
                        if func == "COUNT":
                            outs[out_name] = count.astype(jnp.int64)
                        elif func in ("SUM", "AVG"):
                            s = c_rel[at]
                            r = s / count if func == "AVG" else s
                            outs[out_name] = jnp.where(count > 0, r, jnp.nan)
                        elif func in ("MIN", "MAX"):
                            op = jnp.minimum if func == "MIN" else jnp.maximum
                            fill = jnp.inf if func == "MIN" else -jnp.inf
                            xs = jnp.where(nn, xf, fill)
                            run = seg_scan(op, xs)[at]
                            outs[out_name] = jnp.where(count > 0, run, jnp.nan)
                        elif func == "FIRST":
                            outs[out_name] = sc[arg][seg_start]
                        else:  # LAST: value at the frame end
                            outs[out_name] = sc[arg][at]
                        continue
                    # bounded frames: per-row inclusive [lo, hi] indices,
                    # then prefix-diff (SUM/COUNT/AVG) or sparse-table
                    # range queries (MIN/MAX). A None offset is unbounded
                    # → the segment edge.
                    lo_off, hi_off = tag[1], tag[2]
                    if tag[0] == "rows_bounded":
                        lo = (
                            seg_start
                            if lo_off is None
                            else jnp.maximum(seg_start, iota + lo_off)
                        )
                        hi = (
                            seg_end
                            if hi_off is None
                            else jnp.minimum(seg_end, iota + hi_off)
                        )
                    else:  # range_bounded: value distances on the order key
                        okname, oasc = order_items[0]
                        kv = sc[okname].astype(jnp.float64)
                        if not oasc:
                            kv = -kv  # ascending view (host: sign * okey)

                        def bsearch(targets: Any, right: bool) -> Any:
                            """Per-row binary search of ``targets`` within
                            each row's own [seg_start, seg_end] span of the
                            sorted ``kv`` — first index where kv >= target
                            (or > target when ``right``)."""
                            def step(_, lh):
                                lo_, hi_ = lh
                                ok = lo_ < hi_
                                mid = (lo_ + hi_) // 2
                                km = kv[jnp.clip(mid, 0, n_rows - 1)]
                                go = (km <= targets) if right else (km < targets)
                                return (
                                    jnp.where(ok & go, mid + 1, lo_),
                                    jnp.where(ok & jnp.logical_not(go), mid, hi_),
                                )

                            lo0, _ = jax.lax.fori_loop(
                                0,
                                max(1, int(n_rows).bit_length()),
                                step,
                                (seg_start, seg_end + 1),
                            )
                            return lo0

                        lo = (
                            seg_start
                            if lo_off is None
                            else bsearch(kv + float(lo_off), right=False)
                        )
                        hi = (
                            seg_end
                            if hi_off is None
                            else bsearch(kv + float(hi_off), right=True) - 1
                        )
                    empty = hi < lo
                    lo_c = jnp.clip(lo, 0, n_rows - 1)
                    hi_c = jnp.clip(hi, 0, n_rows - 1)
                    count = n_abs[hi_c] - n_abs[lo_c] + nn[lo_c].astype(jnp.float64)
                    count = jnp.where(empty, 0.0, count)
                    if func == "COUNT":
                        outs[out_name] = count.astype(jnp.int64)
                    elif func in ("SUM", "AVG"):
                        s = c_abs[hi_c] - c_abs[lo_c] + xm[lo_c]
                        s = jnp.where(empty, 0.0, s)
                        if func == "SUM":
                            outs[out_name] = jnp.where(count > 0, s, jnp.nan)
                        else:
                            outs[out_name] = jnp.where(
                                count > 0,
                                s / jnp.where(count > 0, count, 1.0),
                                jnp.nan,
                            )
                    else:  # MIN/MAX: sparse table over NULL-filled values
                        op = jnp.minimum if func == "MIN" else jnp.maximum
                        fill = jnp.inf if func == "MIN" else -jnp.inf
                        xs = jnp.where(nn, xf, fill)
                        # levels cover the largest possible window length
                        if (
                            tag[0] == "rows_bounded"
                            and lo_off is not None
                            and hi_off is not None
                        ):
                            max_len = min(
                                int(n_rows), max(1, hi_off - lo_off + 1)
                            )
                        else:
                            max_len = int(n_rows)
                        lv = max(1, (max_len - 1).bit_length())
                        tables = [xs]
                        for j in range(lv):
                            stp = 1 << j
                            prev = tables[-1]
                            tables.append(
                                op(
                                    prev,
                                    jnp.concatenate(
                                        [
                                            prev[stp:],
                                            jnp.full((stp,), fill, prev.dtype),
                                        ]
                                    ),
                                )
                            )
                        st = jnp.stack(tables)  # (lv+1, n_rows)
                        ln = jnp.maximum(hi - lo + 1, 1)
                        ks = (
                            ln[:, None]
                            >= jnp.left_shift(
                                jnp.int32(1), jnp.arange(1, lv + 1, dtype=jnp.int32)
                            )[None, :]
                        ).sum(axis=1)
                        second = jnp.clip(
                            hi - jnp.left_shift(jnp.int32(1), ks) + 1,
                            0,
                            n_rows - 1,
                        )
                        res = op(st[ks, lo_c], st[ks, second])
                        outs[out_name] = jnp.where(count > 0, res, jnp.nan)
                sc_out = dict(sc)
                sc_out.update(outs)
                sc_out["__wvalid__"] = sv
                return sc_out

            return shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(JP(ROW_AXIS), JP(ROW_AXIS)),
                out_specs=JP(ROW_AXIS),
            )(cols, valid)

        cache[(cache_key, names_sig)] = jax.jit(compute)
    payload = dict(jdf.device_cols)
    for c_, m_ in jdf.null_masks.items():
        payload[f"{mask_prefix}{c_}"] = m_
    out = cache[(cache_key, names_sig)](payload, jdf.device_valid_mask())
    new_valid = out.pop("__wvalid__")
    out_masks = {
        c_: out.pop(f"{mask_prefix}{c_}") for c_ in jdf.null_masks
    }
    _PA_NAMES = {
        "int8", "int16", "int32", "int64",
        "uint8", "uint16", "uint32", "uint64",
        "float32", "float64", "bool",
    }
    import pyarrow as pa

    extra_fields = []
    for spec in specs:
        arr = out[spec[0]]
        out_cast = spec[5] if len(spec) >= 6 else None
        if out_cast == "int64_exact":
            # the kernel emitted the final dtype + a null marker directly
            if spec[1] in ("SUM", "MIN", "MAX"):
                out_masks[spec[0]] = out.pop(f"{mask_prefix}{spec[0]}")
            out_cast = None
        if out_cast is not None:
            # masked-arg/bounded-frame aggregates computed in float64 with
            # NaN=NULL — restore the exact declared dtype, like the host's
            # own float64 round trip. float32 keeps NaN as its NULL; the
            # integer/bool dtypes need a null mask.
            import jax as _jax
            import jax.numpy as _jnp

            ck = ("wcast", out_cast, mesh)
            if ck not in cache:
                if out_cast == "float32":
                    cache[ck] = _jax.jit(
                        lambda a: a.astype(_jnp.float32)
                    )
                else:

                    def _conv(a: Any, _t: str = out_cast):
                        m = _jnp.isnan(a)
                        vals = _jnp.where(m, 0.0, a).astype(_jnp.dtype(_t))
                        return vals, m

                    cache[ck] = _jax.jit(_conv)
            if out_cast == "float32":
                arr = cache[ck](arr)
                out[spec[0]] = arr
            else:
                vals, m = cache[ck](arr)
                out[spec[0]] = vals
                out_masks[spec[0]] = m
                arr = vals
        tname = str(arr.dtype)
        if tname not in _PA_NAMES:
            return None  # unexpected dtype — let the host path handle it
        extra_fields.append(pa.field(spec[0], Schema(f"x:{tname}").types[0]))
    work_schema = Schema(list(jdf.schema.fields) + extra_fields)
    return JaxDataFrame(
        mesh=mesh,
        _internal=dict(
            device_cols={n: out[n] for n in work_schema.names},
            host_tbl=None,
            row_count=jdf._row_count,
            valid_mask=new_valid,
            nan_cols=None,
            # encoded columns rode the sort as codes — their encodings
            # still describe them
            encodings=dict(jdf.encodings),
            # sorted alongside their columns — still row-aligned
            null_masks=out_masks,
            schema=work_schema,
        ),
    )
