"""JaxDataFrame — rows sharded over a device mesh as columnar jax.Arrays.

The TPU-native distributed frame (SURVEY §7.1 "ShardedJaxDataFrame"):

- numeric/bool columns live on device, padded to a multiple of the mesh row
  axis and sharded ``NamedSharding(mesh, P("rows"))``;
- variable-width / nullable-int / nested columns stay host-resident as an
  arrow table aligned by row position (the reference leans on arrow for the
  same data, SURVEY §7 hard parts);
- ``row_count`` tracks the unpadded logical length; padding is masked out in
  device ops and sliced off on conversion back to arrow.
"""

from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..dataframe import ArrowDataFrame, DataFrame, LocalBoundedDataFrame
from ..dataframe.arrow_dataframe import build_arrow_table
from ..exceptions import FugueDataFrameInitError, FugueDataFrameOperationError
from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows, row_sharding
from ..schema import Schema

_DEVICE_DTYPES = {
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "uint8": np.uint8,
    "uint16": np.uint16,
    "uint32": np.uint32,
    "uint64": np.uint64,
    "halffloat": np.float16,
    "float": np.float32,
    "double": np.float64,
    "bool": np.bool_,
}


def _is_device_type(f: pa.Field) -> bool:
    return str(f.type) in _DEVICE_DTYPES


def split_arrow_for_device(tbl: pa.Table) -> Any:
    """Split an arrow table into (device_candidate_cols, host_cols, nan_cols).

    Numeric/bool columns WITHOUT nulls go to device (floats may carry nulls
    as NaN); everything else stays host-side. ``nan_cols`` is the set of
    device float columns that actually contain NaN — kernels skip NULL
    masking for columns proved NaN-free (the common case).
    """
    device_cols: Dict[str, np.ndarray] = {}
    host_names: List[str] = []
    nan_cols: set = set()
    for i, f in enumerate(tbl.schema):
        col = tbl.column(i)
        # nulls can't live on device yet (NaN would silently conflate with
        # null on the way back) — nullable columns stay host-resident
        if _is_device_type(f) and col.null_count == 0:
            arr = np.asarray(col.to_numpy(zero_copy_only=False))
            device_cols[f.name] = arr
            if np.issubdtype(arr.dtype, np.floating) and bool(
                np.isnan(arr).any()
            ):
                nan_cols.add(f.name)
        else:
            host_names.append(f.name)
    host_tbl = tbl.select(host_names) if len(host_names) > 0 else None
    return device_cols, host_tbl, nan_cols


class JaxDataFrame(DataFrame):
    """Distributed frame over a jax device mesh."""

    def __init__(
        self,
        df: Any = None,
        schema: Any = None,
        mesh: Any = None,
        _internal: Optional[dict] = None,
    ):
        if mesh is None:
            from ..parallel.mesh import build_mesh

            mesh = build_mesh()
        self._mesh = mesh
        if _internal is not None:
            self._device_cols = _internal["device_cols"]
            self._host_tbl = _internal["host_tbl"]
            self._row_count = _internal["row_count"]
            self._valid_mask = _internal.get("valid_mask", None)
            # None = unknown → treat every float column as possibly-NaN
            self._nan_cols = _internal.get("nan_cols", None)
            super().__init__(_internal["schema"])
            return
        s = None if schema is None else (schema if isinstance(schema, Schema) else Schema(schema))
        if isinstance(df, JaxDataFrame):
            if s is not None and s != df.schema:
                # schema change requires real conversion, not a relabel
                self._from_arrow(df.as_arrow().cast(s.pa_schema))
                super().__init__(s)
                return
            self._device_cols = dict(df._device_cols)
            self._host_tbl = df._host_tbl
            self._row_count = df._row_count
            self._valid_mask = df._valid_mask
            self._nan_cols = df._nan_cols
            super().__init__(df.schema)
            return
        if isinstance(df, DataFrame):
            tbl = df.as_arrow()
            if s is not None and Schema(tbl.schema) != s:
                tbl = tbl.cast(s.pa_schema)
        else:
            tbl = build_arrow_table(df, s)
        self._from_arrow(tbl)
        super().__init__(Schema(tbl.schema))

    def _from_arrow(self, tbl: pa.Table) -> None:
        import jax

        n = tbl.num_rows
        shards = num_row_shards(self._mesh)
        padded = pad_rows(max(n, shards), shards) if n > 0 else shards
        np_cols, host_tbl, nan_cols = split_arrow_for_device(tbl)
        sharding = row_sharding(self._mesh)
        device_cols: Dict[str, Any] = {}
        for name, arr in np_cols.items():
            if len(arr) < padded:
                pad_val = np.zeros(padded - len(arr), dtype=arr.dtype)
                arr = np.concatenate([arr, pad_val])
            device_cols[name] = jax.device_put(arr, sharding)
        self._device_cols = device_cols
        self._host_tbl = host_tbl
        self._row_count = n
        # None = tail-padding semantics (rows [0, row_count) valid); a device
        # bool array = explicit per-row validity (result of device filters)
        self._valid_mask = None
        self._nan_cols = nan_cols

    # -- properties ---------------------------------------------------------
    @property
    def mesh(self) -> Any:
        return self._mesh

    @property
    def device_cols(self) -> Dict[str, Any]:
        return self._device_cols

    @property
    def host_table(self) -> Optional[pa.Table]:
        return self._host_tbl

    @property
    def valid_mask(self) -> Any:
        """Explicit device validity mask, or None for tail-padding."""
        return self._valid_mask

    def maybe_nan(self, name: str) -> bool:
        """Whether device float column ``name`` may contain NaN (i.e. NULL).

        False only when ingestion proved the column NaN-free; unknown
        provenance (e.g. transformer outputs) is conservatively True.
        """
        if self._nan_cols is None:
            return True
        return name in self._nan_cols

    def device_valid_mask(self) -> Any:
        """A device bool array marking valid rows (built from the row count
        when no explicit mask exists)."""
        if self._valid_mask is not None:
            return self._valid_mask
        import numpy as _np

        from ..ops.segment import _get_compiled_mask

        template = next(iter(self._device_cols.values()))
        return _get_compiled_mask(self._mesh)(template, _np.int64(self._row_count))

    @property
    def native(self) -> "JaxDataFrame":
        # the device frame IS the native object (like a Ray dataset); raw
        # buffers are available via .device_cols — returning those from
        # fa.* verbs would leak padding rows and drop the validity mask
        return self

    @property
    def is_local(self) -> bool:
        return False

    @property
    def is_bounded(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return num_row_shards(self._mesh)

    @property
    def empty(self) -> bool:
        return self.count() == 0

    def count(self) -> int:
        if self._valid_mask is not None and self._row_count < 0:
            import jax as _jax

            self._row_count = int(_jax.device_get(self._valid_mask.sum()))
        return self._row_count

    # -- conversions --------------------------------------------------------
    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        import jax

        mask: Optional[np.ndarray] = None
        if self._valid_mask is not None:
            mask = np.asarray(jax.device_get(self._valid_mask))
        arrays: List[pa.Array] = []
        for f in self.schema.fields:
            if f.name in self._device_cols:
                host = np.asarray(jax.device_get(self._device_cols[f.name]))
                host = host[mask] if mask is not None else host[: self._row_count]
                arrays.append(pa.array(host).cast(f.type, safe=False))
            else:
                assert self._host_tbl is not None
                col = self._host_tbl.column(f.name)
                if mask is not None:
                    col = col.filter(pa.array(mask[: len(col)]))
                else:
                    col = col.slice(0, self._row_count)
                arrays.append(col.combine_chunks())
        return pa.Table.from_arrays(arrays, schema=self.schema.pa_schema)

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        res = ArrowDataFrame(self.as_arrow())
        if self.has_metadata:
            res.reset_metadata(self.metadata)
        return res

    def as_pandas(self) -> pd.DataFrame:
        return self.as_arrow().to_pandas(use_threads=False)

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return ArrowDataFrame(self.as_arrow().slice(0, 1)).peek_array()

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        return ArrowDataFrame(self.as_arrow()).as_array(columns)

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        yield from ArrowDataFrame(self.as_arrow()).as_array_iterable(columns)

    # -- ops ----------------------------------------------------------------
    def _with(self, schema: Schema, device_cols: Dict[str, Any], host_tbl: Optional[pa.Table]) -> "JaxDataFrame":
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=device_cols,
                host_tbl=host_tbl,
                row_count=self._row_count,
                valid_mask=self._valid_mask,
                nan_cols=self._nan_cols,
                schema=schema,
            ),
        )

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema - cols
        dc = {k: v for k, v in self._device_cols.items() if k in schema}
        keep_host = [n for n in schema.names if n not in dc]
        ht = self._host_tbl.select(keep_host) if len(keep_host) > 0 else None
        return self._with(schema, dc, ht)

    def _select_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.extract(cols)
        dc = {k: v for k, v in self._device_cols.items() if k in schema}
        keep_host = [n for n in schema.names if n not in dc]
        ht = self._host_tbl.select(keep_host) if len(keep_host) > 0 else None
        return self._with(schema, dc, ht)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        schema = self.schema.rename(columns)  # validates
        dc = {columns.get(k, k): v for k, v in self._device_cols.items()}
        ht = (
            self._host_tbl.rename_columns(
                [columns.get(n, n) for n in self._host_tbl.column_names]
            )
            if self._host_tbl is not None
            else None
        )
        res = self._with(schema, dc, ht)
        if self._nan_cols is not None:
            res._nan_cols = {columns.get(n, n) for n in self._nan_cols}
        return res

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self.schema.alter(columns)
        if new_schema == self.schema:
            return self
        # simplest correct path: round trip through arrow
        return JaxDataFrame(
            ArrowDataFrame(self.as_arrow()).alter_columns(columns),
            mesh=self._mesh,
        )

    def head(self, n: int, columns: Optional[List[str]] = None) -> LocalBoundedDataFrame:
        tbl = self.as_arrow()
        if columns is not None:
            tbl = tbl.select(columns)
        return ArrowDataFrame(tbl.slice(0, n))
