"""JaxDataFrame — rows sharded over a device mesh as columnar jax.Arrays.

The TPU-native distributed frame (SURVEY §7.1 "ShardedJaxDataFrame"):

- numeric/bool columns live on device, padded to a multiple of the mesh row
  axis and sharded ``NamedSharding(mesh, P("rows"))``; floats carry NULL as
  NaN;
- string columns are DICTIONARY-ENCODED: an int32 code array on device
  (−1 = NULL) plus the small host-side ``pa.Array`` dictionary — groupby /
  distinct / filter on strings run on device over codes, and string
  predicates evaluate host-side over the dictionary into a lookup table
  gathered by code (SURVEY §7 hard parts);
- nullable int/bool columns carry a per-column device null mask; timestamps
  and dates live as epoch int64/int32 with the original arrow type restored
  on conversion;
- anything else (binary, nested, decimal) stays host-resident as an arrow
  table aligned by row position;
- ``row_count`` tracks the unpadded logical length; padding is masked out in
  device ops and sliced off on conversion back to arrow.
"""

from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..dataframe import ArrowDataFrame, DataFrame, LocalBoundedDataFrame
from ..dataframe.arrow_dataframe import build_arrow_table
from ..exceptions import FugueDataFrameInitError, FugueDataFrameOperationError
from ..parallel.mesh import ROW_AXIS, num_row_shards, pad_rows, row_sharding
from ..schema import Schema

_DEVICE_DTYPES = {
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "uint8": np.uint8,
    "uint16": np.uint16,
    "uint32": np.uint32,
    "uint64": np.uint64,
    "halffloat": np.float16,
    "float": np.float32,
    "double": np.float64,
    "bool": np.bool_,
}


# bulk-ingest tables below this size skip the background column pipeline:
# thread + queue setup (~1ms) would exceed the decode it hides
_MIN_PIPELINED_INGEST_BYTES = 8 << 20


def _is_device_type(f: pa.Field) -> bool:
    return str(f.type) in _DEVICE_DTYPES


def split_arrow_for_device(tbl: pa.Table) -> Any:
    """Back-compat split: (plain_device_cols, host_cols, nan_cols).

    Only null-free numeric/bool columns are treated as device candidates —
    the encoding-aware path is :func:`encode_arrow_for_device`.
    """
    device_cols, host_tbl, meta = encode_arrow_for_device(tbl, encode=False)
    return device_cols, host_tbl, meta["nan_cols"]


def _encode_column(col: Any, f: pa.Field, encode: bool) -> Any:
    """Encode ONE arrow column for the device: ``(arr, extra)``.

    ``arr`` is the device-bound numpy array, or None when the column stays
    host-resident. ``extra`` carries the per-column metadata: ``nan``
    (float column may hold NaN), ``encoding`` (dict/datetime internal
    representation) and ``null_mask`` (np bool array, True = NULL).
    The per-column unit of work for the pipelined ingest (`_from_arrow`) —
    the whole-table collector is :func:`encode_arrow_for_device`.
    """
    t = f.type
    if _is_device_type(f):
        if col.null_count == 0:
            arr = np.asarray(col.to_numpy(zero_copy_only=False))
            nan = np.issubdtype(arr.dtype, np.floating) and bool(
                np.isnan(arr).any()
            )
            return arr, ({"nan": True} if nan else {})
        if encode and pa.types.is_floating(t):
            # arrow float→numpy turns nulls into NaN — the device NULL
            arr = np.asarray(col.to_numpy(zero_copy_only=False))
            return arr, {"nan": True}
        if encode:  # nullable int/bool: value array + null mask
            mask = np.asarray(col.is_null().to_numpy(zero_copy_only=False))
            fill = False if pa.types.is_boolean(t) else 0
            vals = np.asarray(
                col.fill_null(fill).to_numpy(zero_copy_only=False)
            )
            return vals, {"null_mask": mask}
    if encode and (pa.types.is_string(t) or pa.types.is_large_string(t)):
        plain = (
            col.chunk(0)
            if isinstance(col, pa.ChunkedArray) and col.num_chunks == 1
            else (
                pa.array([], type=t)
                if isinstance(col, pa.ChunkedArray) and col.num_chunks == 0
                else col
            )
        )
        if isinstance(plain, pa.ChunkedArray):  # pragma: no cover
            plain = pa.concat_arrays(plain.chunks)
        d = plain.dictionary_encode()
        codes = np.asarray(
            d.indices.fill_null(-1).to_numpy(zero_copy_only=False)
        ).astype(np.int32)
        # SORT the dictionary so code order == lexicographic order:
        # MIN/MAX aggregates and presorts on the codes are then exact
        dictionary = d.dictionary.cast(t)
        if len(dictionary) > 1:
            order = np.asarray(
                pa.compute.sort_indices(dictionary).to_numpy(
                    zero_copy_only=False
                )
            )
            dictionary = dictionary.take(pa.array(order))
            inverse = np.empty(len(order), dtype=np.int32)
            inverse[order] = np.arange(len(order), dtype=np.int32)
            codes = np.where(codes >= 0, inverse[np.clip(codes, 0, None)], -1).astype(np.int32)
        return codes, {
            "encoding": {
                "kind": "dict",
                "dictionary": dictionary,
                "type": t,
                "sorted": True,
            }
        }
    if encode and (pa.types.is_timestamp(t) or pa.types.is_date(t)):
        storage = pa.int64() if not pa.types.is_date32(t) else pa.int32()
        ints = col.cast(storage)
        extra: Dict[str, Any] = {
            "encoding": {"kind": "datetime", "dictionary": None, "type": t}
        }
        if col.null_count > 0:
            extra["null_mask"] = np.asarray(
                col.is_null().to_numpy(zero_copy_only=False)
            )
            ints = ints.fill_null(0)
        return np.asarray(ints.to_numpy(zero_copy_only=False)), extra
    return None, None  # host-resident


def encode_arrow_for_device(tbl: pa.Table, encode: bool = True) -> Any:
    """Encode an arrow table for the device: (device_cols, host_tbl, meta).

    ``meta`` has:

    - ``nan_cols``: float columns that may contain NaN (device NULL);
    - ``encodings``: ``{name: {"kind": "dict"|"datetime", "dictionary":
      pa.Array|None, "type": pa.DataType}}`` — internal representations
      whose original arrow type is restored on conversion back;
    - ``null_masks``: ``{name: np bool array}`` — per-column null masks for
      nullable int/bool/datetime columns (True = NULL).
    """
    device_cols: Dict[str, np.ndarray] = {}
    host_names: List[str] = []
    meta: Dict[str, Any] = {"nan_cols": set(), "encodings": {}, "null_masks": {}}
    for i, f in enumerate(tbl.schema):
        arr, extra = _encode_column(tbl.column(i).combine_chunks(), f, encode)
        if arr is None:
            host_names.append(f.name)
            continue
        device_cols[f.name] = arr
        if extra.get("nan"):
            meta["nan_cols"].add(f.name)
        if "encoding" in extra:
            meta["encodings"][f.name] = extra["encoding"]
        if "null_mask" in extra:
            meta["null_masks"][f.name] = extra["null_mask"]
    host_tbl = tbl.select(host_names) if len(host_names) > 0 else None
    return device_cols, host_tbl, meta


def _nan_to_null(tbl: pa.Table) -> pa.Table:
    """Literal NaN → NULL in float columns (the device NULL convention),
    applied to host reads of never-ingested frames."""
    import pyarrow.compute as pc

    arrays: List[Any] = []
    changed = False
    for f in tbl.schema:
        col = tbl.column(f.name)
        if pa.types.is_floating(f.type):
            nan = pc.fill_null(pc.is_nan(col), False)
            if (pc.sum(nan).as_py() or 0) > 0:
                col = pc.if_else(nan, pa.scalar(None, f.type), col)
                changed = True
        arrays.append(col)
    if not changed:
        return tbl
    return pa.Table.from_arrays(arrays, schema=tbl.schema)


class JaxDataFrame(DataFrame):
    """Distributed frame over a jax device mesh."""

    def __init__(
        self,
        df: Any = None,
        schema: Any = None,
        mesh: Any = None,
        _internal: Optional[dict] = None,
        ingest_cache: Optional[bool] = None,
        ingest_prefetch_depth: Optional[int] = None,
        pipeline_stats: Any = None,
    ):
        if mesh is None:
            from ..parallel.mesh import build_mesh

            mesh = build_mesh()
        self._mesh = mesh
        # None → fall back to the global conf (engines pass their own conf)
        self._ingest_cache_opt = ingest_cache
        # pipelined ingest knobs (engines pass their conf's prefetch depth
        # and their PipelineStats sink; direct constructions use defaults)
        self._ingest_prefetch_depth = ingest_prefetch_depth
        self._pipeline_stats = pipeline_stats
        if _internal is not None:
            self._pending_tbl = None
            self._pending_src = None
            self._device_cols = _internal["device_cols"]
            self._host_tbl = _internal["host_tbl"]
            self._row_count = _internal["row_count"]
            self._valid_mask = _internal.get("valid_mask", None)
            # None = unknown → treat every float column as possibly-NaN
            self._nan_cols = _internal.get("nan_cols", None)
            self._encodings = _internal.get("encodings", None) or {}
            self._null_masks = _internal.get("null_masks", None) or {}
            super().__init__(_internal["schema"])
            return
        s = None if schema is None else (schema if isinstance(schema, Schema) else Schema(schema))
        if isinstance(df, JaxDataFrame):
            if s is not None and s != df.schema:
                # schema change requires real conversion, not a relabel
                self._set_pending(df.as_arrow().cast(s.pa_schema))
                super().__init__(s)
                return
            src_pending = getattr(df, "_pending_tbl", None)
            src_frame = getattr(df, "_pending_src", None)
            if src_pending is not None or src_frame is not None:
                self._set_pending(src_pending, src=src_frame)
                super().__init__(df.schema)
                return
            self._pending_tbl = None
            self._pending_src = None
            self._device_cols = dict(df._device_cols)
            self._host_tbl = df._host_tbl
            self._ingest_tbl = getattr(df, "_ingest_tbl", None)
            self._row_count = df._row_count
            self._valid_mask = df._valid_mask
            self._nan_cols = df._nan_cols
            self._encodings = dict(df._encodings)
            self._null_masks = dict(df._null_masks)
            super().__init__(df.schema)
            return
        if isinstance(df, DataFrame):
            if (s is None or s == df.schema) and df.is_local and df.is_bounded:
                # retain the SOURCE frame: host reads of a never-device-
                # touched frame return it as-is (zero conversions); arrow
                # conversion happens only if the device (or an arrow read)
                # actually needs it
                self._set_pending(None, src=df)  # type: ignore[arg-type]
                super().__init__(df.schema)
                return
            tbl = df.as_arrow()
            if s is not None and Schema(tbl.schema) != s:
                tbl = tbl.cast(s.pa_schema)
        else:
            tbl = build_arrow_table(df, s)
        self._set_pending(tbl)
        super().__init__(Schema(tbl.schema))

    def _set_pending(
        self, tbl: Optional[pa.Table], src: Optional[DataFrame] = None
    ) -> None:
        """LAZY ingestion: hold the arrow table (or the untouched source
        frame); device transfer happens on the FIRST device-facing access
        (`device_cols`/`null_masks`/…).

        Host reads (``as_arrow``/``as_pandas``/``count``) of a never-
        device-touched frame come straight from the pending table/source,
        so a host-map result that flows back to the host — the reference's
        default `transform()` shape, where the answer is fetched
        immediately — never pays a device round trip (or even an arrow
        conversion) at all."""
        import threading

        self._pending_tbl: Optional[pa.Table] = tbl
        self._pending_src: Optional[DataFrame] = src
        self._pending_lock = threading.Lock()
        self._device_cols = {}
        self._host_tbl = None
        self._ingest_tbl = None
        self._row_count = tbl.num_rows if tbl is not None else src.count()  # type: ignore[union-attr]
        self._valid_mask = None
        self._nan_cols = None
        self._encodings = {}
        self._null_masks = {}

    def _has_pending(self) -> bool:
        return (
            getattr(self, "_pending_tbl", None) is not None
            or getattr(self, "_pending_src", None) is not None
        )

    def _pending_table(self) -> pa.Table:
        """The pending arrow table, converting (and caching) from the
        retained source frame on first need. Callers must hold
        ``_pending_lock`` (or use ``_pending_snapshot``)."""
        if self._pending_tbl is None:
            self._pending_tbl = self._pending_src.as_arrow()  # type: ignore[union-attr]
        return self._pending_tbl

    def _pending_snapshot(self) -> Optional[pa.Table]:
        """Lock-guarded read of the pending table — safe against a
        concurrent ``_ensure_device`` nulling the pending fields."""
        if not self._has_pending():
            return None
        with self._pending_lock:
            if not self._has_pending():
                return None
            return self._pending_table()

    def _ensure_device(self) -> None:
        if not self._has_pending():
            return
        with self._pending_lock:
            if not self._has_pending():  # raced: another thread ingested
                return
            self._from_arrow(self._pending_table())
            self._pending_tbl = None
            self._pending_src = None

    def _from_arrow(self, tbl: pa.Table) -> None:
        import jax

        n = tbl.num_rows
        shards = num_row_shards(self._mesh)
        padded = pad_rows(max(n, shards), shards) if n > 0 else shards
        sharding = row_sharding(self._mesh)

        def _pad(arr: np.ndarray) -> np.ndarray:
            if len(arr) < padded:
                pad_val = np.zeros(padded - len(arr), dtype=arr.dtype)
                arr = np.concatenate([arr, pad_val])
            return arr

        # PIPELINED bulk ingest: a background producer decodes + pads the
        # NEXT column (arrow→numpy, dictionary encode, null masks) while
        # the consumer issues the H2D `device_put` of the CURRENT one —
        # the per-column analog of the chunk pipeline (docs/streaming.md).
        # Tiny tables skip the thread: its ~ms setup would dominate.
        from .pipeline import default_prefetch_depth, maybe_prefetch

        depth = (
            self._ingest_prefetch_depth
            if getattr(self, "_ingest_prefetch_depth", None) is not None
            else default_prefetch_depth()
        )
        if tbl.nbytes < _MIN_PIPELINED_INGEST_BYTES:
            depth = 0

        def produce() -> Any:
            for i, f in enumerate(tbl.schema):
                arr, extra = _encode_column(
                    tbl.column(i).combine_chunks(), f, True
                )
                if arr is None:
                    yield f.name, None, None, None
                    continue
                mask = extra.get("null_mask")
                yield f.name, _pad(arr), (
                    None if mask is None else _pad(mask)
                ), extra

        host_names: List[str] = []
        meta: Dict[str, Any] = {
            "nan_cols": set(),
            "encodings": {},
            "null_masks": {},
        }
        device_cols: Dict[str, Any] = {}
        device_masks: Dict[str, Any] = {}
        cols_it = maybe_prefetch(
            produce(),
            depth,
            stats=getattr(self, "_pipeline_stats", None),
            verb="ingest",
        )
        try:
            for name, arr, mask, extra in cols_it:
                if arr is None:
                    host_names.append(name)
                    continue
                device_cols[name] = jax.device_put(arr, sharding)
                if extra.get("nan"):
                    meta["nan_cols"].add(name)
                if "encoding" in extra:
                    meta["encodings"][name] = extra["encoding"]
                if mask is not None:
                    device_masks[name] = jax.device_put(mask, sharding)
        finally:
            cols_it.close()
        self._device_cols = device_cols
        host_tbl = tbl.select(host_names) if len(host_names) > 0 else None
        self._host_tbl = host_tbl
        # frames are immutable — the ingestion table stays valid for this
        # instance's lifetime, so host reads (as_arrow/as_pandas) skip the
        # device download entirely. EXCEPT when a float column holds literal
        # NaN values: the device treats NaN as NULL, so the decoded view
        # (NULL) and the raw ingest table (NaN) would diverge — no cache.
        # The cache pins the host copy for the frame's lifetime (~2x host
        # memory for ingest-heavy pipelines) — disable it globally with
        # fugue.tpu.ingest_cache=False when host RAM is the constraint.
        from ..constants import _FUGUE_GLOBAL_CONF, FUGUE_TPU_CONF_INGEST_CACHE

        opt = getattr(self, "_ingest_cache_opt", None)
        cacheable = (
            bool(opt)
            if opt is not None
            else bool(_FUGUE_GLOBAL_CONF.get(FUGUE_TPU_CONF_INGEST_CACHE, True))
        )
        if cacheable:
            for c in meta["nan_cols"]:
                col = tbl.column(c)
                literal_nans = pa.compute.sum(pa.compute.is_nan(col)).as_py()
                if literal_nans:
                    cacheable = False
                    break
        self._ingest_tbl = tbl if cacheable else None
        self._row_count = n
        # None = tail-padding semantics (rows [0, row_count) valid); a device
        # bool array = explicit per-row validity (result of device filters)
        self._valid_mask = None
        self._nan_cols = meta["nan_cols"]
        self._encodings = meta["encodings"]
        self._null_masks = device_masks

    # -- properties ---------------------------------------------------------
    @property
    def mesh(self) -> Any:
        return self._mesh

    @property
    def device_cols(self) -> Dict[str, Any]:
        self._ensure_device()
        return self._device_cols

    @property
    def host_table(self) -> Optional[pa.Table]:
        self._ensure_device()
        return self._host_tbl

    @property
    def valid_mask(self) -> Any:
        """Explicit device validity mask, or None for tail-padding."""
        return self._valid_mask

    def maybe_nan(self, name: str) -> bool:
        """Whether device float column ``name`` may contain NaN (i.e. NULL).

        False only when ingestion proved the column NaN-free; unknown
        provenance (e.g. transformer outputs) is conservatively True.
        """
        self._ensure_device()
        if self._nan_cols is None:
            return True
        return name in self._nan_cols

    @property
    def encodings(self) -> Dict[str, dict]:
        """Per-column internal device representations (dict/datetime)."""
        self._ensure_device()
        return self._encodings

    @property
    def null_masks(self) -> Dict[str, Any]:
        """Per-column device null masks (True = NULL) for nullable columns."""
        self._ensure_device()
        return self._null_masks

    @property
    def device_nbytes(self) -> int:
        """Resident byte footprint for cache/LRU accounting: device column
        buffers (plus masks) when materialized, else the pending host
        table's arrow bytes. Never forces ingestion."""
        if self._has_pending():
            with self._pending_lock:
                tbl = getattr(self, "_pending_tbl", None)
                if tbl is not None:
                    return int(tbl.nbytes)
                src = getattr(self, "_pending_src", None)
                if src is not None:
                    # estimate without forcing the arrow conversion
                    try:
                        return int(src.count()) * max(1, len(src.schema)) * 16
                    except Exception:
                        return 0
            return 0
        total = 0
        for arr in (getattr(self, "_device_cols", None) or {}).values():
            total += int(getattr(arr, "nbytes", 0) or 0)
        for arr in (getattr(self, "_null_masks", None) or {}).values():
            total += int(getattr(arr, "nbytes", 0) or 0)
        if getattr(self, "_valid_mask", None) is not None:
            total += int(getattr(self._valid_mask, "nbytes", 0) or 0)
        return total

    @property
    def has_encoded(self) -> bool:
        """True when any device column is not plainly-typed (encoded or
        masked) — device fast paths that assume plain semantics must gate
        on this."""
        self._ensure_device()
        return len(self._encodings) > 0 or len(self._null_masks) > 0

    def device_valid_mask(self) -> Any:
        """A device bool array marking valid rows (built from the row count
        when no explicit mask exists). Memoized — frames are immutable, and
        on a remote-chip tunnel every extra program dispatch has real
        latency, so repeated ops over one frame must not re-run it."""
        self._ensure_device()
        if self._valid_mask is not None:
            return self._valid_mask
        cached = getattr(self, "_tail_mask_cache", None)
        if cached is not None:
            return cached
        import numpy as _np

        from ..ops.segment import _get_compiled_mask

        template = next(iter(self._device_cols.values()))
        mask = _get_compiled_mask(self._mesh)(template, _np.int64(self._row_count))
        self._tail_mask_cache = mask
        return mask

    def key_range(self, name: str) -> "Tuple[int, int]":
        """Cached ``(min, max)`` of integer device column ``name`` over
        valid rows — the probe behind dense-plan eligibility. Frames are
        immutable, so the probe runs at most once per (frame, column); on a
        remote-chip tunnel every device→host fetch is a full network
        roundtrip, and repeated aggregates over a persisted frame were
        paying it on every call. With no valid rows the kernel's fill
        values come back — ``(iinfo(dtype).max, iinfo(dtype).min)`` —
        so emptiness is detected as ``hi < lo``, never by sentinel."""
        cache = getattr(self, "_key_range_cache", None)
        if cache is None:
            cache = self._key_range_cache = {}
        if name not in cache:
            host_range = self._host_key_range(name)
            if host_range is not None:
                cache[name] = host_range
            else:
                import jax
                import numpy as _np

                from ..ops.segment import _get_compiled_minmax

                lo_a, hi_a = _get_compiled_minmax(self._mesh)(
                    self.device_cols[name], self.device_valid_mask()
                )
                # overlap the two fetches: one tunnel roundtrip, not two
                lo_a.copy_to_host_async()
                hi_a.copy_to_host_async()
                cache[name] = (
                    int(_np.asarray(jax.device_get(lo_a))[0]),
                    int(_np.asarray(jax.device_get(hi_a))[0]),
                )
        return cache[name]

    def _host_key_range(self, name: str) -> "Optional[Tuple[int, int]]":
        """Key range from the retained host/ingest arrow table when one
        exists — zero device traffic. This matters beyond the saved
        roundtrip: on the axon tunnel the FIRST device→host transfer of a
        process permanently drops every later program execution into a
        ~0.4s slow mode (measured live; see BASELINE.md), so a probe that
        stays on the host keeps whole device-resident pipelines in fast
        mode. Only valid for frames without an explicit device mask (all
        ingested rows valid)."""
        if self._valid_mask is not None:
            return None
        pend = self._pending_snapshot()
        if pend is not None:
            # never-ingested frame: probe the pending table, declining
            # exactly where ingestion would mask/encode (nulls present)
            if name not in pend.schema.names:
                return None
            if pend.column(name).null_count > 0:
                return None
            tbl = pend
        else:
            if name in self._null_masks or name in self._encodings:
                # the device column holds fill values / codes for these — a
                # host-side min/max (which skips NULLs) would disagree with
                # the device probe and produce wrong dense-plan bounds
                return None
            tbl = (
                self._ingest_tbl
                if getattr(self, "_ingest_tbl", None) is not None
                else self._host_tbl
            )
        if tbl is None or name not in tbl.schema.names:
            return None
        import pyarrow.compute as pc

        col = tbl.column(name)
        if not pa.types.is_integer(col.type):
            return None
        mm = pc.min_max(col)
        lo, hi = mm["min"].as_py(), mm["max"].as_py()
        if lo is None or hi is None:
            # empty / all-NULL: the device probe's fill-value convention
            # (hi < lo) signals emptiness to callers
            ii = np.iinfo(np.dtype(col.type.to_pandas_dtype()))
            return (ii.max, ii.min)
        return (int(lo), int(hi))

    @property
    def native(self) -> "JaxDataFrame":
        # the device frame IS the native object (like a Ray dataset); raw
        # buffers are available via .device_cols — returning those from
        # fa.* verbs would leak padding rows and drop the validity mask
        return self

    @property
    def is_local(self) -> bool:
        return False

    @property
    def is_bounded(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return num_row_shards(self._mesh)

    @property
    def empty(self) -> bool:
        return self.count() == 0

    def count(self) -> int:
        if self._valid_mask is not None and self._row_count < 0:
            import jax as _jax

            self._row_count = int(_jax.device_get(self._valid_mask.sum()))
        return self._row_count

    # -- conversions --------------------------------------------------------
    def _decode_device_col(
        self, f: pa.Field, host: np.ndarray, nulls: Optional[np.ndarray]
    ) -> pa.Array:
        """Decode a (already row-filtered) host view of a device column back
        to its arrow form — NaN→NULL, dictionary codes→values, epochs→
        timestamps."""
        enc = self._encodings.get(f.name)
        if enc is None:
            # device convention: NaN float IS NULL — restore nulls on
            # the way out (skipped for columns proved NaN-free)
            if np.issubdtype(host.dtype, np.floating) and (
                self._nan_cols is None or f.name in self._nan_cols
            ):
                nn = np.isnan(host)
                nulls = nn if nulls is None else (nulls | nn)
            arr = pa.array(host, mask=nulls)
        elif enc["kind"] == "dict":
            # codes → dictionary values; −1 = NULL
            arr = enc["dictionary"].take(
                pa.array(host.astype(np.int64), mask=host < 0)
            )
        elif enc["kind"] == "datetime":
            arr = pa.array(host, mask=nulls).cast(enc["type"])
        else:  # pragma: no cover
            raise NotImplementedError(enc["kind"])
        return arr.cast(f.type, safe=False)

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        import jax

        pend = self._pending_snapshot()
        if pend is not None:
            # never ingested: the arrow table IS the data — but the device
            # convention (literal NaN == NULL) must hold for host reads too
            return _nan_to_null(pend)
        src = getattr(self, "_ingest_tbl", None)
        if src is not None:
            return src
        mask: Optional[np.ndarray] = None
        if self._valid_mask is not None:
            mask = np.asarray(jax.device_get(self._valid_mask))
        arrays: List[pa.Array] = []
        for f in self.schema.fields:
            if f.name in self._device_cols:
                host = np.asarray(jax.device_get(self._device_cols[f.name]))
                host = host[mask] if mask is not None else host[: self._row_count]
                nulls: Optional[np.ndarray] = None
                if f.name in self._null_masks:
                    nulls = np.asarray(jax.device_get(self._null_masks[f.name]))
                    nulls = (
                        nulls[mask] if mask is not None else nulls[: self._row_count]
                    )
                arrays.append(self._decode_device_col(f, host, nulls))
            else:
                assert self._host_tbl is not None
                col = self._host_tbl.column(f.name)
                if mask is not None:
                    col = col.filter(pa.array(mask[: len(col)]))
                else:
                    col = col.slice(0, self._row_count)
                arrays.append(col.combine_chunks())
        return pa.Table.from_arrays(arrays, schema=self.schema.pa_schema)

    @staticmethod
    def _local_np(arr: Any) -> np.ndarray:
        """This process's rows of a row-sharded device array, in global
        index order (multi-host safe: only addressable shards are read)."""
        shards = sorted(
            arr.addressable_shards,
            key=lambda s: (s.index[0].start or 0) if len(s.index) > 0 else 0,
        )
        return np.concatenate([np.asarray(s.data) for s in shards])

    def as_arrow_local(self) -> pa.Table:
        """THIS process's valid rows as an arrow table (per-host read for
        multi-host meshes; on one process it equals ``as_arrow``).

        Requires an all-device frame — host-resident columns are process-
        replicated and cannot be row-matched to local shards."""
        import jax

        assert_or_throw(
            self._host_tbl is None,
            FugueDataFrameOperationError(
                "as_arrow_local requires an all-device frame"
            ),
        )
        mask = self._local_np(self.device_valid_mask())
        arrays: List[pa.Array] = []
        for f in self.schema.fields:
            host = self._local_np(self._device_cols[f.name])[mask]
            nulls: Optional[np.ndarray] = None
            if f.name in self._null_masks:
                nulls = self._local_np(self._null_masks[f.name])[mask]
            arrays.append(self._decode_device_col(f, host, nulls))
        return pa.Table.from_arrays(arrays, schema=self.schema.pa_schema)

    def as_pandas_local(self) -> pd.DataFrame:
        from .._utils.arrow import pa_table_to_pandas

        return pa_table_to_pandas(self.as_arrow_local())

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        src = getattr(self, "_pending_src", None)
        if src is not None and not self.has_metadata:
            # never device-touched: the retained source IS the data — a
            # host map over an ingested-then-fetched frame costs zero
            # conversions (pandas NaN and arrow NULL are the same thing on
            # the host side, so the NaN-to-NULL step isn't needed; shared
            # zero-copy, same contract as pandas_df_wrapper frames). With
            # metadata to attach, fall through: reset_metadata on the
            # shared source would mutate the caller's frame
            return src.as_local_bounded()
        res = ArrowDataFrame(self.as_arrow())
        if self.has_metadata:
            res.reset_metadata(self.metadata)
        return res

    def as_pandas(self) -> pd.DataFrame:
        src = getattr(self, "_pending_src", None)
        if src is not None:
            return src.as_pandas()
        from .._utils.arrow import pa_table_to_pandas

        return pa_table_to_pandas(self.as_arrow())

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return ArrowDataFrame(self.as_arrow().slice(0, 1)).peek_array()

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[List[Any]]:
        return ArrowDataFrame(self.as_arrow()).as_array(columns)

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[List[Any]]:
        yield from ArrowDataFrame(self.as_arrow()).as_array_iterable(columns)

    # -- ops ----------------------------------------------------------------
    def _with(self, schema: Schema, device_cols: Dict[str, Any], host_tbl: Optional[pa.Table]) -> "JaxDataFrame":
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=device_cols,
                host_tbl=host_tbl,
                row_count=self._row_count,
                valid_mask=self._valid_mask,
                nan_cols=self._nan_cols,
                encodings={
                    k: v for k, v in self._encodings.items() if k in device_cols
                },
                null_masks={
                    k: v for k, v in self._null_masks.items() if k in device_cols
                },
                schema=schema,
            ),
        )

    def _lazy_project(self, schema: Schema) -> Optional["JaxDataFrame"]:
        """Column selection on a NOT-YET-INGESTED frame: select on the
        pending source/arrow table (zero-copy) and stay lazy, so dropped
        columns are never decoded or device_put — the contract the plan
        optimizer's column pruning relies on (docs/plan.md)."""
        if not self._has_pending():
            return None
        with self._pending_lock:
            if not self._has_pending():
                return None
            if self._pending_src is not None and self._pending_tbl is None:
                inner: DataFrame = self._pending_src[schema.names]
            else:
                inner = ArrowDataFrame(self._pending_table().select(schema.names))
        return JaxDataFrame(
            inner,
            mesh=self._mesh,
            ingest_cache=getattr(self, "_ingest_cache_opt", None),
            ingest_prefetch_depth=getattr(self, "_ingest_prefetch_depth", None),
            pipeline_stats=getattr(self, "_pipeline_stats", None),
        )

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema - cols
        lazy = self._lazy_project(schema)
        if lazy is not None:
            return lazy
        self._ensure_device()
        dc = {k: v for k, v in self._device_cols.items() if k in schema}
        keep_host = [n for n in schema.names if n not in dc]
        ht = self._host_tbl.select(keep_host) if len(keep_host) > 0 else None
        return self._with(schema, dc, ht)

    def _select_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.extract(cols)
        lazy = self._lazy_project(schema)
        if lazy is not None:
            return lazy
        self._ensure_device()
        dc = {k: v for k, v in self._device_cols.items() if k in schema}
        keep_host = [n for n in schema.names if n not in dc]
        ht = self._host_tbl.select(keep_host) if len(keep_host) > 0 else None
        return self._with(schema, dc, ht)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        self._ensure_device()
        schema = self.schema.rename(columns)  # validates
        dc = {columns.get(k, k): v for k, v in self._device_cols.items()}
        ht = (
            self._host_tbl.rename_columns(
                [columns.get(n, n) for n in self._host_tbl.column_names]
            )
            if self._host_tbl is not None
            else None
        )
        res = JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=dc,
                host_tbl=ht,
                row_count=self._row_count,
                valid_mask=self._valid_mask,
                nan_cols=(
                    None
                    if self._nan_cols is None
                    else {columns.get(n, n) for n in self._nan_cols}
                ),
                encodings={
                    columns.get(k, k): v for k, v in self._encodings.items()
                },
                null_masks={
                    columns.get(k, k): v for k, v in self._null_masks.items()
                },
                schema=schema,
            ),
        )
        return res

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self.schema.alter(columns)
        if new_schema == self.schema:
            return self
        # simplest correct path: round trip through arrow
        return JaxDataFrame(
            ArrowDataFrame(self.as_arrow()).alter_columns(columns),
            mesh=self._mesh,
        )

    def head(self, n: int, columns: Optional[List[str]] = None) -> LocalBoundedDataFrame:
        tbl = self.as_arrow()
        if columns is not None:
            tbl = tbl.select(columns)
        return ArrowDataFrame(tbl.slice(0, n))
