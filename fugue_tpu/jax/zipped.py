"""Co-sharded zip: the device-native replacement for blob serialization.

The reference's zip/comap protocol serializes every key partition into an
arrow-IPC blob row and unions the blob frames
(``fugue/execution/execution_engine.py:962-1111``). On a device mesh that
roundtrip is replaced by LAYOUT: every input frame hash-repartitions by the
zip keys with the all-to-all exchange (``ops/shuffle.py``), so all rows of
a key live on the same shard of every frame. The zipped result is a thin
wrapper holding the co-sharded frames — no blobs exist unless something
outside the comap path forces materialization (then the host protocol runs
once as a fallback).
"""

from typing import Any, Dict, List, Optional

import pyarrow as pa

from ..dataframe import DataFrame, DataFrames, LocalBoundedDataFrame
from ..schema import Schema
from .dataframe import JaxDataFrame

_BLOB_PREFIX = "__fugue_blob__"


class ZippedJaxDataFrame(JaxDataFrame):
    """Result of a device-side ``zip``: co-sharded frames + zip metadata.

    Presents the same logical schema as the host blob protocol (zip keys +
    binary blob columns) so downstream metadata checks are identical, but
    physically holds the hash-co-partitioned device frames.
    """

    def __init__(
        self,
        frames: List[JaxDataFrame],
        names: List[str],
        named: bool,
        how: str,
        keys: List[str],
        schemas: List[Schema],
        mesh: Any,
        presort: Optional[Dict[str, bool]] = None,
    ):
        key_schema = schemas[0].extract(keys)
        blob_fields = ",".join(
            f"{_BLOB_PREFIX}{i}:binary" for i in range(len(frames))
        )
        blob_schema = (
            Schema(str(key_schema) + "," + blob_fields)
            if len(keys) > 0
            else Schema(blob_fields)
        )
        super().__init__(
            mesh=mesh,
            _internal=dict(
                device_cols={},
                host_tbl=None,
                row_count=-1,
                valid_mask=None,
                schema=blob_schema,
            ),
        )
        self._zip_frames = frames
        self._zip_names = names
        self._zip_named = named
        self._zip_how = how
        self._zip_keys = keys
        self._zip_schemas = schemas
        # zip-time presort: the host blob protocol sorts each partition
        # before serializing, so cotransformers see ordered rows — the
        # device path must replay that ordering per key group in comap
        self._zip_presort: Dict[str, bool] = dict(presort or {})
        self._mat: Optional[LocalBoundedDataFrame] = None
        self.reset_metadata(
            {
                "serialized": True,
                "serialized_cols": [
                    f"{_BLOB_PREFIX}{i}" for i in range(len(frames))
                ],
                "schemas": [str(s) for s in schemas],
                "serialized_has_name": named,
                "names": names,
                "how": how,
                "keys": keys,
                "device_zip": True,
            }
        )

    @property
    def zip_frames(self) -> List[JaxDataFrame]:
        return self._zip_frames

    # -- materialization fallback (anything outside the comap path) ---------
    def _materialize(self) -> LocalBoundedDataFrame:
        """Build the blob representation once via the host protocol."""
        if self._mat is None:
            from ..collections.partition import PartitionSpec
            from ..execution.native_execution_engine import NativeExecutionEngine

            e = NativeExecutionEngine()
            if self._zip_named:
                dfs = DataFrames(
                    {
                        n: f.as_local_bounded()
                        for n, f in zip(self._zip_names, self._zip_frames)
                    }
                )
            else:
                dfs = DataFrames([f.as_local_bounded() for f in self._zip_frames])
            res = e.zip(
                dfs,
                how=self._zip_how,
                partition_spec=PartitionSpec(
                    by=self._zip_keys, presort=self._zip_presort
                )
                if len(self._zip_keys) > 0
                else None,
            )
            mat = res.as_local_bounded()
            mat.reset_metadata(self.metadata)
            self._mat = mat
        return self._mat

    def count(self) -> int:
        return self._materialize().count()

    @property
    def empty(self) -> bool:
        return all(f.empty for f in self._zip_frames)

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return self._materialize().as_arrow()

    def as_local_bounded(self) -> LocalBoundedDataFrame:
        return self._materialize()

    def peek_array(self) -> List[Any]:
        return self._materialize().peek_array()
