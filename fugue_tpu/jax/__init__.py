# dataframe semantics need 64-bit ints/floats; jax defaults to x32
import jax as _jax

_jax.config.update("jax_enable_x64", True)

from .dataframe import JaxDataFrame
from .execution_engine import JaxExecutionEngine, JaxMapEngine
from . import group_ops  # per-group reduction helpers for compiled maps
from . import params  # registers the Dict[str, jax.Array] annotation
from . import registry  # registers engine names + inference

__all__ = ["JaxDataFrame", "JaxExecutionEngine", "JaxMapEngine", "group_ops"]
