"""Register the jax engine with the plugin system.

Parity with backend registries in the reference (e.g.
``fugue_spark/registry.py:63-80``): engine available by name ("jax", "tpu"),
inferred from JaxDataFrame inputs, frames convertible via ``as_fugue_df``.
"""

from typing import Any, List

from .._utils.registry import run_at_def
from ..dataframe.api import as_fugue_df, get_native_as_df
from ..dataset.dataset import get_dataset_display
from ..execution.factory import (
    infer_execution_engine,
    register_execution_engine,
)
from .dataframe import JaxDataFrame
from .execution_engine import JaxExecutionEngine


@infer_execution_engine.candidate(
    lambda objs: any(isinstance(o, JaxDataFrame) for o in objs)
)
def _infer_jax_engine(objs: List[Any]) -> Any:
    return "jax"


@run_at_def
def _register() -> None:
    register_execution_engine(
        "jax", lambda conf, **kwargs: JaxExecutionEngine(conf, **kwargs)
    )
    register_execution_engine(
        "tpu", lambda conf, **kwargs: JaxExecutionEngine(conf, **kwargs)
    )
