"""Register the jax engine with the plugin system.

Parity with backend registries in the reference (e.g.
``fugue_spark/registry.py:63-80``): engine available by name ("jax", "tpu"),
inferred from JaxDataFrame inputs, frames convertible via ``as_fugue_df``.
"""

from typing import Any, List

from ..execution.factory import infer_execution_engine
from .dataframe import JaxDataFrame
from .execution_engine import JaxExecutionEngine


@infer_execution_engine.candidate(
    lambda objs: any(isinstance(o, JaxDataFrame) for o in objs)
)
def _infer_jax_engine(objs: List[Any]) -> Any:
    return "jax"


# engine names "jax"/"tpu" are registered lazily in fugue_tpu/execution/
# __init__.py (single registration site); this module adds only inference
