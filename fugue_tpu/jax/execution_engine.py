"""JaxExecutionEngine — the TPU-native distributed engine (the north star).

Design (SURVEY §7.8, BASELINE.json north_star):

- ``to_df``: arrow → :class:`JaxDataFrame` (row-sharded device arrays over a
  ``Mesh``) via ``jax.device_put`` with ``NamedSharding(mesh, P("rows"))``.
- ``JaxMapEngine.map_dataframe``:
  * **compiled path** — transformers whose params are annotated
    ``Dict[str, jax.Array]`` (format hint "jax") and need no key grouping
    run as ONE ``shard_map`` compiled by XLA across the mesh: the user fn
    traces per shard; no Python per row, no host round trip;
  * **general path** — any Python function: host-side sort+groupby apply
    (the correctness path, same semantics as the native engine), output
    re-sharded to device. This mirrors the Spark engine's pandas-UDF vs RDD
    path split (reference ``fugue_spark/execution_engine.py:137``).
- ``aggregate``: two-phase device groupby (``ops/segment.py``): O(rows)
  lexicographic sort + segment reduction per shard on device, O(groups)
  merge on host.
- ``select``/``assign``/``filter``: column-IR compiled with jax.numpy when
  every referenced column is device-resident; host fallback otherwise.
- ``broadcast``: replicated sharding; ``persist``: device-resident pinning
  (block_until_ready); relational ops without a device kernel yet fall back
  to the in-process oracle engine — the same escape-hatch layering the
  reference uses (Ray extends DuckDB, ``fugue_ray/execution_engine.py:204``).
"""

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np
import pandas as pd
import pyarrow as pa

from .._utils.assertion import assert_or_throw
from ..collections.partition import PartitionCursor, PartitionSpec
from ..column import ColumnExpr, SelectColumns
from ..column.jax_eval import can_evaluate_on_device, evaluate_jnp, pa_type_to_np_dtype
from ..dataframe import (
    ArrowDataFrame,
    DataFrame,
    DataFrames,
    LocalDataFrame,
    PandasDataFrame,
)
from ..exceptions import FugueInvalidOperation
from ..execution.execution_engine import ExecutionEngine, MapEngine, SQLEngine
from ..execution.native_execution_engine import NativeExecutionEngine, PandasMapEngine
from ..parallel.mesh import (
    ROW_AXIS,
    build_mesh,
    num_row_shards,
    replicated_sharding,
    row_sharding,
)
from ..schema import Schema
from .dataframe import JaxDataFrame, _DEVICE_DTYPES
from ..obs import traced_verb
from .._utils.jax_compat import shard_map


def _safe_prefix(base: str, *name_sets: Any) -> str:
    """Internal payload-column prefix guaranteed not to shadow a user column
    (a user column may literally be named ``__mask__x``): prepend ``_`` until
    no provided name starts with the prefix."""
    p = base
    while any(any(str(n).startswith(p) for n in ns) for ns in name_sets):
        p = "_" + p
    return p


class JaxMapEngine(MapEngine):
    @property
    def is_distributed(self) -> bool:
        return True

    @property
    def map_handles_repartition(self) -> bool:
        """Both map paths group internally (host: sort+groupby; compiled:
        per-shard trace) — a device all-to-all before the map would be paid
        and then ignored."""
        return True

    @property
    def execution_engine_constraint(self) -> type:
        return JaxExecutionEngine

    @traced_verb("engine.transform")
    def map_dataframe(
        self,
        df: DataFrame,
        map_func: Callable[[PartitionCursor, LocalDataFrame], LocalDataFrame],
        output_schema: Any,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable[[int, DataFrame], Any]] = None,
        map_func_format_hint: Optional[str] = None,
    ) -> DataFrame:
        engine: JaxExecutionEngine = self.execution_engine  # type: ignore
        output_schema = (
            output_schema if isinstance(output_schema, Schema) else Schema(output_schema)
        )
        if map_func_format_hint == "jax":
            raw = _sniff_jax_func(map_func)
            if raw is not None and len(partition_spec.partition_by) == 0:
                from .streaming import is_stream_frame, streaming_compiled_map

                if is_stream_frame(df):
                    # one-pass stream + keyless compiled UDF: chunk-wise
                    # out-of-core map — never materializes on device
                    return streaming_compiled_map(
                        engine, df, raw, output_schema, on_init
                    )
            elif raw is not None:
                from .streaming import (
                    is_stream_frame,
                    streaming_keyed_compiled_map,
                )

                if is_stream_frame(df):
                    # key-clustered stream + keyed compiled UDF: re-batch
                    # at key boundaries, fixed-capacity device batches
                    # (raises with remediation when ineligible — a one-pass
                    # stream must never silently materialize on device)
                    return streaming_keyed_compiled_map(
                        engine, df, raw, output_schema, partition_spec, on_init
                    )
            if raw is not None:
                jdf = engine.to_df(df)
                keys = list(partition_spec.partition_by)
                # encoded/masked columns have non-plain semantics the UDF
                # can't see — host path renders them as real values. The
                # ONE exception: dictionary-encoded PARTITION keys, whose
                # codes the UDF only groups by and passes through opaquely
                # (the engine reattaches the dictionary on output).
                if isinstance(jdf, JaxDataFrame) and len(keys) == 0:
                    if not jdf.has_encoded:
                        # the compiled path maps shards IN PLACE — an even/
                        # rand spec still needs its physical exchange first
                        # (the processor no longer repartitions for this
                        # engine)
                        if not partition_spec.empty:
                            jdf = engine.repartition(jdf, partition_spec)  # type: ignore[assignment]
                        return self._compiled_map(jdf, raw, output_schema, on_init)
                elif isinstance(jdf, JaxDataFrame):
                    dict_keys_only = len(jdf.null_masks) == 0 and all(
                        e.get("kind") == "dict" and c in keys
                        for c, e in jdf.encodings.items()
                    )
                    # an encoded key that appears in the output must keep
                    # its declared type — the dictionary is reattached to
                    # the (passed-through) codes
                    enc_schema_ok = all(
                        k not in output_schema
                        or output_schema[k].type == jdf.schema[k].type
                        for k in jdf.encodings
                    )
                    nan_key = any(
                        np.issubdtype(
                            np.dtype(jdf.device_cols[k].dtype), np.floating
                        )
                        and jdf.maybe_nan(k)
                        for k in keys
                        if k in jdf.device_cols
                    )
                    if (
                        all(k in jdf.device_cols for k in keys)
                        and not nan_key
                        and jdf.host_table is None
                        and (
                            not jdf.has_encoded
                            or (dict_keys_only and enc_schema_ok)
                        )
                    ):
                        return self._compiled_keyed_map(
                            jdf, raw, output_schema, partition_spec, on_init
                        )
                if len(keys) > 0:
                    # keyed jax UDFs depend on the reserved __segments__/
                    # __valid__ contract that only the compiled plans
                    # provide — a silent host fallback would surface as an
                    # opaque KeyError deep inside the user fn
                    raise FugueInvalidOperation(
                        "compiled keyed map unavailable for partition keys "
                        f"{keys}: keys must be plain or dictionary-encoded "
                        "device columns (no nullable ints/maybe-NaN "
                        "floats), non-key columns must be un-encoded, and "
                        "encoded keys must keep their type in the output "
                        "schema. Use a pandas-annotated transformer for "
                        "these shapes."
                    )
        # general path: host-side partitioned execution, result back on
        # device; CONCURRENCY reflects the mesh, not the host engine
        host_engine = engine._host_engine
        if not hasattr(self, "_host_map"):
            self._host_map = PandasMapEngine(host_engine, parallelism_engine=engine)
        local = engine._host(df)
        res = self._host_map.map_dataframe(
            local,
            map_func,
            output_schema,
            partition_spec,
            on_init=on_init,
            map_func_format_hint=map_func_format_hint,
        )
        return engine.to_df(res)

    def _compiled_keyed_map(
        self,
        df: JaxDataFrame,
        fn: Callable,
        output_schema: Schema,
        partition_spec: PartitionSpec,
        on_init: Optional[Callable],
    ) -> DataFrame:
        """Keyed compiled map: groupby-apply that never leaves the device.

        The device-native answer to the reference's group-map path
        (``fugue_spark/execution_engine.py:192``): hash-repartition
        co-locates each key on one shard, ONE ``shard_map`` then sorts the
        shard by (validity, keys, presort), derives row-aligned contiguous
        ``__segments__`` ids, and traces the user fn over the sorted
        columns. The fn computes per-group results with
        ``jax.ops.segment_sum``-style reductions (``num_segments`` bounded
        by the static shard size) and returns a row-aligned dict. Padding
        rows sort to the shard tail, each in its own segment, and stay
        masked via ``__valid__``.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        engine: JaxExecutionEngine = self.execution_engine  # type: ignore
        keys = list(partition_spec.partition_by)
        dense = self._try_dense_keyed_map(
            df, fn, output_schema, partition_spec, keys, on_init
        )
        if dense is not None:
            return dense
        jdf: JaxDataFrame = engine.repartition(  # type: ignore[assignment]
            df, PartitionSpec(partition_spec, algo="hash")
        )
        if on_init is not None:
            on_init(0, jdf)
        sorts = partition_spec.get_sorts(jdf.schema, with_partition_keys=True)
        sort_items = tuple(sorts.items())
        mesh = jdf.mesh
        cache = engine._jit_cache
        cache_key = ("kmap", fn, mesh, sort_items, tuple(keys))
        if cache_key not in cache:

            def compute(cols: Dict[str, Any], valid: Any):
                def shard_fn(c: Dict[str, Any], v: Any):
                    # sort keys: valid rows first, then group keys (+presort)
                    ops: List[Any] = [jnp.logical_not(v)]
                    for name, asc in sort_items:
                        key = c[name]
                        if jnp.issubdtype(key.dtype, jnp.floating):
                            # NaN is the device NULL — order it FIRST inside
                            # ties, matching the host protocol's
                            # na_position="first" (asc or desc alike)
                            isnan = jnp.isnan(key)
                            ops.append(jnp.logical_not(isnan))
                            key = jnp.where(isnan, jnp.zeros((), key.dtype), key)
                            if not asc:
                                key = -key
                        elif not asc:
                            if key.dtype == jnp.bool_:
                                key = jnp.logical_not(key)
                            else:
                                key = ~key  # monotone reversal
                        ops.append(key)
                    names = list(c.keys())
                    res = jax.lax.sort(
                        tuple(ops) + tuple(c[n] for n in names) + (v,),
                        num_keys=len(ops),
                    )
                    payload = res[len(ops):]
                    sc = dict(zip(names, payload[: len(names)]))
                    sv = payload[len(names)]
                    # contiguous segment ids; every padding row becomes its
                    # own segment so group reductions never mix padding in
                    change = jnp.logical_not(sv)
                    for k in keys:
                        col = sc[k]
                        diff = jnp.concatenate(
                            [
                                jnp.ones((1,), dtype=bool),
                                col[1:] != col[:-1],
                            ]
                        )
                        change = jnp.logical_or(change, diff)
                    change = change.at[0].set(True)
                    seg = jnp.cumsum(change.astype(jnp.int32)) - 1
                    sc["__segments__"] = seg
                    sc["__valid__"] = sv
                    out = fn(sc)
                    out = {k2: v2 for k2, v2 in out.items() if k2 not in ("__segments__", "__valid__")}
                    out["__valid__"] = sv
                    return out

                return shard_map(
                    shard_fn,
                    mesh=mesh,
                    in_specs=(P(ROW_AXIS), P(ROW_AXIS)),
                    out_specs=P(ROW_AXIS),
                )(cols, valid)

            cache[cache_key] = jax.jit(compute)
        out = cache[cache_key](dict(jdf.device_cols), jdf.device_valid_mask())
        assert_or_throw(
            isinstance(out, dict),
            FugueInvalidOperation(
                "compiled transformer must return Dict[str, jax.Array]"
            ),
        )
        new_valid = out.pop("__valid__")
        n_in = next(iter(jdf.device_cols.values())).shape[0]
        missing = [n for n in output_schema.names if n not in out]
        assert_or_throw(
            len(missing) == 0,
            FugueInvalidOperation(
                f"compiled keyed transformer output missing columns {missing}"
            ),
        )
        same_len = all(v.shape[0] == n_in for v in out.values())
        assert_or_throw(
            same_len,
            FugueInvalidOperation(
                "compiled keyed transformers must return row-aligned arrays "
                "(same length as the sorted input shard)"
            ),
        )
        return JaxDataFrame(
            mesh=mesh,
            _internal=dict(
                device_cols={n: out[n] for n in output_schema.names},
                host_tbl=None,
                row_count=jdf.count(),
                valid_mask=new_valid,
                encodings=self._keyed_out_encodings(jdf, keys, output_schema),
                schema=output_schema,
            ),
        )

    def _try_dense_keyed_map(
        self,
        jdf: JaxDataFrame,
        fn: Callable,
        output_schema: Schema,
        partition_spec: PartitionSpec,
        keys: List[str],
        on_init: Optional[Callable],
    ) -> Optional[DataFrame]:
        """Sort-free, exchange-free keyed map (the dense plan).

        Integer keys with a bounded range map to globally-consistent dense
        segment ids (mixed radix over per-key spans); rows never move, and
        per-group reductions merge across shards INSIDE the user fn via the
        ``group_ops`` helpers (``lax.psum`` over the rows axis). This is
        the fast plan on every backend — sorts are the slow path on TPU,
        scatter reductions ride the VPU — and it costs zero data movement.

        Returns None when ineligible (presort, non-integer keys, unbounded
        range) — the caller falls back to the sorted plan.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..constants import FUGUE_TPU_CONF_DENSE_MAP_RANGE
        from .group_ops import SEGMENT_SPACE, SEGMENTS, SPANS_SHARDS, VALID

        engine: JaxExecutionEngine = self.execution_engine  # type: ignore
        if len(partition_spec.presort) > 0:
            return None  # order inside groups requires the sorted plan
        if not all(
            np.issubdtype(np.dtype(jdf.device_cols[k].dtype), np.integer)
            for k in keys
        ):
            return None
        max_range = int(
            engine.conf.get(FUGUE_TPU_CONF_DENSE_MAP_RANGE, 1 << 20)
        )
        mesh = jdf.mesh
        valid = jdf.device_valid_mask()
        bounds: List[int] = []
        spans: List[int] = []
        for k in keys:
            enc = jdf.encodings.get(k)
            if enc is not None:
                # dict codes are bounded by construction: [-1, len) where
                # -1 is the NULL code — static metadata, no device probe
                lo, hi = -1, len(enc["dictionary"]) - 1
            else:
                lo, hi = jdf.key_range(k)  # cached per frame (one probe ever)
            if hi < lo:  # empty frame: degenerate single-bucket space
                lo, hi = 0, 0
            bounds.append(lo)
            spans.append(hi - lo + 1)
        total = 1
        for s in spans:
            total *= s
            if total > max_range:
                return None
        buckets = 1 << max(1, (total).bit_length())  # ≥ total+1: padding slot
        strides: List[int] = []
        acc = 1
        for s in reversed(spans):
            strides.append(acc)
            acc *= s
        strides = list(reversed(strides))
        if on_init is not None:
            on_init(0, jdf)
        cache = engine._jit_cache
        cache_key = ("kmapdense", fn, mesh, buckets, tuple(keys))
        if cache_key not in cache:

            def compute(cols: Dict[str, Any], v: Any, b: Any, st: Any, space: Any):
                def shard_fn(c: Dict[str, Any], v_: Any, b_: Any, st_: Any, sp_: Any):
                    ids = jnp.zeros(v_.shape, dtype=jnp.int64)
                    for i, k in enumerate(keys):
                        ids = ids + (c[k].astype(jnp.int64) - b_[i]) * st_[i]
                    ids = jnp.where(
                        v_, ids, jnp.int64(sp_.shape[0] - 1)
                    ).astype(jnp.int32)
                    sc = dict(c)
                    sc[SEGMENTS] = ids
                    sc[VALID] = v_
                    sc[SEGMENT_SPACE] = sp_
                    sc[SPANS_SHARDS] = sp_[:1]
                    out = fn(sc)
                    return {
                        k2: v2
                        for k2, v2 in out.items()
                        if k2 not in (SEGMENTS, VALID, SEGMENT_SPACE, SPANS_SHARDS)
                    }

                return shard_map(
                    shard_fn,
                    mesh=mesh,
                    in_specs=(P(ROW_AXIS), P(ROW_AXIS), P(), P(), P()),
                    out_specs=P(ROW_AXIS),
                )(cols, v, b, st, space)

            cache[cache_key] = jax.jit(compute)
        out = cache[cache_key](
            dict(jdf.device_cols),
            valid,
            jnp.asarray(bounds, dtype=jnp.int64),
            jnp.asarray(strides, dtype=jnp.int64),
            jnp.zeros((buckets,), dtype=jnp.bool_),
        )
        assert_or_throw(
            isinstance(out, dict),
            FugueInvalidOperation(
                "compiled transformer must return Dict[str, jax.Array]"
            ),
        )
        n_in = next(iter(jdf.device_cols.values())).shape[0]
        missing = [n for n in output_schema.names if n not in out]
        assert_or_throw(
            len(missing) == 0,
            FugueInvalidOperation(
                f"compiled keyed transformer output missing columns {missing}"
            ),
        )
        assert_or_throw(
            all(v2.shape[0] == n_in for v2 in out.values()),
            FugueInvalidOperation(
                "compiled keyed transformers must return row-aligned arrays"
            ),
        )
        # rows never moved: validity/count carry over unchanged
        return JaxDataFrame(
            mesh=mesh,
            _internal=dict(
                device_cols={n: out[n] for n in output_schema.names},
                host_tbl=None,
                row_count=jdf._row_count,
                valid_mask=jdf.valid_mask,
                encodings=self._keyed_out_encodings(jdf, keys, output_schema),
                schema=output_schema,
            ),
        )

    def _keyed_out_encodings(
        self, jdf: JaxDataFrame, keys: List[str], output_schema: Schema
    ) -> Dict[str, Any]:
        """Dictionary encodings to reattach to encoded partition keys that
        the UDF passed through (by contract) into the output."""
        return {
            k: dict(jdf.encodings[k])
            for k in keys
            if k in jdf.encodings and k in output_schema
        }

    def _compiled_map(
        self,
        df: JaxDataFrame,
        fn: Callable,
        output_schema: Schema,
        on_init: Optional[Callable],
    ) -> DataFrame:
        """ONE shard_map for the whole frame; user fn traced per shard.

        The input dict carries a reserved ``"__valid__"`` bool array marking
        real (non-padding) rows — functions doing per-shard reductions must
        mask with it; elementwise functions may ignore it.
        """
        import jax
        import numpy as np_
        from jax.sharding import PartitionSpec as P

        from ..ops.segment import _get_compiled_mask

        if on_init is not None:
            on_init(0, df)
        cols = dict(df.device_cols)
        assert_or_throw(
            len(cols) > 0,
            FugueInvalidOperation("no device columns to map on the compiled path"),
        )
        mesh = df.mesh
        cols["__valid__"] = df.device_valid_mask()
        cache = self.execution_engine._jit_cache  # type: ignore
        key = ("map", fn, mesh)
        if key not in cache:
            cache[key] = jax.jit(
                shard_map(
                    fn, mesh=mesh, in_specs=(P(ROW_AXIS),), out_specs=P(ROW_AXIS)
                )
            )
        mapped = cache[key]
        out = mapped(cols)
        assert_or_throw(
            isinstance(out, dict),
            FugueInvalidOperation("compiled transformer must return Dict[str, jax.Array]"),
        )
        out = {k: v for k, v in out.items() if k != "__valid__"}
        first = next(iter(out.values()))
        same_len = first.shape[0] == next(iter(cols.values())).shape[0]
        from ..constants import FUGUE_TPU_CONF_VALIDATE_COMPILED

        if self.execution_engine.conf.get(FUGUE_TPU_CONF_VALIDATE_COMPILED, False):
            self._validate_compiled(df, fn, cols, out, same_len)
        return JaxDataFrame(
            mesh=mesh,
            _internal=dict(
                device_cols=dict(out),
                host_tbl=None,
                row_count=df.count() if same_len else first.shape[0],
                valid_mask=df.valid_mask if same_len else None,
                schema=output_schema,
            ),
        )


    def _validate_compiled(
        self,
        df: JaxDataFrame,
        fn: Callable,
        cols: Dict[str, Any],
        out: Dict[str, Any],
        same_len: bool,
    ) -> None:
        """Debug cross-check (``fugue.tpu.validate_compiled``): run the UDF
        eagerly on ONE shard's VALID rows only — the reference semantics a
        correct, mask-honoring UDF must reproduce — and compare with the
        compiled output's block for that shard. The shard with the most
        padding is chosen (a mask-ignoring reduction only diverges where
        padding exists). Catches UDFs that reduce over padding rows because
        they ignored the ``__valid__`` mask."""
        import jax
        import jax.numpy as jnp
        import numpy as np_

        shards = num_row_shards(df.mesh)
        local_n = next(iter(cols.values())).shape[0] // shards
        valid_all = np_.asarray(jax.device_get(cols["__valid__"])).reshape(
            shards, local_n
        )
        per_shard = valid_all.sum(axis=1)
        # the shard with the most padding (possibly all-padding: the
        # reference then runs on zero rows — exactly what a correct UDF
        # must reproduce)
        s = int(per_shard.argmin())
        valid0 = valid_all[s]
        sl = slice(s * local_n, (s + 1) * local_n)
        ref_in = {
            k: jnp.asarray(np_.asarray(jax.device_get(v))[sl][valid0])
            for k, v in cols.items()
            if k != "__valid__"
        }
        ref_in["__valid__"] = jnp.ones(int(valid0.sum()), dtype=bool)
        try:
            ref_out = fn(ref_in)
        except Exception:  # collectives etc. can't run eagerly — skip
            self.execution_engine.log.debug(
                "validate_compiled: UDF not eagerly runnable; skipped"
            )
            return
        for name, arr in out.items():
            out_local = arr.shape[0] // shards
            block = np_.asarray(jax.device_get(arr))[
                s * out_local : (s + 1) * out_local
            ]
            if same_len:
                block = block[valid0]
            ref = np_.asarray(jax.device_get(ref_out[name]))
            ok = block.shape == ref.shape and (
                np_.allclose(block, ref, equal_nan=True)
                if np_.issubdtype(block.dtype, np_.floating)
                else bool((block == ref).all())
            )
            assert_or_throw(
                ok,
                FugueInvalidOperation(
                    f"compiled transformer output {name!r} differs from the "
                    "masked reference on shard 0 — the UDF likely ignores "
                    "the __valid__ mask and read padding rows"
                ),
            )


class JaxExecutionEngine(ExecutionEngine):
    """ExecutionEngine over a jax device mesh (name: ``"jax"`` / ``"tpu"``)."""

    def __init__(self, conf: Any = None, mesh: Any = None):
        super().__init__(conf)
        from ..constants import FUGUE_TPU_CONF_MESH_SHAPE

        if mesh is None:
            shape = self.conf.get_or_none(FUGUE_TPU_CONF_MESH_SHAPE, object)
            mesh = build_mesh(shape if shape is None else tuple(shape))
        self._mesh = mesh
        self._host_engine = NativeExecutionEngine(conf)
        # the host fallback engine executes the general (pandas) map path on
        # this engine's behalf — share one counter sink so recovery events
        # (retries, quarantines) are observable on the engine the user holds
        self._host_engine._resilience_stats = self.resilience_stats
        from .pipeline import JitCache, PipelineStats

        self._jit_cache: JitCache = JitCache()
        self._pipeline_stats = PipelineStats()
        from ..shuffle.stats import ShuffleStats

        # out-of-core hash shuffle (ISSUE 8): spill counters + the live
        # spill-dir set the resource sampler probes
        self._shuffle_stats = ShuffleStats()
        self._active_spill_dirs: set = set()
        self._last_join_strategy: Optional[str] = None
        # unified observability surface (ISSUE 3): every stats object this
        # engine owns lives in ONE registry behind engine.stats() /
        # engine.reset_stats(); the legacy attributes below stay as shims
        self.metrics.register("pipeline", lambda: self._pipeline_stats)
        self.metrics.register("jit_cache", lambda: self._jit_cache)
        self.metrics.register("shuffle", lambda: self._shuffle_stats)
        # record the resolved device budget + which detection source won
        # (conf / device_memory_stats / host_meminfo / fallback) so a
        # mis-detected budget is visible in engine.stats()["shuffle"]
        from ..shuffle.strategy import device_budget_info

        try:
            _budget, _budget_src = device_budget_info(self.conf)
            self._shuffle_stats.set_budget(_budget, _budget_src)
        except Exception:
            pass
        # per-verb roofline recording (ISSUE 18, record-only): while
        # tracing is enabled, every traced verb's close folds achieved
        # bytes/s + rows/s into this engine's tuner (TunedStore
        # "rooflines" key); fugue.tpu.tuning.rooflines=false opts out
        from ..tuning import install_verb_observer

        install_verb_observer(self)

    def _resource_probe_fns(self) -> Dict[str, Any]:
        # jax-engine occupancy for the continuous resource sampler
        # (ISSUE 6). Registered from the BASE constructor, before
        # _jit_cache/_pipeline_stats exist — probes run later, on the
        # sampler thread, so they guard with getattr.
        probes = dict(super()._resource_probe_fns())

        def _jit_entries(e: Any) -> float:
            cache = getattr(e, "_jit_cache", None)
            return float(len(cache)) if cache is not None else 0.0

        def _overlap(e: Any) -> float:
            ps = getattr(e, "_pipeline_stats", None)
            return float(ps.as_dict()["overlap_fraction"]) if ps is not None else 0.0

        def _spill_bytes(e: Any) -> float:
            # runs on the sampler thread while joins mutate the spill-dir
            # set — never let a race break the whole resource sampler
            try:
                dirs = getattr(e, "_active_spill_dirs", None)
                if not dirs:
                    return 0.0
                from ..shuffle.partitioner import spill_dir_bytes

                return float(spill_dir_bytes(dirs))
            except Exception:
                return 0.0

        probes["jit_cache_entries"] = _jit_entries
        probes["overlap_fraction"] = _overlap
        probes["shuffle_spill_bytes"] = _spill_bytes
        return probes

    @property
    def mesh(self) -> Any:
        return self._mesh

    @property
    def pipeline_stats(self) -> Any:
        """Ingest-pipeline observability (``fugue_tpu/jax/pipeline.py``):
        chunks prefetched, producer-wait vs consumer-wait seconds, and the
        measured overlap fraction, cumulative plus last run.

        Shim over ``engine.metrics`` — prefer ``engine.stats()["pipeline"]``."""
        return self._pipeline_stats

    @property
    def jit_cache_stats(self) -> Dict[str, int]:
        """Compile-cache hit/miss/entry counters for this engine.

        Shim over ``engine.metrics`` — prefer ``engine.stats()["jit_cache"]``."""
        return self._jit_cache.stats()

    @property
    def is_distributed(self) -> bool:
        return True

    @property
    def log(self) -> logging.Logger:
        return logging.getLogger("JaxExecutionEngine")

    def create_default_map_engine(self) -> MapEngine:
        return JaxMapEngine(self)

    def create_default_sql_engine(self) -> SQLEngine:
        # bind the SQL facet to THIS engine (not the host fallback) so SQL
        # lowers onto the device verbs and conf lookups (e.g. the checkpoint
        # table warehouse) see this engine's live configuration
        from ..execution.native_execution_engine import _PlaceholderSQLEngine

        return _PlaceholderSQLEngine(self)

    def get_current_parallelism(self) -> int:
        return num_row_shards(self._mesh)

    @traced_verb("engine.to_df")
    def to_df(self, df: Any, schema: Any = None) -> DataFrame:
        if isinstance(df, JaxDataFrame):
            if schema is not None and df.schema != Schema(schema):
                # cast through arrow so the data actually converts
                return JaxDataFrame(
                    ArrowDataFrame(df.as_arrow().cast(Schema(schema).pa_schema)),
                    mesh=self._mesh,
                )
            return df
        from ..constants import FUGUE_TPU_CONF_INGEST_CACHE
        from .pipeline import prefetch_depth

        res = JaxDataFrame(
            df if isinstance(df, DataFrame) else self._host_engine.to_df(df, schema),
            mesh=self._mesh,
            ingest_cache=self.conf.get_or_none(
                FUGUE_TPU_CONF_INGEST_CACHE, bool
            ),
            ingest_prefetch_depth=prefetch_depth(self.conf),
            pipeline_stats=self._pipeline_stats,
        )
        src_meta = df.metadata if isinstance(df, DataFrame) and df.has_metadata else None
        if src_meta is not None:
            res.reset_metadata(src_meta)
        return res

    # ---- distribution primitives ------------------------------------------
    @traced_verb("engine.repartition")
    def repartition(self, df: DataFrame, partition_spec: PartitionSpec) -> DataFrame:
        """Physically move rows between shards with an all-to-all exchange.

        ``hash`` (or keyed default) co-locates equal keys on one shard —
        the basis for shuffle joins and co-sharded cotransforms; ``even``
        (or key-less default) rebalances row counts; ``rand`` scatters
        randomly; ``coarse`` is metadata-only by definition. Frames with
        host-resident columns keep their layout (logical partitioning in
        map/aggregate still honors the spec) — that case logs a warning.
        Matches the reference's per-backend repartition algorithms
        (``fugue_spark/_utils/partition.py:15-117``).
        """
        from ..ops.shuffle import compute_dest, exchange_rows

        if partition_spec is None or partition_spec.empty:
            return df
        algo = partition_spec.algo
        by = list(partition_spec.partition_by)
        if algo == "":
            algo = "hash" if len(by) > 0 else "even"
        if algo == "hash" and len(by) == 0:
            algo = "even"
        if algo == "hash":
            # out-of-core layout (ISSUE 8): a one-pass stream, or a
            # bounded frame whose estimate exceeds the device budget,
            # hash-partitions through the on-disk spill partitioner —
            # every key ends up in exactly ONE chunk of the result
            # stream, so arbitrarily large PartitionSpec maps stay
            # key-complete without ever being device-resident at once
            from ..shuffle.strategy import (
                device_budget_bytes,
                estimate_frame_bytes,
                shuffle_enabled,
            )
            from .streaming import is_stream_frame

            if shuffle_enabled(self.conf):
                streaming = is_stream_frame(df)
                est = None if streaming else estimate_frame_bytes(df)
                if streaming or (
                    est is not None and est > device_budget_bytes(self.conf)
                ):
                    from ..shuffle.join import spill_repartition

                    try:
                        num = int(partition_spec.num_partitions or "0")
                    except ValueError:
                        num = 0
                    res = spill_repartition(self, df, by, num=num)
                    if res is not None:
                        return res
        jdf = self.to_df(df)
        if algo == "coarse":
            return jdf
        device_ok = (
            isinstance(jdf, JaxDataFrame)
            and len(jdf.device_cols) > 0
            and jdf.host_table is None
            and (algo != "hash" or all(k in jdf.device_cols for k in by))
        )
        if not device_ok:
            self.log.warning(
                "repartition(%s): frame has host-resident columns; physical "
                "layout unchanged (logical partitioning still applies)",
                algo,
            )
            return jdf
        valid = jdf.device_valid_mask()
        dest = compute_dest(
            self._mesh,
            algo,
            [jdf.device_cols[k] for k in by] if algo == "hash" else [],
            valid,
        )
        return self._exchange_to(jdf, dest, valid)

    def _repartition_single(self, df: DataFrame) -> "JaxDataFrame":
        """Move every row to shard 0 — the one-partition physical layout
        behind global (no PARTITION BY) window evaluation. Fully-device
        frames only; callers gate on that."""
        from ..ops.shuffle import compute_dest

        jdf = self.to_df(df)
        valid = jdf.device_valid_mask()
        dest = compute_dest(self._mesh, "single", [], valid)
        return self._exchange_to(jdf, dest, valid)

    def _exchange_to(
        self, jdf: "JaxDataFrame", dest: Any, valid: Any
    ) -> "JaxDataFrame":
        """All-to-all exchange of a device frame to per-row destinations."""
        from ..ops.shuffle import exchange_rows

        # null masks are row-aligned — they travel with their columns
        mp = _safe_prefix("__mask__", jdf.schema.names)
        payload = dict(jdf.device_cols)
        for c, m in jdf.null_masks.items():
            payload[f"{mp}{c}"] = m
        new_payload, new_valid, _ = exchange_rows(
            self._mesh, payload, valid, dest
        )
        new_cols = {c: new_payload[c] for c in jdf.device_cols}
        new_masks = {
            c: new_payload[f"{mp}{c}"] for c in jdf.null_masks
        }
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=new_cols,
                host_tbl=None,
                row_count=jdf.count(),
                valid_mask=new_valid,
                nan_cols=jdf._nan_cols,
                encodings=dict(jdf.encodings),
                null_masks=new_masks,
                schema=jdf.schema,
            ),
        )

    @traced_verb("engine.broadcast")
    def broadcast(self, df: DataFrame) -> DataFrame:
        import jax

        jdf = self.to_df(df)
        rep = replicated_sharding(self._mesh)
        cols = {k: jax.device_put(v, rep) for k, v in jdf.device_cols.items()}
        # a filtered frame carries an explicit hole-y valid mask; it must
        # travel with the rows or broadcasting silently re-validates them
        vm = jdf.valid_mask
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=cols,
                host_tbl=jdf.host_table,
                row_count=jdf.count(),
                valid_mask=None if vm is None else jax.device_put(vm, rep),
                nan_cols=jdf._nan_cols,
                encodings=dict(jdf.encodings),
                null_masks={
                    k: jax.device_put(v, rep) for k, v in jdf.null_masks.items()
                },
                schema=jdf.schema,
            ),
        )

    @traced_verb("engine.persist")
    def persist(self, df: DataFrame, lazy: bool = False, **kwargs: Any) -> DataFrame:
        import jax

        jdf = self.to_df(df)
        if not lazy:
            for v in jdf.device_cols.values():
                jax.block_until_ready(v)
        if df.has_metadata:
            jdf.reset_metadata(df.metadata)
        return jdf

    # ---- relational ops ----------------------------------------------------
    @traced_verb("engine.filter")
    def filter(self, df: DataFrame, condition: Any, _plan: Any = None) -> DataFrame:
        """Device filter: the condition becomes a validity mask — no rows
        move, downstream device ops and host conversion honor the mask.

        Runs with SQL three-valued NULL semantics (rows where the predicate
        is NULL are dropped): NaN floats and per-column null masks are
        NULLs, and predicates on dictionary-encoded string columns evaluate
        host-side over the dictionary into a lookup table gathered by code.
        ``_plan`` lets ``select`` reuse its already-computed predicate plan.
        """
        from ..column.jax_eval import device_predicate_plan

        jdf = self.to_df(df)
        if (
            isinstance(jdf, JaxDataFrame)
            and len(jdf.device_cols) > 0
            and jdf.host_table is None
        ):
            plan = (
                _plan
                if _plan is not None
                else device_predicate_plan(
                    condition, jdf.device_cols, jdf.encodings
                )
            )
            if plan is not None:
                import jax

                tables, cond = plan  # datetime literals rewritten to epochs
                uuids = tuple(sorted(tables.keys()))
                names = {u: tables[u][0] for u in uuids}
                code_cols = frozenset(
                    c for c, e in jdf.encodings.items() if e["kind"] == "dict"
                )
                cache_key = (
                    "filter3v", cond.__uuid__(), jdf.mesh, uuids, code_cols
                )
                if cache_key not in self._jit_cache:

                    def apply_mask(
                        cols: Dict[str, Any],
                        masks: Dict[str, Any],
                        tarrs: Any,
                        valid: Any,
                    ) -> Any:
                        import jax.numpy as jnp

                        from ..column.jax_eval import evaluate_jnp_3v

                        dt = {u: (names[u], t) for u, t in zip(uuids, tarrs)}
                        v, nl = evaluate_jnp_3v(
                            cols, masks, dt, cond, code_cols
                        )
                        return (
                            valid
                            & jnp.asarray(v, dtype=bool)
                            & jnp.logical_not(nl)
                        )

                    self._jit_cache[cache_key] = jax.jit(apply_mask)
                new_mask = self._jit_cache[cache_key](
                    dict(jdf.device_cols),
                    dict(jdf.null_masks),
                    tuple(tables[u][1] for u in uuids),
                    jdf.device_valid_mask(),
                )
                return JaxDataFrame(
                    mesh=self._mesh,
                    _internal=dict(
                        device_cols=dict(jdf.device_cols),
                        host_tbl=None,
                        row_count=-1,  # computed lazily from the mask
                        valid_mask=new_mask,
                        nan_cols=jdf._nan_cols,
                        encodings=dict(jdf.encodings),
                        null_masks=dict(jdf.null_masks),
                        schema=jdf.schema,
                    ),
                )
        return self._back(self._host_engine.filter(self._host(df), condition))

    @traced_verb("engine.fused")
    def fused_apply(self, df: DataFrame, steps: Any) -> DataFrame:
        """Fused chain execution (``fugue_tpu/plan/fused.py``):

        - one-pass streams apply the steps per chunk INSIDE the chunk
          producer (rows filtered out are never H2D-transferred and the
          stream stays out-of-core);
        - fully-device frames compile the whole chain — the Kleene-AND of
          every filter plus all projections — into ONE jitted step (no
          intermediate device buffers, one kernel launch per chain);
        - anything else falls back to sequential verb application, which
          is exactly what the unfused chain would have run.
        """
        from .streaming import is_stream_frame, streaming_fused_steps

        if is_stream_frame(df):
            return streaming_fused_steps(self, df, steps)
        jdf = self.to_df(df)
        res = self._try_fused_device(jdf, steps)
        if res is not None:
            return res
        return super().fused_apply(jdf, steps)

    def _try_fused_device(self, jdf: DataFrame, steps: Any) -> Optional[DataFrame]:
        """Single-jit execution of a composed chain, or None when any
        step resists composition/device lowering (sequential fallback
        keeps identical semantics)."""
        from ..column.jax_eval import device_predicate_plan
        from ..plan.fused import compose_steps

        if (
            not isinstance(jdf, JaxDataFrame)
            or len(jdf.device_cols) == 0
            or jdf.host_table is not None
        ):
            return None
        composed = compose_steps(list(jdf.schema.names), steps)
        if composed is None:
            return None
        pred, outputs = composed
        passthrough_ids = {
            id(c) for c in outputs if _is_passthrough(c, jdf.device_cols)
        }
        computed = [c for c in outputs if id(c) not in passthrough_ids]
        plain_cols = {
            k: v
            for k, v in jdf.device_cols.items()
            if k not in jdf.encodings and k not in jdf.null_masks
        }
        if not all(can_evaluate_on_device(c, plain_cols) for c in computed):
            return None
        plan = None
        if pred is not None:
            plan = device_predicate_plan(pred, jdf.device_cols, jdf.encodings)
            if plan is None:
                return None
        import jax

        tables, cond = plan if plan is not None else ({}, None)
        uuids = tuple(sorted(tables.keys()))
        names = {u: tables[u][0] for u in uuids}
        code_cols = frozenset(
            c for c, e in jdf.encodings.items() if e["kind"] == "dict"
        )
        cache_key = (
            "fused",
            "" if cond is None else cond.__uuid__(),
            tuple(c.__uuid__() for c in computed),
            jdf.mesh,
            uuids,
            code_cols,
        )
        if cache_key not in self._jit_cache:

            def run(
                cols: Dict[str, Any],
                masks: Dict[str, Any],
                tarrs: Any,
                valid: Any,
            ) -> Any:
                import jax.numpy as jnp

                from ..column.jax_eval import evaluate_jnp_3v

                if cond is not None:
                    dt = {u: (names[u], t) for u, t in zip(uuids, tarrs)}
                    v, nl = evaluate_jnp_3v(cols, masks, dt, cond, code_cols)
                    valid = (
                        valid & jnp.asarray(v, dtype=bool) & jnp.logical_not(nl)
                    )
                outs = {}
                for c in computed:
                    v = evaluate_jnp(cols, c)
                    if not hasattr(v, "shape") or getattr(v, "ndim", 0) == 0:
                        n = next(iter(cols.values())).shape[0]
                        v = jnp.full((n,), v)
                    outs[c.output_name] = v
                return outs, valid

            self._jit_cache[cache_key] = jax.jit(run)
        outs, new_valid = self._jit_cache[cache_key](
            dict(jdf.device_cols),
            dict(jdf.null_masks),
            tuple(tables[u][1] for u in uuids),
            jdf.device_valid_mask(),
        )
        out_cols: Dict[str, Any] = {}
        out_enc: Dict[str, Any] = {}
        out_masks: Dict[str, Any] = {}
        fields = []
        for c in outputs:
            name = c.output_name
            if id(c) in passthrough_ids:
                src = c.name
                out_cols[name] = jdf.device_cols[src]
                if src in jdf.encodings:
                    out_enc[name] = jdf.encodings[src]
                if src in jdf.null_masks:
                    out_masks[name] = jdf.null_masks[src]
                fields.append(pa.field(name, jdf.schema[src].type))
            else:
                out_cols[name] = outs[name]
                t = c.infer_type(jdf.schema)
                fields.append(
                    pa.field(
                        name,
                        t
                        if t is not None
                        else pa.from_numpy_dtype(
                            np.asarray(outs[name]).dtype
                        ),
                    )
                )
        from ..column.expressions import _NamedColumnExpr as _Named

        nan_cols: Optional[set] = None
        if jdf._nan_cols is not None:
            nan_cols = set()
            for c in outputs:
                if isinstance(c, _Named) and c.as_type is None:
                    if c.name in jdf._nan_cols:
                        nan_cols.add(c.output_name)
                else:
                    arr = out_cols[c.output_name]
                    if np.issubdtype(np.dtype(arr.dtype), np.floating):
                        nan_cols.add(c.output_name)
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=out_cols,
                host_tbl=None,
                row_count=jdf._row_count if pred is None else -1,
                valid_mask=jdf.valid_mask if pred is None else new_valid,
                nan_cols=nan_cols,
                encodings=out_enc,
                null_masks=out_masks,
                schema=Schema(fields),
            ),
        )

    def lowered_segment(
        self,
        dfs: List[DataFrame],
        steps: Any,
        terminal: Any,
        partition_spec: Optional[PartitionSpec],
        fingerprint: str = "",
    ) -> DataFrame:
        """Execute a lowered plan segment (``fugue_tpu/plan/lowering.py``)
        as ONE compiled SPMD program where eligible:

        - stream → fused chain → dense aggregate (the flagship): each raw
          chunk goes H2D once and a single jitted ``shard_map`` program
          runs predicate + projections + dense-bucket kernel (cross-shard
          ``psum``/``pmin``/``pmax`` inlined as in-program collectives) +
          donated accumulator fold — one jit-cache entry labeled
          ``segment:<fingerprint>`` for the whole pipeline segment;
        - device-resident frame → fused chain → dense aggregate: the
          whole segment is one jitted program (chain + kernel + finish);
        - stream → fused chain → take / distinct / broadcast-join probe:
          the chain runs as one device program per chunk, survivors feed
          the terminal's running buffer / probe.

        Any refusal (non-composable step, host-only type, ineligible
        aggregate plan, ...) falls back per segment to the per-verb path —
        ``fused_apply`` + the terminal verb, bit-identical to the
        unlowered task pair, same ``engine.<verb>`` spans. A lowered run
        executes under ONE ``plan.segment`` span instead.
        """
        from ..obs import get_tracer

        terminal = tuple(terminal)
        runner = None
        try:
            runner = self._plan_lowered_segment(
                dfs, list(steps), terminal, partition_spec, fingerprint
            )
        except Exception as ex:  # planning must never break execution
            self.log.warning(
                "segment lowering refused with an error (%s: %s); "
                "falling back to the per-verb path",
                type(ex).__name__,
                ex,
            )
            runner = None
        if runner is not None:
            tracer = get_tracer()
            with tracer.span(
                "plan.segment",
                cat="plan",
                annotate=True,
                segment=fingerprint,
                terminal=terminal[0],
                steps=len(steps),
            ):
                res = runner()
            self.plan_stats.inc("segments_executed")
            return res
        self.plan_stats.inc("segments_fallback")
        return super().lowered_segment(
            dfs, steps, terminal, partition_spec, fingerprint=fingerprint
        )

    def _plan_lowered_segment(
        self,
        dfs: List[DataFrame],
        steps: List[Any],
        terminal: Tuple,
        partition_spec: Optional[PartitionSpec],
        fingerprint: str,
    ) -> Optional[Callable[[], DataFrame]]:
        """Phase-1 planning: return a zero-arg runner when the segment
        lowers, None to fall back. Planning never consumes stream data."""
        from .streaming import (
            is_stream_frame,
            plan_lowered_steps_stream,
            plan_streaming_lowered_aggregate,
            streaming_distinct,
            streaming_take,
        )

        if len(steps) == 0:
            return None
        kind = terminal[0]
        if kind == "aggregate":
            keys = (
                list(partition_spec.partition_by)
                if partition_spec is not None
                else []
            )
            agg_cols = list(terminal[1])
            df = dfs[0]
            if is_stream_frame(df):
                return plan_streaming_lowered_aggregate(
                    self, df, steps, keys, agg_cols, fingerprint
                )
            return self._plan_lowered_bounded_aggregate(
                df, steps, keys, agg_cols, fingerprint
            )
        if kind == "take":
            df = dfs[0]
            if not is_stream_frame(df):
                return None
            mk = plan_lowered_steps_stream(self, df, steps, fingerprint)
            if mk is None:
                return None
            return lambda: streaming_take(
                self, mk(), terminal[1], terminal[2], terminal[3], partition_spec
            )
        if kind == "distinct":
            df = dfs[0]
            if not is_stream_frame(df):
                return None
            mk = plan_lowered_steps_stream(self, df, steps, fingerprint)
            if mk is None:
                return None
            return lambda: streaming_distinct(self, mk())
        if kind == "join":
            probe = terminal[3]
            df = dfs[probe]
            build = dfs[1 - probe]
            if not is_stream_frame(df) or is_stream_frame(build):
                return None
            mk = plan_lowered_steps_stream(self, df, steps, fingerprint)
            if mk is None:
                return None

            def run_join() -> DataFrame:
                ldf = mk()
                d1, d2 = (ldf, build) if probe == 0 else (build, ldf)
                return self.join(d1, d2, how=terminal[1], on=list(terminal[2]))

            return run_join
        return None

    def _plan_lowered_bounded_aggregate(
        self,
        df: DataFrame,
        steps: List[Any],
        keys: List[str],
        agg_cols: List[ColumnExpr],
        fingerprint: str,
    ) -> Optional[Callable[[], DataFrame]]:
        """Lowered (chain → dense aggregate) over a fully device-resident
        frame: predicate, projections, dense-bucket kernel (in-program
        cross-shard collectives) and the on-device finish trace into ONE
        jitted program — no intermediate frame, no host roundtrip.
        Eligibility mirrors ``_try_dense_device_aggregate`` with the
        chain's key/value sources required to be plain (un-encoded,
        un-masked) columns or device-computable expressions over them."""
        from ..column.jax_eval import device_predicate_plan
        from ..plan.fused import compose_steps
        from ..ops.segment import (
            _DENSE_MAX_RANGE,
            _DENSE_SUM_BACKEND,
            _get_compiled_dense,
            dense_buckets,
        )
        from .streaming import _np_dtype_of

        if len(keys) != 1:
            return None
        jdf = self.to_df(df)
        if (
            not isinstance(jdf, JaxDataFrame)
            or len(jdf.device_cols) == 0
            or jdf.host_table is not None
        ):
            return None
        composed = compose_steps(list(jdf.schema.names), steps)
        if composed is None:
            return None
        pred, outputs = composed
        outs_by_name = {e.output_name: e for e in outputs}
        if len(outs_by_name) != len(outputs):
            return None
        plain_cols = {
            k: v
            for k, v in jdf.device_cols.items()
            if k not in jdf.encodings and k not in jdf.null_masks
        }
        import jax
        import jax.numpy as jnp

        zcols = {
            k: jnp.zeros((0,), dtype=np.dtype(v.dtype))
            for k, v in plain_cols.items()
        }
        passthrough_ids = {
            id(e) for e in outputs if _is_passthrough(e, jdf.device_cols)
        }
        fields: List[pa.Field] = []
        out_np: Dict[str, np.dtype] = {}
        for e in outputs:
            name = e.output_name
            if id(e) in passthrough_ids:
                fields.append(pa.field(name, jdf.schema[e.name].type))
                continue
            if not can_evaluate_on_device(e, plain_cols):
                return None
            try:
                arr = jnp.asarray(evaluate_jnp(zcols, e))
            except Exception:
                return None
            out_np[name] = np.dtype(arr.dtype)
            t = e.infer_type(jdf.schema)
            fields.append(
                pa.field(
                    name, t if t is not None else pa.from_numpy_dtype(out_np[name])
                )
            )
        probe_schema = Schema(fields)
        empty = pa.Table.from_pylist([], schema=probe_schema.pa_schema)
        try:
            jdf0 = JaxDataFrame(ArrowDataFrame(empty), mesh=self._mesh)
        except Exception:
            return None
        plan = _plan_device_agg(jdf0, keys, agg_cols)
        if (
            plan is None
            or plan["virtual"]
            or plan["dict_srcs"]
            or plan["masked_srcs"]
            or any(p.get("kind") not in ("pass", "avg") for p in plan["post"])
        ):
            return None
        key = keys[0]
        key_expr = outs_by_name.get(key)
        from ..column.expressions import _NamedColumnExpr as _Named

        if (
            not isinstance(key_expr, _Named)
            or key_expr.wildcard
            or key_expr.as_type is not None
        ):
            return None
        raw_key = key_expr.name
        if raw_key not in plain_cols:
            return None
        key_np = np.dtype(jdf.device_cols[raw_key].dtype)
        if key_np.kind not in ("i", "u"):
            return None
        srcs = sorted({s for _, _, s in plan["aggs"]})
        actual: Dict[str, np.dtype] = {}
        src_expr: Dict[str, Any] = {}
        for s in srcs:
            e = outs_by_name.get(s)
            if e is None:
                return None
            if id(e) in passthrough_ids:
                if e.name not in plain_cols:
                    return None  # masked/encoded source would lose its NULLs
                actual[s] = np.dtype(jdf.device_cols[e.name].dtype)
            else:
                actual[s] = out_np[s]
            if actual[s].kind not in ("i", "u", "f"):
                return None
            src_expr[s] = e
        del jdf0
        # range over the RAW key column (pre-filter superset — correct,
        # possibly more buckets; the cached frame probe pays once)
        kmin, kmax = jdf.key_range(raw_key)
        rng = kmax - kmin + 1
        if not (0 < rng <= _DENSE_MAX_RANGE):
            return None
        predicted: Dict[str, np.dtype] = {
            name: (np.dtype(np.int64) if agg == "count" else actual[src])
            for name, agg, src in plan["aggs"]
        }
        spec_rows = _dense_finish_spec(plan, predicted)
        if spec_rows is None:
            return None
        tables: Dict[str, Any] = {}
        cond = None
        if pred is not None:
            pplan = device_predicate_plan(pred, jdf.device_cols, jdf.encodings)
            if pplan is None:
                return None
            tables, cond = pplan
        uuids = tuple(sorted(tables.keys()))
        tnames = {u: tables[u][0] for u in uuids}
        code_cols = frozenset(
            c for c, e in jdf.encodings.items() if e["kind"] == "dict"
        )
        vidx = {s: i for i, s in enumerate(srcs)}
        agg_sig = tuple(
            (name, agg, vidx[src], actual[src].kind == "f")
            for name, agg, src in plan["aggs"]
        )
        buckets = dense_buckets(rng)
        kernel = _get_compiled_dense(self._mesh, buckets, agg_sig)
        kmin_s = np.int64(kmin)
        label = f"segment:{fingerprint or 'anon'}"
        cache_key = (
            label,
            self._mesh,
            buckets,
            agg_sig,
            spec_rows,
            key_np.str,
            kmin,
            uuids,
            code_cols,
            _DENSE_SUM_BACKEND[0],
        )

        def run() -> DataFrame:
            from ..column.jax_eval import evaluate_jnp as _ev
            from ..column.jax_eval import evaluate_jnp_3v as _ev3

            if cache_key not in self._jit_cache:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                arr_names = tuple(s[0] for s in agg_sig)
                fin = self._make_dense_finish(
                    buckets, arr_names, spec_rows, key_np.str
                )

                def prog(
                    cols: Dict[str, Any],
                    masks: Dict[str, Any],
                    tarrs: Any,
                    valid: Any,
                ):
                    import jax.numpy as _jnp

                    if cond is not None:
                        dt = {u: (tnames[u], t) for u, t in zip(uuids, tarrs)}
                        pv, nl = _ev3(cols, masks, dt, cond, code_cols)
                        valid = (
                            valid
                            & _jnp.asarray(pv, dtype=bool)
                            & _jnp.logical_not(nl)
                        )
                    karr = cols[raw_key]
                    vals = []
                    for s in srcs:
                        e = src_expr[s]
                        if id(e) in passthrough_ids:
                            a = cols[e.name]
                        else:
                            a = _ev(cols, e)
                            if (
                                not hasattr(a, "shape")
                                or getattr(a, "ndim", 0) == 0
                            ):
                                a = _jnp.full((valid.shape[0],), a)
                            a = _jnp.asarray(a).astype(actual[s])
                        vals.append(a)
                    outs = kernel(karr, kmin_s, *vals, valid)
                    return fin(kmin_s, outs[0], *outs[1:])

                self._jit_cache[cache_key] = jax.jit(
                    prog,
                    out_shardings=NamedSharding(self._mesh, P(ROW_AXIS)),
                )
            outs = self._jit_cache[cache_key](
                dict(jdf.device_cols),
                dict(jdf.null_masks),
                tuple(tables[u][1] for u in uuids),
                jdf.device_valid_mask(),
            )
            device_cols = {key: outs[0]}
            for (_, name, _, _), arr in zip(spec_rows, outs[2:]):
                device_cols[name] = arr
            return JaxDataFrame(
                mesh=self._mesh,
                _internal=dict(
                    device_cols=device_cols,
                    host_tbl=None,
                    row_count=-1,
                    valid_mask=outs[1],
                    schema=plan["schema"],
                ),
            )

        return run

    def _host(self, df: DataFrame) -> DataFrame:
        return df.as_local_bounded() if isinstance(df, JaxDataFrame) else self._host_engine.to_df(df)

    def _back(self, df: DataFrame) -> DataFrame:
        return self.to_df(df)

    def join(self, df1, df2, how: str, on=None) -> DataFrame:
        """Hash joins run on device (``ops/join.py``): inner / left_outer /
        left_semi / left_anti, multi-key, unique OR duplicate right keys
        (the 1:N/N:M expansion kernel). Strategy ladder (docs/shuffle.md),
        decided from size estimates + conf by ``shuffle.strategy``:
        **broadcast** for small right sides, **copartition** (in-device
        all-to-all + shard-local probe) when both sides fit the device
        budget at once, **device_exchange** (staged one-hop-at-a-time
        on-device exchange, ``fugue_tpu/shuffle/exchange.py``) when the
        sides exceed the per-device budget but fit aggregate mesh
        memory, **shuffle_spill** (on-disk hash buckets joined one pair
        at a time, ``fugue_tpu/shuffle/``) past it — the chosen
        strategy is an attr on the ``engine.join`` span. right_outer
        mirrors left_outer; full_outer composes left_outer ∪ NULL-extended
        anti; cross runs through the expansion kernel on a constant key.
        Host fallback: host-resident frames, keys the preparers can't
        align, and expansions past the per-shard slot budget."""
        from ..obs import get_tracer

        with get_tracer().span("engine.join", cat="engine", annotate=True) as sp:
            return self._join_impl(df1, df2, how, on, sp)

    def _join_impl(self, df1, df2, how: str, on, sp) -> DataFrame:
        from ..dataframe.utils import parse_join_type
        from ..shuffle.strategy import (
            choose_join_strategy,
            estimate_frame_bytes,
            estimate_frame_rows,
            shuffle_enabled,
        )
        from .streaming import is_stream_frame, streaming_hash_join

        self._last_join_strategy = None
        # adaptive execution (docs/tuning.md): inside an enabled run scope
        # the tuner substitutes OBSERVED side cardinalities from previous
        # runs of this plan where the static estimate is unknowable, and
        # carries the calibrated bucket count into the spill shuffle; the
        # runtime decision function below stays authoritative either way
        tuner = getattr(self, "tuner", None)
        if is_stream_frame(df1) or is_stream_frame(df2):
            tune = (
                tuner.join_params(None, None, None)[3]
                if tuner is not None
                else None
            )
            # one-pass input: bounded-memory broadcast-hash join first
            res = streaming_hash_join(self, df1, df2, how, on)
            if res is not None:
                sp.set(strategy="stream")
                return res
            if shuffle_enabled(self.conf):
                # the spill shuffle consumes the stream chunk-by-chunk
                # too — materializing (the unbounded-memory hazard) is
                # now the LAST resort, not the only remaining option
                from ..shuffle.join import shuffle_spill_join

                res = shuffle_spill_join(self, df1, df2, how, on, tune=tune)
                if res is not None:
                    sp.set(
                        strategy="shuffle_spill",
                        reason="stream ineligible for the streaming join plan",
                    )
                    return res
            self.log.warning(
                "streaming join ineligible for this plan; materializing "
                "the stream"
            )
        else:
            est_l = estimate_frame_bytes(df1)
            est_r = estimate_frame_bytes(df2)
            est_rr = estimate_frame_rows(df2)
            tune = None
            if tuner is not None:
                est_l, est_r, est_rr, tune = tuner.join_params(
                    est_l, est_r, est_rr
                )
            dec = choose_join_strategy(
                self.conf,
                est_l,
                est_r,
                est_rr,
                n_shards=num_row_shards(self._mesh),
            )
            if dec.strategy == "device_exchange":
                # sides past the per-device budget but within aggregate
                # mesh memory: rows stay device-resident and move with
                # the staged one-hop schedule (shuffle/exchange.py) —
                # zero host round trips between partition and kernel
                res = self._try_device_exchange(df1, df2, how, on, tune)
                if res is not None:
                    sp.set(strategy="device_exchange", reason=dec.reason)
                    self._shuffle_stats.inc("device_exchange_joins")
                    return res
                # ineligible frames (host-resident columns, keys the
                # preparers can't align, cross joins): spill remains the
                # bit-identical fallback — same discipline as over-budget
                self._shuffle_stats.inc("device_exchange_fallbacks")
                if shuffle_enabled(self.conf):
                    from ..shuffle.join import shuffle_spill_join

                    res = shuffle_spill_join(self, df1, df2, how, on, tune=tune)
                    if res is not None:
                        sp.set(
                            strategy="shuffle_spill",
                            reason=f"device_exchange ineligible; {dec.reason}",
                        )
                        return res
            elif dec.strategy == "shuffle_spill" and shuffle_enabled(self.conf):
                from ..shuffle.join import shuffle_spill_join

                res = shuffle_spill_join(self, df1, df2, how, on, tune=tune)
                if res is not None:
                    sp.set(strategy="shuffle_spill", reason=dec.reason)
                    return res
        jt = parse_join_type(how)
        if jt in ("inner", "left_outer", "left_semi", "left_anti"):
            kernel_how = {
                "inner": "inner",
                "left_outer": "left_outer",
                "left_semi": "semi",
                "left_anti": "anti",
            }[jt]
            res = self._join_device(df1, df2, kernel_how, on)
            if res is not None:
                sp.set(strategy=self._last_join_strategy or "device")
                return res
        elif jt == "right_outer":
            # mirrored left_outer, columns re-ordered to the contract schema
            res = self._join_device(df2, df1, "left_outer", on)
            if res is not None:
                from ..dataframe.utils import get_join_schemas

                _, out_schema = get_join_schemas(
                    self.to_df(df1), self.to_df(df2), how="right_outer", on=on
                )
                if list(res.schema.names) != out_schema.names:
                    res = res[out_schema.names]  # type: ignore[index]
                sp.set(strategy=self._last_join_strategy or "device")
                return res
        elif jt == "full_outer":
            res = self._full_outer_device(df1, df2, on)
            if res is not None:
                sp.set(strategy=self._last_join_strategy or "device")
                return res
        elif jt == "cross":
            res = self._cross_device(df1, df2)
            if res is not None:
                sp.set(strategy="broadcast")
                return res
        sp.set(strategy="host")
        return self._back(self._host_engine.join(self._host(df1), self._host(df2), how=how, on=on))

    def _try_device_exchange(self, df1, df2, how: str, on, tune) -> Optional[DataFrame]:
        """Run the join through the device_exchange rung: the same device
        join-type dispatch as the generic ladder, but the co-partition
        step uses the STAGED exchange (and broadcast is skipped — the
        right side already failed the per-device budget). None → caller
        falls back to spill, bit-identically."""
        from ..dataframe.utils import parse_join_type

        jt = parse_join_type(how)
        if jt in ("inner", "left_outer", "left_semi", "left_anti"):
            kernel_how = {
                "inner": "inner",
                "left_outer": "left_outer",
                "left_semi": "semi",
                "left_anti": "anti",
            }[jt]
            return self._join_device(
                df1, df2, kernel_how, on, exchange_staged=True, tune=tune
            )
        if jt == "right_outer":
            res = self._join_device(
                df2, df1, "left_outer", on, exchange_staged=True, tune=tune
            )
            if res is not None:
                from ..dataframe.utils import get_join_schemas

                _, out_schema = get_join_schemas(
                    self.to_df(df1), self.to_df(df2), how="right_outer", on=on
                )
                if list(res.schema.names) != out_schema.names:
                    res = res[out_schema.names]  # type: ignore[index]
            return res
        if jt == "full_outer":
            return self._full_outer_device(
                df1, df2, on, exchange_staged=True, tune=tune
            )
        return None  # cross: replication-shaped, nothing to exchange

    def _full_outer_device(
        self, df1, df2, on, exchange_staged: bool = False, tune=None
    ) -> Optional[DataFrame]:
        """full_outer = left_outer(L,R) ∪ (anti(R,L) with NULL left
        values) — composed from device verbs, so it inherits all their
        representations (dictionaries, epochs, masks)."""
        from ..dataframe.utils import get_join_schemas

        try:
            _, out_schema = get_join_schemas(
                self.to_df(df1), self.to_df(df2), how="full_outer", on=on
            )
        except Exception:
            return None
        left_part = self._join_device(
            df1, df2, "left_outer", on, exchange_staged=exchange_staged, tune=tune
        )
        if left_part is None:
            return None
        right_only = self._join_device(
            df2, df1, "anti", on, exchange_staged=exchange_staged, tune=tune
        )
        if right_only is None:
            return None
        ext = self._null_extend(right_only, out_schema, self.to_df(df1))
        if ext is None:
            return None
        lp = (
            left_part
            if list(left_part.schema.names) == out_schema.names
            else left_part[out_schema.names]  # type: ignore[index]
        )
        res = self.union(lp, ext, distinct=False)
        return res if isinstance(res, JaxDataFrame) else None

    def _null_extend(
        self, jr: DataFrame, out_schema: Schema, j1: JaxDataFrame
    ) -> Optional[JaxDataFrame]:
        """Extend right-only rows to the full join schema: absent (left-
        side) columns become NULL in each dtype's device representation."""
        import jax

        jr = self.to_df(jr)
        if not isinstance(jr, JaxDataFrame) or jr.host_table is not None:
            return None
        n = next(iter(jr.device_cols.values())).shape[0]
        sharding = row_sharding(self._mesh)
        cols: Dict[str, Any] = {}
        encodings: Dict[str, Any] = dict(jr.encodings)
        null_masks: Dict[str, Any] = dict(jr.null_masks)
        nan_new: set = set()
        for f in out_schema.fields:
            name = f.name
            if name in jr.device_cols:
                cols[name] = jr.device_cols[name]
                continue
            if name not in j1.device_cols:
                return None  # left column wasn't device-resident
            enc = j1.encodings.get(name)
            dt = np.dtype(j1.device_cols[name].dtype)
            if enc is not None and enc["kind"] == "dict":
                cols[name] = jax.device_put(
                    np.full(n, -1, dtype=dt), sharding
                )
                encodings[name] = dict(enc)
            elif enc is not None and enc["kind"] == "datetime":
                cols[name] = jax.device_put(np.zeros(n, dtype=dt), sharding)
                encodings[name] = dict(enc)
                null_masks[name] = jax.device_put(
                    np.ones(n, dtype=bool), sharding
                )
            elif np.issubdtype(dt, np.floating):
                cols[name] = jax.device_put(
                    np.full(n, np.nan, dtype=dt), sharding
                )
                nan_new.add(name)
            else:
                cols[name] = jax.device_put(np.zeros(n, dtype=dt), sharding)
                null_masks[name] = jax.device_put(
                    np.ones(n, dtype=bool), sharding
                )
        nan_cols = (
            None if jr._nan_cols is None else set(jr._nan_cols) | nan_new
        )
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols={name: cols[name] for name in out_schema.names},
                host_tbl=None,
                row_count=jr._row_count,
                valid_mask=jr.valid_mask,
                nan_cols=nan_cols,
                encodings=encodings,
                null_masks=null_masks,
                schema=out_schema,
            ),
        )

    def _cross_device(self, df1, df2) -> Optional[DataFrame]:
        """Cross join via the expansion kernel over a constant synthetic
        key (every left row matches every right row)."""
        import jax

        from ..ops.join import device_expand_join
        from ..shuffle.strategy import broadcast_max_rows

        j1, j2 = self.to_df(df1), self.to_df(df2)
        if not (
            isinstance(j1, JaxDataFrame)
            and isinstance(j2, JaxDataFrame)
            and j1.host_table is None
            and j2.host_table is None
            and len(j1.device_cols) > 0
            and len(j2.device_cols) > 0
        ):
            return None
        n_right = next(iter(j2.device_cols.values())).shape[0]
        if n_right > broadcast_max_rows(self.conf):
            return None
        if any(c in j1.schema for c in j2.schema.names):
            return None  # overlapping names — host handles the error
        mp = _safe_prefix("__mask__", j1.schema.names, j2.schema.names)
        lmp = _safe_prefix("__lmask__", j1.schema.names)
        kp = _safe_prefix("__xkey", j1.schema.names, j2.schema.names)
        rep = replicated_sharding(self._mesh)
        ones_l = jax.device_put(
            np.zeros(next(iter(j1.device_cols.values())).shape[0], np.int8),
            row_sharding(self._mesh),
        )
        ones_r = jax.device_put(np.zeros(n_right, np.int8), rep)
        left_cols = dict(j1.device_cols)
        for c, m in j1.null_masks.items():
            left_cols[f"{lmp}{c}"] = m
        left_cols[f"{kp}0"] = ones_l
        right_entries: List[Any] = []
        encodings: Dict[str, Any] = dict(j1.encodings)
        for v in j2.schema.names:
            arr = jax.device_put(j2.device_cols[v], rep)
            right_entries.append((v, arr, 0))
            enc = j2.encodings.get(v)
            if enc is not None:
                encodings[v] = enc
        for v, m in j2.null_masks.items():
            right_entries.append(
                (f"{mp}{v}", jax.device_put(m, rep), True)
            )
        res = device_expand_join(
            self._mesh,
            "inner",
            left_cols,
            j1.device_valid_mask(),
            [f"{kp}0"],
            [ones_r],
            jax.device_put(j2.device_valid_mask(), rep),
            right_entries,
            strategy="broadcast",
        )
        if res is None:
            return None
        new_cols, new_valid, _ = res
        null_masks: Dict[str, Any] = {}
        for c in list(j1.null_masks):
            m = new_cols.pop(f"{lmp}{c}", None)
            if m is not None:
                null_masks[c] = m
        for v in list(j2.null_masks):
            m = new_cols.pop(f"{mp}{v}", None)
            if m is not None:
                null_masks[v] = m
        new_cols.pop(f"{kp}0", None)
        out_schema = Schema(
            list(j1.schema.fields) + list(j2.schema.fields)
        )
        nan_cols = (
            None
            if j1._nan_cols is None or j2._nan_cols is None
            else set(j1._nan_cols) | set(j2._nan_cols)
        )
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols={n: new_cols[n] for n in out_schema.names},
                host_tbl=None,
                row_count=-1,
                valid_mask=new_valid,
                nan_cols=nan_cols,
                encodings={
                    k: v for k, v in encodings.items() if k in out_schema
                },
                null_masks=null_masks,
                schema=out_schema,
            ),
        )

    def _prepare_join_keys(
        self, j1: JaxDataFrame, j2: JaxDataFrame, keys: List[str]
    ) -> Optional[Any]:
        """Align the two frames' key representations for hashing/equality.

        Returns (left_key_arrs: Dict[mangled→arr], right_key_arrs: List) or
        None on fallback. Dictionary keys remap the right side's codes into
        the left's code space (host-side unification of the small
        dictionaries; NULLs get −1 left / −2 right so they never match);
        nullable numeric keys become float64 NaN views on both sides;
        epoch datetimes compare directly when the arrow types agree.
        """
        import jax
        import jax.numpy as jnp

        def _nullview(arr: Any, mask: Optional[Any]) -> Any:
            cache_key = ("nullview", self._mesh)
            if cache_key not in self._jit_cache:
                self._jit_cache[cache_key] = jax.jit(
                    lambda a, m: jnp.where(m, jnp.nan, a.astype(jnp.float64))
                )
            if mask is None:
                return arr.astype(jnp.float64)
            return self._jit_cache[cache_key](arr, mask)

        def _cast64(arr: Any, kind: str) -> Any:
            cache_key = ("joincast", kind, self._mesh)
            if cache_key not in self._jit_cache:
                tgt = jnp.float64 if kind == "f" else jnp.int64
                self._jit_cache[cache_key] = jax.jit(
                    lambda a, _t=tgt: a.astype(_t)
                )
            return self._jit_cache[cache_key](arr)

        kp = _safe_prefix("__key", j1.schema.names)
        left_keys: Dict[str, Any] = {}
        right_keys: List[Any] = []
        for i, k in enumerate(keys):
            lenc, renc = j1.encodings.get(k), j2.encodings.get(k)
            lm, rm = j1.null_masks.get(k), j2.null_masks.get(k)
            la, ra = j1.device_cols[k], j2.device_cols[k]
            if lenc is None and renc is None:
                if lm is None and rm is None:
                    lk, rk = la, ra
                    ld, rd = np.dtype(la.dtype), np.dtype(ra.dtype)
                    if ld != rd:
                        # cross-dtype keys match by VALUE via the common
                        # type (pandas/SQL coercion semantics — the host
                        # oracle does the same; int64 past 2^53 matches
                        # inexactly there too)
                        if (ld.kind == "u" and ld.itemsize == 8) or (
                            rd.kind == "u" and rd.itemsize == 8
                        ):
                            # uint64 ≥ 2^63 would wrap under an int64 cast
                            # into false matches — host fallback is exact
                            return None
                        if "f" in (ld.kind, rd.kind):
                            lk, rk = _cast64(la, "f"), _cast64(ra, "f")
                        elif ld.kind in "iub" and rd.kind in "iub":
                            lk, rk = _cast64(la, "i"), _cast64(ra, "i")
                        else:
                            return None
                elif np.dtype(la.dtype).kind == "f" or (
                    np.dtype(la.dtype).itemsize < 8
                    and np.dtype(ra.dtype).itemsize < 8
                ):
                    lk, rk = _nullview(la, lm), _nullview(ra, rm)
                else:
                    return None  # 64-bit ints with NULL keys lose exactness
            elif (
                lenc is not None
                and renc is not None
                and lenc["kind"] == "dict"
                and renc["kind"] == "dict"
            ):
                lk = la
                rk = self._remap_dict_codes(lenc, renc, ra)
            elif (
                lenc is not None
                and renc is not None
                and lenc["kind"] == "datetime"
                and renc["kind"] == "datetime"
                and lenc["type"] == renc["type"]
            ):
                if lm is not None or rm is not None:
                    return None  # masked epochs: 64-bit NULL-key problem
                lk, rk = la, ra
            else:
                return None
            left_keys[f"{kp}{i}__"] = lk
            right_keys.append(rk)
        return left_keys, right_keys

    def _remap_dict_codes(self, lenc: dict, renc: dict, right_codes: Any) -> Any:
        """Map right-side dictionary codes into the left's code space.

        Right values absent from the left dictionary get out-of-range codes
        (≥ len(left dict)) so they never match; NULL codes map −1 → −2 so
        NULL never equals NULL (SQL semantics)."""
        import jax
        import jax.numpy as jnp

        idx = pa.compute.index_in(
            renc["dictionary"], value_set=lenc["dictionary"]
        )
        n_left = len(lenc["dictionary"])
        mapped = idx.to_numpy(zero_copy_only=False)
        missing = np.isnan(mapped)
        mapped = np.where(
            missing, n_left + np.arange(len(mapped)), mapped
        ).astype(np.int32)
        table = jnp.asarray(mapped)

        cache_key = ("dictremap", self._mesh)
        if cache_key not in self._jit_cache:
            self._jit_cache[cache_key] = jax.jit(
                lambda codes, t: jnp.where(
                    codes < 0,
                    jnp.int32(-2),
                    t[jnp.clip(codes, 0, t.shape[0] - 1)],
                )
            )
        return self._jit_cache[cache_key](right_codes, table)

    def _join_device(
        self,
        df1,
        df2,
        kernel_how: str,
        on,
        exchange_staged: bool = False,
        tune=None,
    ) -> Optional[DataFrame]:
        """Try the device hash join; None → host fallback.

        ``exchange_staged=True`` is the device_exchange rung: broadcast
        is skipped (the right side already failed the per-device budget)
        and the co-partition step runs the staged one-hop exchange
        instead of the single-shot all-to-all."""
        from ..dataframe.utils import get_join_schemas
        from ..ops.join import device_hash_join

        if not (isinstance(df1, DataFrame) and isinstance(df2, DataFrame)):
            return None
        how_for_schema = {
            "inner": "inner",
            "left_outer": "left_outer",
            "semi": "left_semi",
            "anti": "left_anti",
        }[kernel_how]
        try:
            key_schema, out_schema = get_join_schemas(
                df1, df2, how=how_for_schema, on=on
            )
        except Exception:
            return None
        keys = key_schema.names
        # cheap schema pre-checks BEFORE any device conversion
        supported = all(
            pa.types.is_integer(t)
            or pa.types.is_floating(t)
            or pa.types.is_boolean(t)
            or pa.types.is_string(t)
            or pa.types.is_large_string(t)
            or pa.types.is_timestamp(t)
            or pa.types.is_date(t)
            for t in key_schema.types
        )
        if len(keys) == 0 or not supported:
            return None
        j1, j2 = self.to_df(df1), self.to_df(df2)
        if not (
            isinstance(j1, JaxDataFrame)
            and isinstance(j2, JaxDataFrame)
            and j2.host_table is None
            and len(j2.device_cols) == len(j2.schema)
            and all(k in j1.device_cols for k in keys)
        ):
            return None
        prepared = self._prepare_join_keys(j1, j2, keys)
        if prepared is None:
            return None
        left_key_arrs, right_key_arrs = prepared
        value_names = [
            n for n in j2.schema.names if n not in keys and n in out_schema
        ]
        # value entries: (out_name, array, left_outer miss fill); masked
        # columns ship their mask as an extra gathered array (miss = True)
        import math

        import jax

        mp = _safe_prefix("__mask__", j1.schema.names, j2.schema.names)
        lmp = _safe_prefix("__lmask__", j1.schema.names)
        right_entries: List[Any] = []
        out_value_encodings: Dict[str, Any] = {}
        gen_mask_names: List[str] = []  # plain non-floats: mask = ~match
        for v in value_names:
            arr = j2.device_cols[v]
            enc = j2.encodings.get(v)
            if enc is not None and enc["kind"] == "dict":
                right_entries.append((v, arr, -1))
                out_value_encodings[v] = enc
            elif np.issubdtype(np.dtype(arr.dtype), np.floating):
                right_entries.append((v, arr, math.nan))
            else:
                right_entries.append((v, arr, 0))
                if enc is not None:
                    out_value_encodings[v] = enc
                if kernel_how == "left_outer" and v not in j2.null_masks:
                    gen_mask_names.append(v)
            if v in j2.null_masks:
                right_entries.append(
                    (f"{mp}{v}", j2.null_masks[v], True)
                )
        from ..shuffle.strategy import broadcast_max_rows

        n_right = next(iter(j2.device_cols.values())).shape[0]
        encodings: Dict[str, Any] = {}
        null_masks: Dict[str, Any] = {}
        if not exchange_staged and n_right <= broadcast_max_rows(self.conf):
            strategy = "broadcast"
            self._last_join_strategy = "broadcast"
            rep = replicated_sharding(self._mesh)
            right_entries = [
                (n, jax.device_put(a, rep), f) for n, a, f in right_entries
            ]
            right_key_arrs = [
                jax.device_put(a, rep) for a in right_key_arrs
            ]
            right_valid = jax.device_put(j2.device_valid_mask(), rep)
            left_cols = dict(j1.device_cols)
            left_cols.update(left_key_arrs)
            left_valid = j1.device_valid_mask()
            host_tbl = j1.host_table  # rows stay in place → stays aligned
            nan_cols = j1._nan_cols
            encodings = dict(j1.encodings)  # non-key left cols ride along
            null_masks = dict(j1.null_masks)
        else:
            strategy = "shuffle"
            self._last_join_strategy = (
                "device_exchange" if exchange_staged else "copartition"
            )
            if j1.host_table is not None:
                return None  # rows move; host columns can't follow
            left_cols = dict(j1.device_cols)
            # left null masks travel with their rows through the exchange
            for c, m in j1.null_masks.items():
                left_cols[f"{lmp}{c}"] = m
            left_cols.update(left_key_arrs)
            left_valid = j1.device_valid_mask()
            right_valid = j2.device_valid_mask()
            host_tbl = None
            nan_cols = None
            encodings = dict(j1.encodings)
        if strategy == "shuffle":
            # ONE exchange, shared by the unique probe and any dup-key
            # expansion retry (the retry must not repeat the all-to-all)
            if exchange_staged:
                from ..obs import get_tracer
                from ..shuffle.exchange import staged_copartition_by_keys
                from ..shuffle.strategy import exchange_stage_bytes

                stage_bytes = exchange_stage_bytes(self.conf)
                stages_before = self._shuffle_stats.get("device_exchange_stages")
                with get_tracer().span(
                    "shuffle.exchange", cat="shuffle", annotate=True
                ) as xsp:
                    (
                        left_cols,
                        left_valid,
                        right_key_arrs,
                        right_entries,
                        right_valid,
                    ) = staged_copartition_by_keys(
                        self._mesh,
                        left_cols,
                        left_valid,
                        list(left_key_arrs.keys()),
                        right_key_arrs,
                        right_entries,
                        right_valid,
                        stage_bytes,
                        stats=self._shuffle_stats,
                    )
                    xsp.set(
                        stage_bytes=stage_bytes,
                        peak_stage_bytes=self._shuffle_stats.get(
                            "device_exchange_peak_stage_bytes"
                        ),
                    )
                if tune is not None:
                    tune.observe_exchange(
                        stages=self._shuffle_stats.get("device_exchange_stages")
                        - stages_before,
                        peak_stage_bytes=self._shuffle_stats.get(
                            "device_exchange_peak_stage_bytes"
                        ),
                    )
            else:
                from ..ops.join import copartition_by_keys

                (
                    left_cols,
                    left_valid,
                    right_key_arrs,
                    right_entries,
                    right_valid,
                ) = copartition_by_keys(
                    self._mesh,
                    left_cols,
                    left_valid,
                    list(left_key_arrs.keys()),
                    right_key_arrs,
                    right_entries,
                    right_valid,
                )
            strategy = "local"
        res = device_hash_join(
            self._mesh,
            kernel_how,
            left_cols,
            left_valid,
            list(left_key_arrs.keys()),
            right_key_arrs,
            right_valid,
            right_entries,
            strategy=strategy,
        )
        expanded = False
        if res is None:
            # duplicate right keys: the 1:N/N:M expansion path. semi/anti
            # keep row alignment (mask-only); inner/left_outer materialize
            # (left row, match) pairs — rows move, host columns can't follow
            from ..ops.join import device_expand_join

            if kernel_how in ("inner", "left_outer"):
                if j1.host_table is not None:
                    return None
                if strategy == "broadcast":
                    # the unique-path broadcast payload omitted the left
                    # masks (rows didn't move); expansion gathers rows, so
                    # masks must ride along
                    for c, m2 in j1.null_masks.items():
                        left_cols[f"{lmp}{c}"] = m2
                    host_tbl = None
                    null_masks = {}
            res = device_expand_join(
                self._mesh,
                kernel_how,
                left_cols,
                left_valid,
                list(left_key_arrs.keys()),
                right_key_arrs,
                right_valid,
                right_entries,
                strategy=strategy,
            )
            if res is None:
                return None
            expanded = True
        new_cols, new_valid, match = res
        # reassemble: pop probe keys, split off mask arrays
        for mk in left_key_arrs:
            new_cols.pop(mk, None)
        if strategy == "local" or expanded:
            for c in list(j1.null_masks):
                m = new_cols.pop(f"{lmp}{c}", None)
                if m is not None:
                    null_masks[c] = m
        for v in value_names:
            m = new_cols.pop(f"{mp}{v}", None)
            if m is not None:
                null_masks[v] = m
        if kernel_how == "left_outer":
            if nan_cols is not None:
                # gathered float values may be NaN-filled on misses
                nan_cols = set(nan_cols) | {
                    v
                    for v in value_names
                    if np.issubdtype(
                        np.dtype(j2.device_cols[v].dtype), np.floating
                    )
                }
            if len(gen_mask_names) > 0:
                import jax.numpy as jnp

                cache_key = ("notmask", self._mesh)
                if cache_key not in self._jit_cache:
                    import jax as _jax

                    self._jit_cache[cache_key] = _jax.jit(jnp.logical_not)
                miss = self._jit_cache[cache_key](match)
                for v in gen_mask_names:
                    null_masks[v] = miss
        encodings.update(out_value_encodings)
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols={
                    n: new_cols[n] for n in out_schema.names if n in new_cols
                },
                host_tbl=host_tbl,
                row_count=-1,
                valid_mask=new_valid,
                nan_cols=nan_cols,
                encodings={
                    k: v
                    for k, v in encodings.items()
                    if k in out_schema
                },
                null_masks={
                    k: v for k, v in null_masks.items() if k in out_schema
                },
                schema=out_schema,
            ),
        )

    # ---- co-sharded zip/comap ---------------------------------------------
    def zip(
        self,
        dfs: DataFrames,
        how: str = "inner",
        partition_spec: Optional[PartitionSpec] = None,
        temp_path: Optional[str] = None,
        to_file_threshold: int = -1,
    ) -> DataFrame:
        """Device zip: hash-co-partition every input by the zip keys with
        the all-to-all exchange — no arrow-IPC blobs (SURVEY §5.8 redesign
        of the reference's serialize-by-partition protocol). Falls back to
        the host blob protocol for cross zips, host-resident frames, and
        keys whose device form isn't comparable across frames (strings /
        nullable / NaN-able keys)."""
        from ..collections.partition import PartitionSpec as _PSpec
        from .streaming import is_stream_frame, streaming_zip
        from .zipped import ZippedJaxDataFrame

        spec = partition_spec if partition_spec is not None else _PSpec()
        if any(is_stream_frame(d) for d in dfs.values()):
            # key-sorted one-pass inputs: defer to the co-batched
            # sorted-merge comap (bounded memory); ineligible shapes
            # (cross / keyless) materialize below
            zs = streaming_zip(self, dfs, how, spec)
            if zs is not None:
                return zs
        keys = list(spec.partition_by)
        if how.lower() != "cross" and len(keys) == 0 and len(dfs) > 0:
            keys = [
                n
                for n in dfs[0].schema.names
                if all(n in d.schema for d in dfs.values())
            ]
        if how.lower() != "cross" and len(keys) > 0:
            jdfs = [self.to_df(d) for d in dfs.values()]

            def _key_ok(j: JaxDataFrame, k: str) -> bool:
                if k not in j.device_cols:
                    return False
                enc = j.encodings.get(k)
                if enc is not None and enc["kind"] == "dict":
                    return True  # co-located via code remapping below
                # NULL/NaN keys don't group across frames on the host side
                # (NaN/NaT break the key-tuple lookup) → blob protocol
                return (
                    enc is None
                    and k not in j.null_masks
                    and not j.maybe_nan(k)
                )

            device_ok = all(
                isinstance(j, JaxDataFrame)
                and j.host_table is None
                and len(j.device_cols) == len(j.schema)
                and all(_key_ok(j, k) for k in keys)
                for j in jdfs
            )
            if device_ok:
                # union dictionary per string key: every frame's codes remap
                # into ONE shared space so equal values co-locate even when
                # absent from other frames' dictionaries
                union_dicts: Dict[str, Any] = {}
                for k in keys:
                    dicts = [
                        j.encodings[k]["dictionary"]
                        for j in jdfs
                        if j.encodings.get(k, {}).get("kind") == "dict"
                    ]
                    if len(dicts) > 0:
                        union_dicts[k] = pa.concat_arrays(dicts).unique()
                co = [
                    self._zip_repartition(j, union_dicts, keys) for j in jdfs
                ]
                return ZippedJaxDataFrame(
                    frames=co,  # type: ignore[arg-type]
                    names=list(dfs.keys()),
                    named=dfs.has_key,
                    how=how.lower(),
                    keys=keys,
                    schemas=[j.schema for j in jdfs],
                    mesh=self._mesh,
                    presort=dict(spec.presort),
                )
        return super().zip(
            dfs,
            how=how,
            partition_spec=partition_spec,
            temp_path=temp_path,
            to_file_threshold=to_file_threshold,
        )

    def _zip_repartition(
        self, j: JaxDataFrame, union_dicts: Dict[str, Any], keys: List[str]
    ) -> JaxDataFrame:
        """Hash-repartition a zip input so equal key VALUES co-locate across
        frames: dictionary keys hash via codes remapped into the shared
        union-dictionary space (NULL codes stay −1, so every frame's NULL
        rows share a shard and form one comap group)."""
        from ..collections.partition import PartitionSpec as _PSpec
        from ..ops.shuffle import compute_dest, exchange_rows

        dict_keys = [k for k in keys if k in union_dicts]
        if len(dict_keys) == 0:
            return self.repartition(j, _PSpec(algo="hash", by=keys))  # type: ignore[return-value]
        import jax
        import jax.numpy as jnp

        key_arrs = []
        for k in keys:
            arr = j.device_cols[k]
            if k in dict_keys:
                mapped = np.asarray(
                    pa.compute.index_in(
                        j.encodings[k]["dictionary"], value_set=union_dicts[k]
                    ).to_numpy(zero_copy_only=False)
                )
                if mapped.size == 0:  # no dictionary entries → all NULL rows
                    mapped = np.asarray([-1])
                table = jnp.asarray(mapped.astype(np.int32))
                ck = ("zipremap", self._mesh)
                if ck not in self._jit_cache:
                    self._jit_cache[ck] = jax.jit(
                        lambda c, t: jnp.where(
                            c < 0,
                            jnp.int32(-1),  # NULLs co-locate across frames
                            t[jnp.clip(c, 0, t.shape[0] - 1)],
                        )
                    )
                arr = self._jit_cache[ck](arr, table)
            key_arrs.append(arr)
        valid = j.device_valid_mask()
        dest = compute_dest(self._mesh, "hash", key_arrs, valid)
        mp = _safe_prefix("__mask__", j.schema.names)
        payload = dict(j.device_cols)
        for c, m in j.null_masks.items():
            payload[f"{mp}{c}"] = m
        new_payload, new_valid, _ = exchange_rows(
            self._mesh, payload, valid, dest
        )
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols={c: new_payload[c] for c in j.device_cols},
                host_tbl=None,
                row_count=j.count(),
                valid_mask=new_valid,
                nan_cols=j._nan_cols,
                encodings=dict(j.encodings),
                null_masks={
                    c: new_payload[f"{mp}{c}"] for c in j.null_masks
                },
                schema=j.schema,
            ),
        )

    def comap(
        self,
        df: DataFrame,
        map_func: Callable,
        output_schema: Any,
        partition_spec: Optional[PartitionSpec] = None,
        on_init: Optional[Callable] = None,
    ) -> DataFrame:
        """Comap over a device-zipped frame: each co-sharded frame transfers
        to host once (shard-local on multi-host meshes — the exchange
        already placed each key's rows on its owner), groups by the zip
        keys, and the cotransform runs per key group. No blob rows are
        ever built or parsed."""
        from ..collections.partition import PartitionSpec as _PSpec
        from ..dataframe import ArrayDataFrame
        from .streaming import ZippedStreamDataFrame, streaming_comap
        from .zipped import ZippedJaxDataFrame

        if isinstance(df, ZippedStreamDataFrame):
            return streaming_comap(
                self, df, map_func, output_schema,
                partition_spec=partition_spec, on_init=on_init,
            )
        if not isinstance(df, ZippedJaxDataFrame):
            return super().comap(
                df,
                map_func,
                output_schema,
                partition_spec=partition_spec,
                on_init=on_init,
            )
        out_schema = (
            output_schema
            if isinstance(output_schema, Schema)
            else Schema(output_schema)
        )
        keys = df._zip_keys
        how = df._zip_how
        schemas = df._zip_schemas
        names = [
            df._zip_names[i] if df._zip_named else f"_{i}"
            for i in range(len(schemas))
        ]
        spec = _PSpec(partition_spec, by=keys) if partition_spec is not None else _PSpec(by=keys)
        cursor = spec.get_cursor(df.schema, 0)
        if on_init is not None:
            on_init(
                0,
                DataFrames(
                    {n: ArrayDataFrame([], s) for n, s in zip(names, schemas)}
                ),
            )
        presort = dict(getattr(df, "_zip_presort", {}) or {})
        # the comap-time spec's presort (e.g. from the cotransformer's own
        # partition settings) overrides the zip-time one, matching the host
        # blob protocol where serialization uses the effective spec
        if len(spec.presort) > 0:
            presort = dict(spec.presort)
        # multi-host: the zip exchange already placed each key's rows on
        # exactly one shard, so every process transfers ONLY its local
        # shards and runs the cotransform for its own keys — the per-host
        # parallel execution the reference gets from cluster executors
        from ..parallel.distributed import is_multihost

        multihost = is_multihost()
        if multihost:
            frames_pd = [f.as_pandas_local() for f in df.zip_frames]
        else:
            frames_pd = [f.as_pandas() for f in df.zip_frames]
        if len(presort) > 0:
            # na_position="first" matches the host blob protocol's partition
            # presort (PandasMapEngine) so NULL rows order identically
            frames_pd = [
                p.sort_values(
                    by=[c for c in presort if c in p.columns],
                    ascending=[v for c, v in presort.items() if c in p.columns],
                    kind="mergesort",
                    na_position="first",
                )
                if len(p) > 0 and any(c in p.columns for c in presort)
                else p
                for p in frames_pd
            ]
        grouped: List[Dict[Any, pd.DataFrame]] = []
        key_order: List[Any] = []
        seen: set = set()
        for p in frames_pd:
            g: Dict[Any, pd.DataFrame] = {}
            if len(p) > 0:
                for kv, sub in p.groupby(keys, dropna=False, sort=False):
                    kt = _null_safe_key(kv)
                    g[kt] = sub
                    if kt not in seen:
                        seen.add(kt)
                        key_order.append(kt)
            grouped.append(g)
        results: List[pa.Table] = []
        no = 0
        for kt in key_order:
            subs = [g.get(kt) for g in grouped]
            if how == "inner" and any(s is None for s in subs):
                continue
            if how == "left_outer" and subs[0] is None:
                continue
            if how == "right_outer" and subs[-1] is None:
                continue
            dfs_obj = DataFrames(
                {
                    n: (
                        PandasDataFrame(
                            s.reset_index(drop=True), sch, pandas_df_wrapper=True
                        )
                        if s is not None
                        else ArrayDataFrame([], sch)
                    )
                    for n, s, sch in zip(names, subs, schemas)
                }
            )
            row = list(kt) + [None] * len(schemas)
            cursor.set(lambda r=row: r, no, 0)
            no += 1
            out = map_func(cursor, dfs_obj)
            results.append(out.as_local_bounded().as_arrow())
        if multihost:
            tbl = (
                pa.concat_tables(
                    [t.cast(out_schema.pa_schema) for t in results]
                )
                if len(results) > 0
                else out_schema.create_empty_arrow_table()
            )
            return self._from_process_local_table(tbl)
        if len(results) == 0:
            return self.to_df(ArrayDataFrame([], out_schema))
        tbl = pa.concat_tables(
            [t.cast(out_schema.pa_schema) for t in results]
        )
        return self.to_df(ArrowDataFrame(tbl))

    def _from_process_local_table(self, tbl: pa.Table) -> JaxDataFrame:
        """Assemble a global JaxDataFrame from per-process row sets.

        Each process contributes its own rows (counts may differ); per-shard
        capacity is negotiated with an allgather of the local counts so all
        processes agree on ONE padded global shape, then the device array is
        built from process-local data — no host ever sees another host's
        rows. String columns get a cross-process dictionary union: local
        dictionaries allgather (arrow IPC over padded byte buffers), every
        process derives the SAME sorted union dictionary, and local codes
        remap into it. Datetime encodings are schema-derived and identical
        everywhere, so they pass straight through.
        """
        import jax
        from jax.experimental import multihost_utils

        from .dataframe import encode_arrow_for_device

        np_cols, host_tbl, meta = encode_arrow_for_device(tbl, encode=True)
        assert_or_throw(
            host_tbl is None,
            FugueInvalidOperation(
                "multi-host comap outputs support device-representable "
                "columns only (numeric/bool/string/datetime — no binary/"
                "nested)"
            ),
        )
        dict_encs = {
            n: e for n, e in meta["encodings"].items() if e.get("kind") == "dict"
        }  # datetime encodings are process-independent and pass through
        if len(dict_encs) > 0:
            unions = _allgather_dictionaries(
                {n: e["dictionary"] for n, e in dict_encs.items()}
            )
            for name, enc in dict_encs.items():
                gdict = unions[name].cast(enc["type"])
                # remap local codes into the union's (sorted) code space
                to_global = _dict_mapping(enc["dictionary"], gdict)
                codes = np_cols[name]
                np_cols[name] = np.where(
                    codes >= 0, to_global[np.clip(codes, 0, None)], -1
                ).astype(np.int32)
                meta["encodings"][name] = {
                    "kind": "dict",
                    "dictionary": gdict,
                    "type": enc["type"],
                    "sorted": True,
                }
        local_n = tbl.num_rows
        counts = np.asarray(
            multihost_utils.process_allgather(np.asarray([local_n]))
        ).reshape(-1)
        local_shards = jax.local_device_count()
        total_shards = num_row_shards(self._mesh)
        per_shard = max(
            1, int(-(-int(counts.max()) // local_shards))
        )  # ceil over the busiest process
        cap = 1 << (per_shard - 1).bit_length()  # pow2 keeps jit cache small
        local_rows = local_shards * cap
        global_rows = total_shards * cap
        sharding = row_sharding(self._mesh)

        def _pad(arr: np.ndarray, fill: Any) -> np.ndarray:
            out = np.full(local_rows, fill, dtype=arr.dtype)
            out[: len(arr)] = arr
            return out

        cols = {
            k: jax.make_array_from_process_local_data(
                sharding, _pad(v, 0), (global_rows,)
            )
            for k, v in np_cols.items()
        }
        valid = jax.make_array_from_process_local_data(
            sharding,
            _pad(np.ones(local_n, dtype=bool), False),
            (global_rows,),
        )
        # mask-key sets must be IDENTICAL on every process (divergent frame
        # structure → divergent jitted programs → collective deadlock):
        # allgather the local sets and union them, filling absentees with
        # all-False masks
        schema_names = [f.name for f in tbl.schema]
        local_has = np.asarray(
            [n in meta["null_masks"] for n in schema_names], dtype=np.int32
        )
        union_has = (
            np.asarray(multihost_utils.process_allgather(local_has))
            .reshape(-1, len(schema_names))
            .max(axis=0)
        )
        null_masks = {}
        for i, n in enumerate(schema_names):
            if union_has[i]:
                m = meta["null_masks"].get(
                    n, np.zeros(local_n, dtype=bool)
                )
                null_masks[n] = jax.make_array_from_process_local_data(
                    sharding, _pad(m, True), (global_rows,)
                )
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=cols,
                host_tbl=None,
                row_count=int(counts.sum()),
                valid_mask=valid,
                # nan_cols derived from LOCAL rows would diverge between
                # processes (different plan gating → collective deadlock);
                # None = conservatively maybe-NaN everywhere, identically
                nan_cols=None,
                # dict encodings hold the UNION dictionary (identical on
                # every process); datetime encodings are schema-derived
                encodings=meta["encodings"],
                null_masks=null_masks,
                schema=Schema(tbl.schema),
            ),
        )

    @traced_verb("engine.union")
    def union(self, df1, df2, distinct: bool = True) -> DataFrame:
        """Device union: per-shard concatenation of both frames' blocks in
        one ``shard_map``. Dictionary columns unify into one (re-sorted)
        union dictionary with both sides' codes remapped; null masks
        concatenate with their columns; epoch datetimes concatenate when
        the arrow types agree. ``distinct=True`` runs the device distinct
        on the result."""
        j1, j2 = self.to_df(df1), self.to_df(df2)
        compatible = (
            isinstance(j1, JaxDataFrame)
            and isinstance(j2, JaxDataFrame)
            and j1.schema == j2.schema
            and j1.host_table is None
            and j2.host_table is None
            and len(j1.device_cols) > 0
            and all(
                j1.device_cols[c].dtype == j2.device_cols[c].dtype
                for c in j1.schema.names
            )
            # per-column encodings must agree in KIND (schema equality
            # already forces matching arrow types, incl. timestamp units)
            and all(
                j1.encodings.get(c, {}).get("kind")
                == j2.encodings.get(c, {}).get("kind")
                for c in j1.schema.names
            )
        )
        if compatible:
            import jax
            import jax.numpy as jnp
            from jax.sharding import PartitionSpec as JP

            mesh = self._mesh
            # unify dictionary columns: sorted union dictionary + remapped
            # codes on both sides (NULL code −1 is preserved by the remap)
            cols1, cols2 = dict(j1.device_cols), dict(j2.device_cols)
            encodings: Dict[str, Any] = {}
            for c in j1.schema.names:
                enc1, enc2 = j1.encodings.get(c), j2.encodings.get(c)
                if enc1 is None:
                    continue
                if enc1["kind"] == "datetime":
                    encodings[c] = enc1
                    continue
                union_dict = _sorted_union_dictionary(
                    [enc1["dictionary"], enc2["dictionary"]]
                )
                ck = ("zipremap", mesh)
                if ck not in self._jit_cache:
                    self._jit_cache[ck] = jax.jit(
                        lambda cd, t: jnp.where(
                            cd < 0,
                            jnp.int32(-1),
                            t[jnp.clip(cd, 0, t.shape[0] - 1)],
                        )
                    )
                for cols, enc in ((cols1, enc1), (cols2, enc2)):
                    mapped = _dict_mapping(enc["dictionary"], union_dict)
                    cols[c] = self._jit_cache[ck](
                        cols[c], jnp.asarray(mapped)
                    )
                encodings[c] = {
                    "kind": "dict",
                    "dictionary": union_dict,
                    "type": enc1["type"],
                    "sorted": True,
                }
            # null masks travel with their columns through the concat; a
            # side without a mask for the column contributes all-False
            mp = _safe_prefix("__mask__", j1.schema.names)
            vp = _safe_prefix("__valid__", cols1.keys())
            for c in set(j1.null_masks) | set(j2.null_masks):
                cols1[f"{mp}{c}"] = j1.null_masks.get(
                    c, self._false_mask_like(j1)
                )
                cols2[f"{mp}{c}"] = j2.null_masks.get(
                    c, self._false_mask_like(j2)
                )
            mask_names = [n for n in cols1 if n.startswith(mp)]
            cache_key = (
                "union",
                mesh,
                tuple(sorted(cols1)),
                tuple(str(cols1[c].dtype) for c in sorted(cols1)),
                next(iter(cols1.values())).shape[0],
                next(iter(cols2.values())).shape[0],
            )
            if cache_key not in self._jit_cache:

                def compute(c1: Dict[str, Any], v1: Any, c2: Dict[str, Any], v2: Any):
                    def shard_fn(a: Dict[str, Any], va: Any, b: Dict[str, Any], vb: Any):
                        out = {
                            n: jnp.concatenate([a[n], b[n]]) for n in a
                        }
                        out[vp] = jnp.concatenate([va, vb])
                        return out

                    return shard_map(
                        shard_fn,
                        mesh=mesh,
                        in_specs=(JP(ROW_AXIS),) * 4,
                        out_specs=JP(ROW_AXIS),
                    )(c1, v1, c2, v2)

                self._jit_cache[cache_key] = jax.jit(compute)
            out = self._jit_cache[cache_key](
                cols1,
                j1.device_valid_mask(),
                cols2,
                j2.device_valid_mask(),
            )
            valid = out.pop(vp)
            null_masks = {
                n[len(mp):]: out.pop(n) for n in mask_names
            }
            res: DataFrame = JaxDataFrame(
                mesh=mesh,
                _internal=dict(
                    device_cols=out,
                    host_tbl=None,
                    row_count=-1,
                    valid_mask=valid,
                    nan_cols=(
                        None
                        if j1._nan_cols is None or j2._nan_cols is None
                        else j1._nan_cols | j2._nan_cols
                    ),
                    encodings=encodings,
                    null_masks=null_masks,
                    schema=j1.schema,
                ),
            )
            return self.distinct(res) if distinct else res
        return self._back(
            self._host_engine.union(self._host(df1), self._host(df2), distinct=distinct)
        )

    def _false_mask_like(self, jdf: JaxDataFrame) -> Any:
        """An all-False device bool array row-aligned with the frame."""
        import jax
        import jax.numpy as jnp

        ck = ("falsemask", self._mesh)
        if ck not in self._jit_cache:
            self._jit_cache[ck] = jax.jit(
                lambda t: jnp.zeros(t.shape[0], dtype=bool),
                out_shardings=row_sharding(self._mesh),
            )
        return self._jit_cache[ck](next(iter(jdf.device_cols.values())))

    def _setop_device_ok(self, df: Any) -> bool:
        """Set-difference semantics treat NULL = NULL; the join kernels
        treat NULL keys as never-matching — so the device path requires
        provably NULL-free plain frames."""
        j = self.to_df(df)
        return (
            isinstance(j, JaxDataFrame)
            and j.host_table is None
            and not j.has_encoded
            and j._nan_cols is not None
            and len(j._nan_cols) == 0
            and len(j.device_cols) > 0
        )

    @traced_verb("engine.subtract")
    def subtract(self, df1, df2, distinct: bool = True) -> DataFrame:
        """``distinct=True`` lowers to a device ANTI join of the two
        distinct frames on ALL columns (the deduped right side satisfies
        the unique-key requirement)."""
        if distinct and self._setop_device_ok(df1) and self._setop_device_ok(df2):
            d1, d2 = self.distinct(df1), self.distinct(df2)
            res = self._join_device(
                d1, d2, "anti", on=list(self.to_df(df1).schema.names)
            )
            if res is not None:
                return res
        return self._back(
            self._host_engine.subtract(self._host(df1), self._host(df2), distinct=distinct)
        )

    @traced_verb("engine.intersect")
    def intersect(self, df1, df2, distinct: bool = True) -> DataFrame:
        """``distinct=True`` lowers to a device SEMI join of the two
        distinct frames on ALL columns."""
        if distinct and self._setop_device_ok(df1) and self._setop_device_ok(df2):
            d1, d2 = self.distinct(df1), self.distinct(df2)
            res = self._join_device(
                d1, d2, "semi", on=list(self.to_df(df1).schema.names)
            )
            if res is not None:
                return res
        return self._back(
            self._host_engine.intersect(self._host(df1), self._host(df2), distinct=distinct)
        )

    def _group_key_cols(self, jdf: JaxDataFrame, names: List[str]) -> Any:
        """(key_cols_for_kernel, mask_col_names) — nullable columns add
        their null mask as an extra key so NULL forms its own group distinct
        from the fill value. Maybe-NaN float keys canonicalize to (0, isnan)
        the same way: NaN != NaN would otherwise split every NULL key into
        its own group, diverging from the oracle's dropna=False grouping."""
        key_cols: Dict[str, Any] = {}
        mask_names: Dict[str, str] = {}

        def _mangled(c: str) -> str:
            mn = f"__null__{c}"
            while mn in jdf.schema:
                mn = "_" + mn
            return mn

        for c in names:
            arr = jdf.device_cols[c]
            if c in jdf.null_masks:
                key_cols[c] = arr
                mn = _mangled(c)
                key_cols[mn] = jdf.null_masks[c]
                mask_names[c] = mn
            elif np.issubdtype(np.dtype(arr.dtype), np.floating) and jdf.maybe_nan(c):
                import jax
                import jax.numpy as jnp

                ck = ("nankey", self._mesh)
                if ck not in self._jit_cache:
                    self._jit_cache[ck] = jax.jit(
                        lambda a: (
                            jnp.where(jnp.isnan(a), jnp.zeros_like(a), a),
                            jnp.isnan(a),
                        )
                    )
                canon, isnan = self._jit_cache[ck](arr)
                key_cols[c] = canon
                mn = _mangled(c)
                key_cols[mn] = isnan
                mask_names[c] = mn
            else:
                key_cols[c] = arr
        return key_cols, mask_names

    def _decode_partial_keys(
        self, jdf: JaxDataFrame, partials: pd.DataFrame, mask_names: Dict[str, str]
    ) -> pd.DataFrame:
        """Restore original key semantics on host partials: dictionary codes
        → values, epoch ints → timestamps, masked cells → NA."""
        res = partials
        for c, mn in mask_names.items():
            res[c] = res[c].mask(res[mn].astype(bool))
            res = res.drop(columns=[mn])
        for c, enc in jdf.encodings.items():
            if c not in res.columns:
                continue
            if enc["kind"] == "dict":
                codes = res[c].to_numpy()
                valid = codes >= 0
                decoded = enc["dictionary"].take(
                    pa.array(
                        np.where(valid, codes, 0).astype(np.int64), mask=~valid
                    )
                )
                res[c] = decoded.to_pandas()
            elif enc["kind"] == "datetime":
                ints = res[c]
                na = ints.isna()
                arr = pa.array(
                    ints.fillna(0).to_numpy().astype(np.int64),
                    mask=na.to_numpy() if na.any() else None,
                ).cast(enc["type"])
                res[c] = arr.to_pandas()
        return res

    @traced_verb("engine.distinct")
    def distinct(self, df: DataFrame) -> DataFrame:
        """Device distinct when every column is device-resident: the groupby
        kernel with a presence count — keys of the merged partials are the
        distinct rows. Dictionary codes / epoch ints / null masks group by
        their device identity and decode on the O(groups) host result.
        One-pass streams dedupe chunk-wise without materializing."""
        from .streaming import is_stream_frame, streaming_distinct

        if is_stream_frame(df):
            return streaming_distinct(self, df)
        from ..ops.segment import device_groupby_partials

        from ..constants import FUGUE_TPU_CONF_MAX_PARTIAL_ROWS
        from ..ops.segment import PartialsTooLarge

        jdf = self.to_df(df)
        if (
            isinstance(jdf, JaxDataFrame)
            and jdf.host_table is None
            and len(jdf.device_cols) > 0
            and len(jdf.device_cols) == len(jdf.schema)
        ):
            key_cols, mask_names = self._group_key_cols(jdf, jdf.schema.names)
            first = next(iter(key_cols))
            count_name = "__n__"
            while count_name in jdf.schema:  # never shadow a user column
                count_name = "_" + count_name
            try:
                partials = device_groupby_partials(
                    self._mesh,
                    key_cols,
                    [(count_name, "count", key_cols[first])],
                    jdf.device_valid_mask(),
                    max_partial_rows=self.conf.get(
                        FUGUE_TPU_CONF_MAX_PARTIAL_ROWS, 1 << 22
                    ),
                )
            except PartialsTooLarge:
                # near-unique rows: the O(groups) transfer stops paying off
                return self._back(self._host_engine.distinct(self._host(df)))
            res = partials.drop(columns=[count_name]).drop_duplicates(
                ignore_index=True
            )
            res = self._decode_partial_keys(jdf, res, mask_names)
            return self.to_df(PandasDataFrame(res[jdf.schema.names], jdf.schema))
        return self._back(self._host_engine.distinct(self._host(df)))

    @traced_verb("engine.dropna")
    def dropna(self, df, how="any", thresh=None, subset=None) -> DataFrame:
        """All-device frames: NULL = NaN float, null-masked cell, or
        negative dictionary code — drop by extending the validity mask,
        zero data movement."""
        jdf = self.to_df(df)
        if (
            isinstance(jdf, JaxDataFrame)
            and jdf.host_table is None
            and len(jdf.device_cols) == len(jdf.schema)
        ):
            import jax
            import jax.numpy as jnp

            cols = subset or jdf.schema.names
            dict_cols = frozenset(
                c for c, e in jdf.encodings.items() if e["kind"] == "dict"
            )
            key = (
                "dropna",
                tuple(cols),
                how,
                thresh,
                tuple(jdf.schema.names),
                dict_cols,
                frozenset(jdf.null_masks),
            )
            if key not in self._jit_cache:

                def compute(
                    dcols: Dict[str, Any], masks: Dict[str, Any], valid: Any
                ) -> Any:
                    notnull = []
                    for c in cols:
                        nn = jnp.ones_like(valid)
                        if jnp.issubdtype(dcols[c].dtype, jnp.floating):
                            nn = nn & ~jnp.isnan(dcols[c])
                        if c in masks:
                            nn = nn & ~masks[c]
                        if c in dict_cols:
                            nn = nn & (dcols[c] >= 0)
                        notnull.append(nn)
                    stacked = jnp.stack(notnull, axis=0)
                    if thresh is not None:
                        keep = stacked.sum(axis=0) >= thresh
                    elif how == "all":
                        keep = stacked.any(axis=0)
                    else:
                        keep = stacked.all(axis=0)
                    return valid & keep

                self._jit_cache[key] = jax.jit(compute)
            mask = self._jit_cache[key](
                dict(jdf.device_cols), dict(jdf.null_masks), jdf.device_valid_mask()
            )
            return JaxDataFrame(
                mesh=self._mesh,
                _internal=dict(
                    device_cols=dict(jdf.device_cols),
                    host_tbl=None,
                    row_count=-1,
                    valid_mask=mask,
                    nan_cols=jdf._nan_cols,
                    encodings=dict(jdf.encodings),
                    null_masks=dict(jdf.null_masks),
                    schema=jdf.schema,
                ),
            )
        return self._back(
            self._host_engine.dropna(self._host(df), how=how, thresh=thresh, subset=subset)
        )

    @traced_verb("engine.fillna")
    def fillna(self, df, value, subset=None) -> DataFrame:
        """All-device frames: fill NaN floats and null-masked numeric cells
        on device (filled masks clear). Fills targeting dictionary/datetime
        encoded columns go to the host engine."""
        jdf = self.to_df(df)
        if (
            isinstance(jdf, JaxDataFrame)
            and jdf.host_table is None
            and len(jdf.device_cols) == len(jdf.schema)
        ):
            import jax
            import jax.numpy as jnp

            # validate the value exactly like the host engine (no data moves)
            empty = ArrowDataFrame(None, jdf.schema)
            self._host_engine.fillna(empty, value, subset=subset)
            if isinstance(value, dict):
                fills = dict(value)
            else:
                fills = {c: value for c in (subset or jdf.schema.names)}
            if any(c in jdf.encodings for c in fills):
                return self._back(
                    self._host_engine.fillna(self._host(df), value, subset=subset)
                )
            masked_fills = frozenset(c for c in fills if c in jdf.null_masks)
            fill_sig = tuple(sorted((k, float(v)) for k, v in fills.items() if k in jdf.schema))
            key = ("fillna", fill_sig, tuple(jdf.schema.names), masked_fills)
            if key not in self._jit_cache:

                def compute(
                    dcols: Dict[str, Any], masks: Dict[str, Any]
                ) -> Dict[str, Any]:
                    out = dict(dcols)
                    for c, v in fills.items():
                        arr = dcols.get(c)
                        if arr is None:
                            continue
                        if c in masked_fills:
                            out[c] = jnp.where(
                                masks[c], jnp.asarray(v, arr.dtype), arr
                            )
                        elif jnp.issubdtype(arr.dtype, jnp.floating):
                            out[c] = jnp.where(jnp.isnan(arr), jnp.asarray(v, arr.dtype), arr)
                    return out

                self._jit_cache[key] = jax.jit(compute)
            new_cols = self._jit_cache[key](
                dict(jdf.device_cols), dict(jdf.null_masks)
            )
            new_masks = {
                c: m for c, m in jdf.null_masks.items() if c not in masked_fills
            }
            return JaxDataFrame(
                mesh=self._mesh,
                _internal=dict(
                    device_cols=new_cols,
                    host_tbl=None,
                    row_count=jdf._row_count,
                    valid_mask=jdf.valid_mask,
                    # filled columns become NaN-free — unless the fill value
                    # is itself NaN (a no-op fill must not fake the proof)
                    nan_cols=(
                        None
                        if jdf._nan_cols is None
                        else jdf._nan_cols
                        - {
                            c
                            for c, v in fills.items()
                            if not (isinstance(v, float) and v != v)
                        }
                    ),
                    encodings=dict(jdf.encodings),
                    null_masks=new_masks,
                    schema=jdf.schema,
                ),
            )
        return self._back(self._host_engine.fillna(self._host(df), value, subset=subset))

    @traced_verb("engine.sample")
    def sample(self, df, n=None, frac=None, replace=False, seed=None) -> DataFrame:
        """frac-sampling on device: a Bernoulli mask ANDed into validity —
        zero data movement (n-sampling and replacement go host-side)."""
        jdf = self.to_df(df)
        if (
            frac is not None
            and n is None
            and not replace
            and isinstance(jdf, JaxDataFrame)
            and jdf.host_table is None
            and len(jdf.device_cols) > 0
        ):
            import jax
            import jax.numpy as jnp

            key = ("sample", jdf.mesh)
            if key not in self._jit_cache:

                def compute(valid: Any, rngkey: Any, p: Any) -> Any:
                    u = jax.random.uniform(rngkey, valid.shape)
                    return valid & (u < p)

                self._jit_cache[key] = jax.jit(compute)
            if seed is None:
                import numpy as np_

                seed = int(np_.random.default_rng().integers(0, 2**31 - 1))
            rngkey = jax.random.PRNGKey(seed)
            mask = self._jit_cache[key](
                jdf.device_valid_mask(), rngkey, float(frac)
            )
            return JaxDataFrame(
                mesh=self._mesh,
                _internal=dict(
                    device_cols=dict(jdf.device_cols),
                    host_tbl=None,
                    row_count=-1,
                    valid_mask=mask,
                    nan_cols=jdf._nan_cols,
                    encodings=dict(jdf.encodings),
                    null_masks=dict(jdf.null_masks),
                    schema=jdf.schema,
                ),
            )
        return self._back(
            self._host_engine.sample(self._host(df), n=n, frac=frac, replace=replace, seed=seed)
        )

    @traced_verb("engine.take")
    def take(self, df, n, presort, na_position="last", partition_spec=None) -> DataFrame:
        """Global top-n by any number of device sort keys: per-shard
        lexicographic ``lax.sort`` takes each shard's first k rows, then an
        O(shards·n) host merge picks the global n.

        Exact in all cases the gate admits: each shard contributes its
        lexicographically-first min(k, valid) rows, so the merged pool is
        always a superset of the true global top-n — multi-key presorts,
        full-range int64 keys (no float64 scoring) and NaN tails included
        (XLA sorts NaN after all numbers, matching ``na_position="last"``;
        DESC negates floats / bit-inverts ints, both NaN/order preserving).
        """
        from ..collections.partition import parse_presort_exp
        from .streaming import is_stream_frame, streaming_take

        if is_stream_frame(df):
            # one-pass stream: running top-n buffers, O(n·keys + chunk)
            return streaming_take(
                self, df, n, presort, na_position, partition_spec
            )
        jdf = self.to_df(df)
        sorts = parse_presort_exp(presort) if presort else (
            partition_spec.presort if partition_spec is not None else {}
        )
        no_keys = partition_spec is None or len(partition_spec.partition_by) == 0

        def _sortable(c: str) -> bool:
            if c not in jdf.device_cols:
                return False
            enc = jdf.encodings.get(c)
            if enc is None:
                return True
            # sorted-dict codes and epoch ints order like their values
            return enc["kind"] == "datetime" or (
                enc["kind"] == "dict" and enc.get("sorted", False)
            )

        if (
            no_keys
            and len(sorts) > 0
            and na_position == "last"
            and isinstance(jdf, JaxDataFrame)
            and jdf.host_table is None
            and all(_sortable(c) for c in sorts)
            and n <= 4096
        ):
            import jax
            import jax.numpy as jnp
            import numpy as np_
            from jax.sharding import PartitionSpec as JP

            sort_items = list(sorts.items())
            dict_sort_cols = frozenset(
                c
                for c in sorts
                if jdf.encodings.get(c, {}).get("kind") == "dict"
            )
            masked = frozenset(jdf.null_masks)
            k = min(
                n,
                next(iter(jdf.device_cols.values())).shape[0]
                // num_row_shards(self._mesh),
            )
            if k > 0:
                mesh = jdf.mesh  # bind locally: the closure must not pin jdf
                mp = _safe_prefix("__mask__", jdf.schema.names)
                tvp = _safe_prefix("__take_valid__", jdf.schema.names)
                cache_key = (
                    "take",
                    tuple(sort_items),
                    k,
                    mesh,
                    tuple(jdf.schema.names),
                    dict_sort_cols,
                    masked,
                )
                if cache_key not in self._jit_cache:

                    def compute(
                        cols: Dict[str, Any], masks: Dict[str, Any], valid: Any
                    ):
                        def shard_fn(
                            c: Dict[str, Any], m: Dict[str, Any], v: Any
                        ):
                            # per-key (isnull, transformed-key) pairs: NULLs
                            # sort last within ties of earlier keys, exactly
                            # pandas na_position="last", for any asc/desc mix
                            ops: List[Any] = [jnp.logical_not(v)]  # valid first
                            for name, asc in sort_items:
                                key = c[name]
                                isnull = jnp.zeros(key.shape, dtype=bool)
                                if name in m:
                                    isnull = isnull | m[name]
                                if jnp.issubdtype(key.dtype, jnp.floating):
                                    isnull = isnull | jnp.isnan(key)
                                if name in dict_sort_cols:
                                    isnull = isnull | (key < 0)
                                if not asc:
                                    if jnp.issubdtype(key.dtype, jnp.floating):
                                        key = jnp.where(isnull, key, -key)
                                    elif key.dtype == jnp.bool_:
                                        key = jnp.logical_not(key)
                                    else:
                                        key = ~key  # monotone reversal
                                ops.extend([isnull, key])
                            iota = jax.lax.iota(jnp.int32, v.shape[0])
                            sorted_ops = jax.lax.sort(
                                tuple(ops) + (iota,), num_keys=len(ops)
                            )
                            perm = sorted_ops[-1][:k]
                            out = {name: arr[perm] for name, arr in c.items()}
                            for name, arr in m.items():
                                out[f"{mp}{name}"] = arr[perm]
                            out[tvp] = v[perm]
                            return out

                        return shard_map(
                            shard_fn,
                            mesh=mesh,
                            in_specs=(JP(ROW_AXIS), JP(ROW_AXIS), JP(ROW_AXIS)),
                            out_specs=JP(ROW_AXIS),
                        )(cols, masks, valid)

                    self._jit_cache[cache_key] = jax.jit(compute)
                outs = self._jit_cache[cache_key](
                    dict(jdf.device_cols),
                    dict(jdf.null_masks),
                    jdf.device_valid_mask(),
                )
                host = {
                    name: np_.asarray(jax.device_get(arr))
                    for name, arr in outs.items()
                }
                valid = host.pop(tvp)
                mask_cols = {
                    name[len(mp):]: host.pop(name)[valid]
                    for name in list(host)
                    if name.startswith(mp)
                }
                pdf = pd.DataFrame({k2: v2[valid] for k2, v2 in host.items()})
                for c, m in mask_cols.items():
                    pdf[c] = pdf[c].mask(m)
                # decode codes/epochs so host sorting and output use VALUES
                pdf = self._decode_partial_keys(jdf, pdf, {})
                pdf = pdf.sort_values(
                    [c for c, _ in sort_items],
                    ascending=[a for _, a in sort_items],
                    na_position="last",
                ).head(n)
                return self.to_df(
                    PandasDataFrame(
                        pdf[jdf.schema.names].reset_index(drop=True), jdf.schema
                    )
                )
        return self._back(
            self._host_engine.take(
                self._host(df), n, presort, na_position=na_position, partition_spec=partition_spec
            )
        )

    def load_df(self, path, format_hint=None, columns=None, **kwargs) -> DataFrame:
        return self.to_df(
            self._host_engine.load_df(path, format_hint=format_hint, columns=columns, **kwargs)
        )

    def save_df(
        self, df, path, format_hint=None, mode="overwrite",
        partition_spec=None, force_single=False, **kwargs,
    ) -> DataFrame:
        self._host_engine.save_df(
            self._host(df), path, format_hint=format_hint, mode=mode,
            partition_spec=partition_spec, force_single=force_single, **kwargs,
        )
        return df

    def convert_yield_dataframe(self, df: DataFrame, as_local: bool) -> DataFrame:
        return df.as_local() if as_local else df

    # ---- compiled derived ops ---------------------------------------------
    @traced_verb("engine.select")
    def select(
        self,
        df: DataFrame,
        cols: SelectColumns,
        where: Optional[ColumnExpr] = None,
        having: Optional[ColumnExpr] = None,
    ) -> DataFrame:
        from ..column.jax_eval import device_predicate_plan

        jdf = self.to_df(df)
        sc = cols.replace_wildcard(jdf.schema)
        # WHERE lowers to a device mask filter when possible
        if (
            where is not None
            and len(jdf.device_cols) > 0
            and jdf.host_table is None
        ):
            where_plan = device_predicate_plan(
                where, jdf.device_cols, jdf.encodings
            )
            if where_plan is not None:
                jdf = self.filter(jdf, where, _plan=where_plan)  # type: ignore
                where = None
        # grouped aggregation lowers to the device groupby
        if where is None and sc.has_agg and not sc.is_distinct:
            from ..collections.partition import PartitionSpec as _PSpec
            from ..column.expressions import _NamedColumnExpr as _Named
            from ..column.functions import is_agg as _is_agg

            keys = [c for c in sc.all_cols if not _is_agg(c)]
            aggs = [c for c in sc.all_cols if _is_agg(c)]
            if (
                len(keys) > 0
                and all(
                    isinstance(k, _Named) and k.as_type is None and k.as_name == ""
                    for k in keys
                )
            ):
                spec = _PSpec(by=[k.name for k in keys])
                if _plan_device_agg(jdf, spec.partition_by, aggs) is not None:
                    res = self.aggregate(jdf, spec, aggs)
                    if having is not None:
                        # the aggregate result is O(groups): host filter;
                        # aggregate subexpressions read their computed
                        # output columns (same contract as the oracle)
                        from ..column.eval import rewrite_having_aggs

                        res = self._back(
                            self._host_engine.filter(
                                self._host(res),
                                rewrite_having_aggs(having, aggs),
                            )
                        )
                    # restore declared projection order
                    order = [c.output_name for c in sc.all_cols]
                    if res.schema.names != order:
                        res = res[order]
                    return res
        plain_cols = {
            k: v
            for k, v in jdf.device_cols.items()
            if k not in jdf.encodings and k not in jdf.null_masks
        }
        if (
            where is None
            and having is None
            and not sc.has_agg
            and not sc.is_distinct
            and len(jdf.device_cols) > 0
            and all(
                _is_passthrough(c, jdf.device_cols)
                or can_evaluate_on_device(c, plain_cols)
                for c in sc.all_cols
            )
        ):
            return self._device_project(jdf, sc)
        return self._back(
            self._host_engine.select(
                self._host(jdf), cols, where=where, having=having
            )
        )

    def _device_project(self, jdf: JaxDataFrame, sc: SelectColumns) -> DataFrame:
        import jax

        schema = sc.infer_schema(jdf.schema)
        exprs = sc.all_cols
        # pass-through named columns (any encoding) copy arrays + metadata;
        # only computed expressions go through the compiled projection
        passthrough = [c for c in exprs if _is_passthrough(c, jdf.device_cols)]
        computed = [c for c in exprs if not _is_passthrough(c, jdf.device_cols)]
        out_encodings: Dict[str, Any] = {}
        out_masks: Dict[str, Any] = {}
        out_cols: Dict[str, Any] = {}
        for c in passthrough:
            out_cols[c.output_name] = jdf.device_cols[c.name]
            if c.name in jdf.encodings:
                out_encodings[c.output_name] = jdf.encodings[c.name]
            if c.name in jdf.null_masks:
                out_masks[c.output_name] = jdf.null_masks[c.name]

        if len(computed) > 0:

            def compute(cols: Dict[str, Any]) -> Dict[str, Any]:
                import jax.numpy as jnp

                out = {}
                for c in computed:
                    v = evaluate_jnp(cols, c)
                    if not hasattr(v, "shape") or getattr(v, "ndim", 0) == 0:
                        n = next(iter(cols.values())).shape[0]
                        v = jnp.full((n,), v)
                    out[c.output_name] = v
                return out

            cache_key = ("project", tuple(c.__uuid__() for c in computed), jdf.mesh)
            if cache_key not in self._jit_cache:
                self._jit_cache[cache_key] = jax.jit(compute)
            out_cols.update(self._jit_cache[cache_key](dict(jdf.device_cols)))
        out_cols = {c.output_name: out_cols[c.output_name] for c in exprs}
        if schema is None:
            fields = []
            for c in exprs:
                t = c.infer_type(jdf.schema)
                fields.append(
                    pa.field(c.output_name, t if t is not None else pa.from_numpy_dtype(np.asarray(out_cols[c.output_name]).dtype))
                )
            schema = Schema(fields)
        # pass-through named columns keep their NaN-free proof; computed
        # expressions are conservatively maybe-NaN (left out of the set is
        # only safe when the set is known, so start from the source's)
        from ..column.expressions import _NamedColumnExpr

        nan_cols: Optional[set] = None
        if jdf._nan_cols is not None:
            nan_cols = set()
            for c in exprs:
                if isinstance(c, _NamedColumnExpr) and c.as_type is None:
                    if c.name in jdf._nan_cols:
                        nan_cols.add(c.output_name)
                else:
                    import numpy as _np

                    arr = out_cols[c.output_name]
                    if _np.issubdtype(_np.dtype(arr.dtype), _np.floating):
                        nan_cols.add(c.output_name)
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=out_cols,
                host_tbl=None,
                row_count=jdf._row_count,
                valid_mask=jdf.valid_mask,
                nan_cols=nan_cols,
                encodings=out_encodings,
                null_masks=out_masks,
                schema=schema,
            ),
        )

    def _virtual_agg_array(self, jdf: JaxDataFrame, tag: str, src: str) -> Any:
        """Materialize a derived aggregation input for a null-masked 64-bit
        int column — exactness-preserving views the float64 NaN view can't
        give (SURVEY §7 hard parts; STATUS r2 known gap):

        - ``hi``/``lo``: NULL→0 value split into 32-bit halves, so
          SUM = Σhi·2³² + Σlo stays exact at any magnitude;
        - ``minfill``/``maxfill``: NULLs become the dtype extreme (the
          identity for min/max), nullability recovered from the mask count.
        """
        import jax
        import jax.numpy as jnp

        cache_key = ("vagg", tag, self._mesh)
        if tag == "ones":
            # COUNT(*)'s input: a ones column shaped like any device column
            # (validity masking happens inside the kernel)
            if cache_key not in self._jit_cache:
                self._jit_cache[cache_key] = jax.jit(
                    lambda a: jnp.ones(a.shape, jnp.int64)
                )
            probe = next(iter(jdf.device_cols.values()))
            return self._jit_cache[cache_key](probe)
        if cache_key not in self._jit_cache:

            def build(a: Any, m: Any, _tag: str = tag):
                if _tag == "notnull":
                    return jnp.logical_not(m).astype(jnp.int64)
                filled = jnp.where(m, jnp.zeros((), a.dtype), a)
                if _tag == "hi":
                    return filled >> 32
                if _tag == "lo":
                    return filled & jnp.asarray(0xFFFFFFFF, dtype=a.dtype)
                ii = jnp.iinfo(a.dtype)
                fill = ii.max if _tag == "minfill" else ii.min
                return jnp.where(m, jnp.asarray(fill, dtype=a.dtype), a)

            self._jit_cache[cache_key] = jax.jit(build)
        return self._jit_cache[cache_key](
            jdf.device_cols[src], jdf.null_masks[src]
        )

    def _try_dense_device_aggregate(
        self,
        jdf: JaxDataFrame,
        keys: List[str],
        plan: dict,
        agg_entries: List[Any],
        range_hint: Optional[Tuple[int, int]],
    ) -> Optional[DataFrame]:
        """Finish a dense-plan aggregate ON DEVICE — no host roundtrip.

        The dense kernel's outputs are already cross-shard merged, so for
        plain frames (single int key, no masks/dictionaries/virtual
        columns) the final table is computable in one more jitted step:
        ``key = kmin + arange``, ``valid = present > 0``, avg = sum/count,
        dtype casts to the declared schema. The result frame keeps its
        columns device-resident with an explicit valid mask and a LAZY row
        count — on a remote-chip link this removes the only per-call
        device→host transfer (the reference instead materializes backend
        results per op, e.g. pandas groupby output frames,
        /root/reference/fugue/execution/native_execution_engine.py:172).
        Returns None when ineligible (caller runs the fetch+host-merge
        plan)."""
        from ..ops.segment import _DENSE_MAX_RANGE, dense_buckets

        if range_hint is None:
            return None
        if plan["dict_srcs"] or plan["masked_srcs"]:
            return None
        if any(tag != "ones" for tag, _ in plan["virtual"].values()):
            # hi/lo/fill virtuals need the host-merge finish; the COUNT(*)
            # ones column is a plain int input the fused kernel handles
            return None
        if any(p.get("kind") not in ("pass", "avg") for p in plan["post"]):
            return None
        kmin, kmax = range_hint
        rng = kmax - kmin + 1
        if not (0 < rng <= _DENSE_MAX_RANGE):
            return None

        # predict kernel output dtypes; bail on any cast a NULL could break
        predicted: Dict[str, np.dtype] = {}
        for name, agg, arr, _ in agg_entries:
            predicted[name] = (
                np.dtype(np.int64)
                if agg == "count"
                else np.dtype(arr.dtype)
            )
        key_dt = _np_numeric_dtype(self._field_type(jdf.schema, keys[0]))
        if key_dt is None:
            return None
        spec_rows = _dense_finish_spec(plan, predicted)
        if spec_rows is None:
            return None
        buckets = dense_buckets(rng)
        outs = self._run_dense_fused(
            jdf, keys[0], agg_entries, kmin, buckets, tuple(spec_rows), key_dt.str
        )
        device_cols = {keys[0]: outs[0]}
        for (_, name, _, _), arr in zip(spec_rows, outs[2:]):
            device_cols[name] = arr
        return JaxDataFrame(
            mesh=self._mesh,
            _internal=dict(
                device_cols=device_cols,
                host_tbl=None,
                row_count=-1,
                valid_mask=outs[1],
                schema=plan["schema"],
            ),
        )

    @staticmethod
    def _field_type(schema: Schema, name: str) -> pa.DataType:
        return schema[name].type

    def _run_dense_fused(
        self,
        jdf: JaxDataFrame,
        key: str,
        agg_entries: List[Any],
        kmin: int,
        buckets: int,
        spec_rows: Tuple[Any, ...],
        key_dtype: str,
    ):
        """Dense kernel + finish traced into ONE program — one dispatch per
        aggregate call instead of three (mask/kernel/finish); per-program
        submission latency is the dominant cost on a remote-chip link."""
        import jax

        from ..ops.segment import dense_kernel_parts

        kernel, arrays, agg_sig = dense_kernel_parts(
            self._mesh, agg_entries, buckets
        )
        arr_names = tuple(s[0] for s in agg_sig)
        from ..ops.segment import _DENSE_SUM_BACKEND

        cache_key = (
            "dense_fused",
            self._mesh,
            buckets,
            agg_sig,
            spec_rows,
            key_dtype,
            _DENSE_SUM_BACKEND[0],
        )
        if cache_key not in self._jit_cache:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            fin = self._make_dense_finish(
                buckets, arr_names, spec_rows, key_dtype
            )

            def fused(karr: Any, kmin_s: Any, vm: Any, *arrs: Any):
                outs = kernel(karr, kmin_s, *arrs, vm)
                return fin(kmin_s, outs[0], *outs[1:])

            self._jit_cache[cache_key] = jax.jit(
                fused,
                out_shardings=NamedSharding(self._mesh, P(ROW_AXIS)),
            )
        return self._jit_cache[cache_key](
            jdf.device_cols[key],
            np.int64(kmin),
            jdf.device_valid_mask(),
            *arrays,
        )

    def _make_dense_finish(
        self,
        buckets: int,
        arr_names: Tuple[str, ...],
        spec_rows: Tuple[Tuple[str, str, Tuple[str, ...], str], ...],
        key_dtype: str,
    ):
        """(key, valid, *outs) builder over replicated dense-kernel outputs,
        padded to the row-shard multiple with padding marked invalid. A
        plain closure — it is traced inside the fused jit, whose
        out_shardings reshard the results onto the row axis."""
        import jax.numpy as jnp

        from ..parallel.mesh import num_row_shards, pad_rows

        shards = num_row_shards(self._mesh)
        padded = pad_rows(max(buckets, shards), shards)

        def fin(kmin: Any, present: Any, *aggs: Any):
            named = dict(zip(arr_names, aggs))
            key = (jnp.arange(buckets, dtype=jnp.int64) + kmin).astype(
                jnp.dtype(key_dtype)
            )
            valid = present > 0
            outs = []
            for kind, _, ins, tgt in spec_rows:
                if kind == "avg":
                    s = named[ins[0]].astype(jnp.float64)
                    c = named[ins[1]].astype(jnp.float64)
                    a = s / jnp.where(c == 0, jnp.nan, c)
                else:
                    a = named[ins[0]]
                outs.append(a.astype(jnp.dtype(tgt)))

            def _pad(a: Any) -> Any:
                return jnp.pad(a, (0, padded - buckets))

            return tuple(_pad(x) for x in (key, valid, *outs))

        return fin

    @traced_verb("engine.aggregate")
    def aggregate(
        self,
        df: DataFrame,
        partition_spec: Optional[PartitionSpec],
        agg_cols: List[ColumnExpr],
    ) -> DataFrame:
        """Two-phase device groupby when keys+values are device-resident."""
        from ..column.expressions import _FuncExpr, _NamedColumnExpr
        from ..ops.segment import device_groupby_partials, merge_partials
        from .streaming import is_stream_frame, streaming_dense_aggregate

        if is_stream_frame(df):
            # one-pass stream: chunked ingestion + device-resident
            # accumulators (out-of-core); ineligible plans fall through to
            # materialization below
            res = streaming_dense_aggregate(self, df, partition_spec, agg_cols)
            if res is not None:
                return res
            self.log.warning(
                "streaming aggregate ineligible for this plan; "
                "materializing the stream"
            )
        jdf = self.to_df(df)
        keys = list(partition_spec.partition_by) if partition_spec is not None else []
        plan = _plan_device_agg(jdf, keys, agg_cols)
        if plan is None or len(keys) == 0:
            return self._back(
                self._host_engine.aggregate(self._host(df), partition_spec, agg_cols)
            )
        # dict codes / epoch ints group by device identity; nullable keys add
        # their mask as an extra key so NULL is its own group
        key_cols, mask_names = self._group_key_cols(jdf, keys)
        value_arrs = {}
        for src in {s for _, _, s in plan["aggs"]}:
            if src in plan["virtual"]:
                value_arrs[src] = self._virtual_agg_array(
                    jdf, *plan["virtual"][src]
                )
                continue
            arr = jdf.device_cols[src]
            if src in plan["dict_srcs"]:
                # sorted-dict codes → NaN-null float view (−1 code = NULL)
                cache_key = ("codeview", jdf.mesh)
                if cache_key not in self._jit_cache:
                    import jax
                    import jax.numpy as jnp

                    self._jit_cache[cache_key] = jax.jit(
                        lambda a: jnp.where(
                            a < 0, jnp.nan, a.astype(jnp.float64)
                        )
                    )
                arr = self._jit_cache[cache_key](arr)
            elif src in plan["masked_srcs"]:
                # nullable int/bool value → float64 view with NaN as NULL
                # (exact: 64-bit ints with nulls were rejected in the plan)
                cache_key = ("nullview", jdf.mesh)
                if cache_key not in self._jit_cache:
                    import jax
                    import jax.numpy as jnp

                    self._jit_cache[cache_key] = jax.jit(
                        lambda a, m: jnp.where(
                            m, jnp.nan, a.astype(jnp.float64)
                        )
                    )
                arr = self._jit_cache[cache_key](arr, jdf.null_masks[src])
            value_arrs[src] = arr
        # single plain-int key: reuse the frame's cached range probe so
        # repeated aggregates don't re-pay the device→host roundtrip
        range_hint = None
        if (
            len(keys) == 1
            and len(mask_names) == 0
            and key_cols.get(keys[0]) is jdf.device_cols.get(keys[0])
            and np.issubdtype(
                np.dtype(jdf.device_cols[keys[0]].dtype), np.integer
            )
        ):
            range_hint = jdf.key_range(keys[0])
        agg_entries = [
            (
                name,
                agg,
                value_arrs[src],
                (
                    # virtual arrays (hi/lo/notnull/min-max fills) are
                    # pre-filled plain ints — never NaN-aware
                    False
                    if src in plan["virtual"]
                    else (
                        jdf.maybe_nan(src)
                        or src in plan["masked_srcs"]
                        or src in plan["dict_srcs"]
                    )
                ),
            )
            for name, agg, src in plan["aggs"]
        ]
        res = self._try_dense_device_aggregate(
            jdf, keys, plan, agg_entries, range_hint
        )
        if res is not None:
            return res
        partials = device_groupby_partials(
            self._mesh,
            key_cols,
            agg_entries,
            jdf.device_valid_mask(),
            range_hint=range_hint,
        )
        merged = merge_partials(
            partials,
            keys + list(mask_names.values()),
            [(n, a) for n, a, _ in plan["aggs"]],
        )
        merged = self._decode_partial_keys(jdf, merged, mask_names)
        # finalize: avg = sum/count; restore declared output order and names
        out = pd.DataFrame()
        for k in keys:
            out[k] = merged[k]
        for spec in plan["post"]:
            out[spec["name"]] = spec["fn"](merged)
        out_schema = plan["schema"]
        return self.to_df(PandasDataFrame(out, out_schema))


def _sorted_union_dictionary(pieces: "List[pa.Array]") -> pa.Array:
    """Distinct sorted union of dictionary arrays — THE canonical way a
    union dictionary is built everywhere (device union, multi-host comap
    reassembly), so code order == lexicographic order stays true."""
    u = pa.concat_arrays(pieces).unique().drop_null()
    order = pa.compute.sort_indices(u)
    return u.take(order)


def _dict_mapping(local_dict: pa.Array, union_dict: pa.Array) -> np.ndarray:
    """Index table from local dictionary positions to union positions.

    Apply as ``code >= 0 ? table[code] : -1`` (−1 is the NULL code). An
    empty local dictionary yields a single ``-1`` placeholder so device
    gathers stay in-bounds."""
    mapped = np.asarray(
        pa.compute.index_in(local_dict, value_set=union_dict).to_numpy(
            zero_copy_only=False
        )
    )
    if mapped.size == 0:
        mapped = np.asarray([-1])
    return mapped.astype(np.int32)


def _allgather_dictionaries(
    dicts: "Dict[str, pa.Array]",
) -> "Dict[str, pa.Array]":
    """Union string dictionaries across every process of the multi-host
    runtime, deterministically, in ONE exchange for all columns.

    All local dictionaries pack into a single tagged arrow table,
    serialize to an IPC buffer, and allgather exactly twice (lengths, then
    buffers padded to the global max so shapes agree) regardless of how
    many string columns the frame has. Every process deserializes all
    buffers and computes the IDENTICAL sorted distinct union per column —
    which is what makes the union dictionaries safe to store in frame
    metadata (divergent metadata would desynchronize later jitted
    programs into collective deadlock).
    """
    from jax.experimental import multihost_utils

    names = sorted(dicts)
    tags = np.concatenate(
        [np.full(len(dicts[n]), i, dtype=np.int32) for i, n in enumerate(names)]
    ) if len(names) > 0 else np.zeros(0, dtype=np.int32)
    vals = (
        pa.concat_arrays([dicts[n].cast(pa.large_string()) for n in names])
        if len(names) > 0
        else pa.array([], type=pa.large_string())
    )
    t = pa.table({"tag": pa.array(tags, pa.int32()), "val": vals})
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    buf = np.frombuffer(sink.getvalue(), dtype=np.uint8)
    lens = np.asarray(
        multihost_utils.process_allgather(np.asarray([len(buf)]))
    ).reshape(-1)
    mx = int(lens.max())
    padded = np.zeros(mx, dtype=np.uint8)
    padded[: len(buf)] = buf
    gathered = np.asarray(
        multihost_utils.process_allgather(padded)
    ).reshape(len(lens), mx)
    tag_pieces: List[Any] = []
    val_pieces: List[pa.Array] = []
    for i in range(len(lens)):
        rd = pa.ipc.open_stream(
            pa.py_buffer(gathered[i, : int(lens[i])].tobytes())
        ).read_all()
        tag_pieces.append(
            np.asarray(rd["tag"].to_numpy(zero_copy_only=False))
        )
        val_pieces.append(rd["val"].combine_chunks())
    all_tags = np.concatenate(tag_pieces) if tag_pieces else np.zeros(0, np.int32)
    all_vals = (
        pa.concat_arrays([p.cast(pa.large_string()) for p in val_pieces])
        if val_pieces
        else pa.array([], type=pa.large_string())
    )
    out: Dict[str, pa.Array] = {}
    for i, n in enumerate(names):
        sel = all_vals.take(pa.array(np.nonzero(all_tags == i)[0]))
        out[n] = _sorted_union_dictionary([sel])
    return out


def _null_safe_key(kv: Any) -> tuple:
    """Group-key tuple with every null (None/NaN/NaT) normalized to None.

    NaN group keys break cross-frame alignment: since Python 3.10
    ``hash(nan)`` is identity-based, so each frame's own NaN object forms
    its OWN dict key and the two sides' NULL groups never pair up in
    comap (observed as a full_outer zip splitting the NULL group)."""
    kt = kv if isinstance(kv, tuple) else (kv,)
    out = []
    for v in kt:
        try:
            isna = pd.isna(v)
        except Exception:
            isna = False
        out.append(None if isna is True else v)
    return tuple(out)


def _np_numeric_dtype(tp: pa.DataType) -> Optional[np.dtype]:
    if pa.types.is_integer(tp) or pa.types.is_floating(tp):
        return np.dtype(tp.to_pandas_dtype())
    return None


def _dense_finish_spec(
    plan: dict, predicted: Dict[str, np.dtype]
) -> Optional[Tuple[Tuple[str, str, Tuple[str, ...], str], ...]]:
    """(kind, name, input table names, target dtype) rows driving the
    on-device dense finish, or None when any declared-schema cast could
    corrupt a NULL. ``predicted`` maps each kernel output name to its
    actual table dtype. Factored out of the device-resident aggregate so
    the lowered-segment program validates casts identically."""
    spec_rows: List[Tuple[str, str, Tuple[str, ...], str]] = []
    for p, field_name in zip(plan["post"], plan["schema"].names[1:]):
        tgt = _np_numeric_dtype(plan["schema"][field_name].type)
        if tgt is None:
            return None
        if p["kind"] == "avg":
            ins: Tuple[str, ...] = (f"{p['name']}__sum", f"{p['name']}__cnt")
            src_dt = np.dtype(np.float64)
        else:
            ins = (p["name"],)
            src_dt = predicted[p["name"]]
        if src_dt.kind == "f" and tgt.kind != "f":
            return None  # NaN (NULL) would not survive the cast
        if src_dt.kind not in ("i", "u", "f") or tgt.kind not in ("i", "u", "f"):
            return None
        spec_rows.append((p["kind"], p["name"], ins, tgt.str))
    return tuple(spec_rows)


def _is_passthrough(c: ColumnExpr, device_cols: Any) -> bool:
    """A bare (possibly renamed) named column over a device column — copies
    arrays and metadata without evaluation, so any encoding is fine."""
    from ..column.expressions import _NamedColumnExpr

    return (
        isinstance(c, _NamedColumnExpr)
        and not c.wildcard
        and c.as_type is None
        and c.name in device_cols
    )


def _plan_device_agg(
    jdf: JaxDataFrame, keys: List[str], agg_cols: List[ColumnExpr]
) -> Optional[dict]:
    """Build the device-aggregation plan or None if not device-compatible."""
    from ..column.expressions import _FuncExpr, _NamedColumnExpr
    from ..column import functions as ff

    if len(keys) == 0 or not all(k in jdf.device_cols for k in keys):
        return None
    aggs: List[Any] = []
    post: List[dict] = []
    virtual: Dict[str, Any] = {}  # vname -> (tag, real src)
    masked_srcs: set = set()
    dict_srcs: set = set()
    fields: List[pa.Field] = [jdf.schema[k] for k in keys]
    from ..column.expressions import _LitColumnExpr

    for c in agg_cols:
        if not isinstance(c, _FuncExpr) or not c.is_agg or c.is_distinct:
            return None
        if len(c.args) != 1:
            return None
        if c.func.upper() == "COUNT" and (
            (
                isinstance(c.args[0], _LitColumnExpr)
                and c.args[0].value is not None  # COUNT(NULL) is 0, not *
            )
            or (
                isinstance(c.args[0], _NamedColumnExpr)
                and c.args[0].name == "*"
            )
        ):
            # COUNT(*) / COUNT(1): every row in the group counts, NULLs
            # included — a ones column summed under the validity mask
            name = c.output_name
            if name == "":
                return None
            virtual["__ones__"] = ("ones", None)
            aggs.append((name, "sum", "__ones__"))
            post.append(
                {"name": name, "kind": "pass", "fn": (lambda m, _n=name: m[_n])}
            )
            tp = c.infer_type(jdf.schema)
            fields.append(pa.field(name, tp if tp is not None else pa.int64()))
            continue
        if not isinstance(c.args[0], _NamedColumnExpr):
            return None
        src = c.args[0].name
        func = c.func.upper()
        if src not in jdf.device_cols:
            return None
        enc = jdf.encodings.get(src)
        if enc is not None:
            # sorted-dictionary strings: code order == value order, so
            # MIN/MAX/COUNT reduce over codes (as NaN-null float views) and
            # the min/max code decodes back to its string
            if not (
                enc["kind"] == "dict"
                and enc.get("sorted")
                and func in ("MIN", "MAX", "COUNT")
            ):
                return None
            dict_srcs.add(src)
        big_int_masked = False
        if src in jdf.null_masks:
            import numpy as np_

            dt = np_.dtype(jdf.device_cols[src].dtype)
            if dt.kind == "u" and dt.itemsize >= 8:
                # uint64 > 2^63 has no faithful pandas/post-processing
                # representation here — host engine computes it exactly
                return None
            if dt.kind == "i" and dt.itemsize >= 8:
                # int64 with NULLs: the float64 NaN view loses exactness
                # past 2^53 — SUM/AVG split into hi/lo 32-bit halves
                # (exact), MIN/MAX fill NULLs with dtype extremes, counts
                # come from the null mask
                big_int_masked = True
            else:
                masked_srcs.add(src)
        name = c.output_name
        if name == "":
            return None
        tp = c.infer_type(jdf.schema)
        if big_int_masked:
            if func not in ("SUM", "AVG", "MIN", "MAX", "COUNT"):
                return None
            nn = f"{name}__nn"
            if func in ("SUM", "AVG"):
                virtual[f"{src}__hi__"] = ("hi", src)
                virtual[f"{src}__lo__"] = ("lo", src)
                aggs.append((f"{name}__hi", "sum", f"{src}__hi__"))
                aggs.append((f"{name}__lo", "sum", f"{src}__lo__"))
                virtual[f"{src}__nn__"] = ("notnull", src)
                aggs.append((nn, "sum", f"{src}__nn__"))
                if func == "SUM":
                    post.append(
                        {
                            "name": name,
                            # exact int64 reassembly; SUM over an all-NULL
                            # group is NULL (SQL; the host's float64 NaN
                            # coerces to the same)
                            "fn": (
                                lambda m, _n=name: (
                                    m[f"{_n}__hi"].astype("int64") * (1 << 32)
                                    + m[f"{_n}__lo"].astype("int64")
                                )
                                .astype("Int64")
                                .where(m[f"{_n}__nn"] > 0)
                            ),
                        }
                    )
                else:  # AVG
                    post.append(
                        {
                            "name": name,
                            "fn": (
                                lambda m, _n=name: (
                                    m[f"{_n}__hi"].astype("float64") * (1 << 32)
                                    + m[f"{_n}__lo"].astype("float64")
                                )
                                / m[f"{_n}__nn"].where(m[f"{_n}__nn"] > 0)
                            ),
                        }
                    )
            elif func in ("MIN", "MAX"):
                tag = "minfill" if func == "MIN" else "maxfill"
                virtual[f"{src}__{tag}__"] = (tag, src)
                aggs.append((name, func.lower(), f"{src}__{tag}__"))
                virtual[f"{src}__nn__"] = ("notnull", src)
                aggs.append((nn, "sum", f"{src}__nn__"))
                post.append(
                    {
                        "name": name,
                        # Int64 extension keeps <NA> exact (a float NaN
                        # detour would corrupt values past 2^53)
                        "fn": (
                            lambda m, _n=name: m[_n]
                            .astype("Int64")
                            .where(m[f"{_n}__nn"] > 0)
                        ),
                    }
                )
            else:  # COUNT
                virtual[f"{src}__nn__"] = ("notnull", src)
                aggs.append((name, "sum", f"{src}__nn__"))
                post.append({"name": name, "fn": (lambda m, _n=name: m[_n])})
            fields.append(pa.field(name, tp if tp is not None else pa.float64()))
            continue
        if src in dict_srcs and func in ("MIN", "MAX"):
            dictionary = enc["dictionary"]

            def _decode(m: Any, _n: str = name, _d: Any = dictionary) -> Any:
                codes = m[_n]
                na = codes.isna()
                arr = pa.array(
                    codes.fillna(0).to_numpy().astype(np.int64),
                    mask=na.to_numpy() if na.any() else None,
                )
                return _d.take(arr).to_pandas()

            aggs.append((name, func.lower(), src))
            post.append({"name": name, "fn": _decode})
        elif func in ("SUM", "MIN", "MAX"):
            aggs.append((name, func.lower(), src))
            post.append(
                {"name": name, "kind": "pass", "fn": (lambda m, _n=name: m[_n])}
            )
        elif func == "COUNT":
            aggs.append((name, "count", src))
            post.append(
                {"name": name, "kind": "pass", "fn": (lambda m, _n=name: m[_n])}
            )
        elif func == "AVG":
            aggs.append((f"{name}__sum", "sum", src))
            aggs.append((f"{name}__cnt", "count", src))
            post.append(
                {
                    "name": name,
                    "kind": "avg",
                    "fn": (lambda m, _n=name: m[f"{_n}__sum"] / m[f"{_n}__cnt"]),
                }
            )
        else:
            return None
        fields.append(pa.field(name, tp if tp is not None else pa.float64()))
    return {
        "aggs": aggs,
        "post": post,
        "schema": Schema(fields),
        "masked_srcs": masked_srcs,
        "dict_srcs": dict_srcs,
        "virtual": virtual,
    }


def _sniff_jax_func(map_func: Callable) -> Optional[Callable]:
    """Extract the raw jax function from a transformer runner, if the
    transformer is an interfaceless pure-jax function (input code "j")."""
    runner = getattr(map_func, "__self__", None)
    tf = getattr(runner, "transformer", None)
    wrapper = getattr(tf, "_wrapper", None)
    if wrapper is None or wrapper.input_code != "j" or wrapper.output_code != "j":
        return None
    if getattr(tf, "using_callback", False):
        return None
    return wrapper._func
