"""Pipelined (double-buffered) streaming ingest — ISSUE 2's tentpole.

Every streaming path used to be strictly sequential per chunk: parquet
decode → arrow/pandas → numpy staging → ``jax.device_put`` → jitted step,
each stage blocking the next, so the device idled during host work and the
host idled during device work (BENCH_r05: only 36.3s of the 192s north-star
wall was the device aggregate pass). This module overlaps them:

- :class:`ChunkPrefetcher` runs the *producer* side (host decode +
  staging + H2D ``device_put``) of a chunk stream in ONE background
  thread feeding a bounded queue (depth = ``fugue.tpu.stream.prefetch_depth``,
  default 2 — double buffering), while the caller consumes already-on-device
  chunks. Device memory stays bounded: at most ``depth`` decoded chunks are
  in flight beyond the one being consumed, and the streaming paths' peak
  accounting (``jax.live_arrays()``) naturally counts them because
  prefetched device buffers ARE live arrays.
- Errors raised inside the producer (poison chunks, contract violations,
  injected faults) are carried across the thread boundary and re-raised in
  the consumer WITH the original traceback; a failed producer never leaves
  the consumer blocked on an empty queue, and an abandoned consumer
  (early-stop ``take``, a downstream exception) never leaves the producer
  blocked on a full one (``close()`` drains and signals stop).
- The :data:`SITE_STREAM_CHUNK` fault-injection site fires per produced
  chunk, so the resilience suite can prove the no-deadlock property.
- :class:`PipelineStats` (surfaced as ``engine.pipeline_stats``) records
  chunks prefetched, producer-wait vs consumer-wait seconds and the
  measured overlap fraction; :class:`JitCache` (the engine's
  ``_jit_cache``) counts compile-cache hits/misses. Both feed ``bench.py``'s
  ``extra`` block so the trajectory tracks them.

``prefetch_depth <= 0`` disables the thread entirely and returns a serial
iterator with the identical interface — the A/B switch the parity tests
use (results must be bit-identical either way).
"""

import contextvars
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

__all__ = [
    "DEFAULT_PREFETCH_DEPTH",
    "ChunkPrefetcher",
    "JitCache",
    "PipelineStats",
    "last_run_stats",
    "maybe_prefetch",
    "prefetch_depth",
]

DEFAULT_PREFETCH_DEPTH = 2

# most recent finished prefetch run (mirrors streaming.last_run_stats) —
# the proof artifact tests read without holding an engine
last_run_stats: Dict[str, Any] = {}


def default_prefetch_depth() -> int:
    """The auto default: overlap needs a spare execution unit.

    On a multi-core host (or any non-cpu jax backend, where device compute
    runs off-host) double buffering is free throughput. On a single-core
    host whose "devices" ARE that core (the virtual CPU mesh), the
    producer thread can only steal time from the consumer — measured
    10-15% slower end to end — so the default degrades to serial there.
    An explicit ``fugue.tpu.stream.prefetch_depth`` always wins.
    """
    import os

    if (os.cpu_count() or 1) > 1:
        return DEFAULT_PREFETCH_DEPTH
    try:
        import jax

        if jax.default_backend() != "cpu":
            return DEFAULT_PREFETCH_DEPTH
    except Exception:  # pragma: no cover - jax always importable here
        pass
    return 0


def prefetch_depth(conf: Any) -> int:
    """Resolve ``fugue.tpu.stream.prefetch_depth`` from an engine conf
    (unset → :func:`default_prefetch_depth`)."""
    from ..constants import FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH

    raw = conf.get_or_none(FUGUE_TPU_CONF_STREAM_PREFETCH_DEPTH, object)
    if raw is None:
        return default_prefetch_depth()
    return int(raw)


class PipelineStats:
    """Thread-safe ingest-pipeline counters for one engine.

    ``overlap_fraction`` is the fraction of the serial-time estimate
    (producer busy + consumer busy) that pipelining actually removed from
    the wall clock: 0 = fully serialized, → 1 = fully hidden. Producer
    wait is time the producer sat on a FULL queue (consumer-bound run);
    consumer wait is time the consumer sat on an EMPTY queue
    (producer-bound run) — whichever dominates names the bottleneck.
    """

    # per-stream attribution (ISSUE 12 satellite): keep at most this many
    # distinct stream keys; past it the oldest-inserted is dropped so a
    # long-lived server's registry stays bounded
    MAX_STREAMS = 64

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {
            "runs": 0,
            "chunks_prefetched": 0,
            "producer_busy_s": 0.0,
            "producer_wait_s": 0.0,
            "consumer_wait_s": 0.0,
            "wall_s": 0.0,
            "overlap_saved_s": 0.0,
        }
        self._last: Dict[str, Any] = {}
        self._streams: Dict[str, Dict[str, Any]] = {}

    def record_run(
        self,
        verb: str,
        chunks: int,
        producer_busy_s: float,
        producer_wait_s: float,
        consumer_wait_s: float,
        wall_s: float,
        rows: int = 0,
        nbytes: int = 0,
        stream: str = "",
    ) -> None:
        consumer_busy = max(wall_s - consumer_wait_s, 0.0)
        serial_estimate = producer_busy_s + consumer_busy
        saved = max(serial_estimate - wall_s, 0.0)
        run = {
            "verb": verb,
            "stream": stream or verb,
            "chunks_prefetched": chunks,
            "rows": int(rows),
            "bytes": int(nbytes),
            "producer_busy_s": round(producer_busy_s, 6),
            "producer_wait_s": round(producer_wait_s, 6),
            "consumer_wait_s": round(consumer_wait_s, 6),
            "wall_s": round(wall_s, 6),
            "overlap_saved_s": round(saved, 6),
            "overlap_fraction": round(saved / serial_estimate, 6)
            if serial_estimate > 0
            else 0.0,
        }
        with self._lock:
            t = self._totals
            t["runs"] += 1
            t["chunks_prefetched"] += chunks
            t["producer_busy_s"] += producer_busy_s
            t["producer_wait_s"] += producer_wait_s
            t["consumer_wait_s"] += consumer_wait_s
            t["wall_s"] += wall_s
            t["overlap_saved_s"] += saved
            self._last = run
            # per-stream accumulation keyed by the stream id (the tuning
            # sid when a run scope is active, else the verb): overlap and
            # producer/consumer waits attributable to ONE Load/segment
            # instead of a whole-run blend
            key = stream or verb
            s = self._streams.get(key)
            if s is None:
                while len(self._streams) >= self.MAX_STREAMS:
                    self._streams.pop(next(iter(self._streams)))
                s = self._streams[key] = {
                    "runs": 0,
                    "chunks_prefetched": 0,
                    "rows": 0,
                    "producer_busy_s": 0.0,
                    "producer_wait_s": 0.0,
                    "consumer_wait_s": 0.0,
                    "wall_s": 0.0,
                    "overlap_saved_s": 0.0,
                }
            s["runs"] += 1
            s["chunks_prefetched"] += chunks
            s["rows"] += int(rows)
            s["producer_busy_s"] = round(s["producer_busy_s"] + producer_busy_s, 6)
            s["producer_wait_s"] = round(s["producer_wait_s"] + producer_wait_s, 6)
            s["consumer_wait_s"] = round(s["consumer_wait_s"] + consumer_wait_s, 6)
            s["wall_s"] = round(s["wall_s"] + wall_s, 6)
            s["overlap_saved_s"] = round(s["overlap_saved_s"] + saved, 6)
            serial = s["producer_busy_s"] + max(
                s["wall_s"] - s["consumer_wait_s"], 0.0
            )
            s["overlap_fraction"] = (
                round(s["overlap_saved_s"] / serial, 6) if serial > 0 else 0.0
            )
            s["last_overlap_fraction"] = run["overlap_fraction"]
        global last_run_stats
        last_run_stats = run

    @property
    def last_run(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._last)

    def stream_stats(self, stream: str) -> Dict[str, Any]:
        with self._lock:
            return dict(self._streams.get(stream, {}))

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            t = dict(self._totals)
            streams = {k: dict(v) for k, v in self._streams.items()}
        serial = t["producer_busy_s"] + max(t["wall_s"] - t["consumer_wait_s"], 0.0)
        t["overlap_fraction"] = (
            round(t["overlap_saved_s"] / serial, 6) if serial > 0 else 0.0
        )
        for k in (
            "producer_busy_s",
            "producer_wait_s",
            "consumer_wait_s",
            "wall_s",
            "overlap_saved_s",
        ):
            t[k] = round(t[k], 6)
        t["last_run"] = self.last_run
        t["streams"] = streams
        return t

    def reset(self) -> None:
        with self._lock:
            for k in self._totals:
                self._totals[k] = 0 if k in ("runs", "chunks_prefetched") else 0.0
            self._last = {}
            self._streams = {}


class JitCache(dict):
    """The engine's compile cache with hit/miss observability.

    Every compiled-path gate in the codebase is the ``key not in cache``
    idiom, so counting at ``__contains__`` maps 1:1 onto "would this call
    have paid an XLA compile": absent = miss (a compile follows), present =
    hit. Exposed via ``engine.jit_cache_stats`` and ``bench.py`` extra.

    Entries are labeled by their key's leading element (``by_label``):
    per-verb programs carry the verb-ish tag they always did
    (``filter3v`` / ``fused`` / ``stream_agg_step`` / ...), and a lowered
    plan segment's single program carries ``segment:<fingerprint>`` — so
    the "one jit entry per pipeline segment" claim is checkable from
    ``engine.stats()["jit_cache"]`` alone, without poking at key tuples.

    Concurrency (the ISSUE 10 shared-engine audit): the hit/miss
    counters are read-modify-write and were losing updates under
    concurrent sessions — they increment under a narrow lock now. The
    ``key not in cache → cache[key] = jit(...)`` call-site idiom itself
    stays lock-free by design: two sessions racing the same key both
    compile the IDENTICAL program and the second dict insert harmlessly
    replaces the first (a one-off duplicate compile, never a wrong
    result); serializing every compile behind a cache-wide lock would
    make one tenant's 30s XLA compile block every other tenant's hits.
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._count_lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def __contains__(self, key: Any) -> bool:
        present = super().__contains__(key)
        with self._count_lock:
            if present:
                self.hits += 1
            else:
                self.misses += 1
        return present

    @staticmethod
    def label_of(key: Any) -> str:
        if isinstance(key, tuple) and len(key) > 0:
            return str(key[0])
        return str(key)

    def by_label(self) -> Dict[str, int]:
        """Entry count per label — segment entries keyed by their segment
        fingerprint, never by the first verb they absorbed."""
        out: Dict[str, int] = {}
        # list() snapshots the keys: a concurrent session's insert must
        # not blow up a scrape mid-iteration
        for k in list(self.keys()):
            lab = self.label_of(k)
            out[lab] = out.get(lab, 0) + 1
        return out

    def segment_entries(self) -> Dict[str, int]:
        """Just the lowered-segment programs: {fingerprint: entry count}."""
        return {
            lab.split(":", 1)[1]: n
            for lab, n in self.by_label().items()
            if lab.startswith("segment:")
        }

    def stats(self) -> Dict[str, Any]:
        with self._count_lock:
            hits, misses = self.hits, self.misses
        return {
            "hits": hits,
            "misses": misses,
            "entries": len(self),
            "by_label": self.by_label(),
        }

    # MetricsRegistry source contract (see fugue_tpu/obs/registry.py)
    def as_dict(self) -> Dict[str, int]:
        return self.stats()

    def reset(self) -> None:
        """Zero the hit/miss counters. Compiled entries are KEPT — evicting
        them would force recompiles, turning a stats reset into a perf
        event; ``entries`` therefore survives a reset by design."""
        with self._count_lock:
            self.hits = 0
            self.misses = 0


class _SerialChunks:
    """depth<=0 path: the same iterator/close() surface, no thread — the
    bit-identical serial baseline the parity tests compare against.

    With an ``observer`` attached (an adaptive-tuning handle inside an
    enabled run scope — the single-core default where a producer thread
    would only steal consumer time), the serial path still measures the
    CHUNK-COUNT signal (chunks, rows, bytes, source-advance time, wall)
    so chunk-size tuning works without a pipeline; producer/consumer
    waits report 0 and depth tuning correctly stays put. Observer-less —
    tuning disabled, direct engine calls — it measures nothing at all,
    and it never touches ``PipelineStats`` either way (those counters
    mean "prefetched" and stay bit-compatible with the pre-tuning
    engine)."""

    def __init__(
        self,
        source: Iterator[Any],
        verb: str = "",
        stream: str = "",
        observer: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self._src = source
        self._verb = verb
        self._stream = stream
        self._observer = observer
        self._chunks = 0
        self._rows = 0
        self._bytes = 0
        self._busy = 0.0
        self._done = False
        self._t0 = time.perf_counter() if observer is not None else 0.0

    def __iter__(self) -> "_SerialChunks":
        return self

    def __next__(self) -> Any:
        if self._observer is None:
            return next(self._src)
        t0 = time.perf_counter()
        try:
            item = next(self._src)
        except StopIteration:
            self._finish()
            raise
        self._busy += time.perf_counter() - t0
        self._chunks += 1
        attrs = _chunk_attrs(item)
        self._rows += int(attrs.get("rows", 0))
        self._bytes += int(attrs.get("bytes", 0))
        return item

    def _finish(self) -> None:
        if self._done or self._observer is None:
            return
        self._done = True
        try:  # learning must never fail the stream
            self._observer(
                {
                    "verb": self._verb,
                    "stream": self._stream or self._verb,
                    "chunks_prefetched": self._chunks,
                    "rows": self._rows,
                    "bytes": self._bytes,
                    "producer_busy_s": self._busy,
                    "producer_wait_s": 0.0,
                    "consumer_wait_s": 0.0,
                    "wall_s": time.perf_counter() - self._t0,
                }
            )
        except Exception:
            pass

    def close(self) -> None:
        close = getattr(self._src, "close", None)
        if close is not None:
            close()
        self._finish()


class _Failure:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


_DONE = object()


class ChunkPrefetcher:
    """Background producer over ``source`` with a depth-bounded queue.

    The producer thread advances ``source`` (which performs the host decode
    and ``device_put`` work) and enqueues results; ``__next__`` dequeues.
    At most ``depth`` finished items wait in the queue, plus one being
    produced — the memory bound callers account for.
    """

    def __init__(
        self,
        source: Iterator[Any],
        depth: int,
        stats: Optional[PipelineStats] = None,
        verb: str = "",
        injector: Any = None,
        stream: str = "",
        observer: Optional[Callable[[Dict[str, Any]], None]] = None,
    ):
        self._src = source
        self._depth = max(1, int(depth))
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._stats = stats
        self._verb = verb
        self._stream = stream
        self._observer = observer
        self._injector = injector
        self._chunks = 0
        self._rows = 0
        self._bytes = 0
        self._producer_busy = 0.0
        self._producer_wait = 0.0
        self._consumer_wait = 0.0
        self._finished = False
        self._recorded = False
        self._t0 = time.perf_counter()
        # run the producer inside the consumer's context snapshot so any
        # observability done on that thread keeps the run-attribution
        # labels (contextvars don't cross thread starts on their own)
        self._thread = threading.Thread(
            target=contextvars.copy_context().run,
            args=(self._produce,),
            name=f"fugue-tpu-prefetch-{verb or 'chunks'}",
            daemon=True,
        )
        self._thread.start()

    # -- producer side ------------------------------------------------------
    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(self._src)
                except StopIteration:
                    break
                if self._injector is not None:
                    # the poison-chunk site: an injected error here must
                    # surface in the consumer, never hang the queue
                    from ..resilience import SITE_STREAM_CHUNK

                    self._injector.fire(SITE_STREAM_CHUNK)
                self._producer_busy += time.perf_counter() - t0
                if not self._put(item):
                    return
            self._put(_DONE)
        except BaseException as ex:  # noqa: BLE001 — carried to the consumer
            self._put(_Failure(ex))

    def _put(self, obj: Any) -> bool:
        """Blocking put that aborts when the consumer closed the pipeline —
        a stalled consumer must not pin this thread (and the stream's
        remaining chunks) forever."""
        t0 = time.perf_counter()
        while not self._stop.is_set():
            try:
                self._q.put(obj, timeout=0.05)
                self._producer_wait += time.perf_counter() - t0
                return True
            except queue.Full:
                continue
        return False

    # -- consumer side ------------------------------------------------------
    def __iter__(self) -> "ChunkPrefetcher":
        return self

    def __next__(self) -> Any:
        if self._finished:
            raise StopIteration
        t0 = time.perf_counter()
        obj = self._q.get()
        self._consumer_wait += time.perf_counter() - t0
        if obj is _DONE:
            self._finish()
            raise StopIteration
        if isinstance(obj, _Failure):
            self._finish()
            self.close()
            # re-raising the ORIGINAL exception object keeps its traceback
            # (the producer-side frames), satisfying the propagation
            # contract: the user sees where the decode actually failed
            raise obj.exc
        self._chunks += 1
        attrs = _chunk_attrs(obj)
        self._rows += int(attrs.get("rows", 0))
        self._bytes += int(attrs.get("bytes", 0))
        return obj

    def _finish(self) -> None:
        self._finished = True
        if self._recorded:
            return
        self._recorded = True
        run = {
            "verb": self._verb,
            "stream": self._stream or self._verb,
            "chunks_prefetched": self._chunks,
            "rows": self._rows,
            "bytes": self._bytes,
            "producer_busy_s": self._producer_busy,
            "producer_wait_s": self._producer_wait,
            "consumer_wait_s": self._consumer_wait,
            "wall_s": time.perf_counter() - self._t0,
        }
        if self._stats is not None:
            self._stats.record_run(
                self._verb,
                self._chunks,
                self._producer_busy,
                self._producer_wait,
                self._consumer_wait,
                run["wall_s"],
                rows=self._rows,
                nbytes=self._bytes,
                stream=self._stream,
            )
        if self._observer is not None:
            try:  # learning must never fail the stream
                self._observer(run)
            except Exception:
                pass

    def close(self) -> None:
        """Stop the producer and release everything it buffered. Safe to
        call multiple times; always called from the consuming ``finally``."""
        self._stop.set()
        while True:  # drain so a blocked producer put() can observe stop
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
        self._finish()


def maybe_prefetch(
    source: Iterator[Any],
    depth: int,
    stats: Optional[PipelineStats] = None,
    verb: str = "",
    injector: Any = None,
    stream: str = "",
    observer: Optional[Callable[[Dict[str, Any]], None]] = None,
) -> Any:
    """Wrap a chunk iterator in a :class:`ChunkPrefetcher` (depth > 0) or a
    same-interface serial shim (depth <= 0)."""
    if depth <= 0:
        return _SerialChunks(
            iter(source), verb=verb, stream=stream, observer=observer
        )
    return ChunkPrefetcher(
        iter(source),
        depth,
        stats=stats,
        verb=verb,
        injector=injector,
        stream=stream,
        observer=observer,
    )


class _TracedChunks:
    """Per-chunk span wrapper over a (possibly prefetched) chunk iterator.

    Span ``stream.chunk`` #n opens when chunk n is handed to the consumer
    and closes when the consumer asks for chunk n+1 (or closes the
    stream) — i.e. it measures the DOWNSTREAM processing of that chunk,
    nested inside whatever engine-verb span is open on the consuming
    thread. ``fetch_wait_ns`` records how long the consumer waited for the
    chunk itself (producer-bound time). Spans also enter an XLA
    ``TraceAnnotation`` so per-chunk device work lines up in a Perfetto
    capture.
    """

    def __init__(self, inner: Any, verb: str, tracer: Any):
        self._inner = inner
        self._verb = verb
        self._tracer = tracer
        self._open: Any = None
        self._i = 0

    def __iter__(self) -> "_TracedChunks":
        return self

    def _end_open(self) -> None:
        if self._open is not None:
            self._open.__exit__(None, None, None)
            self._open = None

    def __next__(self) -> Any:
        self._end_open()
        t0 = time.perf_counter_ns()
        item = next(self._inner)
        sp = self._tracer.span(
            "stream.chunk",
            cat="stream",
            annotate=True,
            verb=self._verb,
            chunk=self._i,
            fetch_wait_ns=time.perf_counter_ns() - t0,
            **_chunk_attrs(item),
        )
        sp.__enter__()
        self._open = sp
        self._i += 1
        return item

    def close(self) -> None:
        self._end_open()
        self._inner.close()


def _chunk_attrs(item: Any) -> Dict[str, Any]:
    """Cheap rows/bytes attributes for a chunk of any streaming shape
    (pandas frame, arrow table, (n, device) tuple, LocalDataFrame).

    Producers streaming OPAQUE items (the shuffle's ingested bucket-pair
    groups, the dist worker's fetched fragments) can label them by
    attaching a ``prefetch_attrs`` dict attribute — it wins over every
    heuristic below, so their rows/bytes land in ``PipelineStats`` and
    the per-chunk trace spans without this function learning their
    shape."""
    attrs = getattr(item, "prefetch_attrs", None)
    if isinstance(attrs, dict):
        return attrs
    try:
        if isinstance(item, tuple) and len(item) > 0 and isinstance(item[0], int):
            return {"rows": item[0]}
        num_rows = getattr(item, "num_rows", None)  # pyarrow.Table
        if isinstance(num_rows, int):
            return {"rows": num_rows, "bytes": int(getattr(item, "nbytes", 0))}
        if hasattr(item, "memory_usage") and hasattr(item, "__len__"):  # pandas
            return {
                "rows": len(item),
                "bytes": int(item.memory_usage(index=False).sum()),
            }
        if hasattr(item, "count") and hasattr(item, "schema"):  # LocalDataFrame
            return {"rows": int(item.count())}
    except Exception:
        pass
    return {}


def engine_prefetcher(
    engine: Any, source: Iterator[Any], verb: str
) -> Any:
    """The streaming paths' one-liner: depth/stats/injector from ``engine``,
    plus per-chunk trace spans when the global tracer is enabled.

    When the chunk-size site left an adaptive-tuning handle for this verb
    (``Tuner.stream_params`` inside an enabled run scope, docs/tuning.md),
    the handle supplies the learned prefetch depth, keys the per-stream
    pipeline stats by its stream id, and receives the finished run's
    telemetry as the next generation's evidence. No handle — direct engine
    calls, tuning disabled — resolves exactly as before."""
    from ..obs import get_tracer
    from ..resilience import FaultInjector

    handle = None
    tuner = getattr(engine, "tuner", None)
    if tuner is not None:
        handle = tuner.take_stream_handle(verb)
    depth = (
        handle.prefetch_depth
        if handle is not None and handle.prefetch_depth is not None
        else prefetch_depth(engine.conf)
    )
    observer = None
    stream = ""
    if handle is not None:
        handle.used_depth = depth
        observer = handle.observe
        stream = handle.sid
    it = maybe_prefetch(
        source,
        depth,
        stats=getattr(engine, "pipeline_stats", None),
        verb=verb,
        injector=FaultInjector.from_conf(engine.conf),
        stream=stream,
        observer=observer,
    )
    tracer = get_tracer()
    if tracer.enabled:
        return _TracedChunks(it, verb, tracer)
    return it
