"""fugue-tpu: a TPU-native framework for distributed computation.

A unified interface where plain Python/pandas/arrow functions and SQL run
unchanged across execution engines — with JAX/XLA over TPU device meshes as
the first-class distributed engine. Capability parity target:
fugue-project/fugue (see SURVEY.md).
"""

__version__ = "0.1.0"

from .collections.partition import PartitionCursor, PartitionSpec
from .collections.sql import StructuredRawSQL, TempTableName
from .collections.yielded import PhysicalYielded, Yielded
from .constants import register_global_conf
from .dataframe import (
    ArrayDataFrame,
    ArrowDataFrame,
    DataFrame,
    DataFrames,
    IterableDataFrame,
    IterableArrowDataFrame,
    IterablePandasDataFrame,
    LocalBoundedDataFrame,
    LocalDataFrame,
    LocalDataFrameIterableDataFrame,
    LocalUnboundedDataFrame,
    PandasDataFrame,
    YieldedDataFrame,
)
from .bag.bag import Bag, LocalBag, LocalBoundedBag
from .bag.array_bag import ArrayBag
from .dataset import Dataset, DatasetDisplay
from .execution import (
    ExecutionEngine,
    MapEngine,
    NativeExecutionEngine,
    SQLEngine,
    make_execution_engine,
    make_sql_engine,
    register_default_execution_engine,
    register_execution_engine,
    register_sql_engine,
)
from .extensions import (
    CoTransformer,
    Creator,
    OutputCoTransformer,
    OutputTransformer,
    Outputter,
    Processor,
    Transformer,
    cotransformer,
    creator,
    output_cotransformer,
    output_transformer,
    outputter,
    processor,
    register_creator,
    register_output_transformer,
    register_outputter,
    register_processor,
    register_transformer,
    transformer,
)
from .schema import Schema
from .workflow import (
    FugueWorkflow,
    FugueWorkflowResult,
    WorkflowDataFrame,
    out_transform,
    raw_sql,
    transform,
)
from .sql import FugueSQLWorkflow, fugue_sql, fugue_sql_flow, fsql
from . import jax_annotations as _jax_annotations  # registers Dict[str, jax.Array]
from . import api  # noqa: F401
