"""fugue-tpu: a TPU-native framework for distributed computation.

A unified interface where plain Python/pandas/arrow functions and SQL run
unchanged across execution engines — with JAX/XLA over TPU device meshes as
the first-class distributed engine. See SURVEY.md for the blueprint.
"""

__version__ = "0.1.0"
